"""Flash attention as a Pallas TPU kernel.

The reference computes attention as unfused matmul/softmax/matmul modules
(``DL/nn/Attention.scala:35`` builds a Graph of MM + SoftMax + CMulTable);
at sequence length S that materialises the (S, S) score matrix in memory.
On TPU the memory-bound softmax traffic dominates HBM bandwidth, so the
TPU-native design is the online-softmax (flash) formulation: stream K/V
blocks through VMEM, keep running max/sum statistics, never materialise the
score matrix. Forward is a Pallas kernel; backward recomputes attention
(rematerialisation — FLOPs are cheap on the MXU, HBM is not) with a plain
XLA implementation under ``jax.custom_vjp``.

Shapes follow (batch, heads, seq, head_dim) throughout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_MIN_LANE = 128


def _xla_attention(q, k, v, bias, sm_scale, causal,
                   dropout_rate=0.0, dropout_rng=None):
    """Reference XLA path (also the recompute used by the flash backward).

    Causal convention (shared with the kernel): END-aligned — query row i
    attends key cols j with ``j <= i + (klen - qlen)``, i.e. queries are the
    LAST ``qlen`` positions of the key sequence (the decode-time case; for
    qlen == klen this is the ordinary lower triangle).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, acc_ref, m_ref, l_ref,
                *, sm_scale, causal, block_q, block_k, n_k, causal_offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    should_run = True
    if causal:
        # end-aligned: row i may see cols <= i + causal_offset
        should_run = qi * block_q + block_q - 1 + causal_offset >= ki * block_k

    @pl.when(should_run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)          # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                               # (block_q, block_k)
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows + causal_offset >= cols, s, _NEG_INF)

        m_prev = m_ref[:, :1]                      # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (block_q, 1)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)                    # (block_q, block_k)
        l_next = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)            # fully-masked rows -> 0 output
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lens ({sq},{sk}) not divisible by blocks ({block_q},{block_k})")
    n_q, n_k = sq // block_q, sk // block_k

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        bias = jnp.broadcast_to(bias, (b, h, sq, sk)).reshape(b * h, sq, sk)
        in_specs.append(
            pl.BlockSpec((1, block_q, block_k), lambda bh, qi, ki: (bh, qi, ki))
        )
        args.append(bias)
        kernel = functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, n_k=n_k, causal_offset=sk - sq,
        )
    else:
        kernel = functools.partial(
            lambda qf, kf, vf, o, acc, m, l, **kw: _fwd_kernel(
                qf, kf, vf, None, o, acc, m, l, **kw),
            sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, n_k=n_k, causal_offset=sk - sq,
        )

    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _MIN_LANE), jnp.float32),
            pltpu.VMEM((block_q, _MIN_LANE), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, sq, d)


# --------------------------------------------------------------------- #
# Paged (block-table) attention — the decode-side companion of the flash
# kernel. The serving tier's KV cache is a shared pool of fixed-size
# pages (vLLM's PagedAttention, SOSP '23 — PAPERS.md): per layer,
# K/V are (num_pages, heads, page_size, head_dim) and each slot owns a
# row of int32 page ids. Attention must therefore GATHER a slot's keys
# through its page map instead of slicing a dense lane. Two paths:
#
# - `paged_attention_reference`: pure-jnp `jnp.take` gather that
#   reconstitutes the logical (S, H, L, D) lanes and reuses the exact
#   dense attention ops — bit-identical to the dense slot-table path on
#   the same backend (gathering is data movement; the math that follows
#   is the same op sequence). This is the CPU/tier-1 path.
# - `paged_flash_attention`: a Pallas TPU kernel streaming pages through
#   VMEM with the page map scalar-prefetched, so the physical page id
#   feeds the K/V BlockSpec index_map directly (no materialised gather)
#   and pages wholly past a slot's position are skipped.


def gather_kv_lanes(pages: jax.Array, page_map: jax.Array) -> jax.Array:
    """(num_pages, H, page_size, D) pool + (..., ppn) int32 page map ->
    logical lanes (..., H, ppn * page_size, D). The gather is exact data
    movement: lane bytes equal the pooled page bytes, which is what the
    paged == dense bit-identity tests lean on."""
    h, ps, d = pages.shape[1:]
    lanes = jnp.take(pages, page_map, axis=0)  # (..., ppn, H, ps, D)
    perm = tuple(range(page_map.ndim - 1)) + (
        page_map.ndim, page_map.ndim - 1, page_map.ndim + 1,
        page_map.ndim + 2)
    lanes = lanes.transpose(perm)              # (..., H, ppn, ps, D)
    return lanes.reshape(page_map.shape[:-1] + (h, -1, d))


def gather_scale_lanes(scales: jax.Array, page_map: jax.Array) -> jax.Array:
    """Companion gather for int8 KV: (num_pages, page_size) per-token
    scale pool + (..., ppn) page map -> logical scale lanes
    (..., ppn * page_size), row-aligned with :func:`gather_kv_lanes`
    output so ``nn.int8.dequantize_lanes`` can broadcast them."""
    ps = scales.shape[1]
    lanes = jnp.take(scales, page_map, axis=0)   # (..., ppn, ps)
    return lanes.reshape(page_map.shape[:-1] + (page_map.shape[-1] * ps,))


def paged_attention_reference(q, k_pages, v_pages, page_map, positions,
                              sm_scale: Optional[float] = None,
                              k_scales=None, v_scales=None):
    """Decode-shaped paged attention, pure jnp (the XLA/tier-1 path).

    ``q``: (S, H, D) one query per slot; ``k_pages``/``v_pages``:
    (num_pages, H, page_size, D); ``page_map``: (S, ppn) int32 physical
    page per logical page; ``positions``: (S,) int32 — key column ``j``
    is valid for slot ``s`` iff ``j <= positions[s]`` (the row the
    current token was just written to). Returns (S, H, D).

    ``k_scales``/``v_scales`` (both or neither): int8 pools' per-token
    fp32 scale pools of shape (num_pages, page_size) — lanes are
    dequantized after the gather (``value = int8 * scale``); masked
    columns still contribute exact zeros whatever a recycled page or a
    stale scale holds, so the bit-identity argument of the float path
    carries over unchanged."""
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    lk = gather_kv_lanes(k_pages, page_map)    # (S, H, L, D)
    lv = gather_kv_lanes(v_pages, page_map)
    if k_scales is not None:
        from bigdl_tpu.nn.int8 import dequantize_lanes

        lk = dequantize_lanes(lk, gather_scale_lanes(k_scales, page_map))
        lv = dequantize_lanes(lv, gather_scale_lanes(v_scales, page_map))
    length = lk.shape[2]
    rows = positions[:, None]                  # one query row per slot
    cols = jnp.arange(length)
    validity = jnp.where(cols[None, None, :] <= rows[:, :, None],
                         0.0, -1e9)[:, None, :, :]
    out = _xla_attention(q[:, :, None, :], lk, lv, validity, scale, False)
    return out[:, :, 0, :]


def _paged_kernel(pm_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                  o_ref, acc_ref, m_ref, l_ref, *, sm_scale, page_size,
                  n_pages):
    s = pl.program_id(0)
    pi = pl.program_id(2)

    @pl.when(pi == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[s]

    @pl.when(pi * page_size <= pos)            # page holds >= 1 valid col
    def _compute():
        q = q_ref[...].reshape(1, -1).astype(jnp.float32)    # (1, D)
        k = k_ref[0, 0].astype(jnp.float32)                  # (ps, D)
        v = v_ref[0, 0].astype(jnp.float32)
        if ks_ref is not None:
            # int8 pages: per-token scales ride in their own (1, ps)
            # block DMA'd through the same scalar-prefetched page id
            k = k * ks_ref[0][:, None]
            v = v * vs_ref[0][:, None]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                         # (1, ps)
        cols = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        scores = jnp.where(cols <= pos, scores, _NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - m_next)
        l_next = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(pi == n_pages - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_ref[...] / l).reshape(o_ref.shape).astype(
            o_ref.dtype)


def paged_flash_attention(q, k_pages, v_pages, page_map, positions,
                          sm_scale: Optional[float] = None,
                          interpret: bool = False,
                          k_scales=None, v_scales=None):
    """Pallas paged gather-attention: online-softmax over a slot's mapped
    pages, page ids scalar-prefetched so each K/V block DMA reads the
    physical page directly. Same signature/semantics as
    :func:`paged_attention_reference` (q: (S, H, D) -> (S, H, D));
    int8 pools pass their per-token scale pools, each streamed as a
    (1, page_size) block through the same prefetched page id and applied
    before the score matmul."""
    n_slots, heads, d = q.shape
    n_phys, _, page_size, _ = k_pages.shape
    ppn = page_map.shape[1]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    int8_kv = k_scales is not None

    in_specs = [
        pl.BlockSpec((1, 1, d), lambda s, h, p, pm, pos: (s, h, 0)),
        pl.BlockSpec((1, 1, page_size, d),
                     lambda s, h, p, pm, pos: (pm[s, p], h, 0, 0)),
        pl.BlockSpec((1, 1, page_size, d),
                     lambda s, h, p, pm, pos: (pm[s, p], h, 0, 0)),
    ]
    args = [q, k_pages, v_pages]
    if int8_kv:
        in_specs += [
            pl.BlockSpec((1, page_size),
                         lambda s, h, p, pm, pos: (pm[s, p], 0)),
            pl.BlockSpec((1, page_size),
                         lambda s, h, p, pm, pos: (pm[s, p], 0)),
        ]
        args += [k_scales.astype(jnp.float32), v_scales.astype(jnp.float32)]
        kernel = functools.partial(
            _paged_kernel, sm_scale=scale, page_size=page_size, n_pages=ppn)
    else:
        kernel = functools.partial(
            lambda pm, pos, qf, kf, vf, o, acc, m, l, **kw: _paged_kernel(
                pm, pos, qf, kf, vf, None, None, o, acc, m, l, **kw),
            sm_scale=scale, page_size=page_size, n_pages=ppn)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_slots, heads, ppn),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, d), lambda s, h, p, pm, pos: (s, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, _MIN_LANE), jnp.float32),
            pltpu.VMEM((1, _MIN_LANE), jnp.float32),
        ],
    )
    out_dtype = jnp.float32 if int8_kv else q.dtype
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_slots, heads, d), out_dtype),
        interpret=interpret,
    )(page_map.astype(jnp.int32), positions.astype(jnp.int32), *args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, bias=None, sm_scale: Optional[float] = None,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Fused online-softmax attention. q/k/v: (B, H, S, D)."""
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    return _flash_fwd(q, k, v, bias, scale, causal, block_q, block_k, interpret)


def _vjp_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, bias)


def _vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, bias = res
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5

    def ref(q, k, v, bias):
        if bias is None:
            return _xla_attention(q, k, v, None, scale, causal)
        return _xla_attention(q, k, v, bias, scale, causal)

    if bias is None:
        _, vjp = jax.vjp(lambda q, k, v: ref(q, k, v, None), q, k, v)
        dq, dk, dv = vjp(g)
        return dq, dk, dv, None
    _, vjp = jax.vjp(ref, q, k, v, bias)
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
