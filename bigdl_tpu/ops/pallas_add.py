"""Pallas residual add: a bandwidth-tuned two-operand elementwise sum.

Round-5 measurement (`perf/micro_resadd2.py`, `perf/artifacts/r5_resadd2.txt`):
XLA's STANDALONE materialized add of a (128,256,56,56) bf16 pair runs at
~269 GB/s on this v5e, while a Pallas block add with 64-row blocks over
a (rows, cols)-flattened view reaches ~464 GB/s — 1.7x. The ResNet-50
step carries 16 such standalone residual adds (~4.5 ms of the 44 ms
step, per the r5 profile), whose producers (conv outputs on both sides)
and consumers keep XLA from fusing them away. This op exists to claw
back part of that bucket; it is opt-in via ``BIGDL_RESIDUAL_ADD=pallas``
(read per-trace, like the other perf knobs) because it also BLOCKS any
fusion the surrounding graph might otherwise find.

Semantics: exact two-operand add of same-shape floating arrays;
``custom_vjp`` backward passes the cotangent to both operands (identical
to ``jnp.add``'s transpose for equal shapes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _flat2d(shape):
    """(rows, cols) view: split before the last two dims so the minor
    axis is large (NCHW (B,C,H,W) -> (B*C, H*W); (B,T,F) -> (B, T*F))."""
    if len(shape) == 2:
        return shape
    return int(np.prod(shape[:-2])), int(shape[-2] * shape[-1])


def _block_rows(rows, cols, itemsize):
    """Largest row block <= 64 dividing rows, kept under the VMEM budget
    (3 buffers x double buffering; 64 rows x 3136 cols bf16 ~= 0.4 MB)."""
    bs = 64
    while bs > 1 and rows % bs:
        bs //= 2
    while bs > 1 and bs * cols * itemsize * 6 > 12 * 1024 * 1024:
        bs //= 2
    return bs


def _pallas_add2(x2, y2, bs):
    rows, cols = x2.shape

    def kern(a_ref, b_ref, o_ref):
        o_ref[...] = a_ref[...] + b_ref[...]

    return pl.pallas_call(
        kern, grid=(rows // bs,),
        in_specs=[pl.BlockSpec((bs, cols), lambda i: (i, 0))] * 2,
        out_specs=pl.BlockSpec((bs, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, cols), x2.dtype),
    )(x2, y2)


def _supported(x, y):
    if x.shape != y.shape or x.dtype != y.dtype:
        return False
    if not jnp.issubdtype(x.dtype, jnp.floating) or x.ndim < 2:
        return False
    if jax.default_backend() not in ("tpu",):
        return False
    return x.size >= 1 << 20  # small adds: fusion beats a kernel call


@jax.custom_vjp
def _kernel_add(x, y):
    # only reached for _supported() inputs: same shape, same float dtype
    rows, cols = _flat2d(x.shape)
    bs = _block_rows(rows, cols, x.dtype.itemsize)
    out = _pallas_add2(x.reshape(rows, cols), y.reshape(rows, cols), bs)
    return out.reshape(x.shape)


def _fwd(x, y):
    return _kernel_add(x, y), None


def _bwd(_, g):
    # valid because _kernel_add's operands are guaranteed same-shape,
    # same-dtype (the add's transpose for equal shapes is (g, g))
    return g, g


_kernel_add.defvjp(_fwd, _bwd)


def residual_add(x, y):
    """``x + y`` through the tuned Pallas kernel when supported (TPU,
    same shape/dtype float, >=1M elements), else plain ``jnp.add``.

    Dispatch happens OUTSIDE the custom_vjp: the fallback's broadcasting
    / dtype promotion must use jnp.add's own autodiff (a blanket (g, g)
    backward would return cotangents of the wrong aval for broadcast or
    mixed-dtype operands)."""
    if not _supported(x, y):
        return x + y
    rows, cols = _flat2d(x.shape)
    if _block_rows(rows, cols, x.dtype.itemsize) <= 1:
        return x + y
    return _kernel_add(x, y)
