"""TPU compute ops: Pallas kernels + XLA reference paths.

This is the analogue of the reference's native-kernel tier (the BigDL-core
JNI surface, SURVEY.md §2.1: MKL BLAS/VML + MKL-DNN primitives). On TPU the
compiler provides fusion/layout, so only ops where XLA underperforms get
hand-written Pallas kernels (flash attention); everything else is plain
jax.numpy and relies on XLA fusion (SURVEY.md §7 design translation table).
"""

from bigdl_tpu.ops.attention import dot_product_attention, attention_bias_from_padding, causal_bias
from bigdl_tpu.ops.flash_attention import flash_attention

__all__ = [
    "dot_product_attention",
    "attention_bias_from_padding",
    "causal_bias",
    "flash_attention",
]
