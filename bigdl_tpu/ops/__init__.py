"""TPU compute ops: Pallas kernels + XLA reference paths.

This is the analogue of the reference's native-kernel tier (the BigDL-core
JNI surface, SURVEY.md §2.1: MKL BLAS/VML + MKL-DNN primitives). On TPU the
compiler provides fusion/layout, so only ops where XLA underperforms get
hand-written Pallas kernels (flash attention); everything else is plain
jax.numpy and relies on XLA fusion (SURVEY.md §7 design translation table).
"""

from bigdl_tpu.ops.attention import (
    dot_product_attention,
    attention_bias_from_padding,
    causal_bias,
    paged_attention,
)
from bigdl_tpu.ops.flash_attention import (
    flash_attention,
    gather_kv_lanes,
    paged_flash_attention,
)
from bigdl_tpu.ops.sampling import numpy_reference_sample, sample_tokens
from bigdl_tpu.ops import tf_ops
from bigdl_tpu.ops import control_flow
from bigdl_tpu.ops.tf_ops import *  # noqa: F401,F403 (tf_ops defines __all__)
from bigdl_tpu.ops.control_flow import (
    AssignTo,
    Cond,
    TensorArrayScan,
    Variable,
    While,
)
from bigdl_tpu.ops import tf_ops as _tf_ops

__all__ = [
    "dot_product_attention",
    "attention_bias_from_padding",
    "causal_bias",
    "flash_attention",
    "gather_kv_lanes",
    "numpy_reference_sample",
    "paged_attention",
    "paged_flash_attention",
    "sample_tokens",
    "tf_ops",
    "control_flow",
    "AssignTo",
    "Cond",
    "TensorArrayScan",
    "Variable",
    "While",
] + list(_tf_ops.__all__)
