"""Token sampling INSIDE the jitted decode step.

The generation engine's PR-5 decode step did greedy argmax on-device so
only an int32 token vector crossed to the host per iteration. Real
serving needs temperature / top-k / top-p — but hoisting logits to the
host for sampling would move a ``(max_slots, vocab)`` float tensor per
step and put numpy on the critical path. Instead the whole sampler runs
in-step: per-request parameters are batched as ``(max_slots,)`` arrays
(so they are TRACED values — changing them never recompiles), and each
slot carries its own raw threefry key, split once per step inside the
jit. A slot's stream is therefore a pure function of its request seed:
the same request produces the same tokens whatever slot it lands in,
whenever it is admitted, and under any scheduler — the sampled analogue
of greedy decode's schedule invariance, which the engine tests enforce.

Sampling is inverse-CDF over the sorted nucleus (not Gumbel-max): one
uniform draw per slot per step, so :func:`numpy_reference_sample` can
replay a step exactly from ``(logits, params, u)`` — the per-step parity
oracle the tests run against the jitted path.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def sample_tokens(logits, temperature, top_k, top_p,
                  key_data) -> Tuple[jax.Array, jax.Array]:
    """Sample one token per slot from ``logits`` — jit-friendly, all
    per-slot parameters dynamic.

    - ``logits``: ``(S, V)`` float.
    - ``temperature``: ``(S,)`` float32; ``<= 0`` means GREEDY for that
      slot (bitwise the PR-5 ``argmax`` path — the engine's default).
    - ``top_k``: ``(S,)`` int32; ``<= 0`` disables the top-k filter.
    - ``top_p``: ``(S,)`` float32; ``>= 1`` (or ``<= 0``) disables the
      nucleus filter. The kept set is the smallest prefix of the sorted
      distribution whose exclusive cumulative probability is ``< p``
      (the first token is always kept).
    - ``key_data``: ``(S, 2)`` uint32 raw threefry key words, one stream
      per slot (see ``core.rng.threefry_key_data``).

    Returns ``(tokens (S,) int32, new_key_data (S, 2) uint32)``. Exactly
    ONE split is consumed per slot per call — token ``i`` of a stream
    always draws from split ``i`` of its request key, which is what makes
    sampled output schedule-invariant. Greedy slots burn their split too
    (cheaper than a gather around it, and it keeps the key state's
    evolution independent of the mix of sampling params in the batch).
    """
    logits = logits.astype(jnp.float32)
    n, vocab = logits.shape
    temperature = temperature.astype(jnp.float32)

    # key evolution is UNCONDITIONAL (cheap, O(S)): both the sampled and
    # the all-greedy branch below advance every slot's stream by exactly
    # one split per call, so the mix of sampling params in the batch can
    # never desynchronise a request's stream
    pairs = jax.vmap(jax.random.split)(key_data)          # (S, 2, 2)
    new_keys = pairs[:, 0]
    u = jax.vmap(lambda k: jax.random.uniform(k, (), jnp.float32))(
        pairs[:, 1])                                      # (S,) in [0, 1)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(_):
        t_safe = jnp.where(temperature > 0, temperature, 1.0)[:, None]
        scaled = logits / t_safe
        order = jnp.argsort(-scaled, axis=-1)             # stable, desc
        sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)

        ranks = jnp.arange(vocab)[None, :]
        k_eff = jnp.where(top_k <= 0, vocab,
                          jnp.clip(top_k, 1, vocab))[:, None]
        p_eff = jnp.where((top_p <= 0.0) | (top_p >= 1.0), 1.0,
                          top_p.astype(jnp.float32))[:, None]
        keep = (ranks < k_eff) & (((csum - probs) < p_eff) | (ranks == 0))

        w = jnp.where(keep, probs, 0.0)
        wsum = jnp.cumsum(w, axis=-1)
        total = wsum[:, -1:]
        # smallest rank whose inclusive kept-mass exceeds u * total. Both
        # top-k and top-p keep a PREFIX of the sorted ranks, so clamping
        # to the kept count keeps the pick inside the nucleus even when
        # the f32 product u * total rounds up to the full mass (u near
        # 1): without it, that ~2^-24 edge would return the
        # least-probable token in the whole vocabulary, ignoring the
        # filters.
        idx = jnp.sum(wsum <= u[:, None] * total, axis=-1)
        idx = jnp.clip(idx, 0, jnp.sum(keep, axis=-1) - 1)
        sampled = jnp.take_along_axis(order, idx[:, None], axis=-1)[:, 0]
        return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)

    # the engine's default is all-greedy; the temperatures are traced
    # values, so without the cond XLA would run the O(S * V log V)
    # sort/softmax/cumsum machinery every step just to discard it at the
    # where() — lax.cond skips it whenever no slot is actually sampling
    toks = jax.lax.cond(jnp.any(temperature > 0.0), _sampled,
                        lambda _: greedy, None)
    return toks, new_keys


def split_key_data(key_data: np.ndarray):
    """Host-side replay of the per-step key evolution: returns
    ``(new_key_data, u)`` exactly as one :func:`sample_tokens` call
    advances a single slot's ``(2,)`` key and draws its uniform."""
    pair = jax.random.split(jnp.asarray(key_data, jnp.uint32))
    u = float(jax.random.uniform(pair[1], (), jnp.float32))
    return np.asarray(pair[0]), u


def numpy_reference_sample(logits, temperature, top_k, top_p, u) -> int:
    """Pure-numpy single-slot oracle for one :func:`sample_tokens` step,
    given the SAME uniform draw ``u`` (replay it with
    :func:`split_key_data`). The tests assert the jitted sampler picks
    the identical token id per step at fixed seed."""
    logits = np.asarray(logits, np.float32)
    vocab = logits.shape[-1]
    if temperature <= 0:
        return int(np.argmax(logits))
    scaled = (logits / np.float32(temperature)).astype(np.float32)
    order = np.argsort(-scaled, kind="stable")
    sorted_logits = scaled[order]
    e = np.exp((sorted_logits - sorted_logits.max()).astype(np.float32))
    probs = (e / e.sum()).astype(np.float32)
    csum = np.cumsum(probs, dtype=np.float32)
    ranks = np.arange(vocab)
    k_eff = vocab if top_k <= 0 else min(max(int(top_k), 1), vocab)
    p_eff = 1.0 if (top_p <= 0.0 or top_p >= 1.0) else np.float32(top_p)
    keep = (ranks < k_eff) & (((csum - probs) < p_eff) | (ranks == 0))
    w = np.where(keep, probs, np.float32(0.0))
    wsum = np.cumsum(w, dtype=np.float32)
    total = wsum[-1]
    idx = int(np.sum(wsum <= np.float32(u) * total))
    # keep is a prefix of the sorted ranks: clamp inside it (see the
    # jitted sampler for the u-near-1 rounding edge this guards)
    return int(order[min(idx, int(np.sum(keep)) - 1)])
