"""Token sampling INSIDE the jitted decode step.

The generation engine's PR-5 decode step did greedy argmax on-device so
only an int32 token vector crossed to the host per iteration. Real
serving needs temperature / top-k / top-p — but hoisting logits to the
host for sampling would move a ``(max_slots, vocab)`` float tensor per
step and put numpy on the critical path. Instead the whole sampler runs
in-step: per-request parameters are batched as ``(max_slots,)`` arrays
(so they are TRACED values — changing them never recompiles), and each
slot carries its own raw threefry key, split once per step inside the
jit. A slot's stream is therefore a pure function of its request seed:
the same request produces the same tokens whatever slot it lands in,
whenever it is admitted, and under any scheduler — the sampled analogue
of greedy decode's schedule invariance, which the engine tests enforce.

Sampling is inverse-CDF over the sorted nucleus (not Gumbel-max): one
uniform draw per slot per step, so :func:`numpy_reference_sample` can
replay a step exactly from ``(logits, params, u)`` — the per-step parity
oracle the tests run against the jitted path.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def sample_tokens(logits, temperature, top_k, top_p,
                  key_data, bias=None) -> Tuple[jax.Array, jax.Array]:
    """Sample one token per slot from ``logits`` — jit-friendly, all
    per-slot parameters dynamic.

    - ``logits``: ``(S, V)`` float.
    - ``temperature``: ``(S,)`` float32; ``<= 0`` means GREEDY for that
      slot (bitwise the PR-5 ``argmax`` path — the engine's default).
    - ``top_k``: ``(S,)`` int32; ``<= 0`` disables the top-k filter.
    - ``top_p``: ``(S,)`` float32; ``>= 1`` (or ``<= 0``) disables the
      nucleus filter. The kept set is the smallest prefix of the sorted
      distribution whose exclusive cumulative probability is ``< p``
      (the first token is always kept).
    - ``key_data``: ``(S, 2)`` uint32 raw threefry key words, one stream
      per slot (see ``core.rng.threefry_key_data``).
    - ``bias``: optional ``(S, V)`` float32 additive logit bias, applied
      BEFORE everything else (greedy argmax included) — the grammar
      mask's entry point (0 legal / -1e9 illegal rows from
      ``grammar.TokenAutomaton``; an all-zero row is a no-op). A traced
      value like the parameter arrays: changing it never recompiles.

    Returns ``(tokens (S,) int32, new_key_data (S, 2) uint32)``. Exactly
    ONE split is consumed per slot per call — token ``i`` of a stream
    always draws from split ``i`` of its request key, which is what makes
    sampled output schedule-invariant. Greedy slots burn their split too
    (cheaper than a gather around it, and it keeps the key state's
    evolution independent of the mix of sampling params in the batch).
    """
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    n, vocab = logits.shape
    temperature = temperature.astype(jnp.float32)

    # key evolution is UNCONDITIONAL (cheap, O(S)): both the sampled and
    # the all-greedy branch below advance every slot's stream by exactly
    # one split per call, so the mix of sampling params in the batch can
    # never desynchronise a request's stream
    pairs = jax.vmap(jax.random.split)(key_data)          # (S, 2, 2)
    new_keys = pairs[:, 0]
    u = jax.vmap(lambda k: jax.random.uniform(k, (), jnp.float32))(
        pairs[:, 1])                                      # (S,) in [0, 1)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(_):
        t_safe = jnp.where(temperature > 0, temperature, 1.0)[:, None]
        scaled = logits / t_safe
        order = jnp.argsort(-scaled, axis=-1)             # stable, desc
        sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)

        ranks = jnp.arange(vocab)[None, :]
        k_eff = jnp.where(top_k <= 0, vocab,
                          jnp.clip(top_k, 1, vocab))[:, None]
        p_eff = jnp.where((top_p <= 0.0) | (top_p >= 1.0), 1.0,
                          top_p.astype(jnp.float32))[:, None]
        keep = (ranks < k_eff) & (((csum - probs) < p_eff) | (ranks == 0))

        w = jnp.where(keep, probs, 0.0)
        wsum = jnp.cumsum(w, axis=-1)
        total = wsum[:, -1:]
        # smallest rank whose inclusive kept-mass exceeds u * total. Both
        # top-k and top-p keep a PREFIX of the sorted ranks, so clamping
        # to the kept count keeps the pick inside the nucleus even when
        # the f32 product u * total rounds up to the full mass (u near
        # 1): without it, that ~2^-24 edge would return the
        # least-probable token in the whole vocabulary, ignoring the
        # filters.
        idx = jnp.sum(wsum <= u[:, None] * total, axis=-1)
        idx = jnp.clip(idx, 0, jnp.sum(keep, axis=-1) - 1)
        sampled = jnp.take_along_axis(order, idx[:, None], axis=-1)[:, 0]
        return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)

    # the engine's default is all-greedy; the temperatures are traced
    # values, so without the cond XLA would run the O(S * V log V)
    # sort/softmax/cumsum machinery every step just to discard it at the
    # where() — lax.cond skips it whenever no slot is actually sampling
    toks = jax.lax.cond(jnp.any(temperature > 0.0), _sampled,
                        lambda _: greedy, None)
    return toks, new_keys


# ------------------------------------------------ speculative decoding ----
# Draft-verified generation (Leviathan et al. 2023): a cheap draft model
# proposes k tokens, ONE target forward scores all k+1 positions, and the
# rejection sampler below accepts the longest draft prefix the target
# agrees with, then emits one more token from the normalized residual
# (or, past a full acceptance, from the target's own distribution). Two
# properties are load-bearing here:
#
# - **greedy is lossless.** ``temperature <= 0`` rows are represented as
#   one-hot argmax DELTAS by :func:`filtered_probs`, so the accept test
#   ``u < p/q`` degenerates to exact-prefix match and every residual /
#   bonus pick lands on the target argmax — speculative greedy output is
#   token-identical to plain greedy decode, whatever the draft proposes.
# - **per-(request, output-position) keys.** Unlike the per-step split
#   chain of :func:`sample_tokens`, every uniform here is drawn from
#   ``fold_in(fold_in(request_key, stream), output_position)`` — a pure
#   function of the request and the position the token would occupy.
#   Acceptance-length variance therefore cannot desync a stream: however
#   many tokens a verify step emits, and however rounds align across
#   schedulers, the draw for output position t is always the same.
#
# Streams separate the three draw sites per position (a draft proposal,
# its accept test, and the residual/bonus pick never share a uniform).

DRAFT_STREAM = 1
ACCEPT_STREAM = 2
EXTRA_STREAM = 3


def position_uniform(key_data, stream: int, positions) -> jax.Array:
    """Per-(request, output-position) uniforms: ``key_data`` (S, 2)
    uint32 raw request keys, ``positions`` (S,) or (S, K) int32 output
    positions -> matching-shape float32 draws in [0, 1). Host replay:
    :func:`position_uniform_host`."""
    positions = jnp.asarray(positions, jnp.int32)

    def one(kd, pos):
        k = jax.random.fold_in(kd, stream)
        k = jax.random.fold_in(k, pos)
        return jax.random.uniform(k, (), jnp.float32)

    if positions.ndim == 1:
        return jax.vmap(one)(key_data, positions)
    return jax.vmap(jax.vmap(one, in_axes=(None, 0)))(key_data, positions)


def position_uniform_host(key_data, stream: int, position: int) -> float:
    """Host-side replay of one :func:`position_uniform` draw for a
    single ``(2,)`` request key — the oracle's source of uniforms."""
    k = jax.random.fold_in(jnp.asarray(key_data, jnp.uint32), int(stream))
    k = jax.random.fold_in(k, int(position))
    return float(jax.random.uniform(k, (), jnp.float32))


def filtered_probs(logits, temperature, top_k, top_p,
                   bias=None) -> jax.Array:
    """The sampling DISTRIBUTION each slot actually draws from, in vocab
    order: ``logits`` (S, V) -> (S, V) float32 probabilities, normalized
    over the kept set after temperature scaling and the same top-k /
    top-p prefix filters as :func:`sample_tokens`. ``temperature <= 0``
    rows return the one-hot argmax delta — greedy expressed as a
    distribution, which is what lets the speculative accept/residual
    formulas cover greedy rows with no special cases. ``bias`` is the
    same optional (S, V) additive mask :func:`sample_tokens` takes —
    softmax of a -1e9-masked logit underflows to exact f32 zero, so a
    grammar-illegal token has zero probability here, which is what lets
    ``speculative_sample`` stay unchanged under a grammar (an illegal
    draft proposal is rejected with certainty: p_target = 0)."""
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    n, vocab = logits.shape
    temperature = temperature.astype(jnp.float32)
    t_safe = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / t_safe
    probs = jax.nn.softmax(scaled, axis=-1)
    order = jnp.argsort(-scaled, axis=-1)                 # stable, desc
    sp = jnp.take_along_axis(probs, order, axis=-1)
    csum = jnp.cumsum(sp, axis=-1)
    ranks = jnp.arange(vocab)[None, :]
    k_eff = jnp.where(top_k <= 0, vocab,
                      jnp.clip(top_k, 1, vocab))[:, None]
    p_eff = jnp.where((top_p <= 0.0) | (top_p >= 1.0), 1.0,
                      top_p.astype(jnp.float32))[:, None]
    keep_sorted = (ranks < k_eff) & (((csum - sp) < p_eff) | (ranks == 0))
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(n)[:, None], order].set(keep_sorted)
    w = jnp.where(keep, probs, 0.0)
    w = w / jnp.sum(w, axis=-1, keepdims=True)
    greedy = jax.nn.one_hot(jnp.argmax(logits, axis=-1), vocab,
                            dtype=jnp.float32)
    return jnp.where((temperature > 0)[:, None], w, greedy)


def pick_token(weights, u) -> jax.Array:
    """Inverse-CDF pick over an UNNORMALIZED weight vector per slot:
    ``weights`` (S, V) >= 0, ``u`` (S,) in [0, 1) -> (S,) int32 token
    ids. The pick is the smallest index whose inclusive cumulative
    weight exceeds ``u * total``, clamped to the last positive-weight
    index (the u-near-1 f32 rounding guard — same edge the PR-6 sampler
    clamps); an all-zero row falls back to its argmax."""
    vocab = weights.shape[-1]
    csum = jnp.cumsum(weights, axis=-1)
    total = csum[:, -1]
    pick = jnp.sum(csum <= u[:, None] * total[:, None], axis=-1)
    last_pos = vocab - 1 - jnp.argmax((weights > 0)[:, ::-1], axis=-1)
    pick = jnp.minimum(pick, last_pos)
    return jnp.where(total > 0, pick,
                     jnp.argmax(weights, axis=-1)).astype(jnp.int32)


def draft_sample(logits, temperature, top_k, top_p, key_data,
                 out_pos, bias=None) -> Tuple[jax.Array, jax.Array]:
    """One draft proposal per slot: sample from the draft model's
    filtered distribution using the DRAFT_STREAM draw for each slot's
    output position. Returns ``(tokens (S,) int32, dists (S, V)
    float32)`` — the full distribution rides along because the verify
    step needs it for the accept ratio and the residual. Greedy rows
    (``temperature <= 0``) return the argmax and its one-hot delta.
    ``bias`` masks the draft under a grammar so proposals stay legal."""
    dists = filtered_probs(logits, temperature, top_k, top_p, bias)
    u = position_uniform(key_data, DRAFT_STREAM, out_pos)
    return pick_token(dists, u), dists


def speculative_sample(target_logits, draft_tokens, draft_dists,
                       temperature, top_k, top_p, key_data,
                       out_base) -> Tuple[jax.Array, jax.Array]:
    """The rejection sampler of speculative decoding, batched per slot.

    - ``target_logits``: (S, k+1, V) — the verify step's logits at the
      last accepted token and each of the k draft candidates.
    - ``draft_tokens``: (S, k) int32 draft proposals.
    - ``draft_dists``: (S, k, V) float32 — the draft's filtered sampling
      distribution at each proposal (from :func:`draft_sample`).
    - ``temperature`` / ``top_k`` / ``top_p``: (S,) per-slot params,
      applied identically to every target row.
    - ``key_data``: (S, 2) uint32 raw request keys; ``out_base``: (S,)
      int32 — the output position draft token 0 would occupy.

    Returns ``(n_accepted (S,) int32, tokens (S, k+1) int32)``: token
    column ``i < n_accepted`` is the accepted draft token, column
    ``n_accepted`` is the extra token (residual resample on rejection,
    target-distribution bonus past a full acceptance), columns beyond it
    repeat the extra token and must be ignored by the caller.

    Accept test ``i``: ``u_i < p(d_i) / q(d_i)`` with ``u_i`` the
    ACCEPT_STREAM draw at output position ``out_base + i`` — so the
    emitted marginal equals the target's filtered distribution exactly
    (Leviathan et al. 2023), and greedy rows (delta distributions from
    :func:`filtered_probs`) reduce to exact-prefix match with every
    emitted token a target argmax."""
    target_logits = target_logits.astype(jnp.float32)
    s, k1, vocab = target_logits.shape
    k = k1 - 1
    greedy_rows = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)

    def _greedy(_):
        # all-greedy fast path: exact-prefix match against the target
        # argmax rows; every emitted token is a target argmax. The
        # sampled branch computes the identical result for greedy rows
        # (delta distributions) — this branch just skips the O(S*k*V
        # log V) filtering machinery when nothing in the batch samples.
        acc = (greedy_rows[:, :k] == draft_tokens).astype(jnp.int32)
        n = jnp.sum(jnp.cumprod(acc, axis=-1), axis=-1)
        extra = jnp.take_along_axis(greedy_rows, n[:, None],
                                    axis=1)[:, 0]
        return n.astype(jnp.int32), extra

    def _sampled(_):
        flat = target_logits.reshape(s * k1, vocab)
        rep = lambda a: jnp.repeat(a, k1, axis=0)
        p = filtered_probs(flat, rep(temperature), rep(top_k),
                           rep(top_p)).reshape(s, k1, vocab)
        p_d = jnp.take_along_axis(p[:, :k], draft_tokens[..., None],
                                  axis=-1)[..., 0]           # (S, k)
        q_d = jnp.take_along_axis(draft_dists, draft_tokens[..., None],
                                  axis=-1)[..., 0]           # (S, k)
        pos = out_base[:, None] + jnp.arange(k)[None, :]
        u = position_uniform(key_data, ACCEPT_STREAM, pos)   # (S, k)
        acc = (u * jnp.maximum(q_d, 1e-30) < p_d).astype(jnp.int32)
        n = jnp.sum(jnp.cumprod(acc, axis=-1), axis=-1)
        p_n = jnp.take_along_axis(p, n[:, None, None], axis=1)[:, 0]
        q_pad = jnp.concatenate(
            [draft_dists, jnp.zeros((s, 1, vocab), jnp.float32)], axis=1)
        q_n = jnp.take_along_axis(q_pad, n[:, None, None], axis=1)[:, 0]
        residual = jnp.maximum(p_n - q_n, 0.0)
        u_x = position_uniform(key_data, EXTRA_STREAM, out_base + n)
        extra = pick_token(residual, u_x)
        return n.astype(jnp.int32), extra

    n, extra = jax.lax.cond(jnp.any(temperature > 0.0), _sampled,
                            _greedy, None)
    cand = jnp.concatenate(
        [draft_tokens, jnp.zeros((s, 1), jnp.int32)], axis=1)
    tokens = jnp.where(jnp.arange(k1)[None, :] < n[:, None], cand,
                       extra[:, None]).astype(jnp.int32)
    return n, tokens


def numpy_reference_filtered(logits, temperature, top_k,
                             top_p, bias=None) -> np.ndarray:
    """Pure-numpy single-slot mirror of :func:`filtered_probs` (vocab
    order), same f32 op sequence."""
    logits = np.asarray(logits, np.float32)
    if bias is not None:
        logits = (logits + np.asarray(bias, np.float32)).astype(np.float32)
    vocab = logits.shape[-1]
    if temperature <= 0:
        out = np.zeros(vocab, np.float32)
        out[int(np.argmax(logits))] = 1.0
        return out
    scaled = (logits / np.float32(temperature)).astype(np.float32)
    m = scaled.max()
    e = np.exp((scaled - m).astype(np.float32))
    probs = (e / e.sum()).astype(np.float32)
    order = np.argsort(-scaled, kind="stable")
    sp = probs[order]
    csum = np.cumsum(sp, dtype=np.float32)
    ranks = np.arange(vocab)
    k_eff = vocab if top_k <= 0 else min(max(int(top_k), 1), vocab)
    p_eff = 1.0 if (top_p <= 0.0 or top_p >= 1.0) else np.float32(top_p)
    keep_sorted = (ranks < k_eff) & (((csum - sp) < p_eff) | (ranks == 0))
    keep = np.zeros(vocab, bool)
    keep[order] = keep_sorted
    w = np.where(keep, probs, np.float32(0.0)).astype(np.float32)
    return (w / w.sum()).astype(np.float32)


def numpy_reference_pick(weights, u) -> int:
    """Pure-numpy mirror of :func:`pick_token` for one slot."""
    weights = np.asarray(weights, np.float32)
    vocab = weights.shape[-1]
    csum = np.cumsum(weights, dtype=np.float32)
    total = csum[-1]
    if not total > 0:
        return int(np.argmax(weights))
    pick = int(np.sum(csum <= np.float32(u) * total))
    positive = np.flatnonzero(weights > 0)
    return int(min(pick, positive[-1] if positive.size else vocab - 1))


def numpy_reference_draft(logits, temperature, top_k, top_p, key_data,
                          out_pos, bias=None):
    """Single-slot oracle for :func:`draft_sample`: -> (token, dist)."""
    dist = numpy_reference_filtered(logits, temperature, top_k, top_p,
                                    bias)
    u = position_uniform_host(key_data, DRAFT_STREAM, out_pos)
    return numpy_reference_pick(dist, u), dist


def numpy_reference_speculative(target_logits, draft_tokens, draft_dists,
                                temperature, top_k, top_p, key_data,
                                out_base, bias=None):
    """Single-slot oracle for one :func:`speculative_sample` step:
    ``target_logits`` (k+1, V), ``draft_tokens`` (k,), ``draft_dists``
    (k, V); -> ``(n_accepted, emitted token list of length
    n_accepted + 1)``. Uniforms replay via
    :func:`position_uniform_host`, so the oracle is driven by exactly
    the draws the jitted sampler consumes. ``bias`` is the grammar mask
    per verify position ((k+1, V)) — added to the target logits before
    filtering, exactly where the verify kernel adds it (the sampler
    itself stays unchanged: masked tokens carry zero target mass)."""
    target_logits = np.asarray(target_logits, np.float32)
    if bias is not None:
        target_logits = (target_logits
                         + np.asarray(bias, np.float32)).astype(np.float32)
    k = len(draft_tokens)
    p = [numpy_reference_filtered(target_logits[i], temperature, top_k,
                                  top_p) for i in range(k + 1)]
    n = 0
    for i in range(k):
        d = int(draft_tokens[i])
        u = position_uniform_host(key_data, ACCEPT_STREAM,
                                  int(out_base) + i)
        q = np.float32(draft_dists[i][d])
        if np.float32(u) * max(q, np.float32(1e-30)) < p[i][d]:
            n += 1
        else:
            break
    q_n = (np.asarray(draft_dists[n], np.float32) if n < k
           else np.zeros_like(p[n]))
    residual = np.maximum(p[n] - q_n, np.float32(0.0)).astype(np.float32)
    u_x = position_uniform_host(key_data, EXTRA_STREAM, int(out_base) + n)
    extra = numpy_reference_pick(residual, u_x)
    return n, [int(t) for t in draft_tokens[:n]] + [extra]


def split_key_data(key_data: np.ndarray):
    """Host-side replay of the per-step key evolution: returns
    ``(new_key_data, u)`` exactly as one :func:`sample_tokens` call
    advances a single slot's ``(2,)`` key and draws its uniform."""
    pair = jax.random.split(jnp.asarray(key_data, jnp.uint32))
    u = float(jax.random.uniform(pair[1], (), jnp.float32))
    return np.asarray(pair[0]), u


def numpy_reference_sample(logits, temperature, top_k, top_p, u,
                           bias=None) -> int:
    """Pure-numpy single-slot oracle for one :func:`sample_tokens` step,
    given the SAME uniform draw ``u`` (replay it with
    :func:`split_key_data`). The tests assert the jitted sampler picks
    the identical token id per step at fixed seed. ``bias`` mirrors the
    sampler's grammar-mask row: added to the f32 logits before the
    greedy argmax and the filters — constrained greedy is the argmax
    over the LEGAL set."""
    logits = np.asarray(logits, np.float32)
    if bias is not None:
        logits = (logits + np.asarray(bias, np.float32)).astype(np.float32)
    vocab = logits.shape[-1]
    if temperature <= 0:
        return int(np.argmax(logits))
    scaled = (logits / np.float32(temperature)).astype(np.float32)
    order = np.argsort(-scaled, kind="stable")
    sorted_logits = scaled[order]
    e = np.exp((sorted_logits - sorted_logits.max()).astype(np.float32))
    probs = (e / e.sum()).astype(np.float32)
    csum = np.cumsum(probs, dtype=np.float32)
    ranks = np.arange(vocab)
    k_eff = vocab if top_k <= 0 else min(max(int(top_k), 1), vocab)
    p_eff = 1.0 if (top_p <= 0.0 or top_p >= 1.0) else np.float32(top_p)
    keep = (ranks < k_eff) & (((csum - probs) < p_eff) | (ranks == 0))
    w = np.where(keep, probs, np.float32(0.0))
    wsum = np.cumsum(w, dtype=np.float32)
    total = wsum[-1]
    idx = int(np.sum(wsum <= np.float32(u) * total))
    # keep is a prefix of the sorted ranks: clamp inside it (see the
    # jitted sampler for the u-near-1 rounding edge this guards)
    return int(order[min(idx, int(np.sum(keep)) - 1)])
