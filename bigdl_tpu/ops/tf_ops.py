"""TF-style operation set.

Reference: ``DL/nn/ops/`` (71 files — ``Operation`` forward-only base,
arithmetic/comparison/logical ops, ``BatchMatMul``, ``Gather``, ``OneHot``,
``TopK``, ``Select``, feature-column ops) and ``DL/nn/tf/``
(``StridedSlice``, ``Pad``/``Tile``/``Rank``/``Shape`` helpers).

TPU-native: every op is a thin, jit-safe ``jnp``/``lax`` wrapper exposed as
a :class:`Module` so graphs mix ops and layers freely (the reference runs
these inside its Graph when loading TF GraphDefs). Forward-only semantics
(the reference's ``Operation.updateGradInput`` throws) are natural here —
an op with no params simply contributes its VJP via jax.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Context, Module


class Operation(Module):
    """Forward-only module base (reference ``Operation.scala``)."""


def _binary(name, fn, doc):
    cls = type(name, (Operation,), {
        "forward": lambda self, ctx, x: fn(*x),
        "__doc__": doc,
    })
    return cls


# -- arithmetic (reference DL/nn/ops/MathOps.scala et al.) --
AddOp = _binary("AddOp", lambda a, b: a + b, "Reference ops/Add")
SubOp = _binary("SubOp", lambda a, b: a - b, "Reference ops/Sub")
MulOp = _binary("MulOp", lambda a, b: a * b, "Reference ops/Mul")
DivOp = _binary("DivOp", lambda a, b: a / b, "Reference ops/RealDiv")
FloorDivOp = _binary("FloorDivOp", lambda a, b: jnp.floor_divide(a, b), "Reference ops/FloorDiv")
ModOp = _binary("ModOp", lambda a, b: jnp.mod(a, b), "Reference ops/FloorMod")
PowOp = _binary("PowOp", lambda a, b: jnp.power(a, b), "Reference ops/Pow")
MaximumOp = _binary("MaximumOp", jnp.maximum, "Reference ops/Maximum")
MinimumOp = _binary("MinimumOp", jnp.minimum, "Reference ops/Minimum")
SquaredDifference = _binary(
    "SquaredDifference", lambda a, b: jnp.square(a - b), "Reference ops/SquaredDifference")
TruncateDiv = _binary(
    "TruncateDiv", lambda a, b: jnp.trunc(a / b).astype(a.dtype), "Reference ops/TruncateDiv")

# -- comparison (reference ops/Equal.scala, Greater.scala, ...) --
Equal = _binary("Equal", lambda a, b: a == b, "Reference ops/Equal")
NotEqual = _binary("NotEqual", lambda a, b: a != b, "Reference ops/NotEqual")
Greater = _binary("Greater", lambda a, b: a > b, "Reference ops/Greater")
GreaterEqual = _binary("GreaterEqual", lambda a, b: a >= b, "Reference ops/GreaterEqual")
Less = _binary("Less", lambda a, b: a < b, "Reference ops/Less")
LessEqual = _binary("LessEqual", lambda a, b: a <= b, "Reference ops/LessEqual")
ApproximateEqual = _binary(
    "ApproximateEqual", lambda a, b: jnp.abs(a - b) < 1e-5, "Reference ops/ApproximateEqual")

# -- logical (reference ops/LogicalAnd.scala, ...) --
LogicalAnd = _binary("LogicalAnd", jnp.logical_and, "Reference ops/LogicalAnd")
LogicalOr = _binary("LogicalOr", jnp.logical_or, "Reference ops/LogicalOr")


class LogicalNot(Operation):
    """Reference ops/LogicalNot."""

    def forward(self, ctx, x):
        return jnp.logical_not(x)


class Select(Operation):
    """Elementwise where(cond, a, b) (reference ``ops/Select.scala``)."""

    def forward(self, ctx, x):
        cond, a, b = x
        return jnp.where(cond, a, b)


class BatchMatMul(Operation):
    """Reference ``ops/BatchMatMul.scala`` (adj_x/adj_y transposes)."""

    def __init__(self, adj_x: bool = False, adj_y: bool = False):
        super().__init__()
        self.adj_x = adj_x
        self.adj_y = adj_y

    def forward(self, ctx, x):
        a, b = x
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


class Gather(Operation):
    """Reference ``ops/Gather.scala``: take rows of x by index tensor."""

    def __init__(self, axis: int = 0):
        super().__init__()
        self.axis = axis

    def forward(self, ctx, x):
        t, idx = x
        return jnp.take(t, idx.astype(jnp.int32), axis=self.axis)


class OneHot(Operation):
    """Reference ``ops/OneHot.scala``."""

    def __init__(self, depth: int, on_value: float = 1.0, off_value: float = 0.0,
                 axis: int = -1):
        super().__init__()
        self.depth = depth
        self.on_value = on_value
        self.off_value = off_value
        self.axis = axis

    def forward(self, ctx, x):
        oh = jax.nn.one_hot(x.astype(jnp.int32), self.depth, axis=self.axis)
        return oh * (self.on_value - self.off_value) + self.off_value


class TopK(Operation):
    """Reference ``ops/TopK.scala``: returns (values, indices)."""

    def __init__(self, k: int, sorted: bool = True):
        super().__init__()
        self.k = k

    def forward(self, ctx, x):
        values, indices = lax.top_k(x, self.k)
        return values, indices


class ArgMax(Operation):
    """Reference ``ops/ArgMax.scala``."""

    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, ctx, x):
        return jnp.argmax(x, axis=self.axis)


class Cast(Operation):
    """Reference ``ops/Cast.scala``."""

    def __init__(self, dtype):
        super().__init__()
        self.dtype = jnp.dtype(dtype)

    def forward(self, ctx, x):
        return x.astype(self.dtype)


class Rank(Operation):
    """Reference ``tf/Rank``: static rank as a scalar array."""

    def forward(self, ctx, x):
        return jnp.asarray(x.ndim, jnp.int32)


class ShapeOp(Operation):
    """Reference ``tf/Shape``: static shape as an int array."""

    def forward(self, ctx, x):
        return jnp.asarray(x.shape, jnp.int32)


class SizeOp(Operation):
    def forward(self, ctx, x):
        return jnp.asarray(x.size, jnp.int32)


class ExpandDims(Operation):
    """Reference ``ops/ExpandDims.scala``."""

    def __init__(self, axis: int):
        super().__init__()
        self.axis = axis

    def forward(self, ctx, x):
        return jnp.expand_dims(x, self.axis)


class Tile(Operation):
    """Reference ``ops/Tile.scala``."""

    def __init__(self, multiples: Sequence[int]):
        super().__init__()
        self.multiples = tuple(multiples)

    def forward(self, ctx, x):
        return jnp.tile(x, self.multiples)


class Pad(Operation):
    """Reference ``ops/Pad.scala`` (constant mode)."""

    def __init__(self, paddings: Sequence[Sequence[int]], value: float = 0.0):
        super().__init__()
        self.paddings = tuple(map(tuple, paddings))
        self.value = value

    def forward(self, ctx, x):
        return jnp.pad(x, self.paddings, constant_values=self.value)


class StridedSlice(Operation):
    """Reference ``tf/StridedSlice.scala``: begin/end/stride per dim
    (static — XLA requires static shapes)."""

    def __init__(self, begin: Sequence[int], end: Sequence[int],
                 strides: Optional[Sequence[int]] = None):
        super().__init__()
        self.begin = tuple(begin)
        self.end = tuple(end)
        self.strides = tuple(strides) if strides else (1,) * len(self.begin)

    def forward(self, ctx, x):
        slices = tuple(
            slice(b, e, s) for b, e, s in zip(self.begin, self.end, self.strides)
        )
        return x[slices]


class _Reduction(Operation):
    fn = None

    def __init__(self, axis=None, keep_dims: bool = False):
        super().__init__()
        self.axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        self.keep_dims = keep_dims

    def forward(self, ctx, x):
        return type(self).fn(x, axis=self.axis, keepdims=self.keep_dims)


class ReduceSum(_Reduction):
    fn = staticmethod(jnp.sum)


class ReduceMean(_Reduction):
    fn = staticmethod(jnp.mean)


class ReduceMax(_Reduction):
    fn = staticmethod(jnp.max)


class ReduceMin(_Reduction):
    fn = staticmethod(jnp.min)


class ReduceProd(_Reduction):
    fn = staticmethod(jnp.prod)


class ReduceAll(_Reduction):
    fn = staticmethod(jnp.all)


class ReduceAny(_Reduction):
    fn = staticmethod(jnp.any)


# -- unary math (reference ops/Erf.scala, Lgamma.scala, ...) --
def _unary(name, fn, doc):
    return type(name, (Operation,), {
        "forward": lambda self, ctx, x: fn(x),
        "__doc__": doc,
    })


Floor = _unary("Floor", jnp.floor, "Reference ops/Floor")
Ceil = _unary("Ceil", jnp.ceil, "Reference ops/Ceil")
Round = _unary("Round", jnp.round, "Reference ops/Round")
Sign = _unary("Sign", jnp.sign, "Reference ops/Sign")
Rsqrt = _unary("Rsqrt", lax.rsqrt, "Reference ops/Rsqrt")
Inv = _unary("Inv", lambda x: 1.0 / x, "Reference ops/Inv")
Log1p = _unary("Log1p", jnp.log1p, "Reference ops/Log1p")
Expm1 = _unary("Expm1", jnp.expm1, "Reference ops/Expm1")
Erf = _unary("Erf", lax.erf, "Reference ops/Erf")
Erfc = _unary("Erfc", lax.erfc, "Reference ops/Erfc")
Lgamma = _unary("Lgamma", lax.lgamma, "Reference ops/Lgamma")
Digamma = _unary("Digamma", lax.digamma, "Reference ops/Digamma")
IsFinite = _unary("IsFinite", jnp.isfinite, "Reference ops/IsFinite")
IsInf = _unary("IsInf", jnp.isinf, "Reference ops/IsInf")
IsNan = _unary("IsNan", jnp.isnan, "Reference ops/IsNan")


class InTopK(Operation):
    """Reference ``ops/InTopK.scala``: is the target among the top-k
    predictions per row."""

    def __init__(self, k: int):
        super().__init__()
        self.k = k

    def forward(self, ctx, x):
        predictions, targets = x
        _, idx = lax.top_k(predictions, self.k)
        return jnp.any(idx == targets[..., None].astype(idx.dtype), axis=-1)


# ------------------------------------------------- feature-column ops


class BucketizedCol(Operation):
    """Bucketize by boundaries (reference ``ops/BucketizedCol.scala``)."""

    def __init__(self, boundaries: Sequence[float]):
        super().__init__()
        self.boundaries = jnp.asarray(sorted(boundaries), jnp.float32)

    def forward(self, ctx, x):
        return jnp.searchsorted(self.boundaries, x.astype(jnp.float32), side="right")


class CategoricalColHashBucket(Operation):
    """Hash integer ids into buckets (reference
    ``ops/CategoricalColHashBucket.scala``; strings must be pre-hashed to
    ints host-side — XLA has no string type)."""

    def __init__(self, hash_bucket_size: int):
        super().__init__()
        self.hash_bucket_size = hash_bucket_size

    def forward(self, ctx, x):
        h = x.astype(jnp.uint32) * jnp.uint32(2654435761)  # Knuth hash
        return (h % jnp.uint32(self.hash_bucket_size)).astype(jnp.int32)


class IndicatorCol(Operation):
    """Multi-hot indicator of categorical ids (reference
    ``ops/IndicatorCol.scala``)."""

    def __init__(self, fea_len: int):
        super().__init__()
        self.fea_len = fea_len

    def forward(self, ctx, x):
        oh = jax.nn.one_hot(x.astype(jnp.int32), self.fea_len)
        return jnp.max(oh, axis=-2) if x.ndim > 1 else oh


class CrossCol(Operation):
    """Hash-cross of multiple categorical columns (reference
    ``ops/CrossCol.scala``)."""

    def __init__(self, hash_bucket_size: int):
        super().__init__()
        self.hash_bucket_size = hash_bucket_size

    def forward(self, ctx, x):
        acc = jnp.zeros_like(x[0], dtype=jnp.uint32)
        for col in x:
            acc = acc * jnp.uint32(1000003) + col.astype(jnp.uint32)
        return (acc % jnp.uint32(self.hash_bucket_size)).astype(jnp.int32)


__all__ = [n for n, v in list(globals().items())
           if isinstance(v, type) and issubclass(v, Operation)] + ["Operation"]
