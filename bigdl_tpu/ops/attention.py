"""Scaled-dot-product attention dispatch + attention-bias helpers.

Reference semantics: ``DL/nn/Attention.scala`` computes
softmax(QK^T / sqrt(d) + bias) V with an additive bias carrying both the
padding mask (``TransformerOperation.getPaddingBias``) and, for decoders,
the causal mask (``TransformerOperation.attentionBiasLowerTriangle``).
Here the same contract is a single functional op that routes to the Pallas
flash kernel on TPU (fused, no S×S materialisation) and to a plain XLA
einsum path elsewhere.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.ops import flash_attention as _fa

_NEG = -1e9


def attention_bias_from_padding(padding_mask: jax.Array) -> jax.Array:
    """(B, S) 1-where-padding -> additive bias (B, 1, 1, S).

    Reference: ``TransformerOperation.getPaddingBias`` (pad positions get
    a large negative logit)."""
    return (padding_mask.astype(jnp.float32) * _NEG)[:, None, None, :]


def causal_bias(length: int) -> jax.Array:
    """(1, 1, S, S) additive lower-triangle bias.

    Reference: ``TransformerOperation.attentionBiasLowerTriangle``."""
    mask = jnp.tril(jnp.ones((length, length), jnp.float32))
    return ((1.0 - mask) * _NEG)[None, None, :, :]


def _flash_ok(q, k) -> bool:
    if q.shape[-1] > 256:
        return False
    sq, sk = q.shape[-2], k.shape[-2]
    bq = min(128, sq)
    bk = min(128, sk)
    return sq % bq == 0 and sk % bk == 0


def paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_map: jax.Array,
    positions: jax.Array,
    *,
    sm_scale: Optional[float] = None,
    use_kernel: Optional[bool] = None,
    k_scales: Optional[jax.Array] = None,
    v_scales: Optional[jax.Array] = None,
) -> jax.Array:
    """Decode-step attention over a paged (block-table) KV cache.

    ``q``: (S, H, D) one query per slot; ``k_pages``/``v_pages``:
    (num_pages, H, page_size, D) shared pools; ``page_map``: (S, ppn)
    int32; ``positions``: (S,) — key column ``j`` valid iff
    ``j <= positions[s]``. ``use_kernel=None`` auto-selects the Pallas
    scalar-prefetch kernel on TPU and the pure-jnp gather reference
    elsewhere; the reference path is bit-identical to dense slot-table
    attention on the same backend (test-enforced), which is what lets
    the serving tier swap lanes for pages without changing one token.
    Int8 pools pass their per-token fp32 scale pools
    (``k_scales``/``v_scales``, shape (num_pages, page_size)): both
    paths dequantize on gather.
    """
    platform = jax.devices()[0].platform
    if use_kernel is None:
        use_kernel = platform == "tpu" and q.shape[-1] <= 256
    if use_kernel:
        return _fa.paged_flash_attention(
            q, k_pages, v_pages, page_map, positions, sm_scale,
            interpret=(platform != "tpu"),
            k_scales=k_scales, v_scales=v_scales,
        )
    return _fa.paged_attention_reference(
        q, k_pages, v_pages, page_map, positions, sm_scale,
        k_scales=k_scales, v_scales=v_scales)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    use_flash: Optional[bool] = None,
) -> jax.Array:
    """Attention over (B, H, S, D) tensors.

    ``use_flash=None`` auto-selects: Pallas kernel on TPU when shapes allow
    and there is no attention dropout (dropout inside the probability matrix
    defeats the fused formulation; the reference's attentionDropout is only
    active in training, where the XLA path is used instead).
    """
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    platform = jax.devices()[0].platform
    if use_flash is None:
        use_flash = platform == "tpu" and dropout_rate == 0.0 and _flash_ok(q, k)

    if use_flash and dropout_rate == 0.0:
        return _fa.flash_attention(
            q, k, v, bias, scale, causal,
            interpret=(platform != "tpu"),
        )

    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("attention dropout needs dropout_rng")
    return _fa._xla_attention(
        q, k, v, bias, scale, causal,
        dropout_rate=dropout_rate, dropout_rng=dropout_rng,
    )
