"""Mask R-CNN (inference).

Reference: ``DL/models/maskrcnn/MaskRCNN.scala`` (768 LoC — ResNet-FPN
backbone, RegionProposal, BoxHead, MaskHead over ImageFrame input).

TPU-native design: the whole forward is ONE jittable program with static
shapes — proposals/detections are fixed-size (post-NMS top-k + validity
masks) instead of the reference's variable-length arrays, and the
multi-level RoI pooling uses the one-hot ``Pooler`` blend. Single-image
inference (B=1), matching the reference's per-partition predict path.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.core.rng import np_rng
import bigdl_tpu.nn as nn
from bigdl_tpu.models import resnet
from bigdl_tpu.nn.layers.detection import (
    Anchor, BoxHead, FPN, MaskHead, Pooler, RegionProposal, bbox_clip,
    bbox_decode, nms,
)
from bigdl_tpu.nn.module import Context, Module


class ResNetFPNBackbone(Module):
    """ResNet stages C2-C5 + FPN (reference MaskRCNN backbone)."""

    def __init__(self, depth: int = 50, out_channels: int = 256):
        super().__init__()
        kind, counts = resnet.IMAGENET_CFG[depth]
        block = resnet.basic_block if kind == "basic" else resnet.bottleneck
        expansion = 1 if kind == "basic" else 4
        self.stem = nn.Sequential(
            resnet._conv(3, 64, 7, 2, 3),
            resnet._bn(64),
            nn.ReLU(),
            nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1),
        )
        cin = 64
        self.stage_channels = []
        for stage, (planes, n_blocks) in enumerate(zip([64, 128, 256, 512], counts)):
            s = nn.Sequential()
            for i in range(n_blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                s.add(block(cin, planes, stride))
                cin = planes * expansion
            self.add(s, f"layer{stage + 1}")
            self.stage_channels.append(cin)
        self.fpn = FPN(self.stage_channels, out_channels)
        self.out_channels = out_channels

    def forward(self, ctx: Context, x):
        h = self.run_child(ctx, "stem", x)
        feats = []
        for i in range(1, 5):
            h = self.run_child(ctx, f"layer{i}", h)
            feats.append(h)
        return self.run_child(ctx, "fpn", tuple(feats))


class MaskRCNN(Module):
    """Full detector (reference ``MaskRCNN.scala``). ``forward(image)`` with
    image (1, 3, H, W) returns a dict: boxes (K, 4), scores (K,),
    labels (K,), masks (K, 28, 28) logits per detection (class-selected),
    valid (K,) — fixed K = ``detections_per_img``."""

    def __init__(self, num_classes: int = 81, depth: int = 50,
                 out_channels: int = 256,
                 post_nms_topn: int = 100, detections_per_img: int = 20,
                 box_score_thresh: float = 0.05, box_nms_thresh: float = 0.5,
                 resolution: int = 7, mask_resolution: int = 14):
        super().__init__()
        self.backbone = ResNetFPNBackbone(depth, out_channels)
        self.rpn = RegionProposal(
            out_channels, Anchor(scales=(8.0,)), post_nms_topn=post_nms_topn)
        self.pooler = Pooler(resolution, scales=(1 / 4, 1 / 8, 1 / 16, 1 / 32))
        self.box_head = BoxHead(out_channels, resolution, num_classes)
        self.mask_pooler = Pooler(mask_resolution,
                                  scales=(1 / 4, 1 / 8, 1 / 16, 1 / 32))
        self.mask_head = MaskHead(out_channels, num_classes)
        self.num_classes = num_classes
        self.detections_per_img = detections_per_img
        self.box_score_thresh = box_score_thresh
        self.box_nms_thresh = box_nms_thresh

    def forward(self, ctx: Context, x):
        img_h, img_w = x.shape[2], x.shape[3]
        feats = self.run_child(ctx, "backbone", x)
        # RPN on the stride-16 level (P4), the reference runs per-level and
        # merges; single-level keeps the program small (documented deviation)
        rois, roi_scores, roi_valid = self.rpn.forward(
            ctx.child("rpn"), feats[2], im_size=(img_h, img_w), stride=16.0)

        pooled = self.pooler.forward(ctx.child("pooler"), (feats, rois))
        cls_logits, box_deltas = self.box_head.forward(ctx.child("box_head"), pooled)
        probs = jax.nn.softmax(cls_logits, axis=-1)

        # best non-background class per roi
        fg = probs[:, 1:]
        best_c = jnp.argmax(fg, axis=-1) + 1
        best_p = jnp.max(fg, axis=-1) * roi_valid
        deltas = jnp.take_along_axis(
            box_deltas.reshape(-1, self.num_classes, 4),
            best_c[:, None, None].repeat(4, -1), axis=1)[:, 0]
        boxes = bbox_clip(bbox_decode(rois, deltas, weights=(10., 10., 5., 5.)),
                          img_h, img_w)
        keep, valid = nms(boxes, jnp.where(best_p > self.box_score_thresh,
                                           best_p, -jnp.inf),
                          self.box_nms_thresh, self.detections_per_img)
        det_boxes = jnp.where(valid[:, None], boxes[keep], 0.0)
        det_scores = jnp.where(valid, best_p[keep], 0.0)
        det_labels = jnp.where(valid, best_c[keep], 0)

        mask_feats = self.mask_pooler.forward(ctx.child("mask_pooler"),
                                              (feats, det_boxes))
        mask_logits = self.mask_head.forward(ctx.child("mask_head"), mask_feats)
        det_masks = jnp.take_along_axis(
            mask_logits,
            det_labels[:, None, None, None].repeat(
                mask_logits.shape[2], 2).repeat(mask_logits.shape[3], 3),
            axis=1)[:, 0]
        return {
            "boxes": det_boxes,
            "scores": det_scores,
            "labels": det_labels,
            "masks": det_masks,
            "valid": valid,
        }


def build(num_classes: int = 81, depth: int = 50, **kw) -> MaskRCNN:
    return MaskRCNN(num_classes=num_classes, depth=depth, **kw)


def paste_masks(masks: "np.ndarray", boxes: "np.ndarray", valid: "np.ndarray",
                im_h: int, im_w: int, threshold: float = 0.5):
    """Paste per-detection mask logits into full-image binary masks
    (reference ``MaskRCNN.scala`` postprocessing / ``MaskUtils``): sigmoid
    the (K, M, M) logits, bilinear-resize each to its box, threshold, and
    write into a (K, im_h, im_w) canvas. Host-side numpy."""
    import numpy as np

    from bigdl_tpu.vision.augmentation import resize_image

    masks = np.asarray(masks, np.float32)
    boxes = np.asarray(boxes, np.float32)
    probs = 1.0 / (1.0 + np.exp(-masks))
    out = np.zeros((masks.shape[0], im_h, im_w), bool)
    for k in range(masks.shape[0]):
        if not valid[k]:
            continue
        x1, y1, x2, y2 = boxes[k]
        x1i, y1i = int(np.floor(x1)), int(np.floor(y1))
        x2i, y2i = int(np.ceil(x2)), int(np.ceil(y2))
        # resize to the FULL (possibly out-of-image) box extent, then crop
        # the in-image window — clipping first would squash the mask
        bw, bh = x2i - x1i, y2i - y1i
        if bw <= 0 or bh <= 0:
            continue
        m = resize_image(probs[k][..., None], bh, bw)[..., 0] > threshold
        x0, y0 = max(x1i, 0), max(y1i, 0)
        x1c, y1c = min(x2i, im_w), min(y2i, im_h)
        if x1c <= x0 or y1c <= y0:
            continue
        out[k, y0:y1c, x0:x1c] = m[y0 - y1i:y1c - y1i, x0 - x1i:x1c - x1i]
    return out


class MaskRCNNPredictor:
    """Raw image in, detections out (reference: the full
    ``DL/models/maskrcnn`` path over ImageFrame — normalization, aspect
    resize, forward, box rescale, mask pasting).

    ``predict(image_hwc)`` takes one HWC RGB image (uint8 or float) and
    returns a dict with ``boxes`` (K, 4 in ORIGINAL pixel coords),
    ``scores`` (K,), ``labels`` (K,), ``valid`` (K,) and ``masks``
    (K, H, W) full-resolution booleans.
    """

    def __init__(self, model: MaskRCNN, params, state,
                 min_size: int = 800, max_size: int = 1333,
                 means=(122.7717, 115.9465, 102.9801), stds=(1.0, 1.0, 1.0),
                 pad_multiple: int = 32):
        import jax as _jax

        self.model = model
        self.params = params
        self.state = state or {}
        self.min_size = min_size
        self.max_size = max_size
        self.means = means
        self.stds = stds
        self.pad_multiple = pad_multiple
        self._fwd = _jax.jit(
            lambda p, s, x: model.apply(p, x, state=s, training=False)[0])

    def preprocess(self, image):
        """HWC image -> (padded NCHW batch-of-1, scale, (oh, ow))."""
        import numpy as np

        from bigdl_tpu.vision import (
            AspectScale, ChannelNormalize, ImageFeature, MatToTensor,
        )

        feat = ImageFeature(np.asarray(image, np.float32))
        oh, ow = feat.image.shape[:2]
        AspectScale(self.min_size, self.max_size)(feat)
        # per-axis ratios: AspectScale rounds h and w independently
        scale = (feat.image.shape[1] / ow, feat.image.shape[0] / oh)
        ChannelNormalize(self.means, self.stds)(feat)
        MatToTensor()(feat)
        chw = feat["tensor"]
        _, h, w = chw.shape
        ph = (h + self.pad_multiple - 1) // self.pad_multiple * self.pad_multiple
        pw = (w + self.pad_multiple - 1) // self.pad_multiple * self.pad_multiple
        padded = np.zeros((1, chw.shape[0], ph, pw), np.float32)
        padded[0, :, :h, :w] = chw
        return padded, scale, (oh, ow)

    def predict(self, image):
        import numpy as np

        batch, (sx, sy), (oh, ow) = self.preprocess(image)
        out = self._fwd(self.params, self.state, batch)
        boxes = np.array(out["boxes"], np.float32)  # writable host copy
        boxes[:, 0::2] /= sx
        boxes[:, 1::2] /= sy
        valid = np.asarray(out["valid"])
        # paste against the UNCLIPPED boxes (a detection may extend into
        # the pad margin); clip only the reported coordinates
        masks = paste_masks(np.asarray(out["masks"]), boxes, valid, oh, ow)
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, ow)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, oh)
        return {
            "boxes": boxes,
            "scores": np.asarray(out["scores"]),
            "labels": np.asarray(out["labels"]),
            "masks": masks,
            "valid": valid,
        }


def main(argv=None):
    """CLI (reference: the ``DL/models/maskrcnn`` Test path): ``predict``
    runs a raw image through the full pipeline and prints detections;
    ``evaluate`` computes detection AP@0.5 over an image set (synthetic
    boxes when no dataset folder is given)."""
    import argparse

    import numpy as np
    import jax

    from bigdl_tpu.optim.validation import detection_average_precision

    ap = argparse.ArgumentParser("maskrcnn")
    ap.add_argument("--mode", choices=["predict", "evaluate"],
                    default="predict")
    ap.add_argument("--image", default=None, help="image file (synthetic if absent)")
    ap.add_argument("--numClasses", type=int, default=81)
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--minSize", type=int, default=800)
    ap.add_argument("--maxSize", type=int, default=1333)
    ap.add_argument("--nImages", type=int, default=4)
    ap.add_argument("--cocoMap", action="store_true",
                    help="evaluate: report COCO-style box+mask mAP@[.5:.95] "
                         "(reference MeanAveragePrecisionObjectDetection, "
                         "ValidationMethod.scala:675) instead of AP@0.5")
    args = ap.parse_args(argv)

    model = build(args.numClasses, args.depth)
    params, state = model.init(jax.random.key(0))
    predictor = MaskRCNNPredictor(model, params, state,
                                  min_size=args.minSize,
                                  max_size=args.maxSize)

    def load_image():
        if args.image:
            from PIL import Image

            return np.asarray(Image.open(args.image).convert("RGB"))
        return (np_rng(0).random((240, 320, 3)) * 255).astype(np.uint8)

    if args.mode == "predict":
        out = predictor.predict(load_image())
        n = int(np.asarray(out["valid"]).sum())
        print(f"{n} detections")
        for k in range(len(out["valid"])):
            if out["valid"][k]:
                b = out["boxes"][k]
                print(f"  label={int(out['labels'][k])} "
                      f"score={float(out['scores'][k]):.3f} "
                      f"box=({b[0]:.0f},{b[1]:.0f},{b[2]:.0f},{b[3]:.0f}) "
                      f"mask_px={int(out['masks'][k].sum())}")
        return out

    # evaluate: (random-weight) detections vs synthetic truth
    rng = np_rng(1)
    dets, gts, cdets, cgts = [], [], [], []
    for _ in range(args.nImages):
        img = (rng.random((160, 200, 3)) * 255).astype(np.uint8)
        out = predictor.predict(img)
        keep = np.asarray(out["valid"]).astype(bool)
        dets.append((out["boxes"][keep], out["scores"][keep]))
        gt_boxes = np.asarray([[10, 10, 60, 60], [80, 40, 150, 120]],
                              np.float32)
        gts.append(gt_boxes)
        if args.cocoMap:
            h, w = img.shape[:2]

            def box_mask(b):
                m = np.zeros((h, w), bool)
                m[int(b[1]):int(b[3]), int(b[0]):int(b[2])] = True
                return m

            cdets.append({
                "boxes": out["boxes"][keep], "scores": out["scores"][keep],
                "labels": np.asarray(out["labels"])[keep],
                "masks": [np.asarray(m) > 0.5
                          for m, k in zip(out["masks"], keep) if k],
            })
            cgts.append({
                "boxes": gt_boxes, "labels": np.ones(len(gt_boxes), int),
                "masks": [box_mask(b) for b in gt_boxes],
            })
    if args.cocoMap:
        from bigdl_tpu.optim.validation import coco_detection_map

        box_map = coco_detection_map(cdets, cgts, args.numClasses)
        mask_map = coco_detection_map(cdets, cgts, args.numClasses,
                                      masks=True)
        print(f"box mAP@[.5:.95]: {box_map:.4f}  "
              f"mask mAP@[.5:.95]: {mask_map:.4f} over {args.nImages} images")
        return box_map, mask_map
    ap_val = detection_average_precision(dets, gts, iou_threshold=0.5)
    print(f"AP@0.5: {ap_val:.4f} over {args.nImages} images")
    return ap_val


if __name__ == "__main__":
    main()
