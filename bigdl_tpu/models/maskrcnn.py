"""Mask R-CNN (inference).

Reference: ``DL/models/maskrcnn/MaskRCNN.scala`` (768 LoC — ResNet-FPN
backbone, RegionProposal, BoxHead, MaskHead over ImageFrame input).

TPU-native design: the whole forward is ONE jittable program with static
shapes — proposals/detections are fixed-size (post-NMS top-k + validity
masks) instead of the reference's variable-length arrays, and the
multi-level RoI pooling uses the one-hot ``Pooler`` blend. Single-image
inference (B=1), matching the reference's per-partition predict path.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.models import resnet
from bigdl_tpu.nn.layers.detection import (
    Anchor, BoxHead, FPN, MaskHead, Pooler, RegionProposal, bbox_clip,
    bbox_decode, nms,
)
from bigdl_tpu.nn.module import Context, Module


class ResNetFPNBackbone(Module):
    """ResNet stages C2-C5 + FPN (reference MaskRCNN backbone)."""

    def __init__(self, depth: int = 50, out_channels: int = 256):
        super().__init__()
        kind, counts = resnet.IMAGENET_CFG[depth]
        block = resnet.basic_block if kind == "basic" else resnet.bottleneck
        expansion = 1 if kind == "basic" else 4
        self.stem = nn.Sequential(
            resnet._conv(3, 64, 7, 2, 3),
            resnet._bn(64),
            nn.ReLU(),
            nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1),
        )
        cin = 64
        self.stage_channels = []
        for stage, (planes, n_blocks) in enumerate(zip([64, 128, 256, 512], counts)):
            s = nn.Sequential()
            for i in range(n_blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                s.add(block(cin, planes, stride))
                cin = planes * expansion
            self.add(s, f"layer{stage + 1}")
            self.stage_channels.append(cin)
        self.fpn = FPN(self.stage_channels, out_channels)
        self.out_channels = out_channels

    def forward(self, ctx: Context, x):
        h = self.run_child(ctx, "stem", x)
        feats = []
        for i in range(1, 5):
            h = self.run_child(ctx, f"layer{i}", h)
            feats.append(h)
        return self.run_child(ctx, "fpn", tuple(feats))


class MaskRCNN(Module):
    """Full detector (reference ``MaskRCNN.scala``). ``forward(image)`` with
    image (1, 3, H, W) returns a dict: boxes (K, 4), scores (K,),
    labels (K,), masks (K, 28, 28) logits per detection (class-selected),
    valid (K,) — fixed K = ``detections_per_img``."""

    def __init__(self, num_classes: int = 81, depth: int = 50,
                 out_channels: int = 256,
                 post_nms_topn: int = 100, detections_per_img: int = 20,
                 box_score_thresh: float = 0.05, box_nms_thresh: float = 0.5,
                 resolution: int = 7, mask_resolution: int = 14):
        super().__init__()
        self.backbone = ResNetFPNBackbone(depth, out_channels)
        self.rpn = RegionProposal(
            out_channels, Anchor(scales=(8.0,)), post_nms_topn=post_nms_topn)
        self.pooler = Pooler(resolution, scales=(1 / 4, 1 / 8, 1 / 16, 1 / 32))
        self.box_head = BoxHead(out_channels, resolution, num_classes)
        self.mask_pooler = Pooler(mask_resolution,
                                  scales=(1 / 4, 1 / 8, 1 / 16, 1 / 32))
        self.mask_head = MaskHead(out_channels, num_classes)
        self.num_classes = num_classes
        self.detections_per_img = detections_per_img
        self.box_score_thresh = box_score_thresh
        self.box_nms_thresh = box_nms_thresh

    def forward(self, ctx: Context, x):
        img_h, img_w = x.shape[2], x.shape[3]
        feats = self.run_child(ctx, "backbone", x)
        # RPN on the stride-16 level (P4), the reference runs per-level and
        # merges; single-level keeps the program small (documented deviation)
        rois, roi_scores, roi_valid = self.rpn.forward(
            ctx.child("rpn"), feats[2], im_size=(img_h, img_w), stride=16.0)

        pooled = self.pooler.forward(ctx.child("pooler"), (feats, rois))
        cls_logits, box_deltas = self.box_head.forward(ctx.child("box_head"), pooled)
        probs = jax.nn.softmax(cls_logits, axis=-1)

        # best non-background class per roi
        fg = probs[:, 1:]
        best_c = jnp.argmax(fg, axis=-1) + 1
        best_p = jnp.max(fg, axis=-1) * roi_valid
        deltas = jnp.take_along_axis(
            box_deltas.reshape(-1, self.num_classes, 4),
            best_c[:, None, None].repeat(4, -1), axis=1)[:, 0]
        boxes = bbox_clip(bbox_decode(rois, deltas, weights=(10., 10., 5., 5.)),
                          img_h, img_w)
        keep, valid = nms(boxes, jnp.where(best_p > self.box_score_thresh,
                                           best_p, -jnp.inf),
                          self.box_nms_thresh, self.detections_per_img)
        det_boxes = jnp.where(valid[:, None], boxes[keep], 0.0)
        det_scores = jnp.where(valid, best_p[keep], 0.0)
        det_labels = jnp.where(valid, best_c[keep], 0)

        mask_feats = self.mask_pooler.forward(ctx.child("mask_pooler"),
                                              (feats, det_boxes))
        mask_logits = self.mask_head.forward(ctx.child("mask_head"), mask_feats)
        det_masks = jnp.take_along_axis(
            mask_logits,
            det_labels[:, None, None, None].repeat(
                mask_logits.shape[2], 2).repeat(mask_logits.shape[3], 3),
            axis=1)[:, 0]
        return {
            "boxes": det_boxes,
            "scores": det_scores,
            "labels": det_labels,
            "masks": det_masks,
            "valid": valid,
        }


def build(num_classes: int = 81, depth: int = 50, **kw) -> MaskRCNN:
    return MaskRCNN(num_classes=num_classes, depth=depth, **kw)
