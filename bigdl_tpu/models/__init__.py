from bigdl_tpu.models import lenet
