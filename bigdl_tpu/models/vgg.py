"""VGG models.

Reference: ``DL/models/vgg/VggForCifar10.scala`` (conv-BN-ReLU stacks with
dropout head) and ``DL/models/vgg/Vgg_16.scala`` / ``Vgg_19``
(plain ImageNet VGG with fc6/fc7/fc8 head, used by the Caffe-loaded
inference benchmark config).
"""

from __future__ import annotations

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.init import MsraFiller

VGG16_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
             512, 512, 512, "M", 512, 512, 512, "M"]
VGG19_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
             512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]


def build_cifar(class_num: int = 10, has_dropout: bool = True) -> nn.Sequential:
    """VGG-16-shaped CIFAR model with BN (reference ``VggForCifar10.apply``)."""
    model = nn.Sequential()
    cin = 3
    for v in VGG16_CFG:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            model.add(nn.SpatialConvolution(cin, v, 3, 3, 1, 1, 1, 1,
                                            weight_init=MsraFiller()))
            model.add(nn.SpatialBatchNormalization(v))
            model.add(nn.ReLU())
            cin = v
    model.add(nn.Reshape([512]))
    model.add(nn.Linear(512, 512))
    model.add(nn.BatchNormalization(512))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(512, class_num))
    model.add(nn.LogSoftMax())
    return model


def _vgg_imagenet(cfg, class_num: int, has_dropout: bool) -> nn.Sequential:
    model = nn.Sequential()
    cin = 3
    for v in cfg:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            model.add(nn.SpatialConvolution(cin, v, 3, 3, 1, 1, 1, 1))
            model.add(nn.ReLU())
            cin = v
    model.add(nn.Reshape([512 * 7 * 7]))
    model.add(nn.Linear(512 * 7 * 7, 4096).set_name("fc6"))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, 4096).set_name("fc7"))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, class_num).set_name("fc8"))
    return model


def build_vgg16(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """ImageNet VGG-16 (reference ``Vgg_16.scala``)."""
    return _vgg_imagenet(VGG16_CFG, class_num, has_dropout)


def build_vgg19(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """ImageNet VGG-19 (reference ``Vgg_19.scala``)."""
    return _vgg_imagenet(VGG19_CFG, class_num, has_dropout)
