"""VGG models.

Reference: ``DL/models/vgg/VggForCifar10.scala`` (conv-BN-ReLU stacks with
dropout head) and ``DL/models/vgg/Vgg_16.scala`` / ``Vgg_19``
(plain ImageNet VGG with fc6/fc7/fc8 head, used by the Caffe-loaded
inference benchmark config).
"""

from __future__ import annotations

from bigdl_tpu.core.rng import np_rng
import bigdl_tpu.nn as nn
from bigdl_tpu.nn.init import MsraFiller

VGG16_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
             512, 512, 512, "M", 512, 512, 512, "M"]
VGG19_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
             512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]


def build_cifar(class_num: int = 10, has_dropout: bool = True) -> nn.Sequential:
    """VGG-16-shaped CIFAR model with BN (reference ``VggForCifar10.apply``)."""
    model = nn.Sequential()
    cin = 3
    for v in VGG16_CFG:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            model.add(nn.SpatialConvolution(cin, v, 3, 3, 1, 1, 1, 1,
                                            weight_init=MsraFiller()))
            model.add(nn.SpatialBatchNormalization(v))
            model.add(nn.ReLU())
            cin = v
    model.add(nn.Reshape([512]))
    model.add(nn.Linear(512, 512))
    model.add(nn.BatchNormalization(512))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(512, class_num))
    model.add(nn.LogSoftMax())
    return model


def _vgg_imagenet(cfg, class_num: int, has_dropout: bool) -> nn.Sequential:
    model = nn.Sequential()
    cin = 3
    for v in cfg:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            model.add(nn.SpatialConvolution(cin, v, 3, 3, 1, 1, 1, 1))
            model.add(nn.ReLU())
            cin = v
    model.add(nn.Reshape([512 * 7 * 7]))
    model.add(nn.Linear(512 * 7 * 7, 4096).set_name("fc6"))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, 4096).set_name("fc7"))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, class_num).set_name("fc8"))
    return model


def build_vgg16(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """ImageNet VGG-16 (reference ``Vgg_16.scala``)."""
    return _vgg_imagenet(VGG16_CFG, class_num, has_dropout)


def build_vgg19(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """ImageNet VGG-19 (reference ``Vgg_19.scala``)."""
    return _vgg_imagenet(VGG19_CFG, class_num, has_dropout)


def main(argv=None):
    """Train/inference CLI (reference: ``vgg/Train.scala`` CIFAR recipe;
    ``example/loadmodel`` for the Caffe-loaded VGG-16 inference config —
    the BASELINE \'VGG-16 Caffe-loaded inference\' benchmark path)."""
    import logging
    import time

    import numpy as np

    from bigdl_tpu.models.cli import fit, make_parser

    parser = make_parser("vgg", batch_size=112, max_epoch=5,
                         learning_rate=0.01,
                         folder_help="cifar-10 dir (synthetic data if absent)")
    parser.add_argument("--from-caffe", nargs=2, metavar=("PROTOTXT", "CAFFEMODEL"),
                        help="run Caffe-loaded VGG inference instead of training")
    parser.add_argument("--iters", type=int, default=10,
                        help="inference iterations for --from-caffe")
    args = parser.parse_args(argv)

    if args.from_caffe:
        from bigdl_tpu.interop.caffe import load_caffe
        from bigdl_tpu.optim.predictor import Predictor

        logging.basicConfig(level=logging.INFO)
        graph, params, state = load_caffe(*args.from_caffe)
        shape = getattr(graph, "caffe_input_shapes", {}) or {}
        in_shape = next(iter(shape.values()), (1, 3, 224, 224))
        x = np_rng(0).random((args.batchSize, *in_shape[1:])).astype("float32")
        pred = Predictor(graph, params, state, batch_size=args.batchSize)
        outs = pred.predict(x, flatten=False)  # warmup/compile
        t0 = time.perf_counter()
        for _ in range(args.iters):
            outs = pred.predict(x, flatten=False)
        dt = (time.perf_counter() - t0) / args.iters
        top1 = np.argmax(np.asarray(outs[0]), -1)
        logging.info("caffe-vgg inference: %.1f images/sec (batch %d)",
                     args.batchSize / dt, args.batchSize)
        return top1

    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.datasets import load_cifar10
    from bigdl_tpu.optim import SGD, optimizer
    from bigdl_tpu.optim.schedules import EpochStep

    x, y = load_cifar10(args.folder, train=True)
    x = (x / 255.0 - 0.5) / 0.25
    ds = DataSet.tensors(x.astype("float32"), y)
    model = build_cifar(10)
    opt = optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=args.batchSize)
    # reference recipe: lr decayed 0.4x every 25 epochs
    opt.set_optim_method(SGD(learning_rate=args.learningRate, momentum=0.9,
                             weight_decay=5e-4, schedule=EpochStep(25, 0.4)))
    return fit(opt, args)


if __name__ == "__main__":
    main()
