"""MNIST fully-connected autoencoder.

Reference: ``DL/models/autoencoder/Autoencoder.scala`` (784-32-784 MLP
with sigmoid output trained with MSE, ``Train.scala`` uses Adagrad).
"""

from __future__ import annotations

import bigdl_tpu.nn as nn


def build(class_num: int = 32) -> nn.Sequential:
    """``class_num`` is the bottleneck width, matching the reference's
    (mis)use of the name (``Autoencoder.scala:30``)."""
    return nn.Sequential(
        nn.Reshape([784]),
        nn.Linear(784, class_num),
        nn.ReLU(),
        nn.Linear(class_num, 784),
        nn.Sigmoid(),
    )
