"""ResNet for CIFAR-10 and ImageNet.

Reference: ``DL/models/resnet/ResNet.scala`` (CIFAR + ImageNet variants,
shortcut types A/B/C, basic vs bottleneck blocks, optimnet-style init),
``DL/models/resnet/Train.scala`` / ``TrainImageNet.scala`` (recipes:
warmup + multi-step / poly decay, momentum SGD, label smoothing option).

TPU-native notes: residual add + BN + ReLU fuse in XLA; blocks are built
with ``ConcatTable``/``CAddTable`` exactly like the reference's Sequential
composition, so the params tree mirrors the reference's module tree. The
ImageNet stem uses the 7x7/2 conv + 3x3/2 maxpool; bottleneck stride
placement follows the reference's "v1.5" choice (stride on the 3x3,
``ResNet.scala`` ``useConv`` path) which is also the better MXU mapping.
"""

from __future__ import annotations

from typing import Optional

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.init import MsraFiller, Zeros


def _conv(cin, cout, k, stride=1, pad=0):
    return nn.SpatialConvolution(
        cin, cout, k, k, stride, stride, pad, pad,
        with_bias=False, weight_init=MsraFiller(),
    )


def _bn(n, zero_init=False):
    # reference zero-inits the last BN gamma of each block when
    # optnet/warm-up recipes are on (ResNet.scala getShortcut/iChannels)
    return (
        nn.SpatialBatchNormalization(n, weight_init=Zeros())
        if zero_init
        else nn.SpatialBatchNormalization(n)
    )


def shortcut(cin: int, cout: int, stride: int, shortcut_type: str = "B") -> nn.Module:
    """Shortcut types (reference ``ResNet.scala`` ``shortcut``):
    A = identity/zero-pad (CIFAR), B = 1x1 conv when shape changes,
    C = always 1x1 conv."""
    use_conv = shortcut_type == "C" or (shortcut_type == "B" and (cin != cout or stride != 1))
    if use_conv:
        return nn.Sequential(_conv(cin, cout, 1, stride), _bn(cout))
    if cin != cout:
        # type A: stride then zero-pad channels (Pad on channel dim)
        return nn.Sequential(
            nn.SpatialAveragePooling(1, 1, stride, stride),
            nn.Padding(1, cout - cin),
        )
    return nn.Identity()


def basic_block(cin: int, cout: int, stride: int, shortcut_type: str = "B",
                zero_init_residual: bool = False) -> nn.Module:
    block = nn.Sequential(
        _conv(cin, cout, 3, stride, 1),
        _bn(cout),
        nn.ReLU(),
        _conv(cout, cout, 3, 1, 1),
        _bn(cout, zero_init=zero_init_residual),
    )
    return nn.Sequential(
        nn.ConcatTable(block, shortcut(cin, cout, stride, shortcut_type)),
        nn.CAddTable(),
        nn.ReLU(),
    )


def bottleneck(cin: int, planes: int, stride: int, shortcut_type: str = "B",
               zero_init_residual: bool = False) -> nn.Module:
    cout = planes * 4
    block = nn.Sequential(
        _conv(cin, planes, 1),
        _bn(planes),
        nn.ReLU(),
        _conv(planes, planes, 3, stride, 1),
        _bn(planes),
        nn.ReLU(),
        _conv(planes, cout, 1),
        _bn(cout, zero_init=zero_init_residual),
    )
    return nn.Sequential(
        nn.ConcatTable(block, shortcut(cin, cout, stride, shortcut_type)),
        nn.CAddTable(),
        nn.ReLU(),
    )


IMAGENET_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def build_imagenet(depth: int = 50, class_num: int = 1000, shortcut_type: str = "B",
                   zero_init_residual: bool = True) -> nn.Sequential:
    """ImageNet ResNet (reference ``ResNet.apply`` dataset=ImageNet branch)."""
    if depth not in IMAGENET_CFG:
        raise ValueError(f"unsupported imagenet resnet depth {depth}")
    kind, counts = IMAGENET_CFG[depth]
    block = basic_block if kind == "basic" else bottleneck
    expansion = 1 if kind == "basic" else 4

    model = nn.Sequential(
        _conv(3, 64, 7, 2, 3).set_name("conv1"),
        _bn(64),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1),
    )
    cin = 64
    for stage, (planes, n_blocks) in enumerate(zip([64, 128, 256, 512], counts)):
        for i in range(n_blocks):
            stride = 2 if (stage > 0 and i == 0) else 1
            model.add(
                block(cin, planes, stride, shortcut_type, zero_init_residual),
                name=f"layer{stage + 1}_{i}",
            )
            cin = planes * expansion
    model.add(nn.GlobalAveragePooling2D())
    model.add(nn.Linear(cin, class_num, weight_init=MsraFiller()).set_name("fc"))
    return model


def build_cifar(depth: int = 20, class_num: int = 10, shortcut_type: str = "A") -> nn.Sequential:
    """CIFAR-10 ResNet: depth = 6n+2 basic blocks (reference ``ResNet.apply``
    CIFAR-10 branch)."""
    if (depth - 2) % 6 != 0:
        raise ValueError("cifar resnet depth must be 6n+2")
    n = (depth - 2) // 6
    model = nn.Sequential(
        _conv(3, 16, 3, 1, 1),
        _bn(16),
        nn.ReLU(),
    )
    cin = 16
    for stage, planes in enumerate([16, 32, 64]):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            model.add(
                basic_block(cin, planes, stride, shortcut_type),
                name=f"stage{stage + 1}_{i}",
            )
            cin = planes
    model.add(nn.GlobalAveragePooling2D())
    model.add(nn.Linear(cin, class_num, weight_init=MsraFiller()).set_name("fc"))
    return model


def build(depth: int = 50, class_num: int = 1000, dataset: str = "imagenet",
          shortcut_type: Optional[str] = None) -> nn.Sequential:
    if dataset.lower() in ("imagenet", "i"):
        return build_imagenet(depth, class_num, shortcut_type or "B")
    return build_cifar(depth, class_num, shortcut_type or "A")
