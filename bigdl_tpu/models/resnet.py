"""ResNet for CIFAR-10 and ImageNet.

Reference: ``DL/models/resnet/ResNet.scala`` (CIFAR + ImageNet variants,
shortcut types A/B/C, basic vs bottleneck blocks, optimnet-style init),
``DL/models/resnet/Train.scala`` / ``TrainImageNet.scala`` (recipes:
warmup + multi-step / poly decay, momentum SGD, label smoothing option).

TPU-native notes: residual add + BN + ReLU fuse in XLA; blocks are built
with ``ConcatTable``/``CAddTable`` exactly like the reference's Sequential
composition, so the params tree mirrors the reference's module tree. The
ImageNet stem uses the 7x7/2 conv + 3x3/2 maxpool; bottleneck stride
placement follows the reference's "v1.5" choice (stride on the 3x3,
``ResNet.scala`` ``useConv`` path) which is also the better MXU mapping.
"""

from __future__ import annotations

from typing import Optional

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.init import MsraFiller, Zeros


def _conv(cin, cout, k, stride=1, pad=0, data_format="NCHW",
          kernel_format="OIHW"):
    return nn.SpatialConvolution(
        cin, cout, k, k, stride, stride, pad, pad,
        with_bias=False, weight_init=MsraFiller(), data_format=data_format,
        kernel_format=kernel_format,
    )


class Conv1SpaceToDepth(nn.Module):
    """The ImageNet stem conv (7x7/2, 3->64) computed via the MLPerf
    space-to-depth trick: fold 2x2 pixel blocks into channels so the
    MXU's contraction dim sees 12 input channels instead of 3, and run
    the mathematically IDENTICAL 4x4/1 convolution on the folded layout.

    Derivation: with original index ``2*oh + kh - 3`` (stride 2, pad 3)
    and ``kh = 2*kh' + p - 1`` (p the 2-pixel phase), the sum becomes a
    stride-1 conv over folded index ``oh + kh' - 2`` — kernel 4, padding
    (2, 1). Weights stay stored in the canonical (64, 3, 7, 7) layout
    (checkpoint/serializer compatible); the fold is a 9.4K-element
    pad+reshape recomputed per step (negligible). Zero-padded taps make
    the result exactly the original convolution up to fp summation
    order. NCHW only (the bench layout).
    """

    def __init__(self, cout: int = 64):
        super().__init__()
        self.cout = cout

    def build_params(self, rng):
        from bigdl_tpu.core.rng import fold_in_str
        w = MsraFiller()(fold_in_str(rng, "w"), (self.cout, 3, 7, 7),
                         3 * 49, self.cout * 49)
        return {"weight": w}

    def forward(self, ctx, x):
        import jax.numpy as jnp

        w = ctx.param("weight").astype(x.dtype)  # (O, 3, 7, 7)
        O = w.shape[0]
        B, C, H, W = x.shape
        xf = (x.reshape(B, C, H // 2, 2, W // 2, 2)
              .transpose(0, 1, 3, 5, 2, 4)
              .reshape(B, C * 4, H // 2, W // 2))  # channel order (c, p, q)
        wp = jnp.pad(w, ((0, 0), (0, 0), (1, 0), (1, 0)))  # tap -1 -> zero
        wf = (wp.reshape(O, C, 4, 2, 4, 2)
              .transpose(0, 1, 3, 5, 2, 4)
              .reshape(O, C * 4, 4, 4))
        import jax.lax as lax
        return lax.conv_general_dilated(
            xf, wf, (1, 1), [(2, 1), (2, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))


def _bn(n, zero_init=False, data_format="NCHW"):
    # reference zero-inits the last BN gamma of each block when
    # optnet/warm-up recipes are on (ResNet.scala getShortcut/iChannels)
    return nn.SpatialBatchNormalization(
        n, weight_init=Zeros() if zero_init else None, data_format=data_format)


def shortcut(cin: int, cout: int, stride: int, shortcut_type: str = "B",
             data_format: str = "NCHW", kernel_format: str = "OIHW") -> nn.Module:
    """Shortcut types (reference ``ResNet.scala`` ``shortcut``):
    A = identity/zero-pad (CIFAR), B = 1x1 conv when shape changes,
    C = always 1x1 conv."""
    use_conv = shortcut_type == "C" or (shortcut_type == "B" and (cin != cout or stride != 1))
    if use_conv:
        return nn.Sequential(_conv(cin, cout, 1, stride, data_format=data_format,
                                   kernel_format=kernel_format),
                             _bn(cout, data_format=data_format))
    if cin != cout:
        # type A: stride then zero-pad channels (Pad on channel dim)
        ch_dim = 1 if data_format == "NCHW" else 3
        return nn.Sequential(
            nn.SpatialAveragePooling(1, 1, stride, stride,
                                     data_format=data_format),
            nn.Padding(ch_dim, cout - cin),
        )
    return nn.Identity()


def basic_block(cin: int, cout: int, stride: int, shortcut_type: str = "B",
                zero_init_residual: bool = False,
                data_format: str = "NCHW", kernel_format: str = "OIHW") -> nn.Module:
    df, kf = data_format, kernel_format
    block = nn.Sequential(
        _conv(cin, cout, 3, stride, 1, data_format=df, kernel_format=kf),
        _bn(cout, data_format=df),
        nn.ReLU(),
        _conv(cout, cout, 3, 1, 1, data_format=df, kernel_format=kf),
        _bn(cout, zero_init=zero_init_residual, data_format=df),
    )
    return nn.Sequential(
        nn.ConcatTable(block, shortcut(cin, cout, stride, shortcut_type, df, kf)),
        nn.CAddTable(),
        nn.ReLU(),
    )


def bottleneck(cin: int, planes: int, stride: int, shortcut_type: str = "B",
               zero_init_residual: bool = False,
               data_format: str = "NCHW", kernel_format: str = "OIHW") -> nn.Module:
    df, kf = data_format, kernel_format
    cout = planes * 4
    block = nn.Sequential(
        _conv(cin, planes, 1, data_format=df, kernel_format=kf),
        _bn(planes, data_format=df),
        nn.ReLU(),
        _conv(planes, planes, 3, stride, 1, data_format=df, kernel_format=kf),
        _bn(planes, data_format=df),
        nn.ReLU(),
        _conv(planes, cout, 1, data_format=df, kernel_format=kf),
        _bn(cout, zero_init=zero_init_residual, data_format=df),
    )
    return nn.Sequential(
        nn.ConcatTable(block, shortcut(cin, cout, stride, shortcut_type, df, kf)),
        nn.CAddTable(),
        nn.ReLU(),
    )


IMAGENET_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def build_imagenet(depth: int = 50, class_num: int = 1000, shortcut_type: str = "B",
                   zero_init_residual: bool = True,
                   data_format: str = "NCHW",
                   kernel_format: str = "OIHW",
                   stem_s2d: bool = False) -> nn.Sequential:
    """ImageNet ResNet (reference ``ResNet.apply`` dataset=ImageNet branch).

    ``data_format="NHWC"`` builds the channels-last variant (input
    (B, H, W, C)). ``data_format="MIXED"`` is the measured-fastest TPU
    layout (PERF_NOTES.md round 3): NCHW for the stem + 64-channel
    layer1 (narrow channels underfill the 128-lane dimension in NHWC,
    making those convs ~2x slower), one transpose, then NHWC for
    layers 2-4 where convs are up to 1.8x faster AND the BN statistic
    reductions become lane-minor accumulations. Input stays NCHW.
    """
    if depth not in IMAGENET_CFG:
        raise ValueError(f"unsupported imagenet resnet depth {depth}")
    kind, counts = IMAGENET_CFG[depth]
    block = basic_block if kind == "basic" else bottleneck
    expansion = 1 if kind == "basic" else 4
    mixed = data_format == "MIXED"
    df, kf = ("NCHW", kernel_format) if mixed else (data_format, kernel_format)

    if stem_s2d and df != "NCHW":
        raise ValueError("stem_s2d supports the NCHW layout only")
    stem_conv = (Conv1SpaceToDepth(64) if stem_s2d
                 else _conv(3, 64, 7, 2, 3, data_format=df,
                            kernel_format=kf))
    model = nn.Sequential(
        stem_conv.set_name("conv1"),
        _bn(64, data_format=df),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1, data_format=df),
    )
    cin = 64
    for stage, (planes, n_blocks) in enumerate(zip([64, 128, 256, 512], counts)):
        if mixed and stage == 1:
            # NCHW -> NHWC between layer1 and layer2
            model.add(nn.Transpose((1, 2), (2, 3)), name="to_nhwc")
            df = "NHWC"
        for i in range(n_blocks):
            stride = 2 if (stage > 0 and i == 0) else 1
            model.add(
                block(cin, planes, stride, shortcut_type, zero_init_residual,
                      df, kf),
                name=f"layer{stage + 1}_{i}",
            )
            cin = planes * expansion
    model.add(nn.GlobalAveragePooling2D(data_format=df))
    model.add(nn.Linear(cin, class_num, weight_init=MsraFiller()).set_name("fc"))
    return model


def build_cifar(depth: int = 20, class_num: int = 10, shortcut_type: str = "A") -> nn.Sequential:
    """CIFAR-10 ResNet: depth = 6n+2 basic blocks (reference ``ResNet.apply``
    CIFAR-10 branch)."""
    if (depth - 2) % 6 != 0:
        raise ValueError("cifar resnet depth must be 6n+2")
    n = (depth - 2) // 6
    model = nn.Sequential(
        _conv(3, 16, 3, 1, 1),
        _bn(16),
        nn.ReLU(),
    )
    cin = 16
    for stage, planes in enumerate([16, 32, 64]):
        for i in range(n):
            stride = 2 if (stage > 0 and i == 0) else 1
            model.add(
                basic_block(cin, planes, stride, shortcut_type),
                name=f"stage{stage + 1}_{i}",
            )
            cin = planes
    model.add(nn.GlobalAveragePooling2D())
    model.add(nn.Linear(cin, class_num, weight_init=MsraFiller()).set_name("fc"))
    return model


def build(depth: int = 50, class_num: int = 1000, dataset: str = "imagenet",
          shortcut_type: Optional[str] = None) -> nn.Sequential:
    if dataset.lower() in ("imagenet", "i"):
        return build_imagenet(depth, class_num, shortcut_type or "B")
    return build_cifar(depth, class_num, shortcut_type or "A")


def main(argv=None):
    """Train CLI (reference: ``resnet/Train.scala`` CIFAR recipe /
    ``TrainImageNet.scala``)."""
    import numpy as np

    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.datasets import _synthetic_images, load_cifar10
    from bigdl_tpu.models.cli import fit, make_parser
    from bigdl_tpu.optim import SGD, optimizer
    from bigdl_tpu.optim.schedules import MultiStep

    parser = make_parser("resnet-train", batch_size=128, max_epoch=10,
                         learning_rate=0.1,
                         folder_help="cifar-10 dir (synthetic data if absent)")
    parser.add_argument("--depth", type=int, default=20)
    parser.add_argument("--dataset", default="cifar10", choices=["cifar10", "imagenet"])
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--weightDecay", type=float, default=1e-4)
    parser.add_argument("--dataFormat", default="NCHW",
                        choices=["NCHW", "NHWC"],
                        help="imagenet variant only; NHWC = channels-last")
    args = parser.parse_args(argv)

    if args.dataset == "imagenet":
        model = build_imagenet(args.depth if args.depth in IMAGENET_CFG else 50,
                               1000, data_format=args.dataFormat)
        x, y = _synthetic_images(64, (3, 224, 224), 1000, seed=1)
        if args.dataFormat == "NHWC":
            x = np.ascontiguousarray(np.transpose(x, (0, 2, 3, 1)))
    else:
        model = build_cifar(args.depth, 10)
        x, y = load_cifar10(args.folder, train=True)
        mean = np.asarray([125.3, 123.0, 113.9], np.float32).reshape(3, 1, 1)
        std = np.asarray([63.0, 62.1, 66.7], np.float32).reshape(3, 1, 1)
        x = (x - mean) / std
    ds = DataSet.tensors(x.astype("float32"), y)

    # reference CIFAR recipe: momentum SGD with multi-step decay
    opt = optimizer(model, ds, nn.CrossEntropyCriterion(), batch_size=args.batchSize)
    opt.set_optim_method(SGD(learning_rate=args.learningRate,
                             momentum=args.momentum,
                             weight_decay=args.weightDecay,
                             schedule=MultiStep([32000, 48000], 0.1)))
    return fit(opt, args)


if __name__ == "__main__":
    main()
