"""Shared train-CLI plumbing for the model mains.

Reference analogue: the common scopt options each ``DL/models/*/Utils.scala``
re-declares (dataFolder, batchSize, maxEpoch, learningRate, checkpoint) —
centralized here so the five mains share one parser tail and one
optimizer-wiring tail.
"""

from __future__ import annotations

import argparse
import logging
from typing import Optional


def make_parser(name: str, batch_size: int, max_epoch: int,
                learning_rate: float, folder_help: str) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(name)
    parser.add_argument("-f", "--folder", default=None, help=folder_help)
    parser.add_argument("-b", "--batchSize", type=int, default=batch_size)
    parser.add_argument("-e", "--maxEpoch", type=int, default=max_epoch)
    parser.add_argument("--maxIteration", type=int, default=0,
                        help="overrides maxEpoch when > 0")
    parser.add_argument("--learningRate", type=float, default=learning_rate)
    parser.add_argument("--checkpoint", default=None)
    return parser


def fit(opt, args, checkpoint_trigger=None):
    """Wire the shared end/checkpoint policy and run (the tail every
    Train.scala repeats)."""
    from bigdl_tpu.optim import Trigger

    logging.basicConfig(level=logging.INFO)
    opt.set_end_when(Trigger.max_iteration(args.maxIteration)
                     if args.maxIteration else Trigger.max_epoch(args.maxEpoch))
    if args.checkpoint:
        opt.set_checkpoint(args.checkpoint,
                           checkpoint_trigger or Trigger.every_epoch())
    return opt.optimize()
