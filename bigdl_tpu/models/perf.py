"""Standalone perf harness over the model zoo.

Reference: ``DL/models/utils/DistriOptimizerPerf.scala:82`` /
``LocalOptimizerPerf.scala`` (dummy-data training throughput for a
selectable model) and ``DL/nn/mkldnn/Perf.scala:56`` (fwd/bwd latency,
incl. int8 inference).

Usage::

    python -m bigdl_tpu.models.perf --model resnet50 -b 32 --mode train
    python -m bigdl_tpu.models.perf --model vgg16 --mode fwd --int8

Timing uses the same differential scheme as ``bench.py`` (two iteration
counts, min-of-each then difference) so the tunneled runner's dispatch
overhead cancels.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from bigdl_tpu.core.rng import np_rng
import jax
import jax.numpy as jnp


def build_model(name: str, class_num: int):
    from bigdl_tpu.models import inception, lenet, resnet, vgg

    shapes = {"lenet": (1, 28, 28)}
    if name == "lenet":
        return lenet.build(class_num if class_num != 1000 else 10), (1, 28, 28)
    if name == "resnet50":
        return resnet.build_imagenet(50, class_num), (3, 224, 224)
    if name == "resnet18":
        return resnet.build_imagenet(18, class_num), (3, 224, 224)
    if name == "inception-v1":
        return inception.build(class_num), (3, 224, 224)
    if name == "vgg16":
        return vgg.build_vgg16(class_num=class_num), (3, 224, 224)
    if name == "vgg19":
        return vgg.build_vgg19(class_num=class_num), (3, 224, 224)
    if name == "alexnet":
        from bigdl_tpu.models import alexnet

        return alexnet.build_owt(class_num), (3, 224, 224)
    raise ValueError(f"unknown model {name}")


def timed_scan(body, carry, n1, n2, reps=3):
    def runner(n):
        @jax.jit
        def multi(c):
            _, r = jax.lax.scan(lambda c, _: body(c), c, None, length=n)
            return r
        return multi

    m1, m2 = runner(n1), runner(n2)
    np.asarray(m1(carry)); np.asarray(m2(carry))
    t1s, t2s = [], []
    for _ in range(reps):
        t0 = time.perf_counter(); np.asarray(m1(carry)); t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); np.asarray(m2(carry)); t2s.append(time.perf_counter() - t0)
    return (min(t2s) - min(t1s)) / (n2 - n1)


def main(argv=None):
    from bigdl_tpu.nn import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import SGD

    ap = argparse.ArgumentParser("perf")
    ap.add_argument("--model", default="resnet50",
                    choices=["lenet", "resnet18", "resnet50", "inception-v1",
                             "vgg16", "vgg19", "alexnet"])
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("--mode", choices=["train", "fwd"], default="train")
    ap.add_argument("--int8", action="store_true",
                    help="quantize for the fwd mode (Perf.scala int8 path)")
    ap.add_argument("--classNum", type=int, default=1000)
    ap.add_argument("--iters", type=int, nargs=2, default=[4, 12])
    args = ap.parse_args(argv)

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    model, shape = build_model(args.model, args.classNum)
    params, mstate = model.init(jax.random.key(0))
    rng = np_rng(0)
    x = jnp.asarray(rng.random((args.batchSize, *shape)), dtype)
    y = jnp.asarray(rng.integers(0, args.classNum, (args.batchSize,)), jnp.int32)

    if args.mode == "fwd":
        if args.int8:
            from bigdl_tpu.nn.quantized import quantize

            model, params = quantize(model, params)
            x = x.astype(jnp.float32)

        def body(c):
            p, xx = c
            out, _ = model.apply(p, xx, state=mstate, training=False)
            s = out.astype(jnp.float32).mean()
            return (p, xx + (s * 1e-30).astype(xx.dtype)), s
        dt = timed_scan(body, (params, x), *args.iters)
    else:
        crit = CrossEntropyCriterion()
        method = SGD(learning_rate=0.01, momentum=0.9)
        ostate = method.init_state(params)

        def body(c):
            p, ms, os_ = c

            def loss_fn(pp):
                # fixed dropout rng: fine for throughput (mask compute cost
                # is identical every step), required by Dropout-bearing
                # models (inception/vgg/alexnet) in training mode
                out, nms = model.apply(pp, x, state=ms, training=True,
                                       rng=jax.random.key(1))
                return crit.forward(out.astype(jnp.float32), y), nms

            (loss, nms), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            np_, nos = method.update(g, p, os_, jnp.int32(1))
            return (np_, nms, nos), loss
        dt = timed_scan(body, (params, mstate, ostate), *args.iters)

    print(json.dumps({
        "model": args.model, "mode": args.mode, "int8": args.int8,
        "batch": args.batchSize,
        "ms_per_iter": round(dt * 1e3, 2),
        "records_per_sec": round(args.batchSize / dt, 1),
        "platform": jax.devices()[0].platform,
    }))


if __name__ == "__main__":
    main()
