"""Faster-RCNN: the classic two-stage detector assembled from the zoo.

Reference: the ``Proposal`` + ``DetectionOutputFrcnn`` layer pair
(``DL/nn/Proposal.scala``, ``DL/nn/DetectionOutputFrcnn.scala``) exists in
the reference precisely to assemble VGG16-backbone Faster-RCNN inference
(py-faster-rcnn style: single-scale features, stride-16 RPN, RoI pool,
two FCs, per-class box regression + NMS post-processing).

TPU-native: every stage is fixed-shape (masked proposals/detections), so
the whole pipeline jits into one XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.core.rng import np_rng
import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Context, Module


class FasterRCNN(Module):
    """Single-scale Faster-RCNN inference graph.

    ``forward((image (1, 3, H, W), im_info (1, 4)))`` ->
    ``(boxes (K, 4), scores (K,), labels (K,), valid (K,))``.
    ``im_info`` = [height, width, scale_h, scale_w] like the reference.
    """

    def __init__(self, n_classes: int = 21, backbone_channels: int = 256,
                 pool_resolution: int = 7, stride: float = 16.0,
                 pre_nms_topn: int = 300, post_nms_topn: int = 64,
                 max_per_image: int = 100, representation: int = 256):
        super().__init__()
        c = backbone_channels
        self.stride = stride
        # compact VGG-ish stride-16 backbone (swap for vgg.features for the
        # full reference config)
        self.backbone = nn.Sequential(
            nn.SpatialConvolution(3, c // 4, 3, 3, 2, 2, 1, 1), nn.ReLU(),
            nn.SpatialConvolution(c // 4, c // 2, 3, 3, 2, 2, 1, 1), nn.ReLU(),
            nn.SpatialConvolution(c // 2, c, 3, 3, 2, 2, 1, 1), nn.ReLU(),
            nn.SpatialConvolution(c, c, 3, 3, 2, 2, 1, 1), nn.ReLU(),
        )
        self.n_classes = n_classes
        a_ratios, a_scales = (0.5, 1.0, 2.0), (8.0, 16.0, 32.0)
        n_anchors = len(a_ratios) * len(a_scales)
        self.rpn_conv = nn.SpatialConvolution(c, c, 3, 3, 1, 1, 1, 1)
        self.rpn_cls = nn.SpatialConvolution(c, 2 * n_anchors, 1, 1)
        self.rpn_box = nn.SpatialConvolution(c, 4 * n_anchors, 1, 1)
        self.proposal = nn.Proposal(
            pre_nms_topn_test=pre_nms_topn, post_nms_topn_test=post_nms_topn,
            ratios=a_ratios, scales=a_scales, min_size=16.0, stride=stride)
        self.roi_pool = nn.RoiAlign(1.0 / stride, 2, pool_resolution,
                                    pool_resolution)
        self.box_head = nn.BoxHead(c, pool_resolution, n_classes,
                                   representation=representation)
        self.detection_out = nn.DetectionOutputFrcnn(
            n_classes=n_classes, max_per_image=max_per_image)

    def forward(self, ctx: Context, x):
        image, im_info = x
        feat = self.run_child(ctx, "backbone", image)
        rpn = jnp.maximum(self.run_child(ctx, "rpn_conv", feat), 0.0)
        cls_scores = self.run_child(ctx, "rpn_cls", rpn)
        box_deltas = self.run_child(ctx, "rpn_box", rpn)
        # rank proposals by P(object), not the raw obj logit: softmax each
        # (bg, obj) channel pair like the reference pipeline (SoftMax over
        # the 2A score map before Proposal; ADVICE r3)
        n, c2a, fh, fw = cls_scores.shape
        pair = cls_scores.reshape(n, 2, c2a // 2, fh, fw)
        cls_probs = jax.nn.softmax(pair, axis=1).reshape(n, c2a, fh, fw)
        rois5, _, roi_valid = self.run_child(
            ctx, "proposal", (cls_probs, box_deltas, im_info))
        pooled = self.run_child(ctx, "roi_pool", (feat, rois5[:, 1:]))
        scores, deltas = self.run_child(ctx, "box_head", pooled)
        # zero the padded (invalid) proposals' probabilities so they fall
        # below DetectionOutputFrcnn's score threshold (same convention as
        # maskrcnn.py's best_p * roi_valid)
        probs = jax.nn.softmax(scores, axis=-1) * roi_valid[:, None]
        return self.run_child(
            ctx, "detection_out", (probs, deltas, rois5, im_info))


def build(n_classes: int = 21, **kw) -> FasterRCNN:
    return FasterRCNN(n_classes=n_classes, **kw)


def main(argv=None):
    """Predict CLI: run a (synthetic or file) image through the two-stage
    pipeline and print detections."""
    import argparse

    import jax
    import numpy as np

    ap = argparse.ArgumentParser("frcnn")
    ap.add_argument("--image", default=None)
    ap.add_argument("--numClasses", type=int, default=21)
    args = ap.parse_args(argv)

    model = build(args.numClasses)
    params, state = model.init(jax.random.key(0))
    if args.image:
        from PIL import Image

        img = np.asarray(Image.open(args.image).convert("RGB"), np.float32)
    else:
        img = (np_rng(0).random((224, 224, 3)) * 255).astype(np.float32)
    h, w = img.shape[:2]
    x = img.transpose(2, 0, 1)[None] / 128.0 - 1.0
    im_info = np.asarray([[h, w, 1.0, 1.0]], np.float32)
    fwd = jax.jit(lambda p, xx: model.apply(p, xx, state=state,
                                            training=False)[0])
    boxes, scores, labels, valid = map(
        np.asarray, fwd(params, (x, im_info)))
    n = int(valid.sum())
    print(f"{n} detections")
    for k in range(len(valid)):
        if valid[k]:
            b = boxes[k]
            print(f"  label={int(labels[k])} score={float(scores[k]):.3f} "
                  f"box=({b[0]:.0f},{b[1]:.0f},{b[2]:.0f},{b[3]:.0f})")
    return n


if __name__ == "__main__":
    main()
