"""Inception v1 (GoogLeNet).

Reference: ``DL/models/inception/Inception_v1.scala`` (graph builders,
1,208 LoC) — inception modules as a 4-tower ``Concat`` (1x1 / 1x1-3x3 /
1x1-5x5 / pool-1x1). ``build`` is the no-aux variant
(``Inception_v1_NoAuxClassifier.apply``); ``build_with_aux`` is the full
training network with the two auxiliary classifier heads after 4a and 4d
(``Inception_v1.apply``), trained with the (1.0, 0.3, 0.3)-weighted
multi-loss recipe (see :func:`aux_criterion`).
"""

from __future__ import annotations

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.graph import Graph, Input, Node
from bigdl_tpu.nn.init import Xavier


def _conv(cin, cout, k, stride=1, pad=0, name=""):
    seq = nn.Sequential(
        nn.SpatialConvolution(cin, cout, k, k, stride, stride, pad, pad,
                              weight_init=Xavier()).set_name(name + "_conv"),
        nn.ReLU(),
    )
    return seq


def inception_module(cin: int, config, name: str = "") -> nn.Concat:
    """``config`` = [[c1x1], [c3x3r, c3x3], [c5x5r, c5x5], [pool_proj]]
    (reference ``Inception_v1.scala`` ``inception`` function)."""
    (c1,), (c3r, c3), (c5r, c5), (cp,) = config
    return nn.Concat(
        1,
        _conv(cin, c1, 1, name=name + "1x1"),
        nn.Sequential(
            _conv(cin, c3r, 1, name=name + "3x3r"),
            _conv(c3r, c3, 3, pad=1, name=name + "3x3"),
        ),
        nn.Sequential(
            _conv(cin, c5r, 1, name=name + "5x5r"),
            _conv(c5r, c5, 5, pad=2, name=name + "5x5"),
        ),
        nn.Sequential(
            nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1),
            _conv(cin, cp, 1, name=name + "pool"),
        ),
    )


def _stem() -> nn.Sequential:
    """Shared input->4a trunk (reference ``Inception_v1.scala``)."""
    return nn.Sequential(
        _conv(3, 64, 7, 2, 3, "conv1/7x7_s2"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        _conv(64, 64, 1, name="conv2/3x3_reduce"),
        _conv(64, 192, 3, pad=1, name="conv2/3x3"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        inception_module(192, [[64], [96, 128], [16, 32], [32]], "inception_3a/"),
        inception_module(256, [[128], [128, 192], [32, 96], [64]], "inception_3b/"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        inception_module(480, [[192], [96, 208], [16, 48], [64]], "inception_4a/"),
    ).set_name("stem")


def _mid() -> nn.Sequential:
    """Shared 4a->4d trunk."""
    return nn.Sequential(
        inception_module(512, [[160], [112, 224], [24, 64], [64]], "inception_4b/"),
        inception_module(512, [[128], [128, 256], [24, 64], [64]], "inception_4c/"),
        inception_module(512, [[112], [144, 288], [32, 64], [64]], "inception_4d/"),
    ).set_name("mid")


def _top(class_num: int, has_dropout: bool) -> nn.Sequential:
    """Shared 4d->classifier trunk."""
    return nn.Sequential(
        inception_module(528, [[256], [160, 320], [32, 128], [128]], "inception_4e/"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        inception_module(832, [[256], [160, 320], [32, 128], [128]], "inception_5a/"),
        inception_module(832, [[384], [192, 384], [48, 128], [128]], "inception_5b/"),
        nn.GlobalAveragePooling2D(),
        *([nn.Dropout(0.4)] if has_dropout else []),
        nn.Linear(1024, class_num, weight_init=Xavier()).set_name("loss3/classifier"),
        nn.LogSoftMax(),
    ).set_name("top")


def build(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """Inception-v1 without aux heads (reference
    ``Inception_v1_NoAuxClassifier.apply``)."""
    return nn.Sequential(_stem(), _mid(), _top(class_num, has_dropout))


def _aux_head(cin: int, class_num: int, name: str, has_dropout: bool) -> nn.Sequential:
    """Auxiliary classifier (reference ``Inception_v1.scala`` loss1/loss2
    branches): AvgPool 5x5/3 -> 1x1 conv 128 -> FC 1024 -> ReLU ->
    Dropout(0.7) (when enabled, :224/:240) -> FC class_num -> LogSoftMax."""
    return nn.Sequential(
        nn.SpatialAveragePooling(5, 5, 3, 3).ceil(),
        _conv(cin, 128, 1, name=name + "/conv"),
        nn.Reshape([-1]),
        nn.Linear(128 * 4 * 4, 1024, weight_init=Xavier()).set_name(name + "/fc"),
        nn.ReLU(),
        *([nn.Dropout(0.7)] if has_dropout else []),
        nn.Linear(1024, class_num, weight_init=Xavier()).set_name(name + "/classifier"),
        nn.LogSoftMax(),
    )


def build_with_aux(class_num: int = 1000, has_dropout: bool = True) -> Graph:
    """Full Inception-v1 training graph with aux heads (reference
    ``Inception_v1.apply``): returns a Graph whose forward yields
    ``(main, aux1, aux2)`` log-probabilities."""
    inp = Input()
    n4a = Node(_stem(), [inp])
    n4d = Node(_mid(), [n4a])
    main = Node(_top(class_num, has_dropout), [n4d])
    aux1 = Node(_aux_head(512, class_num, "loss1", has_dropout).set_name("aux1"), [n4a])
    aux2 = Node(_aux_head(528, class_num, "loss2", has_dropout).set_name("aux2"), [n4d])
    return Graph(inp, [main, aux1, aux2])


def aux_criterion() -> nn.ParallelCriterion:
    """The multi-loss training recipe (reference ``Train.scala`` inception:
    main + 0.3*aux1 + 0.3*aux2 over ClassNLL on log-probs). Apply to the
    (main, aux1, aux2) output tuple with a shared integer target."""
    crit = nn.ParallelCriterion(repeat_target=True)
    crit.add(nn.ClassNLLCriterion(), 1.0)
    crit.add(nn.ClassNLLCriterion(), 0.3)
    crit.add(nn.ClassNLLCriterion(), 0.3)
    return crit


def main(argv=None):
    """Train CLI (reference: ``inception/Train.scala`` + ``Options.scala``)."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.datasets import _synthetic_images
    from bigdl_tpu.models.cli import fit, make_parser
    from bigdl_tpu.optim import SGD, Trigger, optimizer
    from bigdl_tpu.optim.schedules import Poly

    parser = make_parser("inception-train", batch_size=32, max_epoch=10,
                         learning_rate=0.01,
                         folder_help="imagenet dir (synthetic data if absent)")
    parser.add_argument("--classNum", type=int, default=1000)
    parser.add_argument("--weightDecay", type=float, default=0.0002)
    parser.add_argument("--no-aux", action="store_true",
                        help="train the NoAuxClassifier variant")
    args = parser.parse_args(argv)

    x, y = _synthetic_images(max(64, args.batchSize * 2), (3, 224, 224),
                             args.classNum, seed=2)
    ds = DataSet.tensors(x.astype("float32"), y)

    if args.no_aux:
        model = build(args.classNum)
        criterion = nn.ClassNLLCriterion()
    else:
        model = build_with_aux(args.classNum)
        criterion = aux_criterion()

    opt = optimizer(model, ds, criterion, batch_size=args.batchSize)
    # reference recipe: poly(0.5) decay over the iteration budget
    decay_span = args.maxIteration or 62000
    opt.set_optim_method(SGD(learning_rate=args.learningRate,
                             weight_decay=args.weightDecay,
                             schedule=Poly(0.5, decay_span)))
    return fit(opt, args, checkpoint_trigger=Trigger.several_iteration(620))


if __name__ == "__main__":
    main()
