"""Inception v1 (GoogLeNet).

Reference: ``DL/models/inception/Inception_v1.scala`` (graph builders,
1,208 LoC) — inception modules as a 4-tower ``Concat`` (1x1 / 1x1-3x3 /
1x1-5x5 / pool-1x1). This builds the no-aux-head variant
(``Inception_v1_NoAuxClassifier.apply``); the aux-classifier training
heads are a later addition alongside the multi-loss training recipe.
"""

from __future__ import annotations

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.init import Xavier


def _conv(cin, cout, k, stride=1, pad=0, name=""):
    seq = nn.Sequential(
        nn.SpatialConvolution(cin, cout, k, k, stride, stride, pad, pad,
                              weight_init=Xavier()).set_name(name + "_conv"),
        nn.ReLU(),
    )
    return seq


def inception_module(cin: int, config, name: str = "") -> nn.Concat:
    """``config`` = [[c1x1], [c3x3r, c3x3], [c5x5r, c5x5], [pool_proj]]
    (reference ``Inception_v1.scala`` ``inception`` function)."""
    (c1,), (c3r, c3), (c5r, c5), (cp,) = config
    return nn.Concat(
        1,
        _conv(cin, c1, 1, name=name + "1x1"),
        nn.Sequential(
            _conv(cin, c3r, 1, name=name + "3x3r"),
            _conv(c3r, c3, 3, pad=1, name=name + "3x3"),
        ),
        nn.Sequential(
            _conv(cin, c5r, 1, name=name + "5x5r"),
            _conv(c5r, c5, 5, pad=2, name=name + "5x5"),
        ),
        nn.Sequential(
            nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1),
            _conv(cin, cp, 1, name=name + "pool"),
        ),
    )


def build(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """Inception-v1 without aux heads (reference
    ``Inception_v1_NoAuxClassifier.apply``)."""
    model = nn.Sequential(
        _conv(3, 64, 7, 2, 3, "conv1/7x7_s2"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        _conv(64, 64, 1, name="conv2/3x3_reduce"),
        _conv(64, 192, 3, pad=1, name="conv2/3x3"),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
    )
    model.add(inception_module(192, [[64], [96, 128], [16, 32], [32]], "inception_3a/"))
    model.add(inception_module(256, [[128], [128, 192], [32, 96], [64]], "inception_3b/"))
    model.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    model.add(inception_module(480, [[192], [96, 208], [16, 48], [64]], "inception_4a/"))
    model.add(inception_module(512, [[160], [112, 224], [24, 64], [64]], "inception_4b/"))
    model.add(inception_module(512, [[128], [128, 256], [24, 64], [64]], "inception_4c/"))
    model.add(inception_module(512, [[112], [144, 288], [32, 64], [64]], "inception_4d/"))
    model.add(inception_module(528, [[256], [160, 320], [32, 128], [128]], "inception_4e/"))
    model.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    model.add(inception_module(832, [[256], [160, 320], [32, 128], [128]], "inception_5a/"))
    model.add(inception_module(832, [[384], [192, 384], [48, 128], [128]], "inception_5b/"))
    model.add(nn.GlobalAveragePooling2D())
    if has_dropout:
        model.add(nn.Dropout(0.4))
    model.add(nn.Linear(1024, class_num, weight_init=Xavier()).set_name("loss3/classifier"))
    model.add(nn.LogSoftMax())
    return model
