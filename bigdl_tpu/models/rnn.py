"""PTB-style recurrent language model.

Reference: ``DL/models/rnn/SimpleRNN.scala`` (LookupTable -> recurrent
stack -> TimeDistributed Linear -> LogSoftMax over time),
``DL/example/languagemodel/PTBModel.scala`` (the LSTM LM variant) and
``Train.scala`` (TimeDistributedCriterion(CrossEntropy) loss).
"""

from __future__ import annotations

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.layers.recurrent import LSTMCell, MultiRNNCell, Recurrent, RnnCell, TimeDistributed


def build_simple_rnn(vocab_size: int = 4000, hidden_size: int = 40,
                     class_num: int = 4000) -> nn.Sequential:
    """reference ``SimpleRNN.scala`` (embedding + vanilla RNN + softmax)."""
    return nn.Sequential(
        nn.LookupTable(vocab_size, hidden_size),
        Recurrent(RnnCell(hidden_size, hidden_size)),
        TimeDistributed(nn.Linear(hidden_size, class_num)),
        nn.LogSoftMax(),
    )


def build_ptb_lstm(vocab_size: int = 10000, embed_size: int = 650,
                   hidden_size: int = 650, num_layers: int = 2,
                   dropout: float = 0.5, class_num: int = 0) -> nn.Sequential:
    """PTB LSTM LM (reference ``PTBModel.scala``): embedding, stacked LSTM,
    per-timestep projection."""
    class_num = class_num or vocab_size
    cells = [LSTMCell(embed_size if i == 0 else hidden_size, hidden_size)
             for i in range(num_layers)]
    model = nn.Sequential(
        nn.LookupTable(vocab_size, embed_size),
        nn.Dropout(dropout),
        Recurrent(MultiRNNCell(cells)),
        nn.Dropout(dropout),
        TimeDistributed(nn.Linear(hidden_size, class_num)),
        nn.LogSoftMax(),
    )
    return model
