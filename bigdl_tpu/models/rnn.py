"""PTB-style recurrent language model.

Reference: ``DL/models/rnn/SimpleRNN.scala`` (LookupTable -> recurrent
stack -> TimeDistributed Linear -> LogSoftMax over time),
``DL/example/languagemodel/PTBModel.scala`` (the LSTM LM variant) and
``Train.scala`` (TimeDistributedCriterion(CrossEntropy) loss).
"""

from __future__ import annotations

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.layers.recurrent import LSTMCell, MultiRNNCell, Recurrent, RnnCell, TimeDistributed


def build_simple_rnn(vocab_size: int = 4000, hidden_size: int = 40,
                     class_num: int = 4000) -> nn.Sequential:
    """reference ``SimpleRNN.scala`` (embedding + vanilla RNN + softmax)."""
    return nn.Sequential(
        nn.LookupTable(vocab_size, hidden_size),
        Recurrent(RnnCell(hidden_size, hidden_size)),
        TimeDistributed(nn.Linear(hidden_size, class_num)),
        nn.LogSoftMax(),
    )


def build_ptb_lstm(vocab_size: int = 10000, embed_size: int = 650,
                   hidden_size: int = 650, num_layers: int = 2,
                   dropout: float = 0.5, class_num: int = 0) -> nn.Sequential:
    """PTB LSTM LM (reference ``PTBModel.scala``): embedding, stacked LSTM,
    per-timestep projection."""
    class_num = class_num or vocab_size
    cells = [LSTMCell(embed_size if i == 0 else hidden_size, hidden_size)
             for i in range(num_layers)]
    model = nn.Sequential(
        nn.LookupTable(vocab_size, embed_size),
        nn.Dropout(dropout),
        Recurrent(MultiRNNCell(cells)),
        nn.Dropout(dropout),
        TimeDistributed(nn.Linear(hidden_size, class_num)),
        nn.LogSoftMax(),
    )
    return model


def ptb_windows(stream, seq_len: int):
    """Token stream -> (inputs (N, T), targets (N, T)) next-token pairs."""
    import numpy as np

    n = (len(stream) - 1) // seq_len
    x = stream[: n * seq_len].reshape(n, seq_len)
    y = stream[1 : n * seq_len + 1].reshape(n, seq_len)
    return x.astype(np.int32), y.astype(np.int32)


def main(argv=None):
    """Train CLI (reference: ``rnn/Train.scala`` PTB LM with
    TimeDistributedCriterion(CrossEntropy))."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.datasets import load_ptb
    from bigdl_tpu.models.cli import fit, make_parser
    from bigdl_tpu.optim import Adagrad, optimizer

    parser = make_parser("rnn-train", batch_size=20, max_epoch=2,
                         learning_rate=0.1,
                         folder_help="ptb dir (synthetic stream if absent)")
    parser.add_argument("--seqLength", type=int, default=20)
    parser.add_argument("--vocabSize", type=int, default=1000)
    parser.add_argument("--hiddenSize", type=int, default=64)
    parser.add_argument("--numLayers", type=int, default=1)
    parser.add_argument("--idsFile", default=None,
                        help=".npy int32 token-id stream (overrides --folder; "
                             "used by examples/language_model.py)")
    args = parser.parse_args(argv)

    if args.idsFile:
        stream = np.load(args.idsFile).astype(np.int32)
    else:
        stream = load_ptb(args.folder, "train", vocab_size=args.vocabSize)
    vocab = int(stream.max()) + 1
    x, y = ptb_windows(stream, args.seqLength)
    ds = DataSet.tensors(x, y)

    model = build_ptb_lstm(vocab, args.hiddenSize, args.hiddenSize,
                           args.numLayers, dropout=0.0)
    criterion = nn.TimeDistributedCriterion(
        nn.ClassNLLCriterion(), size_average=True)
    opt = optimizer(model, ds, criterion, batch_size=args.batchSize)
    opt.set_optim_method(Adagrad(learning_rate=args.learningRate))
    return fit(opt, args)


if __name__ == "__main__":
    main()
