"""AlexNet (OWT variant + the original grouped/LRN variant).

Reference: ``DL/example/loadmodel/AlexNet.scala`` — ``AlexNet_OWT``
("one weird trick" single-tower layout used by the loadmodel example)
and ``AlexNet`` (the original Caffe layout with LRN and grouped convs).
"""

from __future__ import annotations

from bigdl_tpu.core.rng import np_rng
import bigdl_tpu.nn as nn


def build_owt(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """AlexNet-OWT (reference ``AlexNet_OWT.apply``); input 3x224x224."""
    model = nn.Sequential(
        nn.SpatialConvolution(3, 64, 11, 11, 4, 4, 2, 2).set_name("conv1"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"),
        nn.SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2).set_name("conv2"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"),
        nn.SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1).set_name("conv3"),
        nn.ReLU(),
        nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1).set_name("conv4"),
        nn.ReLU(),
        nn.SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1).set_name("conv5"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"),
        nn.View(256 * 6 * 6),
        nn.Linear(256 * 6 * 6, 4096).set_name("fc6"),
        nn.ReLU(),
    )
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, 4096).set_name("fc7"))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, class_num).set_name("fc8"))
    model.add(nn.LogSoftMax())
    return model


def build(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    """Original AlexNet (reference ``AlexNet.apply``): LRN after the
    first two stages, grouped conv2/4/5; input 3x227x227."""
    model = nn.Sequential(
        nn.SpatialConvolution(3, 96, 11, 11, 4, 4).set_name("conv1"),
        nn.ReLU(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm1"),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"),
        nn.SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, n_group=2).set_name("conv2"),
        nn.ReLU(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm2"),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"),
        nn.SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1).set_name("conv3"),
        nn.ReLU(),
        nn.SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, n_group=2).set_name("conv4"),
        nn.ReLU(),
        nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, n_group=2).set_name("conv5"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"),
        nn.View(256 * 6 * 6),
        nn.Linear(256 * 6 * 6, 4096).set_name("fc6"),
        nn.ReLU(),
    )
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, 4096).set_name("fc7"))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, class_num).set_name("fc8"))
    model.add(nn.LogSoftMax())
    return model


def main(argv=None):
    """Train CLI on synthetic ImageNet-shaped data (reference: the
    loadmodel example consumes AlexNet for validation; a Train main is
    provided for recipe parity with the other zoo models)."""
    import numpy as np

    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.models.cli import fit, make_parser
    from bigdl_tpu.optim import SGD, optimizer

    parser = make_parser("alexnet-train", batch_size=64, max_epoch=2,
                         learning_rate=0.01,
                         folder_help="unused (synthetic data)")
    parser.add_argument("--variant", choices=["owt", "original"], default="owt")
    parser.add_argument("--classNum", type=int, default=1000)
    args = parser.parse_args(argv)

    size = 224 if args.variant == "owt" else 227
    model = (build_owt if args.variant == "owt" else build)(args.classNum)
    rng = np_rng(0)
    x = rng.random((4 * args.batchSize, 3, size, size)).astype("float32")
    y = rng.integers(0, args.classNum, (4 * args.batchSize,)).astype("int32")
    ds = DataSet.tensors(x, y)

    opt = optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=args.batchSize)
    opt.set_optim_method(SGD(learning_rate=args.learningRate, momentum=0.9))
    return fit(opt, args)


if __name__ == "__main__":
    main()
