"""LeNet-5 on MNIST.

Reference: ``DL/models/lenet/LeNet5.scala`` (Sequential, graph and
dnnGraph variants), ``Train.scala`` (scopt CLI: batchSize, maxEpoch,
checkpoint, optim state resume), ``Test.scala``.
"""

from __future__ import annotations

import argparse
import logging

import bigdl_tpu.nn as nn


def build(class_num: int = 10) -> nn.Sequential:
    """Sequential LeNet-5 (reference: ``LeNet5.apply``)."""
    return nn.Sequential(
        nn.Reshape([1, 28, 28]),
        nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([12 * 4 * 4]),
        nn.Linear(12 * 4 * 4, 100).set_name("fc1"),
        nn.Tanh(),
        nn.Linear(100, class_num).set_name("fc2"),
        nn.LogSoftMax(),
    )


def build_graph(class_num: int = 10) -> nn.Graph:
    """Graph variant (reference: ``LeNet5.graph``)."""
    inp = nn.Input()
    x = nn.Reshape([1, 28, 28])(inp)
    x = nn.SpatialConvolution(1, 6, 5, 5)(x)
    x = nn.Tanh()(x)
    x = nn.SpatialMaxPooling(2, 2, 2, 2)(x)
    x = nn.SpatialConvolution(6, 12, 5, 5)(x)
    x = nn.Tanh()(x)
    x = nn.SpatialMaxPooling(2, 2, 2, 2)(x)
    x = nn.Reshape([12 * 4 * 4])(x)
    x = nn.Linear(12 * 4 * 4, 100)(x)
    x = nn.Tanh()(x)
    x = nn.Linear(100, class_num)(x)
    out = nn.LogSoftMax()(x)
    return nn.Graph(inp, out)


def mnist_train_pipeline(folder=None, batch_size=128, train=True):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.datasets import (
        MNIST_TRAIN_MEAN,
        MNIST_TRAIN_STD,
        load_mnist,
    )

    x, y = load_mnist(folder, train=train)
    x = (x - MNIST_TRAIN_MEAN) / MNIST_TRAIN_STD
    ds = DataSet.tensors(x[:, None].astype("float32"), y)
    if train:
        return ds >> SampleToMiniBatch(batch_size)
    return ds


def main(argv=None):
    """Train CLI (reference: ``lenet/Train.scala``)."""
    from bigdl_tpu.models.cli import fit, make_parser
    from bigdl_tpu.optim import SGD, Top1Accuracy, Trigger, optimizer

    parser = make_parser("lenet-train", batch_size=128, max_epoch=5,
                         learning_rate=0.05,
                         folder_help="mnist dir (synthetic if absent)")
    args = parser.parse_args(argv)

    model = build()
    criterion = nn.ClassNLLCriterion()
    train_ds = mnist_train_pipeline(args.folder, args.batchSize, train=True)
    val_ds = mnist_train_pipeline(args.folder, train=False)

    opt = optimizer(model, train_ds, criterion, batch_size=args.batchSize)
    opt.set_optim_method(SGD(learning_rate=args.learningRate, momentum=0.9))
    opt.set_validation(Trigger.every_epoch(), val_ds, [Top1Accuracy()], args.batchSize)
    return fit(opt, args)


if __name__ == "__main__":
    main()
