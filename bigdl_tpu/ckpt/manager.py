"""CheckpointManager: async, crash-consistent checkpointing.

The fault-tolerance tier above the format layer
(``bigdl_tpu/utils/checkpoint.py``). One manager owns one checkpoint
directory and provides:

- **async saves** — ``save()`` snapshots the device pytrees to host numpy
  on the calling thread (the cheap part: a device->host copy that must
  complete before the train step donates those buffers), then serializes
  and writes on a single background worker so the step loop never blocks
  on msgpack or disk. ``wait()``/``close()`` drain in-flight saves; a
  second ``save()`` of a tag still in flight raises
  :class:`CheckpointInFlightError`.
- **atomic, verified commits** — blob bytes go to ``<tag>.ckpt.tmp``,
  are fsynced, and renamed in; size + sha256 are then recorded in
  ``MANIFEST.json`` via write-staging-then-``os.replace``. A crash at any
  point leaves either the old or the new manifest — never a torn
  checkpoint — and an unreferenced blob is just garbage for the GC.
- **restore with fallback** — :meth:`restore_latest` verifies each
  manifest entry (size + sha256 + deserialization) newest-first and falls
  back to the previous committed entry on corruption instead of raising.
- **retention** — keep-last-N plus keep-every-K-steps GC of blobs,
  sidecars, and stale staging files, applied after each commit.
- **preemption** — :meth:`install_preemption_hook` registers a SIGTERM
  (by default) handler that only sets a flag; the training loop polls
  :attr:`preemption_requested` at step boundaries and saves with
  ``preempted=True``, which marks the manifest entry so a resuming run
  can tell an intentional milestone from an eviction save.

Reference: the driver checkpoint that blocks between iterations
(``AbstractOptimizer.scala:205``) and the retry window that trusts an
mtime scan (``DistriOptimizer.scala:881-960, :986``); the async-snapshot /
verified-commit design follows Orbax's async checkpointing and Meta's
Check-N-Run (PAPERS.md) — on TPUs preemption is the dominant failure mode
and blocking saves the dominant checkpoint cost, and both are avoidable.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from bigdl_tpu import faults
from bigdl_tpu.ckpt.manifest import (
    ManifestEntry,
    apply_retention,
    fsync_dir,
    load_manifest,
    sha256_bytes,
    shard_files,
    verify_shards,
    write_manifest,
)
from bigdl_tpu.faults import RetryPolicy
from bigdl_tpu.obs.recorder import record_event
from bigdl_tpu.utils.checkpoint import (
    deserialize_payload,
    latest_checkpoint,
    load_checkpoint,
    serialize_payload,
)

log = logging.getLogger("bigdl_tpu.ckpt")


class CheckpointInFlightError(RuntimeError):
    """A save of this tag is already being written."""


class SaveHandle:
    """Handle for one (possibly in-flight) save."""

    def __init__(self, tag: str, future: "Future[ManifestEntry]"):
        self.tag = tag
        self._future = future

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout: Optional[float] = None) -> ManifestEntry:
        """Block until committed; returns the manifest entry (or raises the
        worker's exception)."""
        return self._future.result(timeout)


def _host_snapshot(tree):
    """Device->host copy on the CALLING thread. This must finish before
    returning: the train loop donates the param/state buffers to the next
    step, and a donated jax array read later from the worker thread would
    be a use-after-free. numpy leaves pass through by reference (already
    immutable-by-contract once handed to save)."""
    from bigdl_tpu.utils.checkpoint import _to_numpy

    return _to_numpy(tree)


class CheckpointManager:
    """Front door for fault-tolerant checkpointing of one directory.

    Thread model: ``save()`` may be called from any single training
    thread; serialization, writes, manifest commits, and GC all run on one
    worker thread, so commits are ordered and GC never races a write.
    """

    def __init__(
        self,
        directory: str,
        *,
        keep_last_n: Optional[int] = None,
        keep_every_k_steps: Optional[int] = None,
        async_save: bool = True,
        fsync: bool = True,
        max_pending: int = 2,
        retry: Optional[RetryPolicy] = None,
    ):
        self.directory = str(directory)
        self.keep_last_n = keep_last_n
        self.keep_every_k_steps = keep_every_k_steps
        self.async_save = async_save
        self.fsync = fsync
        # transient-IO healing: checkpoint directories live on network
        # filesystems where EIO-class hiccups are routine, and a dropped
        # save silently shortens the fallback chain. Blob and manifest
        # writes retry OSError-class failures on this policy (bounded,
        # capped backoff, deterministic jitter); exhaustion still fails
        # the save LOUDLY — the existing verified-fallback chain and the
        # wait()/close() error surfacing are untouched.
        self.retry = retry or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=2.0,
            transient=(OSError,))
        # backpressure bound: each queued save holds a full host snapshot
        # of params+state, so an unbounded queue on a slow disk would eat
        # host memory one model-copy per trigger until OOM; past the bound
        # save() blocks on the oldest commit (Orbax does the same)
        self.max_pending = max(1, int(max_pending))
        os.makedirs(self.directory, exist_ok=True)
        # the writer pool is created on first save and torn down by wait()
        # once fully drained: a drained manager holds no idle ckpt-writer
        # thread, so optimize()-style callers that wait() at the end leave
        # nothing behind (the concurrency sanitizer enforces this)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._lock = threading.Lock()
        self._inflight: Dict[str, SaveHandle] = {}
        self._closed = False
        self._preempted = threading.Event()
        self._prev_handlers: List[Tuple[int, Any]] = []
        # obs-tier counters: committed/failed saves and verification
        # fallbacks during restore, surfaced via snapshot() into the
        # metrics registry (the manifest itself stays the durable truth)
        self.commits = 0
        self.commit_failures = 0
        self.restores = 0
        self.restore_fallbacks = 0  # manifest entries skipped (corrupt)

    def _ensure_pool(self) -> ThreadPoolExecutor:
        """Create the single-worker writer pool on demand (caller must
        hold ``self._lock``)."""
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer")
        return self._pool

    # ------------------------------------------------------------- save --
    def save(
        self,
        tag: str,
        params: Any,
        module_state: Any = None,
        optim_state: Any = None,
        meta: Optional[Dict[str, Any]] = None,
        *,
        step: Optional[int] = None,
        blocking: Optional[bool] = None,
        preempted: bool = False,
    ) -> SaveHandle:
        """Snapshot now, commit in the background. Returns a handle;
        ``blocking=True`` (or ``async_save=False``) waits for the commit."""
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        meta = dict(meta or {})
        if step is None:
            step = int(meta.get("iteration", 0))
        while True:  # backpressure BEFORE snapshotting (caps peak memory)
            with self._lock:
                pending = [h for h in self._inflight.values() if not h.done()]
            if len(pending) < self.max_pending:
                break
            try:
                pending[0].result()  # block on the oldest in-flight commit
            except Exception:
                pass  # surfaced by wait()/the failing handle's owner
        snapshot = {
            "params": _host_snapshot(params),
            "module_state": _host_snapshot(module_state or {}),
            "optim_state": _host_snapshot(optim_state or {}),
        }
        with self._lock:
            live = self._inflight.get(tag)
            if live is not None and not live.done():
                raise CheckpointInFlightError(
                    f"checkpoint '{tag}' already has a save in flight")
            # prune handles that committed cleanly (tags are unique per
            # step, so a long run would otherwise hold one dead handle per
            # save); failed ones stay so wait() still surfaces the error
            for t in [t for t, h in self._inflight.items()
                      if h.done() and h._future.exception() is None]:
                del self._inflight[t]
            future = self._ensure_pool().submit(
                self._commit, tag, snapshot, meta, step, preempted)
            handle = SaveHandle(tag, future)
            self._inflight[tag] = handle
        if blocking or (blocking is None and not self.async_save):
            handle.result()
        return handle

    def _commit(self, tag, snapshot, meta, step, preempted) -> ManifestEntry:
        try:
            entry = self._commit_inner(tag, snapshot, meta, step, preempted)
        except BaseException as e:
            with self._lock:
                self.commit_failures += 1
            record_event("ckpt.commit_failed", tag=tag, step=int(step),
                         error=type(e).__name__)
            raise
        with self._lock:
            self.commits += 1
        record_event("ckpt.commit", tag=tag, step=entry.step,
                     preempted=entry.preempted)
        return entry

    def _commit_inner(self, tag, snapshot, meta, step,
                      preempted) -> ManifestEntry:
        blob = serialize_payload(snapshot["params"], snapshot["module_state"],
                                 snapshot["optim_state"])
        meta.setdefault("wall_time", time.time())
        final = os.path.join(self.directory, f"{tag}.ckpt")
        tmp = final + ".tmp"

        def write_blob():
            # retried as ONE unit: the sequence is idempotent (same
            # bytes, staged then atomically replaced), so a transient
            # EIO on any line restarts it cleanly
            faults.fire("ckpt.blob_write", tag=tag)
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                if self.fsync:
                    os.fsync(fh.fileno())
            os.replace(tmp, final)
            # legacy sidecar: keeps latest_checkpoint()/load_checkpoint()
            # able to read a manager directory without the manifest
            side_tmp = final[: -len(".ckpt")] + ".meta.json.tmp"
            with open(side_tmp, "w") as fh:
                json.dump(meta, fh)
                fh.flush()
                if self.fsync:
                    os.fsync(fh.fileno())
            os.replace(side_tmp, final[: -len(".ckpt")] + ".meta.json")
            if self.fsync:
                fsync_dir(self.directory)

        self.retry.call(write_blob,
                        describe=f"checkpoint '{tag}' blob write")

        entry = ManifestEntry(
            tag=tag, file=os.path.basename(final), step=int(step),
            size=len(blob), sha256=sha256_bytes(blob),
            wall_time=float(meta["wall_time"]), meta=meta,
            preempted=bool(preempted),
        )
        entries = load_manifest(self.directory)
        if not entries:
            # first commit into a pre-manifest directory: adopt the legacy
            # single-file checkpoints into the manifest (hashing them once)
            # so they join the verified fallback chain and the retention
            # policy, instead of being GC'd as unreferenced orphans
            entries = self._adopt_legacy_entries(exclude=entry.file)
        entries = [e for e in entries if e.tag != tag]
        entries.append(entry)
        kept = apply_retention(entries, self.keep_last_n,
                               self.keep_every_k_steps)

        def write_mf():
            faults.fire("ckpt.manifest_write", tag=tag)
            write_manifest(self.directory, kept, fsync=self.fsync)

        # the write stages then os.replace()s, so a transient failure on
        # any attempt leaves the OLD manifest intact — retrying is safe
        self.retry.call(write_mf,
                        describe=f"checkpoint '{tag}' manifest write")
        # per-shard blobs (multi-host entries) are live data: reference
        # them so the orphan sweep can never eat another host's shard
        self._gc(referenced={k.file for k in kept} | shard_files(kept))
        return entry

    def _adopt_legacy_entries(self, exclude: str) -> List[ManifestEntry]:
        adopted = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return []
        for name in names:
            if not name.endswith(".ckpt") or name == exclude:
                continue
            side = os.path.join(self.directory,
                                name[: -len(".ckpt")] + ".meta.json")
            blob_path = os.path.join(self.directory, name)
            try:
                with open(side) as fh:
                    meta = json.load(fh)
                with open(blob_path, "rb") as fh:
                    blob = fh.read()
            except (OSError, ValueError):
                continue  # sidecar-less or unreadable: a torn legacy save
            adopted.append(ManifestEntry(
                tag=name[: -len(".ckpt")], file=name,
                step=int(meta.get("iteration", 0)), size=len(blob),
                sha256=sha256_bytes(blob),
                wall_time=float(meta.get("wall_time", 0.0)), meta=meta))
        adopted.sort(key=lambda e: (e.step, e.wall_time))
        if adopted:
            log.info("adopted %d legacy checkpoint(s) into the manifest",
                     len(adopted))
        return adopted

    def _gc(self, referenced) -> None:
        """Remove every blob/sidecar the manifest doesn't reference, and
        any stale staging files. Covers retention-dropped entries AND
        orphans from a crash between blob rename and manifest replace —
        once a manifest exists, unreferenced blobs are unreachable through
        restore_latest(), so they are pure garbage. Runs on the worker
        thread AFTER the manifest commit, so a crash during GC only leaves
        extra files, never a manifest pointing at a deleted blob. No other
        write is concurrent (single worker), so every ``*.tmp`` seen here
        is a dead survivor."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            stale = (
                name.endswith(".tmp")
                or (name.endswith(".ckpt") and name not in referenced)
                or (name.endswith(".meta.json")
                    and name[: -len(".meta.json")] + ".ckpt" not in referenced)
            )
            if stale:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    # ---------------------------------------------------------- restore --
    def restore_latest(
        self, template: Optional[Dict[str, Any]] = None,
    ) -> Optional[Tuple[Dict[str, Any], ManifestEntry]]:
        """Newest verifiable checkpoint as ``(payload, entry)``, walking
        back through the manifest on corruption; None when nothing is
        restorable. Payload keys: params / module_state / optim_state."""
        from bigdl_tpu.ckpt.manifest import verify_entry

        self.wait(raise_errors=False)  # an in-flight commit may be newest
        entries = load_manifest(self.directory)
        for entry in reversed(entries):
            blob = verify_entry(self.directory, entry)
            if blob is None:
                with self._lock:
                    self.restore_fallbacks += 1
                record_event("ckpt.fallback", tag=entry.tag,
                             why="blob_verification")
                log.warning(
                    "checkpoint '%s' failed verification (missing, "
                    "truncated, or checksum mismatch); falling back to the "
                    "previous manifest entry", entry.tag)
                continue
            if not verify_shards(self.directory, entry):
                # a sharded entry restores only when EVERY host shard
                # verifies — one torn shard fails the whole entry over
                with self._lock:
                    self.restore_fallbacks += 1
                record_event("ckpt.fallback", tag=entry.tag,
                             why="shard_verification")
                log.warning(
                    "checkpoint '%s' has a missing or corrupt per-host "
                    "shard; falling back to the previous manifest entry",
                    entry.tag)
                continue
            try:
                payload = deserialize_payload(blob, template)
            except Exception as e:
                # the sha256 already proved these are the exact bytes we
                # wrote, so this is a template/structure mismatch (model or
                # optim-method change), not corruption — every other entry
                # would fail identically, and silently walking back would
                # end in a from-scratch restart that GCs the user's
                # progress. Raise the config error loudly instead.
                raise ValueError(
                    f"checkpoint '{entry.tag}' passed checksum "
                    "verification but does not deserialize against the "
                    "provided template — structure/config mismatch (e.g. "
                    "a different model or optim method), not disk "
                    "corruption") from e
            with self._lock:
                self.restores += 1
            return payload, entry
        if entries:
            # every manifest entry failed verification: do NOT fall through
            # to the unverified scan — it would happily return the same
            # corrupt blob the checksum walk just rejected
            log.error("no manifest entry in %s survived verification",
                      self.directory)
            return None
        # pre-manifest directory (written by the legacy single-file layer):
        # fall back to the unverified mtime scan so old runs stay resumable
        legacy = latest_checkpoint(self.directory)
        if legacy is not None:
            try:
                payload, meta = load_checkpoint(legacy, template)
            except Exception:
                log.warning("legacy checkpoint %s unreadable", legacy,
                            exc_info=True)
                return None
            tag = os.path.basename(legacy)[: -len(".ckpt")]
            entry = ManifestEntry(
                tag=tag, file=os.path.basename(legacy),
                step=int(meta.get("iteration", 0)), size=-1, sha256="",
                wall_time=float(meta.get("wall_time", 0.0)), meta=meta)
            return payload, entry
        return None

    # ------------------------------------------------------- lifecycle --
    def wait(self, raise_errors: bool = True) -> None:
        """Drain every in-flight save. With ``raise_errors=False`` failed
        saves are logged (never silently dropped) instead of raised."""
        with self._lock:
            handles = list(self._inflight.values())
        first_error = None
        for h in handles:
            try:
                h.result()
            except Exception as e:
                log.error("checkpoint '%s' failed to commit: %s", h.tag, e)
                if first_error is None:
                    first_error = e
        with self._lock:
            for tag in [t for t, h in self._inflight.items() if h.done()]:
                del self._inflight[tag]
            # fully drained: release the idle writer thread. save() holds
            # this same lock to submit, so nothing can enqueue between the
            # emptiness check and the swap; the next save() re-creates the
            # pool. Joined outside the lock — the worker is idle, but
            # _commit's error path takes self._lock.
            pool = None
            if not self._inflight and self._pool is not None:
                pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)
        if first_error is not None and raise_errors:
            raise first_error

    def close(self) -> None:
        """Drain, release the worker, and uninstall any signal hooks.
        Errors from in-flight saves are logged, not raised — close() runs
        on shutdown paths where raising would mask the original failure."""
        if self._closed:
            return
        self._closed = True
        try:
            self.wait(raise_errors=False)
        finally:
            with self._lock:
                pool, self._pool = self._pool, None
            if pool is not None:
                pool.shutdown(wait=True)
            self.uninstall_preemption_hook()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def mark_preempted(self, tag: str) -> None:
        """Flip an existing entry's ``preempted`` flag via a manifest-only
        rewrite. This is the cheap path when preemption lands on a step
        whose blob is already committed: milliseconds, vs re-snapshotting
        and re-writing a potentially multi-GB blob inside the eviction
        grace window. Runs on the writer thread (ordered after any
        in-flight commit) and blocks until durable."""
        def _mark():
            entries = load_manifest(self.directory)
            for e in entries:
                if e.tag == tag:
                    e.preempted = True
            # eviction-window write: transient-IO healing matters MOST
            # here (no second chance after the grace period)
            self.retry.call(
                lambda: write_manifest(self.directory, entries,
                                       fsync=self.fsync),
                describe=f"preemption mark for '{tag}'")

        with self._lock:
            fut = self._ensure_pool().submit(_mark)
        fut.result()

    # ------------------------------------------------------ preemption --
    @property
    def preemption_requested(self) -> bool:
        return self._preempted.is_set()

    def request_preemption(self) -> None:
        """Manually request a preemption save (what the signal hook does)."""
        self._preempted.set()

    def clear_preemption(self) -> None:
        self._preempted.clear()

    def install_preemption_hook(self, signals=(signal.SIGTERM,)) -> bool:
        """Arm SIGTERM (TPU eviction notice) to request an immediate save
        at the next step boundary. Only a flag is set in the handler —
        everything else (snapshot, write, manifest) happens on normal
        threads, because signal context allows almost nothing safely.
        Returns False (with a warning) off the main thread, where CPython
        forbids installing handlers."""
        try:
            for sig in signals:
                prev = signal.signal(sig, self._on_signal)
                self._prev_handlers.append((sig, prev))
        except ValueError:
            log.warning("cannot install preemption hook off the main "
                        "thread; call request_preemption() instead")
            return False
        return True

    def uninstall_preemption_hook(self) -> None:
        while self._prev_handlers:
            sig, prev = self._prev_handlers.pop()
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass

    def _on_signal(self, signum, frame) -> None:
        self._preempted.set()

    # -------------------------------------------------------- queries --
    def snapshot(self) -> Dict[str, Any]:
        """Registry-friendly gauges: commit/fallback counters, pending
        saves, and the healing policy's retry counts. Pure host state —
        no manifest read, so scraping cannot hit the disk."""
        with self._lock:
            pending = sum(1 for h in self._inflight.values()
                          if not h.done())
            return {"commits": self.commits,
                    "commit_failures": self.commit_failures,
                    "restores": self.restores,
                    "restore_fallbacks": self.restore_fallbacks,
                    "pending_saves": pending,
                    "preemption_requested": self._preempted.is_set(),
                    "retry": self.retry.snapshot()}

    def entries(self) -> List[ManifestEntry]:
        """Committed entries, oldest -> newest."""
        return load_manifest(self.directory)

    @property
    def last_step(self) -> Optional[int]:
        entries = load_manifest(self.directory)
        return entries[-1].step if entries else None
