"""Checkpoint manifest: the single source of truth for committed saves.

A checkpoint directory holds blobs (``<tag>.ckpt``, written by the format
layer in ``bigdl_tpu/utils/checkpoint.py``) plus one ``MANIFEST.json``
recording, per committed save, the blob name, its byte size, its sha256,
the training step, host counters, and a ``preempted`` flag. Every update
rewrites the whole manifest to a staging file, fsyncs, and ``os.replace``s
it over the old one — a crash at ANY point leaves either the previous or
the new manifest on disk, never a torn one, and a blob is only *committed*
once the manifest that references it has been replaced in. Blobs without a
manifest entry (a crash between blob rename and manifest replace) are
garbage, collected by the retention pass.

Reference: the driver's ``getLatestFile`` mtime scan
(``DistriOptimizer.scala:986``) trusted the filesystem listing; Check-N-Run
style verified checkpoints record size+checksum at commit so restore can
prove integrity instead of assuming it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

MANIFEST_NAME = "MANIFEST.json"
_VERSION = 1


@dataclasses.dataclass
class ManifestEntry:
    """One committed checkpoint.

    ``shards`` is the multi-host groundwork: when a checkpoint's leaves
    are written as per-host blobs (each host owning its mesh shard), the
    entry lists every shard as ``{"path", "size", "sha256"}`` relative to
    the directory, verified alongside the main blob at restore/hot-reload
    time. Single-writer saves leave it empty — the schema is the
    format-level prerequisite for sharded hot-reload, not a writer
    change."""

    tag: str
    file: str                     # blob basename, relative to the directory
    step: int
    size: int
    sha256: str
    wall_time: float
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    preempted: bool = False
    shards: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ManifestEntry":
        known = {f.name for f in dataclasses.fields(ManifestEntry)}
        return ManifestEntry(**{k: v for k, v in d.items() if k in known})


def sha256_bytes(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


def manifest_path(directory: str) -> str:
    return os.path.join(directory, MANIFEST_NAME)


def load_manifest(directory: str) -> List[ManifestEntry]:
    """Entries oldest -> newest; [] when the manifest is absent or its
    JSON is corrupt (the blobs may still be fine — the caller's legacy
    scan is the availability path of last resort). A manifest that EXISTS
    but cannot be read (EACCES/EIO) raises: treating it as absent would
    silently downgrade restore to the unverified legacy scan."""
    path = manifest_path(directory)
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return []
    except ValueError:
        return []
    entries = []
    for raw in doc.get("entries", []):
        try:
            entries.append(ManifestEntry.from_json(raw))
        except TypeError:
            continue  # unknown/partial entry from a future or corrupt writer
    return entries


def fsync_dir(directory: str) -> None:
    """Durability for the rename itself (POSIX: os.replace is atomic but
    only durable once the directory entry is synced)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds — best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_manifest(directory: str, entries: List[ManifestEntry],
                   fsync: bool = True) -> str:
    """Atomically replace the manifest with ``entries`` (oldest -> newest)."""
    path = manifest_path(directory)
    tmp = path + ".tmp"
    doc = {
        "version": _VERSION,
        "updated": time.time(),
        "entries": [e.to_json() for e in entries],
    }
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.flush()
        if fsync:
            os.fsync(fh.fileno())
    os.replace(tmp, path)
    if fsync:
        fsync_dir(directory)
    return path


def verify_entry(directory: str, entry: ManifestEntry) -> Optional[bytes]:
    """Return the blob bytes iff size and sha256 match; None otherwise."""
    path = os.path.join(directory, entry.file)
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except OSError:
        return None
    if len(blob) != entry.size or sha256_bytes(blob) != entry.sha256:
        return None
    return blob


def verify_shards(directory: str, entry: ManifestEntry) -> bool:
    """True iff EVERY per-shard blob the entry lists matches its recorded
    size and sha256 (vacuously true for shard-less entries). A sharded
    checkpoint is only as restorable as its worst shard, so restore and
    hot-reload gate on this alongside :func:`verify_entry` — one torn
    host shard fails the whole entry over to the previous commit."""
    for sh in entry.shards or []:
        try:
            path = os.path.join(directory, str(sh.get("path", "")))
            want_size = int(sh.get("size", -1))
            want_sha = sh.get("sha256")
            digest = hashlib.sha256()
            size = 0
            # hash in chunks: shards are the GB-scale objects here and
            # this runs on every restore and hot-reload poll — a full
            # read would spike RAM by the shard size just to discard it
            with open(path, "rb") as fh:
                while chunk := fh.read(1 << 20):
                    digest.update(chunk)
                    size += len(chunk)
        except (OSError, AttributeError, TypeError, ValueError):
            # unreadable blob OR malformed metadata (a corrupt/future
            # writer): both mean "this entry does not verify", never an
            # exception — callers use the bool to fall back an entry
            return False
        if size != want_size or digest.hexdigest() != want_sha:
            return False
    return True


def shard_files(entries: List[ManifestEntry]) -> set:
    """Every shard path referenced by ``entries`` (for the GC's
    referenced set — shards are live data, not orphans)."""
    out = set()
    for e in entries:
        for sh in e.shards or []:
            if not isinstance(sh, dict):
                continue  # malformed metadata: nothing referencable
            p = str(sh.get("path", ""))
            if p:
                out.add(p)
    return out


def apply_retention(
    entries: List[ManifestEntry],
    keep_last_n: Optional[int],
    keep_every_k_steps: Optional[int],
) -> List[ManifestEntry]:
    """Entries to KEEP (oldest -> newest). The newest entry is always kept;
    an entry survives if it is among the last N or its step is a multiple
    of K (the Check-N-Run "milestone" rule)."""
    if not entries:
        return []
    keep = set()
    if keep_last_n is None and keep_every_k_steps is None:
        return list(entries)
    n = keep_last_n if keep_last_n is not None else 1
    for e in entries[-max(1, n):]:
        keep.add(e.tag)
    if keep_every_k_steps:
        for e in entries:
            if e.step % keep_every_k_steps == 0 and e.step > 0:
                keep.add(e.tag)
    keep.add(entries[-1].tag)
    return [e for e in entries if e.tag in keep]
