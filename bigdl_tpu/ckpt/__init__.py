"""bigdl_tpu.ckpt — fault-tolerant checkpointing.

``CheckpointManager`` is the front door: async saves that never block the
step loop, atomic size+sha256-verified commits through ``MANIFEST.json``,
restore with fallback to the previous good checkpoint, keep-last-N /
keep-every-K retention, and SIGTERM preemption handling. The byte format
stays in ``bigdl_tpu/utils/checkpoint.py`` — both layers read each
other's files.
"""

from bigdl_tpu.ckpt.manager import (
    CheckpointInFlightError,
    CheckpointManager,
    SaveHandle,
)
from bigdl_tpu.ckpt.manifest import ManifestEntry, load_manifest

__all__ = [
    "CheckpointInFlightError",
    "CheckpointManager",
    "ManifestEntry",
    "SaveHandle",
    "load_manifest",
]
