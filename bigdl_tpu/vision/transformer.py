"""FeatureTransformer — per-feature transform with ``>>`` chaining.

Reference: ``DL/transform/vision/image/FeatureTransformer.scala`` (chains
via ``->``; failures logged and the feature passed through when
``ignoreImageException`` is set).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

from bigdl_tpu.core.rng import RandomGenerator
from bigdl_tpu.vision.image_frame import ImageFeature

log = logging.getLogger(__name__)


class FeatureTransformer:
    """Base class: override :meth:`transform_mat` (image-only transforms)
    or :meth:`transform` (full feature access)."""

    ignore_image_exception = False

    def transform_mat(self, feature: ImageFeature) -> None:
        """Mutate feature[MAT] in place (most augmentations)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        try:
            self.transform_mat(feature)
        except Exception:
            if not self.ignore_image_exception:
                raise
            log.exception("transformer %s failed; passing feature through",
                          type(self).__name__)
        return feature

    def __call__(self, feature: ImageFeature) -> ImageFeature:
        return self.transform(feature)

    def __rshift__(self, other: "FeatureTransformer") -> "ChainedFeatureTransformer":
        return ChainedFeatureTransformer([self, other])

    def apply_frame(self, frame):
        return frame.transform(self)


class ChainedFeatureTransformer(FeatureTransformer):
    """Reference: ``FeatureTransformer.->`` composition."""

    def __init__(self, transformers: Sequence[FeatureTransformer]):
        self.transformers = list(transformers)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        for t in self.transformers:
            feature = t(feature)
        return feature

    def __rshift__(self, other: FeatureTransformer) -> "ChainedFeatureTransformer":
        return ChainedFeatureTransformer(self.transformers + [other])


class RandomTransformer(FeatureTransformer):
    """Apply ``inner`` with probability ``prob`` (reference
    ``augmentation/RandomTransformer.scala``)."""

    def __init__(self, inner: FeatureTransformer, prob: float,
                 rng: Optional[RandomGenerator] = None):
        self.inner = inner
        self.prob = prob
        self.rng = rng or RandomGenerator.default()

    def transform(self, feature: ImageFeature) -> ImageFeature:
        if self.rng.numpy().random() < self.prob:
            return self.inner(feature)
        return feature


class Pipeline(ChainedFeatureTransformer):
    """Alias matching the reference python API naming (``Pipeline`` in
    ``PY/transform/vision/image.py``)."""
