"""Image -> tensor conversion (reference:
``DL/transform/vision/image/MatToTensor.scala``, ``ImageFrameToSample``)."""

from __future__ import annotations

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.vision.image_frame import ImageFeature
from bigdl_tpu.vision.transformer import FeatureTransformer


class MatToTensor(FeatureTransformer):
    """HWC float image -> CHW float32 tensor under feature['tensor']
    (reference ``MatToTensor.scala``; to_chw mirrors ``toRGB``/format
    knobs)."""

    def __init__(self, to_chw: bool = True, key: str = "tensor"):
        self.to_chw = to_chw
        self.key = key

    def transform(self, feature: ImageFeature) -> ImageFeature:
        img = np.asarray(feature.image, np.float32)
        if self.to_chw and img.ndim == 3:
            img = img.transpose(2, 0, 1)
        feature[self.key] = np.ascontiguousarray(img)
        return feature


class ImageFrameToSample(FeatureTransformer):
    """Pack feature['tensor'] (+ label) into a Sample under SAMPLE
    (reference ``ImageFrameToSample.scala``)."""

    def __init__(self, input_keys=("tensor",), target_keys=("label",)):
        self.input_keys = list(input_keys)
        self.target_keys = list(target_keys)

    def transform(self, feature: ImageFeature) -> ImageFeature:
        feats = [np.asarray(feature[k], np.float32) for k in self.input_keys]
        targets = [
            np.asarray(feature[k]) for k in self.target_keys
            if feature.get(k) is not None
        ]
        feature[ImageFeature.SAMPLE] = Sample(
            feats[0] if len(feats) == 1 else tuple(feats),
            (targets[0] if len(targets) == 1 else tuple(targets)) if targets else None,
        )
        return feature
