"""ROI label transforms — keep detection labels consistent with image
augmentation.

Reference: ``DL/transform/vision/image/label/roi/`` — ``RoiLabel`` (class
+ bbox (+ masks) ground truth), ``RoiNormalize``, ``RoiHFlip``,
``RoiResize``, ``RoiProject`` (crop/expand coordinate projection).
Boxes are (N, 4) xyxy pixel coordinates unless normalized.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigdl_tpu.vision.image_frame import ImageFeature
from bigdl_tpu.vision.transformer import FeatureTransformer

LABEL_KEY = "roi_label"


class RoiLabel:
    """Ground truth for one image (reference ``RoiLabel.scala``):
    ``classes`` (N,), ``bboxes`` (N, 4) xyxy, optional ``masks``
    (list of (H, W) binary arrays or polygon lists)."""

    def __init__(self, classes: np.ndarray, bboxes: np.ndarray, masks=None):
        self.classes = np.asarray(classes)
        self.bboxes = np.asarray(bboxes, np.float32).reshape(-1, 4)
        self.masks = masks

    def __len__(self):
        return len(self.classes)

    def copy(self) -> "RoiLabel":
        return RoiLabel(self.classes.copy(), self.bboxes.copy(),
                        None if self.masks is None else list(self.masks))


def attach_roi(feature: ImageFeature, label: RoiLabel) -> ImageFeature:
    feature[LABEL_KEY] = label
    return feature


class RoiNormalize(FeatureTransformer):
    """Pixel xyxy -> normalized [0, 1] (reference ``RoiNormalize.scala``)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        roi: Optional[RoiLabel] = feature.get(LABEL_KEY)
        if roi is not None:
            h, w = feature.image.shape[:2]
            roi = roi.copy()
            roi.bboxes[:, 0::2] /= w
            roi.bboxes[:, 1::2] /= h
            feature[LABEL_KEY] = roi
        return feature


class RoiHFlip(FeatureTransformer):
    """Mirror boxes (and masks) after HFlip (reference
    ``RoiHFlip.scala``). ``normalized`` selects coordinate space."""

    def __init__(self, normalized: bool = True):
        self.normalized = normalized

    def transform(self, feature: ImageFeature) -> ImageFeature:
        roi: Optional[RoiLabel] = feature.get(LABEL_KEY)
        if roi is not None:
            width = 1.0 if self.normalized else feature.image.shape[1]
            roi = roi.copy()
            x1 = roi.bboxes[:, 0].copy()
            roi.bboxes[:, 0] = width - roi.bboxes[:, 2]
            roi.bboxes[:, 2] = width - x1
            if roi.masks is not None:
                roi.masks = [np.asarray(m)[:, ::-1].copy() for m in roi.masks]
            feature[LABEL_KEY] = roi
        return feature


class RoiResize(FeatureTransformer):
    """Scale pixel boxes to the current image size after a Resize
    (reference ``RoiResize.scala``). Requires ORIGINAL_SIZE."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        roi: Optional[RoiLabel] = feature.get(LABEL_KEY)
        if roi is not None:
            oh, ow = feature[ImageFeature.ORIGINAL_SIZE][:2]
            h, w = feature.image.shape[:2]
            roi = roi.copy()
            roi.bboxes[:, 0::2] *= w / ow
            roi.bboxes[:, 1::2] *= h / oh
            if roi.masks is not None:
                from bigdl_tpu.vision.augmentation import resize_image

                roi.masks = [
                    (resize_image(np.asarray(m, np.float32), h, w) > 0.5)
                    for m in roi.masks
                ]
            feature[LABEL_KEY] = roi
        return feature


class RoiProject(FeatureTransformer):
    """Project boxes through a crop recorded in feature['crop_box']
    (reference ``RoiProject.scala``): shift, clip, drop empty boxes."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        roi: Optional[RoiLabel] = feature.get(LABEL_KEY)
        crop = feature.get("crop_box")
        if roi is not None and crop is not None:
            x1, y1, x2, y2 = crop
            roi = roi.copy()
            roi.bboxes[:, 0::2] = np.clip(roi.bboxes[:, 0::2] - x1, 0, x2 - x1)
            roi.bboxes[:, 1::2] = np.clip(roi.bboxes[:, 1::2] - y1, 0, y2 - y1)
            keep = ((roi.bboxes[:, 2] > roi.bboxes[:, 0]) &
                    (roi.bboxes[:, 3] > roi.bboxes[:, 1]))
            roi.bboxes = roi.bboxes[keep]
            roi.classes = roi.classes[keep]
            if roi.masks is not None:
                roi.masks = [m for m, k in zip(roi.masks, keep) if k]
            feature[LABEL_KEY] = roi
        return feature
