"""ImageFrame / ImageFeature — the vision pipeline's data model.

Reference: ``DL/transform/vision/image/ImageFrame.scala:36`` (trait with
``LocalImageFrame`` :185 / ``DistributedImageFrame`` :212) and
``ImageFeature.scala`` (a string-keyed hash of image/bytes/label/metadata).

TPU-native redesign: one host-side ``ImageFrame`` (a list of features —
the reference's Distributed variant is an RDD of the same thing; here
distribution happens at the batch-sharding level, not the container
level). Images are numpy HWC float32 arrays (the reference's OpenCV Mat);
PIL stands in for the JavaCPP OpenCV codec.
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np


class ImageFeature(dict):
    """String-keyed feature hash (reference ``ImageFeature.scala``).

    Well-known keys mirror the reference: ``bytes`` (raw file content),
    ``mat`` (decoded HWC float32 image), ``label``, ``uri``,
    ``original_size`` ((h, w, c) at decode time), ``size`` (current),
    ``sample`` (converted Sample), ``prediction``.
    """

    BYTES = "bytes"
    MAT = "mat"
    LABEL = "label"
    URI = "uri"
    ORIGINAL_SIZE = "original_size"
    SAMPLE = "sample"
    PREDICTION = "prediction"

    def __init__(self, image=None, label=None, uri: Optional[str] = None,
                 **kw):
        super().__init__(**kw)
        if image is not None:
            if isinstance(image, (bytes, bytearray)):
                self[self.BYTES] = bytes(image)
            else:
                mat = np.asarray(image)
                self[self.MAT] = mat
                self[self.ORIGINAL_SIZE] = mat.shape
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> Optional[np.ndarray]:
        return self.get(self.MAT)

    @image.setter
    def image(self, mat: np.ndarray) -> None:
        self[self.MAT] = mat

    @property
    def label(self):
        return self.get(self.LABEL)

    def size(self):
        """(h, w, c) of the current image (reference ``getSize``)."""
        mat = self.get(self.MAT)
        return None if mat is None else mat.shape

    def width(self) -> int:
        return self.size()[1]

    def height(self) -> int:
        return self.size()[0]


class ImageFrame:
    """A collection of ImageFeatures with ``transform`` chaining
    (reference ``ImageFrame.scala:36``; local variant :185)."""

    def __init__(self, features: Sequence[ImageFeature]):
        self.features: List[ImageFeature] = list(features)

    # -- constructors ------------------------------------------------------
    @staticmethod
    def read(path: str, with_label: bool = False) -> "ImageFrame":
        """Read image file(s) (reference ``ImageFrame.read``). ``path`` may
        be a file or a directory; with_label=True uses subdirectory names
        as integer class labels (ImageFolder convention)."""
        from PIL import Image

        feats = []
        if os.path.isdir(path):
            if with_label:
                classes = sorted(
                    d for d in os.listdir(path)
                    if os.path.isdir(os.path.join(path, d)))
                for ci, cls in enumerate(classes):
                    cdir = os.path.join(path, cls)
                    for fn in sorted(os.listdir(cdir)):
                        fp = os.path.join(cdir, fn)
                        img = np.asarray(Image.open(fp).convert("RGB"), np.float32)
                        feats.append(ImageFeature(img, label=ci, uri=fp))
            else:
                for fn in sorted(os.listdir(path)):
                    fp = os.path.join(path, fn)
                    img = np.asarray(Image.open(fp).convert("RGB"), np.float32)
                    feats.append(ImageFeature(img, uri=fp))
        else:
            img = np.asarray(Image.open(path).convert("RGB"), np.float32)
            feats.append(ImageFeature(img, uri=path))
        return ImageFrame(feats)

    @staticmethod
    def from_arrays(images: Iterable[np.ndarray], labels=None) -> "ImageFrame":
        labels = list(labels) if labels is not None else None
        feats = []
        for i, img in enumerate(images):
            feats.append(ImageFeature(
                np.asarray(img, np.float32),
                label=None if labels is None else labels[i]))
        return ImageFrame(feats)

    # -- transformation ----------------------------------------------------
    def transform(self, transformer) -> "ImageFrame":
        """Apply a FeatureTransformer to every feature (reference
        ``ImageFrame.transform``). Returns self for chaining."""
        self.features = [transformer(f) for f in self.features]
        return self

    def __rshift__(self, transformer) -> "ImageFrame":
        return self.transform(transformer)

    def __len__(self) -> int:
        return len(self.features)

    def __iter__(self):
        return iter(self.features)

    def __getitem__(self, i) -> ImageFeature:
        return self.features[i]

    # -- conversion --------------------------------------------------------
    def to_samples(self):
        """Collected Samples (features must have passed ImageFrameToSample)."""
        return [f[ImageFeature.SAMPLE] for f in self.features]

    def to_dataset(self):
        from bigdl_tpu.dataset.dataset import DataSet

        return DataSet.array(self.to_samples())
