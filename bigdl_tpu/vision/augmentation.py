"""Vision augmentation ops.

Reference: ``DL/transform/vision/image/augmentation/`` — Resize,
AspectScale, RandomAspectScale, CenterCrop/RandomCrop/FixedCrop, Expand,
HFlip, Brightness, Contrast, Saturation, Hue, ChannelOrder, ColorJitter,
Lighting, ChannelNormalize, ChannelScaledNormalizer, Filler,
PixelNormalizer. OpenCV Mats become numpy HWC float32 arrays; bilinear
resampling via scipy.ndimage (the JavaCPP-OpenCV codec/resize analogue).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.core.rng import RandomGenerator
from bigdl_tpu.vision.image_frame import ImageFeature
from bigdl_tpu.vision.transformer import FeatureTransformer


def resize_image(img: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear HWC resize (reference uses cv2.resize INTER_LINEAR)."""
    from scipy import ndimage

    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img.astype(np.float32, copy=False)
    zoom = (h / ih, w / iw) + (1,) * (img.ndim - 2)
    return ndimage.zoom(img.astype(np.float32), zoom, order=1,
                        grid_mode=True, mode="nearest")


class PixelBytesToMat(FeatureTransformer):
    """Decode feature[BYTES] into feature[MAT] (reference
    ``BytesToMat.scala``; PIL replaces the OpenCV codec)."""

    def transform(self, feature: ImageFeature) -> ImageFeature:
        import io

        from PIL import Image

        raw = feature[ImageFeature.BYTES]
        img = np.asarray(Image.open(io.BytesIO(raw)).convert("RGB"), np.float32)
        feature[ImageFeature.MAT] = img
        feature[ImageFeature.ORIGINAL_SIZE] = img.shape
        return feature


class Resize(FeatureTransformer):
    """Resize to (resize_h, resize_w) (reference ``Resize.scala``)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def transform_mat(self, feature: ImageFeature) -> None:
        feature.image = resize_image(feature.image, self.h, self.w)


class AspectScale(FeatureTransformer):
    """Scale so the short side is ``min_size`` capped by ``max_size``,
    preserving aspect (reference ``AspectScale.scala``; the Mask R-CNN
    preprocessing scale)."""

    def __init__(self, min_size: int, max_size: int = 1000,
                 scale_multiple_of: int = 1):
        self.min_size = min_size
        self.max_size = max_size
        self.multiple = scale_multiple_of

    def _target(self, h: int, w: int) -> Tuple[int, int]:
        short, long = min(h, w), max(h, w)
        scale = self.min_size / short
        if long * scale > self.max_size:
            scale = self.max_size / long
        th, tw = int(round(h * scale)), int(round(w * scale))
        if self.multiple > 1:
            th = ((th + self.multiple - 1) // self.multiple) * self.multiple
            tw = ((tw + self.multiple - 1) // self.multiple) * self.multiple
        return th, tw

    def transform_mat(self, feature: ImageFeature) -> None:
        h, w = feature.image.shape[:2]
        th, tw = self._target(h, w)
        feature.image = resize_image(feature.image, th, tw)


class RandomAspectScale(AspectScale):
    """Pick min_size randomly from ``scales`` (reference
    ``RandomAspectScale.scala``)."""

    def __init__(self, scales: Sequence[int], max_size: int = 1000,
                 rng: Optional[RandomGenerator] = None):
        super().__init__(scales[0], max_size)
        self.scales = list(scales)
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        self.min_size = int(self.rng.numpy().choice(self.scales))
        super().transform_mat(feature)


class CenterCrop(FeatureTransformer):
    def __init__(self, crop_w: int, crop_h: int):
        self.cw, self.ch = crop_w, crop_h

    def transform_mat(self, feature: ImageFeature) -> None:
        h, w = feature.image.shape[:2]
        y = max(0, (h - self.ch) // 2)
        x = max(0, (w - self.cw) // 2)
        feature["crop_box"] = (x, y, x + self.cw, y + self.ch)
        feature.image = feature.image[y:y + self.ch, x:x + self.cw]


class RandomCrop(FeatureTransformer):
    def __init__(self, crop_w: int, crop_h: int,
                 rng: Optional[RandomGenerator] = None):
        self.cw, self.ch = crop_w, crop_h
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        h, w = feature.image.shape[:2]
        r = self.rng.numpy()
        y = int(r.integers(0, max(1, h - self.ch + 1)))
        x = int(r.integers(0, max(1, w - self.cw + 1)))
        feature["crop_box"] = (x, y, x + self.cw, y + self.ch)
        feature.image = feature.image[y:y + self.ch, x:x + self.cw]


class FixedCrop(FeatureTransformer):
    """Crop a fixed box, absolute pixels or normalized [0,1] coords
    (reference ``Crop.scala`` FixedCrop)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = False):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def transform_mat(self, feature: ImageFeature) -> None:
        h, w = feature.image.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        x1, y1, x2, y2 = (int(round(v)) for v in (x1, y1, x2, y2))
        feature["crop_box"] = (x1, y1, x2, y2)
        feature.image = feature.image[y1:y2, x1:x2]


class Expand(FeatureTransformer):
    """Place the image on a larger mean-filled canvas (reference
    ``Expand.scala``; SSD-style zoom-out augmentation)."""

    def __init__(self, means: Sequence[float] = (123.0, 117.0, 104.0),
                 max_expand_ratio: float = 4.0,
                 rng: Optional[RandomGenerator] = None):
        self.means = np.asarray(means, np.float32)
        self.max_ratio = max_expand_ratio
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        r = self.rng.numpy()
        ratio = float(r.uniform(1.0, self.max_ratio))
        h, w, c = feature.image.shape
        nh, nw = int(h * ratio), int(w * ratio)
        y = int(r.integers(0, nh - h + 1))
        x = int(r.integers(0, nw - w + 1))
        canvas = np.empty((nh, nw, c), np.float32)
        canvas[:] = self.means[:c]
        canvas[y:y + h, x:x + w] = feature.image
        feature["expand_offset"] = (x, y)
        feature["expand_ratio"] = ratio
        feature.image = canvas


class HFlip(FeatureTransformer):
    """Deterministic horizontal flip (reference ``HFlip.scala``); wrap in
    RandomTransformer for the usual coin toss."""

    def transform_mat(self, feature: ImageFeature) -> None:
        feature.image = feature.image[:, ::-1].copy()
        feature["flipped"] = True


class Brightness(FeatureTransformer):
    """Add a uniform delta (reference ``Brightness.scala``)."""

    def __init__(self, delta_low: float, delta_high: float,
                 rng: Optional[RandomGenerator] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        delta = float(self.rng.numpy().uniform(self.low, self.high))
        feature.image = feature.image + delta


class Contrast(FeatureTransformer):
    """Scale around zero (reference ``Contrast.scala``)."""

    def __init__(self, delta_low: float, delta_high: float,
                 rng: Optional[RandomGenerator] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        scale = float(self.rng.numpy().uniform(self.low, self.high))
        feature.image = feature.image * scale


def _rgb_to_hsv(img: np.ndarray) -> np.ndarray:
    x = img / 255.0
    mx = x.max(-1)
    mn = x.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) * 60
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    return np.stack([h, s, mx], -1)


def _hsv_to_rgb(hsv: np.ndarray) -> np.ndarray:
    h, s, v = hsv[..., 0] / 60.0, hsv[..., 1], hsv[..., 2]
    i = np.floor(h) % 6
    f = h - np.floor(h)
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    r = np.select([i == 0, i == 1, i == 2, i == 3, i == 4], [v, q, p, p, t], v)
    g = np.select([i == 0, i == 1, i == 2, i == 3, i == 4], [t, v, v, q, p], p)
    b = np.select([i == 0, i == 1, i == 2, i == 3, i == 4], [p, p, t, v, v], q)
    return np.stack([r, g, b], -1) * 255.0


class Saturation(FeatureTransformer):
    """Scale HSV saturation (reference ``Saturation.scala``)."""

    def __init__(self, delta_low: float, delta_high: float,
                 rng: Optional[RandomGenerator] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        scale = float(self.rng.numpy().uniform(self.low, self.high))
        hsv = _rgb_to_hsv(np.clip(feature.image, 0, 255))
        hsv[..., 1] = np.clip(hsv[..., 1] * scale, 0, 1)
        feature.image = _hsv_to_rgb(hsv)


class Hue(FeatureTransformer):
    """Rotate HSV hue by a uniform delta in degrees (reference
    ``Hue.scala``)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0,
                 rng: Optional[RandomGenerator] = None):
        self.low, self.high = delta_low, delta_high
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        delta = float(self.rng.numpy().uniform(self.low, self.high))
        hsv = _rgb_to_hsv(np.clip(feature.image, 0, 255))
        hsv[..., 0] = (hsv[..., 0] + delta) % 360
        feature.image = _hsv_to_rgb(hsv)


class ChannelOrder(FeatureTransformer):
    """Randomly permute channels (reference ``ChannelOrder.scala``)."""

    def __init__(self, rng: Optional[RandomGenerator] = None):
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        perm = self.rng.numpy().permutation(feature.image.shape[-1])
        feature.image = feature.image[..., perm]


class ColorJitter(FeatureTransformer):
    """Random brightness/contrast/saturation in random order (reference
    ``ColorJitter.scala``; also the ImageNet-recipe jitter)."""

    def __init__(self, brightness: float = 32.0, contrast: float = 0.5,
                 saturation: float = 0.5, shuffle: bool = True,
                 rng: Optional[RandomGenerator] = None):
        self.rng = rng or RandomGenerator.default()
        self.ops = [
            Brightness(-brightness, brightness, self.rng),
            Contrast(1 - contrast, 1 + contrast, self.rng),
            Saturation(1 - saturation, 1 + saturation, self.rng),
        ]
        self.shuffle = shuffle

    def transform(self, feature: ImageFeature) -> ImageFeature:
        order = (self.rng.numpy().permutation(len(self.ops))
                 if self.shuffle else range(len(self.ops)))
        for i in order:
            feature = self.ops[int(i)](feature)
        feature.image = np.clip(feature.image, 0, 255)
        return feature


class Lighting(FeatureTransformer):
    """AlexNet-style PCA lighting noise (reference ``Lighting.scala`` with
    the same ImageNet eigen decomposition constants)."""

    EIG_VAL = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    EIG_VEC = np.asarray([
        [-0.5675, 0.7192, 0.4009],
        [-0.5808, -0.0045, -0.8140],
        [-0.5836, -0.6948, 0.4203],
    ], np.float32)

    def __init__(self, alphastd: float = 0.1,
                 rng: Optional[RandomGenerator] = None):
        self.alphastd = alphastd
        self.rng = rng or RandomGenerator.default()

    def transform_mat(self, feature: ImageFeature) -> None:
        alpha = self.rng.numpy().normal(0.0, self.alphastd, 3).astype(np.float32)
        noise = (self.EIG_VEC * alpha * self.EIG_VAL).sum(axis=1)
        feature.image = feature.image + noise


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel (reference
    ``ChannelNormalize.scala``)."""

    def __init__(self, means: Sequence[float], stds: Sequence[float] = (1, 1, 1)):
        self.means = np.asarray(means, np.float32)
        self.stds = np.asarray(stds, np.float32)

    def transform_mat(self, feature: ImageFeature) -> None:
        feature.image = (feature.image - self.means) / self.stds


class ChannelScaledNormalizer(ChannelNormalize):
    """Mean subtraction + global scale (reference
    ``ChannelScaledNormalizer.scala``)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 scale: float = 1.0):
        super().__init__((mean_r, mean_g, mean_b))
        self.scale = scale

    def transform_mat(self, feature: ImageFeature) -> None:
        feature.image = (feature.image - self.means) * self.scale


class PixelNormalizer(FeatureTransformer):
    """Subtract a full per-pixel mean image (reference
    ``PixelNormalizer.scala``)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def transform_mat(self, feature: ImageFeature) -> None:
        feature.image = feature.image - self.means.reshape(feature.image.shape)


class Filler(FeatureTransformer):
    """Fill a (normalized-coordinate) region with a constant (reference
    ``Filler.scala``; random-erasing style)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 value: float = 255.0):
        self.box = (x1, y1, x2, y2)
        self.value = value

    def transform_mat(self, feature: ImageFeature) -> None:
        h, w = feature.image.shape[:2]
        x1, y1, x2, y2 = self.box
        img = feature.image.copy()
        img[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        feature.image = img
