"""Vision pipeline (reference: ``DL/transform/vision/`` — ImageFrame +
FeatureTransformer chains + OpenCV augmentation ops + ROI label
transforms, 4,591 LoC / 31 files)."""

from bigdl_tpu.vision.image_frame import ImageFeature, ImageFrame  # noqa: F401
from bigdl_tpu.vision.transformer import (  # noqa: F401
    ChainedFeatureTransformer, FeatureTransformer, Pipeline, RandomTransformer,
)
from bigdl_tpu.vision.augmentation import (  # noqa: F401
    AspectScale, Brightness, CenterCrop, ChannelNormalize, ChannelOrder,
    ChannelScaledNormalizer, ColorJitter, Contrast, Expand, Filler, FixedCrop,
    HFlip, Hue, Lighting, PixelBytesToMat, PixelNormalizer, RandomAspectScale,
    RandomCrop, Resize, Saturation, resize_image,
)
from bigdl_tpu.vision.roi import (  # noqa: F401
    RoiHFlip, RoiLabel, RoiNormalize, RoiProject, RoiResize, attach_roi,
)
from bigdl_tpu.vision.to_tensor import ImageFrameToSample, MatToTensor  # noqa: F401
