"""bigdl_tpu — a TPU-native distributed deep-learning framework.

A ground-up JAX/XLA/pjit/Pallas rebuild of the capabilities of BigDL
(distributed deep learning on Apache Spark; reference surveyed in SURVEY.md):

- Torch-style module/criterion library over pure-functional params/state
  pytrees (reference: ``DL/nn/abstractnn/AbstractModule.scala``).
- Composable data pipeline (``Sample``/``MiniBatch``/``Transformer`` chains)
  feeding device prefetch (reference: ``DL/dataset/*``).
- Synchronous data-parallel training via XLA collectives over a
  ``jax.sharding.Mesh`` (replacing the reference's BlockManager parameter
  server ``DL/parameters/AllReduceParameter.scala``), with sharded
  optimizer state, plus tensor/sequence/pipeline parallel axes.
- Local and distributed optimizers with triggers, validation, checkpoints
  (reference: ``DL/optim/*``).
- Model zoo (LeNet-5, ResNet, Inception-v1, VGG, PTB LSTM, autoencoder).
- Serving tier (``bigdl_tpu.serving``): dynamic-batching
  ``InferenceService`` with admission control, deadlines, and SLO
  metrics (replacing the reference's one-request-per-forward
  ``PredictionService.scala`` model pool).
- Robustness tier (``bigdl_tpu.faults``): deterministic seeded fault
  injection at named sites across the stack, plus the shared
  ``RetryPolicy`` backoff and stall ``Watchdog`` machinery that heals
  them (replacing the reference's reliance on Spark task retry).

Compute is JAX on TPU: MXU-friendly matmuls/convs in bfloat16 with fp32
masters, XLA fusion instead of hand-scheduled MKL-DNN primitives, and
Pallas kernels where XLA underperforms.
"""

from bigdl_tpu.version import __version__

from bigdl_tpu.core.engine import Engine
from bigdl_tpu.core.config import EngineConfig, DtypePolicy
from bigdl_tpu.core.rng import RandomGenerator

__all__ = [
    "__version__",
    "Engine",
    "EngineConfig",
    "DtypePolicy",
    "RandomGenerator",
]
