"""Sparse tensor support.

Reference: ``DL/tensor/SparseTensor.scala`` (1,467 LoC COO tensor) +
``SparseTensorBLAS``/``SparseTensorMath``, consumed by
``LookupTableSparse``/``SparseLinear`` and ``SparseMiniBatch``
(``MiniBatch.scala:588``).

TPU-native redesign: XLA wants static shapes, so the device-side format is
**padded COO** — every bag/row padded to a fixed ``max_nnz`` with a
validity mask; gathers + masked reductions replace the reference's sparse
BLAS loops and map onto the MXU/VPU cleanly. The host-side
:class:`SparseTensor` is a plain numpy COO container with dense
round-trips and CSR views; ``to_padded`` produces the device layout.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


class SparseTensor:
    """Host-side COO tensor (reference ``SparseTensor.scala``):
    ``indices`` (nnz, ndim) int32, ``values`` (nnz,), ``shape``."""

    def __init__(self, indices: np.ndarray, values: np.ndarray,
                 shape: Sequence[int]):
        self.indices = np.asarray(indices, np.int32).reshape(-1, len(shape))
        self.values = np.asarray(values)
        self.shape = tuple(int(d) for d in shape)
        if len(self.indices) != len(self.values):
            raise ValueError("indices/values length mismatch")

    @property
    def nnz(self) -> int:
        return len(self.values)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_dense(dense: np.ndarray) -> "SparseTensor":
        dense = np.asarray(dense)
        idx = np.argwhere(dense != 0)
        return SparseTensor(idx, dense[tuple(idx.T)], dense.shape)

    @staticmethod
    def from_bags(bags: Sequence[Sequence[int]], n_cols: int,
                  weights: Optional[Sequence[Sequence[float]]] = None) -> "SparseTensor":
        """Ragged id-bags -> 2-D sparse (reference python API takes
        (indices, values) pairs per row)."""
        rows, cols, vals = [], [], []
        for r, bag in enumerate(bags):
            for j, c in enumerate(bag):
                rows.append(r)
                cols.append(int(c))
                vals.append(1.0 if weights is None else float(weights[r][j]))
        idx = np.stack([rows, cols], -1) if rows else np.zeros((0, 2), np.int32)
        return SparseTensor(idx, np.asarray(vals, np.float32),
                            (len(bags), n_cols))

    # -- conversions -------------------------------------------------------
    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.shape, self.values.dtype)
        if self.nnz:
            np.add.at(out, tuple(self.indices.T), self.values)
        return out

    def to_csr(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(indptr, col_indices, values) for a 2-D tensor."""
        if self.ndim != 2:
            raise ValueError("CSR view requires a 2-D sparse tensor")
        order = np.lexsort((self.indices[:, 1], self.indices[:, 0]))
        rows = self.indices[order, 0]
        cols = self.indices[order, 1]
        vals = self.values[order]
        indptr = np.zeros(self.shape[0] + 1, np.int32)
        np.add.at(indptr, rows + 1, 1)
        return np.cumsum(indptr).astype(np.int32), cols, vals

    def to_padded(self, max_nnz: Optional[int] = None):
        """Device layout for a 2-D (batch x feature) sparse tensor:
        ``(ids (B, max_nnz) int32, weights (B, max_nnz) f32,
        mask (B, max_nnz) f32)`` — the static-shape padded-COO format every
        sparse module consumes."""
        if self.ndim != 2:
            raise ValueError("to_padded requires a 2-D sparse tensor")
        b = self.shape[0]
        counts = np.zeros(b, np.int64)
        if self.nnz:
            np.add.at(counts, self.indices[:, 0], 1)
        width = int(max_nnz if max_nnz is not None else max(1, counts.max()))
        if counts.max() > width:
            raise ValueError(f"row has {counts.max()} nnz > max_nnz={width}")
        ids = np.zeros((b, width), np.int32)
        weights = np.zeros((b, width), np.float32)
        mask = np.zeros((b, width), np.float32)
        cursor = np.zeros(b, np.int64)
        for (r, c), v in zip(self.indices, self.values):
            k = cursor[r]
            ids[r, k] = c
            weights[r, k] = v
            mask[r, k] = 1.0
            cursor[r] += 1
        return ids, weights, mask

    def __repr__(self):
        return f"SparseTensor(shape={self.shape}, nnz={self.nnz})"
