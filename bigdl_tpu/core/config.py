"""Centralized typed configuration.

The reference scatters ~30 ad-hoc ``bigdl.*`` JVM system properties across
use sites (reference: ``DL/utils/Engine.scala:191-251``,
``DL/nn/mkldnn/Fusion.scala:34``, ``DL/parameters/AllReduceParameter.scala:32-44``;
catalogued in SURVEY.md §5 "Config / flag system" which recommends
centralizing). Here every knob lives in one typed, immutable config object,
overridable from environment variables prefixed ``BIGDL_TPU_``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

import jax.numpy as jnp


def _env(name: str, default, cast=str):
    raw = os.environ.get("BIGDL_TPU_" + name)
    if raw is None:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """Mixed-precision policy.

    Replaces the reference's ``TensorNumeric[Float]``/``TensorNumeric[Double]``
    typeclass dispatch (reference: ``DL/tensor/TensorNumeric.scala:545``) and
    its fp16 wire compression (``DL/parameters/FP16CompressedTensor.scala``).
    On TPU the idiomatic choice is bfloat16 compute on the MXU with float32
    parameter masters; collectives run in ``reduce_dtype``.
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    output_dtype: jnp.dtype = jnp.float32
    reduce_dtype: jnp.dtype = jnp.float32

    @staticmethod
    def full_precision() -> "DtypePolicy":
        return DtypePolicy(
            param_dtype=jnp.float32,
            compute_dtype=jnp.float32,
            output_dtype=jnp.float32,
            reduce_dtype=jnp.float32,
        )

    @staticmethod
    def mixed() -> "DtypePolicy":
        return DtypePolicy()

    def cast_compute(self, x):
        import jax

        return jax.tree_util.tree_map(
            lambda a: a.astype(self.compute_dtype)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            x,
        )


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Global engine configuration.

    Mesh axis names follow the dp/tp/pp/sp/ep convention; the reference
    supports only dp (sync data parallel; SURVEY.md §2.3) — the extra axes
    are TPU-native capabilities layered on ``jax.sharding.Mesh``.
    """

    # env overrides resolve at instance-construction time (default_factory),
    # so BIGDL_TPU_* vars set after import still take effect
    seed: int = dataclasses.field(default_factory=lambda: _env("SEED", 1, int))
    # mesh topology: axis name -> size; None = use all devices on the dp axis
    mesh_shape: Optional[Tuple[Tuple[str, int], ...]] = None
    dp_axis: str = "dp"
    tp_axis: str = "tp"
    pp_axis: str = "pp"
    sp_axis: str = "sp"
    ep_axis: str = "ep"
    # training loop
    default_batch_size: int = dataclasses.field(
        default_factory=lambda: _env("BATCH_SIZE", 128, int)
    )
    # failure handling (reference: bigdl.failure.retryTimes, DistriOptimizer.scala:881-960)
    failure_retry_times: int = dataclasses.field(
        default_factory=lambda: _env("FAILURE_RETRY_TIMES", 5, int)
    )
    failure_retry_interval_sec: float = dataclasses.field(
        default_factory=lambda: _env("FAILURE_RETRY_INTERVAL", 120.0, float)
    )
    # logging
    log_every_n_steps: int = dataclasses.field(default_factory=lambda: _env("LOG_EVERY", 1, int))
    # checkpoint
    overwrite_checkpoint: bool = dataclasses.field(
        default_factory=lambda: _env("OVERWRITE_CHECKPOINT", True, bool)
    )
    dtypes: DtypePolicy = dataclasses.field(default_factory=DtypePolicy.full_precision)

    def replace(self, **kw) -> "EngineConfig":
        return dataclasses.replace(self, **kw)
