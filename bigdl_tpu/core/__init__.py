from bigdl_tpu.core.config import EngineConfig, DtypePolicy
from bigdl_tpu.core.engine import Engine
from bigdl_tpu.core.rng import RandomGenerator
from bigdl_tpu.core.table import T, Table

__all__ = ["EngineConfig", "DtypePolicy", "Engine", "RandomGenerator", "T", "Table"]
