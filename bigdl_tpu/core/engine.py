"""Execution engine: device topology and mesh management.

The reference's ``Engine`` (``DL/utils/Engine.scala:41``) holds global
node/core topology (``coreNumber()``, ``nodeNumber()``), an engine-type enum
(MklBlas/MklDnn) and thread pools used for intra-node model replicas. On TPU
all of that collapses into a ``jax.sharding.Mesh``: one XLA program per chip,
intra-chip parallelism handled by the compiler, inter-chip parallelism by
collectives over ICI/DCN. ``Engine`` here owns mesh construction and the
default sharding axes.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.core.config import EngineConfig

log = logging.getLogger("bigdl_tpu")


class Engine:
    """Singleton-ish engine (reference: ``Engine.init``, ``Engine.scala:106``).

    Unlike the reference there is no node/core bookkeeping: ``node_number``
    maps to ``jax.process_count()`` and ``core_number`` to
    ``jax.local_device_count()``.
    """

    _lock = threading.Lock()
    _instance: Optional["Engine"] = None

    def __init__(self, config: Optional[EngineConfig] = None):
        self.config = config or EngineConfig()
        self._mesh: Optional[Mesh] = None

    # ---- topology (reference: Engine.nodeNumber/coreNumber) ----
    @staticmethod
    def node_number() -> int:
        return jax.process_count()

    @staticmethod
    def core_number() -> int:
        return jax.local_device_count()

    @staticmethod
    def device_count() -> int:
        return jax.device_count()

    # ---- init / singleton ----
    @classmethod
    def init(cls, config: Optional[EngineConfig] = None) -> "Engine":
        with cls._lock:
            if cls._instance is None or config is not None:
                cls._instance = Engine(config)
            return cls._instance

    @classmethod
    def get(cls) -> "Engine":
        return cls.init()

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None

    @classmethod
    def init_multihost(cls, coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None,
                       config: Optional[EngineConfig] = None) -> "Engine":
        """Multi-host initialization (the reference's cluster entry:
        ``Engine.init(nodeNumber, coreNumber, onSpark=true)``,
        ``Engine.scala:106``).

        Wraps ``jax.distributed.initialize`` — each host process calls
        this before any other JAX use; afterwards ``jax.devices()`` spans
        the whole slice and every mesh built by this Engine covers all
        hosts, with XLA routing collectives over ICI within a slice and
        DCN across slices. On Cloud TPU the three arguments are
        auto-detected from the metadata server; pass them explicitly for
        manual clusters (coordinator ``host:port``, world size, rank).
        """
        import os

        # IMPORTANT: decide whether to initialize WITHOUT touching any
        # jax backend API — jax.distributed.initialize must run before
        # the backend is created. Distributed init engages when the
        # caller passed explicit topology args OR a cluster environment
        # is detectable; a plain single-process call is an ordinary init.
        explicit = any(a is not None
                       for a in (coordinator_address, num_processes, process_id))
        # TPU_WORKER_HOSTNAMES is set even on single-host TPU-VMs: only a
        # multi-entry list means a real multi-host slice
        cluster_env = (
            os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")
            or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
            or "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""))
        if explicit or cluster_env:
            kwargs = {}
            if coordinator_address is not None:
                kwargs["coordinator_address"] = coordinator_address
            if num_processes is not None:
                kwargs["num_processes"] = num_processes
            if process_id is not None:
                kwargs["process_id"] = process_id
            jax.distributed.initialize(**kwargs)
        return cls.init(config)

    # ---- mesh ----
    def mesh(self, mesh_shape: Optional[Sequence[Tuple[str, int]]] = None) -> Mesh:
        """Build (and cache) the device mesh.

        Default: all devices on the data-parallel axis — the TPU-native
        equivalent of the reference's one-model-replica-per-core data
        parallelism (``DistriOptimizer.initThreadModels``,
        ``DL/optim/DistriOptimizer.scala:564-567``).
        """
        shape = tuple(mesh_shape or self.config.mesh_shape or ((self.config.dp_axis, jax.device_count()),))
        if self._mesh is not None and tuple(zip(self._mesh.axis_names, self._mesh.devices.shape)) == shape:
            return self._mesh
        names = tuple(n for n, _ in shape)
        sizes = tuple(s for _, s in shape)
        n = int(np.prod(sizes))
        if n > jax.device_count():
            raise ValueError(
                f"mesh {dict(shape)} needs {n} devices, only {jax.device_count()} available"
            )
        devices = np.asarray(jax.devices()[:n]).reshape(sizes)
        self._mesh = Mesh(devices, names)
        return self._mesh

    def data_sharding(self, mesh: Optional[Mesh] = None) -> NamedSharding:
        """Batch-dimension sharding over the dp axis."""
        mesh = mesh or self.mesh()
        return NamedSharding(mesh, P(self.config.dp_axis))

    def replicated_sharding(self, mesh: Optional[Mesh] = None) -> NamedSharding:
        mesh = mesh or self.mesh()
        return NamedSharding(mesh, P())
