"""Deterministic random number generation.

The reference uses a per-thread Mersenne twister
(``DL/utils/RandomGenerator.scala``); on TPU the idiomatic equivalent is
JAX's splittable threefry PRNG. ``RandomGenerator`` wraps a root key with
deterministic fold-in by string path so every module/transformer draws an
independent, reproducible stream.
"""

from __future__ import annotations

import copy
import zlib
from typing import Optional

import jax
import numpy as np


def fold_in_str(key: jax.Array, name: str) -> jax.Array:
    """Deterministically derive a subkey from a string (stable across runs,
    unlike Python's randomized ``hash``)."""
    return jax.random.fold_in(key, zlib.crc32(name.encode("utf-8")) & 0x7FFFFFFF)


_M64 = (1 << 64) - 1


def element_seed(base_seed: int, index: int, stream: int = 0) -> int:
    """Stable per-element seed for stream element ``index`` under
    ``base_seed`` (splitmix64 finalizer over the mixed inputs — pure int
    ops, ~1 us: this runs on the host input-pipeline hot path, once per
    element per rng-bearing transformer, where a SeedSequence would cost
    5x and a ``default_rng`` rebuild 25x). ``stream`` separates draws for
    multiple rng-bearing transformers applied to the same element. The
    parallel transformer pool seeds each element's augmentation from
    ``(base_seed, element_index)`` so the emitted stream is bit-identical
    regardless of worker count."""
    x = (int(base_seed) * 0x9E3779B97F4A7C15
         + int(index) * 0xBF58476D1CE4E5B9
         + int(stream) * 0x94D049BB133111EB + 0x2545F4914F6CDD1D) & _M64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _M64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _M64
    x ^= x >> 31
    return x >> 1  # non-negative, < 2**63


def uniform01(seed: int, index: int, stream: int = 0) -> float:
    """Deterministic uniform draw in [0, 1) from the splitmix64 stream
    keyed on ``(seed, index, stream)`` — :func:`element_seed` scaled to
    the unit interval. The faults tier draws rate-plan decisions and
    backoff jitter from this, so its schedules replay exactly."""
    return element_seed(seed, index, stream) / float(1 << 63)


def np_rng(seed: int, index: int = 0, stream: int = 0) -> np.random.Generator:
    """The blessed constructor for host-side numpy randomness in library
    code (graftlint GL004 flags any direct ``np.random.*`` touch outside
    this module).  ``np_rng(seed)`` is bit-identical to
    ``np.random.default_rng(seed)``; pass ``index``/``stream`` to derive
    an independent keyed sub-stream via :func:`element_seed` — the same
    recipe the pipeline pool and the faults tier key their draws with,
    so every library draw is a pure function of an explicit seed."""
    if index or stream:
        seed = element_seed(seed, index, stream)
    return np.random.default_rng(int(seed))


def threefry_key_data(seed: int) -> np.ndarray:
    """Raw ``(2,)`` uint32 threefry key words for ``seed`` — the host-side
    equivalent of ``jax.random.PRNGKey(seed)`` without a device dispatch.
    The serving tier keeps one such key PER SLOT in a ``(max_slots, 2)``
    array that rides through the jitted decode step (split + uniform draw
    inside the step), so sampling costs no extra host<->device round trip
    and each request's stream is a pure function of its seed."""
    seed = int(seed)
    return np.array([(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                    np.uint32)


def request_seed(base_seed: int, payload: bytes, stream: int = 0) -> int:
    """Deterministic per-request seed from an engine-level ``base_seed``
    and the request's identifying bytes (e.g. its prompt token ids).
    Built on :func:`element_seed` with a crc32 of the payload as the
    element index, so the derived sampling stream depends only on the
    request CONTENT — never on admission order, slot assignment, or
    wall-clock — which is what makes sampled generation reproducible
    across schedulers and submission orderings. Two byte-identical
    requests share a stream; pass an explicit per-request seed when they
    must diverge."""
    return element_seed(base_seed, zlib.crc32(payload), stream)


class RandomGenerator:
    """Stateful convenience wrapper over a splittable key.

    Used at pipeline/host level (shuffles, augmentation); inside jitted
    compute, raw keys are threaded functionally instead. The jax key is
    materialized lazily: host-side transformers only touch the numpy
    generator, and the pipeline worker pool reseeds per element — an
    eager ``jax.random.key`` there would put a device dispatch on every
    element of the input stream.
    """

    _default: Optional["RandomGenerator"] = None

    def __init__(self, seed: int = 1):
        self._seed = seed
        self._key = None  # lazily jax.random.key(seed) on first next_key()
        self._np = np.random.default_rng(seed)

    @classmethod
    def default(cls) -> "RandomGenerator":
        if cls._default is None:
            cls._default = RandomGenerator()
        return cls._default

    def set_seed(self, seed: int) -> "RandomGenerator":
        self.__init__(seed)
        return self

    def reseed(self, seed: int) -> "RandomGenerator":
        """Cheap deterministic reseed (the pipeline-pool per-element hot
        path). Rebuilding a ``default_rng`` costs ~25 us; poking the
        PCG64 state directly costs ~2 us. Both the 128-bit state AND the
        stream increment are derived from the (already splitmix-mixed)
        seed, so ``reseed(s)`` yields identical draws whatever generator
        it lands on — load-bearing for worker-pool determinism: chain
        copies on different workers (deepcopied or unpickled from
        different origins) must draw identically for equal seeds. Falls
        back to a full reinit for non-PCG64 bit generators."""
        self._seed = seed
        self._key = None
        try:
            bg = self._np.bit_generator
            st = bg.state
            if st.get("bit_generator") == "PCG64":
                mixed = (seed * 0x9E3779B97F4A7C15) & _M64
                inc = (seed * 0xBF58476D1CE4E5B9 + 0x94D049BB133111EB) & _M64
                st["state"]["state"] = (mixed << 64) | (seed & _M64)
                # PCG64 stream selector must be odd; deriving it from the
                # seed (not keeping the old one) makes reseed(s) yield
                # identical draws whatever generator it lands on
                st["state"]["inc"] = ((inc << 64) | (mixed ^ seed)) | 1
                st["has_uint32"] = 0
                st["uinteger"] = 0
                bg.state = st
                return self
        except (AttributeError, KeyError, TypeError):
            pass
        self._np = np.random.default_rng(seed)
        return self

    @property
    def seed(self) -> int:
        return self._seed

    def next_key(self) -> jax.Array:
        if self._key is None:
            self._key = jax.random.key(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    def __deepcopy__(self, memo):
        # worker pools deepcopy transformer chains; jax keys are immutable
        # so sharing the key array is correct, and the numpy generator is
        # copied with its state
        new = object.__new__(RandomGenerator)
        new._seed = self._seed
        new._key = self._key
        new._np = copy.deepcopy(self._np, memo)
        memo[id(self)] = new
        return new

    def numpy(self) -> np.random.Generator:
        return self._np

    # host-side draws (numpy; used by data pipeline, not by jitted code)
    def uniform(self, low=0.0, high=1.0, size=None):
        return self._np.uniform(low, high, size)

    def normal(self, mean=0.0, stdv=1.0, size=None):
        return self._np.normal(mean, stdv, size)

    def permutation(self, n: int):
        return self._np.permutation(n)
