"""Deterministic random number generation.

The reference uses a per-thread Mersenne twister
(``DL/utils/RandomGenerator.scala``); on TPU the idiomatic equivalent is
JAX's splittable threefry PRNG. ``RandomGenerator`` wraps a root key with
deterministic fold-in by string path so every module/transformer draws an
independent, reproducible stream.
"""

from __future__ import annotations

import zlib
from typing import Optional

import jax
import numpy as np


def fold_in_str(key: jax.Array, name: str) -> jax.Array:
    """Deterministically derive a subkey from a string (stable across runs,
    unlike Python's randomized ``hash``)."""
    return jax.random.fold_in(key, zlib.crc32(name.encode("utf-8")) & 0x7FFFFFFF)


class RandomGenerator:
    """Stateful convenience wrapper over a splittable key.

    Used at pipeline/host level (shuffles, augmentation); inside jitted
    compute, raw keys are threaded functionally instead.
    """

    _default: Optional["RandomGenerator"] = None

    def __init__(self, seed: int = 1):
        self._seed = seed
        self._key = jax.random.key(seed)
        self._np = np.random.default_rng(seed)

    @classmethod
    def default(cls) -> "RandomGenerator":
        if cls._default is None:
            cls._default = RandomGenerator()
        return cls._default

    def set_seed(self, seed: int) -> "RandomGenerator":
        self.__init__(seed)
        return self

    @property
    def seed(self) -> int:
        return self._seed

    def next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def numpy(self) -> np.random.Generator:
        return self._np

    # host-side draws (numpy; used by data pipeline, not by jitted code)
    def uniform(self, low=0.0, high=1.0, size=None):
        return self._np.uniform(low, high, size)

    def normal(self, mean=0.0, stdv=1.0, size=None):
        return self._np.normal(mean, stdv, size)

    def permutation(self, n: int):
        return self._np.permutation(n)
