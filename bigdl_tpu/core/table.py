"""Activity/Table conventions.

The reference's ``Activity`` is a ``Tensor | Table`` union
(``DL/nn/abstractnn/Activity.scala``) and ``Table`` is a Torch-style
int-keyed map (``DL/utils/Table.scala:34``) built with the ``T()`` helper.
In JAX the natural union is "pytree": a single ``jax.Array``, a tuple/list,
or a dict. ``T(...)`` builds a tuple (the common positional-table case) or a
dict for keyword entries, so ported model code reads the same while staying
an ordinary pytree that jit/grad understand.
"""

from __future__ import annotations

from typing import Any

# An Activity is any pytree of arrays. A Table is a tuple or dict.
Table = tuple


def T(*args: Any, **kwargs: Any):
    """Torch-style table builder (reference ``T()`` in ``DL/utils/Table.scala``).

    ``T(a, b)`` -> ``(a, b)``; ``T(x=a)`` -> ``{"x": a}``.
    """
    if args and kwargs:
        raise ValueError("T() takes positional or keyword entries, not both")
    if kwargs:
        return dict(kwargs)
    return tuple(args)


def is_table(x: Any) -> bool:
    return isinstance(x, (tuple, list, dict))


def flatten_activity(x):
    import jax

    return jax.tree_util.tree_leaves(x)
