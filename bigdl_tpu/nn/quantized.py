"""Int8 post-training quantization for inference.

Reference: ``DL/nn/quantized/`` — ``Quantizer``/``Quantizable`` module-tree
rewrite, quantized ``SpatialConvolution``/``Linear`` holding int8 weights
with per-output-channel scales (``Desc.scala`` quant params), entry point
``AbstractModule.quantize()`` (``AbstractModule.scala:920``).

TPU-native design:

- weights are quantized **per output channel** to int8 symmetric
  (``w_q = round(w / scale)``, ``scale = max|w| / 127``), like the
  reference's per-output scales;
- activations are quantized **dynamically per sample** at runtime (one
  scale per batch row — a batch-wide absmax would couple co-batched
  serving requests; the reference computes input min/max per forward
  too, and calibrated static scales skip the pass entirely);
- the Linear matmul runs as a true int8 x int8 -> int32
  ``lax.dot_general`` (``preferred_element_type=int32``) — on TPU this is
  the MXU's native int8 path at double the bf16 throughput;
- convolutions compute the quantized integer values in f32 by default
  (exact for products; partial sums can round past 2^24 — see the int32
  path). A TRUE int8 conv exists behind ``BIGDL_INT8_CONV=dot`` (im2col
  + one s8 x s8 -> s32 ``dot_general``), but it is a parity/exactness
  tier, NOT a speed tier: round-5 measurements show XLA's int8 MATMUL
  does hit the MXU's native int8 path at ~1.9x bf16 (350 TOP/s,
  ``perf/micro_int8.py`` — which is why ``QuantizedLinear`` uses it),
  while for convs the im2col patch traffic, int32 output transposes and
  per-layer activation quantization cost 10x more than the matmul saves
  (136.7 ms/fwd im2col vs 42.3 float-int vs 14.4 bf16, ResNet-50 b128;
  ``perf/artifacts/r5_int8.txt``). The reference's conv-int8 win was
  CPU-VNNI-specific (``DL/nn/mkldnn/Perf.scala:56``).

``quantize(module, params)`` returns a NEW (module, params) pair; the
original float model is untouched (reference semantics).
"""

from __future__ import annotations

import copy
import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import int8 as _int8
from bigdl_tpu.nn.containers import Sequential
from bigdl_tpu.nn.graph import Graph, Node
from bigdl_tpu.nn.layers.conv import SpatialConvolution
from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.module import Context, Module


def _quantize_weight(w: jax.Array, channel_axis: int = 0):
    """Symmetric per-output-channel int8 (reference ``Desc.scala`` scales)."""
    reduce_axes = tuple(i for i in range(w.ndim) if i != channel_axis)
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    wq = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return wq, scale.astype(jnp.float32)


def _quantize_activation(x: jax.Array, static_scale=None):
    """Symmetric int8 activations. The DYNAMIC path quantizes PER
    SAMPLE (one scale per batch row, absmax over the rest): a
    per-tensor absmax over a packed batch would make one request's
    output depend on which requests the DynamicBatcher co-batched it
    with — the same neighbour-coupling the serving engine's per-token
    scales exist to prevent (an `InferenceService(quantize="int8")`
    answer must be a function of the request, not of concurrent
    traffic). With a calibrated ``static_scale`` > 0 the absmax pass is
    skipped entirely and one fixed scale serves every sample (reference
    ``GenerateInt8Scales`` semantics — also coupling-free, by
    constancy). ``lax.cond`` (not ``where``) so the reduction is
    genuinely NOT executed on the calibrated path."""
    axes = tuple(range(1, x.ndim))
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)

    def dyn(_):
        return jnp.maximum(jnp.max(jnp.abs(x), axis=axes, keepdims=True),
                           1e-8) / 127.0

    if static_scale is None:
        scale = dyn(None)
    else:
        scale = lax.cond(
            static_scale > 0,
            lambda _: jnp.broadcast_to(static_scale.astype(jnp.float32),
                                       shape),
            dyn, None)
    xq = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return xq, scale


class QuantizedLinear(Module):
    """Int8 Linear (reference ``quantized/Linear.scala``): int8 GEMM with
    int32 accumulation on the MXU, per-output-channel dequantization."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias

    @staticmethod
    def convert_params(float_params: Dict[str, Any]) -> Dict[str, Any]:
        w = jnp.asarray(float_params["weight"])  # (out, in) layout (x @ w.T)
        wq, scale = _quantize_weight(w, channel_axis=0)
        p = {"weight_q": wq, "scale": scale.reshape(1, -1),
             "act_scale": jnp.zeros((), jnp.float32)}  # 0 = dynamic
        if "bias" in float_params:
            p["bias"] = jnp.asarray(float_params["bias"], jnp.float32)
        return p

    def build_state(self):
        return {"act_absmax": jnp.zeros((), jnp.float32)}

    def forward(self, ctx: Context, x):
        wq = ctx.param("weight_q")  # (out, in)
        scale_w = ctx.param("scale")  # (1, out)
        orig_shape = x.shape
        x2 = x.reshape(-1, orig_shape[-1]).astype(jnp.float32)
        if ctx.training:  # calibration pass: record the running absmax
            ctx.put_state("act_absmax", jnp.maximum(
                ctx.get_state("act_absmax"), jnp.max(jnp.abs(x2))))
        xq, scale_x = _quantize_activation(x2, ctx.param("act_scale"))
        acc = lax.dot_general(
            xq, wq, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        y = acc.astype(jnp.float32) * (scale_x * scale_w)
        if self.with_bias:
            y = y + ctx.param("bias")
        return y.reshape(orig_shape[:-1] + (self.output_size,)).astype(x.dtype)


class QuantizedSpatialConvolution(Module):
    """Int8 conv (reference ``quantized/SpatialConvolution.scala``).
    Integer values computed in float (exact — see module docstring)."""

    def __init__(self, src: SpatialConvolution):
        super().__init__()
        self.stride = src.stride
        self.pad = src.pad
        self.n_group = src.n_group
        self.with_bias = src.with_bias
        self.data_format = src.data_format
        self.dilation = getattr(src, "dilation", (1, 1))
        self.n_output_plane = src.n_output_plane
        if getattr(src, "kernel_format", "OIHW") != "OIHW":
            raise ValueError(
                "quantization expects OIHW-stored conv weights; transpose "
                "the params (SpatialConvolution.weight_as_oihw) first")

    @staticmethod
    def convert_params(float_params: Dict[str, Any]) -> Dict[str, Any]:
        w = jnp.asarray(float_params["weight"])  # (O, I, kh, kw)
        wq, scale = _quantize_weight(w, channel_axis=0)
        p = {"weight_q": wq, "scale": scale.reshape(-1),
             "act_scale": jnp.zeros((), jnp.float32)}  # 0 = dynamic
        if "bias" in float_params:
            p["bias"] = jnp.asarray(float_params["bias"], jnp.float32)
        return p

    def build_state(self):
        return {"act_absmax": jnp.zeros((), jnp.float32)}

    def _int8_dot_path(self, xq, wq):
        """Kernel-point-decomposed TRUE int8 conv: one s8 x s8 -> s32
        ``dot_general`` per (kh, kw) tap, accumulated in int32.

        Round-5 measurement: XLA's int8 conv lowering upcasts (5x slower
        than bf16) but its int8 MATMUL hits the MXU's native int8 path at
        ~350 TOP/s = 1.9x the measured bf16 peak (`perf/micro_int8.py`).
        Decomposing the conv into KH*KW shifted matmuls rides that path;
        int32 accumulation is also EXACT where the old float path could
        round (partial sums can exceed 2^24). NCHW, groups == 1.
        """
        B, I, H, W = xq.shape
        O, _, KH, KW = wq.shape
        sh, sw = self.stride
        dh, dw = self.dilation
        ph, pw = self.pad
        if ph or pw:
            xq = jnp.pad(xq, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        Hp, Wp = H + 2 * ph, W + 2 * pw
        Ho = (Hp - ((KH - 1) * dh + 1)) // sh + 1
        Wo = (Wp - ((KW - 1) * dw + 1)) // sw + 1
        # im2col, ONE dot: a first per-tap-accumulation formulation wrote
        # and re-read the (O, B, N) int32 accumulator once per tap (9x for
        # 3x3) and measured 192 ms/fwd vs bf16's 14.4 — the patches concat
        # keeps everything int8 and the int32 output is written once
        taps = []
        for kh in range(KH):
            for kw in range(KW):
                taps.append(lax.slice(
                    xq, (0, 0, kh * dh, kw * dw),
                    (B, I, kh * dh + (Ho - 1) * sh + 1,
                     kw * dw + (Wo - 1) * sw + 1),
                    (1, 1, sh, sw)).reshape(B, I, Ho * Wo))
        # tap order must match: the concat is (kh, kw)-major blocks of I
        # channels, so the weights flatten as (O, kh, kw, I)
        xs_all = taps[0] if len(taps) == 1 else jnp.concatenate(taps, axis=1)
        w2 = wq.transpose(0, 2, 3, 1).reshape(O, KH * KW * I)
        # (O, K) x (B, K, N) contracting K -> (O, B, N)
        acc = lax.dot_general(
            w2, xs_all, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        return acc.transpose(1, 0, 2).reshape(B, O, Ho, Wo)

    def forward(self, ctx: Context, x):
        from bigdl_tpu.nn.layers.conv import _dimension_numbers, _padding

        scale_w = ctx.param("scale")
        xf = x.astype(jnp.float32)
        if ctx.training:  # calibration pass: record the running absmax
            ctx.put_state("act_absmax", jnp.maximum(
                ctx.get_state("act_absmax"), jnp.max(jnp.abs(xf))))
        xq, scale_x = _quantize_activation(xf, ctx.param("act_scale"))
        # read per-trace like BIGDL_BN_STATS (norm.py): flippable late in
        # tests/experiments, but NOTE a cached jit trace keeps the path it
        # was traced with — re-jit (new shapes or fresh function) after
        # changing the env var
        use_dot = (self.n_group == 1 and self.data_format == "NCHW"
                   and self.pad[0] >= 0 and self.pad[1] >= 0  # -1 = SAME
                   and os.environ.get("BIGDL_INT8_CONV", "float") == "dot")
        if use_dot:
            y = self._int8_dot_path(xq, ctx.param("weight_q")).astype(jnp.float32)
        else:
            y = lax.conv_general_dilated(
                xq.astype(jnp.float32), ctx.param("weight_q").astype(jnp.float32),
                window_strides=self.stride,
                padding=_padding(*self.pad),
                rhs_dilation=self.dilation,
                feature_group_count=self.n_group,
                dimension_numbers=_dimension_numbers(self.data_format),
            )
        # scale_x is per SAMPLE, (B, 1, 1, 1) — it broadcasts against y
        # directly; only the per-channel weight scale needs axis placement
        if self.data_format == "NCHW":
            y = y * scale_x * scale_w[None, :, None, None]
            if self.with_bias:
                y = y + ctx.param("bias")[None, :, None, None]
        else:
            y = y * scale_x * scale_w
            if self.with_bias:
                y = y + ctx.param("bias")
        return y.astype(x.dtype)


def _quantize_node(module: Module, params) -> Tuple[Module, Any]:
    # exact type checks (not isinstance): parallel subclasses like
    # ColumnParallelLinear carry sharding specs and collectives that a
    # plain QuantizedLinear would silently drop
    if type(module) is Linear:
        q = QuantizedLinear(module.input_size, module.output_size, module.with_bias)
        return q, QuantizedLinear.convert_params(params)
    if type(module) is SpatialConvolution:
        q = QuantizedSpatialConvolution(module)
        return q, QuantizedSpatialConvolution.convert_params(params)
    return None, None


def quantize(module: Module, params) -> Tuple[Module, Any]:
    """Rewrite the module tree, quantizing every Linear / SpatialConvolution
    (reference ``Quantizer.quantize`` / ``AbstractModule.quantize()``).
    Returns a new (module, params); the original pair is untouched."""
    q, qp = _quantize_node(module, params)
    if q is not None:
        return q, qp

    if isinstance(module, Graph):
        # rebuild the node DAG with quantized elements (shared modules stay
        # shared — keyed by id)
        mapping: Dict[int, Module] = {}
        new_params: Dict[str, Any] = {}
        for node in module._topo:
            el = node.element
            if el is None or id(el) in mapping:
                continue
            name = module._names[id(node)]
            sub_params = params.get(name, {}) if params else {}
            new_el, new_sub = quantize(el, sub_params)
            # keep the old graph's node name so param keys stay aligned
            # (a rewritten class would otherwise rename e.g. Linear_0 ->
            # QuantizedLinear_0)
            new_el.set_name(name)
            mapping[id(el)] = new_el
            if new_sub:
                new_params[name] = new_sub
        node_map: Dict[int, Node] = {}
        for node in module._topo:
            el = None if node.element is None else mapping[id(node.element)]
            node_map[id(node)] = Node(el, [node_map[id(p)] for p in node.prev])
        g = Graph([node_map[id(n)] for n in module.inputs],
                  [node_map[id(n)] for n in module.outputs])
        return g, new_params

    # generic container / layer: shallow-copy, recurse into children
    clone = copy.copy(module)
    object.__setattr__(clone, "_modules", {})
    new_params = dict(params) if isinstance(params, dict) else {}
    for name, child in module.modules.items():
        sub = params.get(name, {}) if isinstance(params, dict) else {}
        new_child, new_sub = quantize(child, sub)
        clone._modules[name] = new_child
        # keep attribute aliases (e.g. self.fc1) pointing at the new child
        for attr, val in vars(module).items():
            if val is child:
                object.__setattr__(clone, attr, new_child)
        if new_sub:
            new_params[name] = new_sub
    return clone, new_params


def quantize_for_serving(params):
    """Post-training int8 transform for the SERVING ``nn.Transformer``
    param tree (the decode surface: ``prefill``/``decode_step`` and
    their paged twins).

    Every GEMM weight — the q/k/v/output projections, FFN up/down, and
    the lm head — is replaced by symmetric per-output-channel int8
    (``weight`` -> ``weight_q`` int8 + ``scale`` fp32 (out,)); norms,
    biases and the embedding-lookup table stay float. ``Linear.forward``
    and ``Transformer._logits`` detect the quantized keys and execute as
    a true ``s8 x s8 -> s32`` ``dot_general`` with dynamic PER-TOKEN
    activation quantization inside the jitted step
    (``nn.int8.quantize_rows``) — the MXU's ~1.9x-over-bf16 path
    (round-5 measurement). Per-token (one scale per row), never
    per-tensor, is load-bearing: a decode batch holds every active slot,
    and a batch-wide absmax would make one request's logits depend on
    its co-scheduled neighbours, breaking the stream = f(seed)
    schedule-invariance contract the order-reversal tests pin
    (PERF_NOTES round 8). Shapes and the
    tree structure are a pure function of the input tree, so a reload
    that re-runs this transform hits the SAME compiled executable.

    A shared-embedding lm head gets a dedicated int8 copy
    (``embedding_q`` + ``lm_scale``, quantized per vocab row) next to
    the float ``embedding`` used for lookups — int8 lookup would
    perturb the hidden stream for no GEMM win.

    Returns a NEW params tree; the input is untouched. Generic rule: a
    subtree whose keys are exactly ``{weight[, bias]}`` with a 2-D
    weight is a GEMM (norm weights are 1-D, convs never appear in the
    decode surface)."""

    def walk(node):
        if not isinstance(node, dict):
            return node
        keys = set(node.keys())
        if "weight" in keys and keys <= {"weight", "bias"} \
                and getattr(node["weight"], "ndim", 0) == 2:
            wq, scale = _int8.quantize_weight(node["weight"])
            out = {"weight_q": wq, "scale": scale}
            if "bias" in node:
                out["bias"] = jnp.asarray(node["bias"], jnp.float32)
            return out
        out = {k: walk(v) for k, v in node.items()}
        if "embedding" in keys and "project" not in keys \
                and getattr(node["embedding"], "ndim", 0) == 2:
            # shared-embedding head only: an untied Transformer carries a
            # "project" Linear (quantized by the rule above) and never
            # reads embedding_q — emitting it there would hold dead int8
            # bytes and over-count quantized_gemms
            eq, es = _int8.quantize_weight(node["embedding"])
            out["embedding_q"] = eq
            out["lm_scale"] = es
        return out

    return walk(params)


def count_quantized_gemms(params) -> int:
    """Number of int8 GEMMs in a ``quantize_for_serving`` param tree —
    the ``ServingMetrics.quantized_gemms`` gauge for the engine path.
    Correct THERE because that transform only ever emits ``weight_q``
    for weights that execute the s8 x s8 -> s32 dot (the decode surface
    has no convs). For the module-rewrite (reference-tier) path use
    :func:`count_executed_gemms` — a param-tree count would also pick
    up quantized convs that execute as float."""
    if not isinstance(params, dict):
        return 0
    n = int("weight_q" in params) + int("embedding_q" in params)
    return n + sum(count_quantized_gemms(v) for v in params.values()
                   if isinstance(v, dict))


def count_executed_gemms(module: Module) -> int:
    """GEMMs of a quantized MODULE tree that actually execute the
    s8 x s8 -> s32 path — the ``ServingMetrics.quantized_gemms`` gauge
    for ``InferenceService(quantize="int8")``. ``QuantizedLinear``
    always runs the int8 dot; ``QuantizedSpatialConvolution`` counts
    only under ``BIGDL_INT8_CONV=dot`` — its default executes the
    quantized integer values as a FLOAT conv (exactness tier, not an
    int8 GEMM; see the module docstring), so counting it would report
    MXU-int8 engagement that never happens. The env var is read at call
    time, mirroring the per-trace read in the conv forward."""
    n = 0
    if isinstance(module, QuantizedLinear):
        n += 1
    elif isinstance(module, QuantizedSpatialConvolution):
        n += int(os.environ.get("BIGDL_INT8_CONV", "float") == "dot")
    seen = set()
    for child in module.modules.values():
        if id(child) in seen:  # shared graph nodes count once
            continue
        seen.add(id(child))
        n += count_executed_gemms(child)
    return n


def calibrate(qmodule: Module, qparams, batches, state=None):
    """Static activation-scale calibration (reference
    ``GenerateInt8Scales.scala``: run sample data through the model and
    record per-layer activation ranges, then persist the scales).

    Runs ``batches`` through the quantized model in training mode — each
    quantized layer records its running input absmax in module state —
    then bakes ``act_scale = absmax / 127`` into the params so inference
    skips the dynamic absmax pass. Returns (calibrated_params, state).
    """
    import jax

    if state is None:
        _, state = qmodule.init(jax.random.key(0))
    for x in batches:
        _, state = qmodule.apply(qparams, x, state=state, training=True)

    def bake(params, st):
        if not isinstance(params, dict):
            return params
        out = {}
        for k, v in params.items():
            if k == "act_scale" and isinstance(st, dict) and "act_absmax" in st:
                out[k] = jnp.maximum(jnp.asarray(st["act_absmax"]), 1e-8) / 127.0
            elif isinstance(v, dict):
                out[k] = bake(v, st.get(k, {}) if isinstance(st, dict) else {})
            else:
                out[k] = v
        return out

    return bake(qparams, state), state
