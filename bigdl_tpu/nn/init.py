"""Weight initialization methods.

Reference: ``DL/nn/InitializationMethod.scala`` — Zeros, Ones, ConstInitMethod,
RandomUniform, RandomNormal, Xavier (glorot), MsraFiller (kaiming),
BilinearFiller; layers expose ``setInitMethod(weight, bias)``.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


class InitializationMethod:
    def __init_subclass__(cls, **kw):
        from bigdl_tpu.nn.module import capture_init_args

        super().__init_subclass__(**kw)
        capture_init_args(cls)

    def __call__(self, rng: jax.Array, shape: Tuple[int, ...], fan_in: int, fan_out: int, dtype=jnp.float32):
        raise NotImplementedError


class Zeros(InitializationMethod):
    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


class Ones(InitializationMethod):
    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.ones(shape, dtype)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class RandomUniform(InitializationMethod):
    """Uniform in [lower, upper]; default Torch-style 1/sqrt(fan_in)."""

    def __init__(self, lower: Optional[float] = None, upper: Optional[float] = None):
        self.lower, self.upper = lower, upper

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        if self.lower is None:
            stdv = 1.0 / math.sqrt(max(1, fan_in))
            lo, hi = -stdv, stdv
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(rng, shape, dtype, minval=lo, maxval=hi)


class RandomNormal(InitializationMethod):
    def __init__(self, mean: float = 0.0, stdv: float = 1.0):
        self.mean, self.stdv = mean, stdv

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return self.mean + self.stdv * jax.random.normal(rng, shape, dtype)


class Xavier(InitializationMethod):
    """Glorot uniform (reference default for convolutions)."""

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, minval=-limit, maxval=limit)


class MsraFiller(InitializationMethod):
    """Kaiming/He normal (reference: MsraFiller, used by ResNet)."""

    def __init__(self, variance_norm_average: bool = False):
        self.variance_norm_average = variance_norm_average

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        n = (fan_in + fan_out) / 2.0 if self.variance_norm_average else fan_out
        std = math.sqrt(2.0 / max(1.0, n))
        return std * jax.random.normal(rng, shape, dtype)


class BilinearFiller(InitializationMethod):
    """Bilinear upsampling weights for deconvolution."""

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        # shape: (out_ch, in_ch, kh, kw)
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = math.ceil(kh / 2.0), math.ceil(kw / 2.0)
        c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        ys = jnp.arange(kh)[:, None]
        xs = jnp.arange(kw)[None, :]
        filt = (1 - jnp.abs(ys / f_h - c_h)) * (1 - jnp.abs(xs / f_w - c_w))
        return jnp.broadcast_to(filt, shape).astype(dtype)
