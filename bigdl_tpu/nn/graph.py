"""Graph container: arbitrary DAGs of modules.

Reference: ``DL/nn/Graph.scala`` (node DAG + topo sort via
``DL/utils/DirectedGraph.scala``) executed by ``StaticGraph``
(``DL/nn/StaticGraph.scala:56-68``: pre-topo-sorted array walk). Here the
topo-sorted walk happens at Python trace time; XLA sees one flat fused
program, so there is no dynamic scheduler to build (the reference's
``DynamicGraph``/``Scheduler``/``FrameManager`` data-driven execution is
subsumed by ``lax.cond``/``lax.while_loop`` for genuinely dynamic control
flow).

Building syntax mirrors the reference's functional API::

    inp = Input()
    h = ReLU()(SpatialConvolution(1, 6, 5, 5)(inp))
    out = LogSoftMax()(Linear(84, 10)(h))
    model = Graph(inp, out)

Weight sharing: using the same module instance at two nodes shares one
params subtree (the analogue of shared weight storage in the reference's
``ModelBroadcast`` replica cloning).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.module import Context, Module, Params, State


class Node:
    """A module wired into a DAG with its input nodes."""

    __slots__ = ("element", "prev", "keras_shape", "name")

    def __init__(self, element: Optional[Module], prev: Sequence["Node"] = ()):
        self.element = element
        self.prev = list(prev)

    def __repr__(self):
        return f"Node({self.element!r})"


def Input() -> Node:
    """Graph input placeholder (reference: ``DL/nn/Input.scala``)."""
    return Node(None, [])


def to_node(x: Union[Node, Module]) -> Node:
    if isinstance(x, Node):
        return x
    if isinstance(x, Module):
        return Node(x, [])
    raise TypeError(f"cannot wire {type(x).__name__} into a graph")


class Graph(Module):
    def __init__(
        self,
        inputs: Union[Node, Sequence[Node]],
        outputs: Union[Node, Sequence[Node]],
    ):
        super().__init__()
        self.inputs: List[Node] = [inputs] if isinstance(inputs, Node) else list(inputs)
        self.outputs: List[Node] = [outputs] if isinstance(outputs, Node) else list(outputs)
        self._topo: List[Node] = self._topo_sort()
        self._names: Dict[int, str] = self._assign_names()
        # register unique modules as children in topo order for init()
        for node in self._topo:
            if node.element is not None:
                name = self._names[id(node)]
                if name not in self._modules:
                    self._modules[name] = node.element

    def _topo_sort(self) -> List[Node]:
        """Deterministic post-order DFS from outputs (reference:
        ``DirectedGraph.topologySort``)."""
        order: List[Node] = []
        seen: Dict[int, int] = {}  # id -> 0 visiting, 1 done
        def visit(n: Node):
            nid = id(n)
            st = seen.get(nid)
            if st == 1:
                return
            if st == 0:
                raise ValueError("Graph contains a cycle")
            seen[nid] = 0
            for p in n.prev:
                visit(p)
            seen[nid] = 1
            order.append(n)
        for out in self.outputs:
            visit(out)
        for inp in self.inputs:
            if id(inp) not in seen:
                raise ValueError("a declared Graph input is not reachable from outputs")
        return order

    def _assign_names(self) -> Dict[int, str]:
        names: Dict[int, str] = {}
        by_module: Dict[int, str] = {}
        counters: Dict[str, int] = {}
        for node in self._topo:
            if node.element is None:
                continue
            mid = id(node.element)
            if mid in by_module:  # shared module -> shared params subtree
                names[id(node)] = by_module[mid]
                continue
            base = node.element.get_name() or type(node.element).__name__
            k = counters.get(base, 0)
            counters[base] = k + 1
            name = base if node.element.get_name() else f"{base}_{k}"
            by_module[mid] = name
            names[id(node)] = name
        return names

    def forward(self, ctx: Context, x):
        acts: Dict[int, object] = {}
        xs = (x,) if len(self.inputs) == 1 else tuple(x)
        if len(xs) != len(self.inputs):
            raise ValueError(f"Graph expects {len(self.inputs)} inputs, got {len(xs)}")
        for node, xi in zip(self.inputs, xs):
            acts[id(node)] = xi
        for node in self._topo:
            if id(node) in acts:
                continue
            if node.element is None:
                raise ValueError("unbound Input node (not listed in Graph inputs)")
            parents = [acts[id(p)] for p in node.prev]
            nin = parents[0] if len(parents) == 1 else tuple(parents)
            acts[id(node)] = node.element.forward(ctx.child(self._names[id(node)]), nin)
        outs = tuple(acts[id(n)] for n in self.outputs)
        return outs[0] if len(outs) == 1 else outs

    def node_names(self) -> List[str]:
        return [self._names[id(n)] for n in self._topo if n.element is not None]
