"""Containers: Sequential, Concat, ConcatTable, ParallelTable, MapTable, Bottle.

Reference: ``DL/nn/Container.scala``, ``Sequential.scala``, ``Concat.scala``,
``ConcatTable.scala``, ``ParallelTable.scala``, ``MapTable.scala``,
``Bottle.scala``. Children are registered under stable string keys so the
params/state pytrees mirror the module tree.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.module import Context, Module


class Container(Module):
    """Ordered container base (reference: ``Container.scala:237``)."""

    def __init__(self, *modules: Module):
        super().__init__()
        for m in modules:
            self.add(m)

    def add(self, module: Module, name: Optional[str] = None) -> "Container":
        name = name or module.get_name() or str(len(self._modules))
        if name in self._modules:
            name = f"{name}_{len(self._modules)}"
        self._modules[name] = module
        return self

    def __len__(self) -> int:
        return len(self._modules)

    def __getitem__(self, i: int) -> Module:
        return list(self._modules.values())[i]


class Sequential(Container):
    """Feed modules in registration order (reference: ``Sequential.scala``)."""

    def forward(self, ctx: Context, x):
        for name, m in self._modules.items():
            x = m.forward(ctx.child(name), x)
        return x


class ConcatTable(Container):
    """Apply every child to the same input, return a tuple of outputs
    (reference: ``ConcatTable.scala``)."""

    def forward(self, ctx: Context, x):
        return tuple(m.forward(ctx.child(name), x) for name, m in self._modules.items())


class ParallelTable(Container):
    """Apply i-th child to i-th input element (reference: ``ParallelTable.scala``)."""

    def forward(self, ctx: Context, x):
        items = list(self._modules.items())
        if len(items) != len(x):
            raise ValueError(f"ParallelTable: {len(items)} children but {len(x)} inputs")
        return tuple(m.forward(ctx.child(name), xi) for (name, m), xi in zip(items, x))


class Concat(Container):
    """Apply every child to the same input and concatenate outputs along
    ``dimension`` (reference: ``Concat.scala``; used by Inception towers).
    Dimension is 0-indexed over the full batched shape (the reference is
    1-indexed; dim=1 there == dim=1 here for NCHW batched input)."""

    def __init__(self, dimension: int, *modules: Module):
        super().__init__(*modules)
        self.dimension = dimension

    def forward(self, ctx: Context, x):
        outs = [m.forward(ctx.child(name), x) for name, m in self._modules.items()]
        return jnp.concatenate(outs, axis=self.dimension)


class MapTable(Container):
    """Apply the single child to every element of the input table
    (reference: ``MapTable.scala``). Parameters are shared across elements."""

    def __init__(self, module: Module):
        super().__init__()
        self.add(module, "0")

    def forward(self, ctx: Context, x):
        (name, m), = self._modules.items()
        return tuple(m.forward(ctx.child(name), xi) for xi in x)


class Bottle(Container):
    """Flatten leading dims to apply an n-D module to higher-D input
    (reference: ``Bottle.scala``)."""

    def __init__(self, module: Module, n_input_dim: int = 2, n_output_dim: Optional[int] = None):
        super().__init__()
        self.add(module, "0")
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim or n_input_dim

    def forward(self, ctx: Context, x):
        (name, m), = self._modules.items()
        shape = x.shape
        lead = shape[: len(shape) - self.n_input_dim + 1]
        flat = x.reshape((-1,) + shape[len(shape) - self.n_input_dim + 1 :])
        y = m.forward(ctx.child(name), flat)
        return y.reshape(lead + y.shape[1:])
