"""Module and Criterion core.

TPU-native redesign of the reference's ``AbstractModule``
(``DL/nn/abstractnn/AbstractModule.scala:59``). The reference's modules are
mutable objects holding weight/gradWeight tensors, with hand-written
``updateOutput``/``updateGradInput``/``accGradParameters``. Here a module is
a *static description*; its learnable parameters and mutable buffers live in
separate pytrees so the whole model is a pure function

    ``output, new_state = module.apply(params, x, state=..., training=...)``

that jit/grad/vmap/pjit understand. Backward passes come from ``jax.grad`` —
there are no hand-written gradients except where numerics demand a
``custom_vjp`` (SURVEY.md §7 design translation table).

Naming/paths: containers register children under string keys, producing
nested params/state dicts mirroring the module tree (the analogue of the
reference's ``getParametersTable()`` keyed by module name,
``AbstractModule.scala:414``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.core.rng import fold_in_str

Params = Dict[str, Any]
State = Dict[str, Any]


def capture_init_args(cls) -> None:
    """Wrap ``cls.__init__`` (own, not inherited) to record the outermost
    constructor call's ``(args, kwargs)`` as ``self._init_config``.

    This powers structure serialization (``utils/serializer.py``): the
    reference persists every module through reflection over its constructor
    (``ModuleSerializer.scala:36`` default ``ModuleSerializable``); here the
    captured config is the reflective record. Inner ``super().__init__``
    calls see the attribute already set and leave it alone.
    """
    if "__init__" not in cls.__dict__ or getattr(cls.__init__, "_bigdl_captured", False):
        return
    orig = cls.__init__

    def wrapped(self, *args, **kwargs):
        outermost = not hasattr(self, "_init_config")
        if outermost:
            object.__setattr__(self, "_init_config", (args, kwargs))
        orig(self, *args, **kwargs)
        if outermost and hasattr(self, "_modules"):
            # which children the constructor itself created — the
            # serializer re-encodes only children added AFTER construction
            object.__setattr__(self, "_ctor_children", frozenset(self._modules))

    wrapped._bigdl_captured = True
    wrapped.__wrapped__ = orig
    wrapped.__name__ = "__init__"
    cls.__init__ = wrapped


class Context:
    """Per-apply context threading params/state subtree, training flag and RNG.

    Collects state updates (e.g. BN running stats) into a shared flat dict
    keyed by absolute module path; ``Module.apply`` merges them back into a
    nested state tree after the (traced) forward completes.
    """

    __slots__ = ("params", "state", "training", "_rng", "path", "_updates", "_rng_count")

    def __init__(self, params, state, training, rng, path=(), updates=None, rng_count=None):
        self.params = params if params is not None else {}
        self.state = state if state is not None else {}
        self.training = training
        self._rng = rng
        self.path = path
        self._updates = updates if updates is not None else {}
        self._rng_count = rng_count if rng_count is not None else [0]

    def child(self, name: str) -> "Context":
        return Context(
            self.params.get(name, {}),
            self.state.get(name, {}),
            self.training,
            self._rng,
            self.path + (name,),
            self._updates,
            self._rng_count,
        )

    # params / state access for leaf modules
    def param(self, key: str):
        try:
            return self.params[key]
        except (KeyError, TypeError):
            raise KeyError(
                f"missing parameter '{key}' at module path {'/'.join(self.path) or '<root>'}; "
                f"did you pass the params tree returned by init()?"
            ) from None

    def get_state(self, key: str):
        return self.state[key]

    def put_state(self, key: str, value) -> None:
        self._updates.setdefault(self.path, {})[key] = value

    def rng(self) -> jax.Array:
        """Deterministic per-path, per-call RNG stream."""
        if self._rng is None:
            raise ValueError(
                "this module needs an rng (e.g. Dropout in training mode): "
                "pass rng=... to apply()"
            )
        self._rng_count[0] += 1
        key = fold_in_str(self._rng, "/".join(self.path))
        return jax.random.fold_in(key, self._rng_count[0])

    @property
    def updates(self):
        return self._updates


def _merge_updates(state: State, updates: Dict[Tuple[str, ...], Dict[str, Any]]) -> State:
    if not updates:
        return state
    new_state = dict(state)
    for path, kv in updates.items():
        node = new_state
        for name in path:
            child = dict(node.get(name, {}))
            node[name] = child
            node = child
        node.update(kv)
    return new_state


class Module:
    """Base class for all layers and containers.

    Key API (mirrors the reference surface where it makes sense):

    - ``init(rng) -> (params, state)`` — build parameter/buffer pytrees
      (replaces the reference's eager ``reset()`` weight allocation).
    - ``apply(params, x, state=None, training=False, rng=None)``
      ``-> (output, new_state)`` — pure forward
      (replaces ``forward``/``updateOutput``, ``AbstractModule.scala:255``).
    - ``forward(ctx, x)`` — override point for subclasses.
    - ``parameters(params)`` — flat (path, array) list (analogue of
      ``AbstractModule.parameters()``, ``AbstractModule.scala:347``).
    - ``set_name`` / ``get_name`` (``AbstractModule.scala`` setName).
    """

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        capture_init_args(cls)

    def __init__(self):
        object.__setattr__(self, "_modules", {})
        object.__setattr__(self, "_name", None)

    # -- submodule registration via attribute assignment --
    def __setattr__(self, key: str, value: Any) -> None:
        if isinstance(value, Module) and not key.startswith("_"):
            self._modules[key] = value
        object.__setattr__(self, key, value)

    def add(self, module: "Module", name: Optional[str] = None) -> "Module":
        """Register a child (containers override ordering semantics)."""
        name = name or module.get_name() or str(len(self._modules))
        if name in self._modules:
            raise ValueError(f"duplicate submodule name '{name}' in {self}")
        self._modules[name] = module
        return self

    @property
    def modules(self) -> Dict[str, "Module"]:
        return self._modules

    # -- naming --
    def set_name(self, name: str) -> "Module":
        object.__setattr__(self, "_name", name)
        return self

    def get_name(self) -> Optional[str]:
        return self._name

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._name or ''})"

    # -- init --
    def build_params(self, rng: jax.Array) -> Params:
        """Leaf parameter construction; override in layers with weights."""
        return {}

    def build_state(self) -> State:
        """Leaf buffer construction (e.g. BN running stats); override."""
        return {}

    def init(self, rng: jax.Array) -> Tuple[Params, State]:
        params: Params = {}
        state: State = {}
        for name, m in self._modules.items():
            p, s = m.init(fold_in_str(rng, name))
            if p:
                params[name] = p
            if s:
                state[name] = s
        params.update(self.build_params(fold_in_str(rng, "~self")))
        state.update(self.build_state())
        return params, state

    def build_param_pspecs(self) -> Dict[str, Any]:
        """Leaf parameter PartitionSpecs (mirrors ``build_params`` keys).

        Override in tensor/expert-parallel layers to declare how their
        weights shard over named mesh axes; trainers consult this via
        ``param_pspecs()`` when placing params (the TPU-native analogue of
        the reference deciding which PS partition owns which weight slice,
        ``DL/parameters/AllReduceParameter.scala:177-190``).
        """
        return {}

    def param_pspecs(self) -> Dict[str, Any]:
        """Nested PartitionSpec tree mirroring the params tree (sparse:
        only annotated leaves appear; everything else is trainer's choice)."""
        out: Dict[str, Any] = {}
        for name, m in self._modules.items():
            sub = m.param_pspecs()
            if sub:
                out[name] = sub
        out.update(self.build_param_pspecs())
        return out

    # -- forward --
    def forward(self, ctx: Context, x):
        raise NotImplementedError(f"{type(self).__name__}.forward")

    def run_child(self, ctx: Context, name: str, x):
        return self._modules[name].forward(ctx.child(name), x)

    def apply(
        self,
        params: Params,
        x,
        state: Optional[State] = None,
        training: bool = False,
        rng: Optional[jax.Array] = None,
        **forward_kwargs,
    ):
        state = state if state is not None else {}
        ctx = Context(params, state, training, rng)
        out = self.forward(ctx, x, **forward_kwargs)
        return out, _merge_updates(state, ctx.updates)

    def __call__(self, *nodes):
        """Graph-building sugar: ``layer(node)`` wires this module into a
        ``Graph`` DAG (reference: ``Node`` / ``inputs(...)`` in
        ``DL/nn/Graph.scala``)."""
        from bigdl_tpu.nn.graph import Node, to_node

        return Node(self, [to_node(n) for n in nodes])

    # -- parameter utilities --
    def parameters(self, params: Params):
        """Flat list of (path, leaf) pairs, path like 'conv1/weight'."""
        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for path, leaf in flat:
            keys = [getattr(k, "key", str(k)) for k in path]
            out.append(("/".join(keys), leaf))
        return out

    def n_parameters(self, params: Params) -> int:
        return sum(int(jnp.size(v)) for _, v in self.parameters(params))

    # -- persistence (reference: ``AbstractModule.saveModule``) --
    def save_module(self, file: str, params=None, state=None, overwrite: bool = True) -> str:
        from bigdl_tpu.utils.serializer import save_module

        return save_module(file, self, params=params, state=state, overwrite=overwrite)

    # -- convenience: stateful eager mode (tests / small scripts) --
    def init_run(self, rng: Optional[jax.Array] = None) -> "Module":
        if rng is None:
            from bigdl_tpu.core.rng import RandomGenerator

            rng = RandomGenerator.default().next_key()
        p, s = self.init(rng)
        object.__setattr__(self, "_eager_params", p)
        object.__setattr__(self, "_eager_state", s)
        return self

    def eager_forward(self, x, training: bool = False, rng=None):
        out, new_state = self.apply(
            self._eager_params, x, state=self._eager_state, training=training, rng=rng
        )
        object.__setattr__(self, "_eager_state", new_state)
        return out


class Criterion:
    """Loss function base (reference: ``AbstractCriterion``).

    Pure: ``loss = criterion.forward(output, target)``. Gradients of the
    loss w.r.t. output come from ``jax.grad`` over the composed train step —
    there is no ``backward``/``updateGradInput`` to hand-write.
    """

    size_average: bool = True

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        capture_init_args(cls)

    def forward(self, output, target):
        raise NotImplementedError

    def __call__(self, output, target):
        return self.forward(output, target)


class LambdaLayer(Module):
    """Wrap a pure function as a parameterless module."""

    def __init__(self, fn: Callable, name: Optional[str] = None):
        super().__init__()
        self._fn = fn
        if name:
            self.set_name(name)

    def forward(self, ctx: Context, x):
        return self._fn(x)
