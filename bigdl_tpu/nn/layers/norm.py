"""Normalization layers.

Reference: ``DL/nn/BatchNormalization.scala`` /
``SpatialBatchNormalization.scala`` (running stats kept per replica and
copied from replica 0, ``LocalOptimizer.scala:209``), ``DL/nn/Normalize.scala``,
``DL/nn/LayerNormalization.scala``.

Deliberate TPU deviation (documented in SURVEY.md §7 "hard parts"): under
SPMD the batch axis is sharded across chips but semantically global — batch
statistics computed with ``jnp.mean`` over a sharded batch make XLA insert
the cross-replica ``psum`` automatically, so running stats are *global*
cross-replica statistics rather than replica-0's local view.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, Ones, Zeros
from bigdl_tpu.nn.module import Context, Module


def _bcast(v, ndim, axis):
    shape = [1] * ndim
    shape[axis] = v.shape[0]
    return v.reshape(shape)


def _bn_apply(x, mean, var, gamma, beta, eps, ch):
    """y = (x - mean) * rsqrt(var + eps) * gamma + beta, folded into one
    fused scale/shift in x.dtype (per-channel factors stay fp32)."""
    inv = lax.rsqrt(var + eps)
    scale = inv * gamma
    shift = beta - mean * scale
    y = x * _bcast(scale, x.ndim, ch).astype(x.dtype) + _bcast(shift, x.ndim, ch).astype(x.dtype)
    return y, inv


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def bn_train(x, gamma, beta, axes, eps):
    """Training-mode batch norm with a hand-fused backward.

    Autodiff of the naive formulation materializes full-size fp32
    activation tensors in the backward (fp32 cotangents through the fp32
    stats path), roughly doubling HBM traffic of the bandwidth-bound BN
    stages. This custom_vjp keeps every full-size tensor in ``x.dtype``
    (bf16 under the mixed policy) and uses fp32 only for the per-channel
    reductions — the textbook fused BN backward.

    Returns ``(y, mean, var)``; mean/var feed the running-stat update and
    are treated as non-differentiable (their cotangents are ignored —
    nothing differentiates through running statistics).
    """
    (y, mean, var), _ = _bn_train_fwd(x, gamma, beta, axes, eps)
    return y, mean, var


# BN statistic-sweep implementation: "reduce" (XLA convert+reduce fusions,
# VPU) or "dot" (both sweeps as lax.dot_general with bf16 inputs and fp32
# MXU accumulation via preferred_element_type — mean contracts against
# ones, sum-of-squares is x·x with the channel as a batch dim). Selectable
# for A/B perf experiments (PERF_NOTES.md round-4); numerics of "dot" are
# at least as good: the MXU multiplies bf16 exactly and accumulates fp32.
# "frozen" is a PERF DIAGNOSTIC ONLY (round-5): constant stats forward and
# no stat sums backward — mathematically WRONG training, it exists to
# measure the end-to-end cost of every BN stat sweep at once (the ceiling
# any fused-stats kernel could win back). Never use it to train.
# Read per-trace (not at import) so tests/experiments can flip it late.
def _bn_stats_impl():
    return os.environ.get("BIGDL_BN_STATS", "reduce")


def _stats_reduce(x, axes):
    # two jnp sums, NOT a variadic lax.reduce: XLA-TPU fuses each
    # convert+square into its reduce and overlaps the sweeps; a measured
    # variadic-reduce variant was 16% SLOWER end-to-end (110 vs 95 ms/step
    # on ResNet-50 b256) because it lowers to a slower loop shape
    mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
    mean_sq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes,
                       dtype=jnp.float32)
    return mean, mean_sq


def _dot_sums(a, b, axes):
    """Per-channel ``(sum(a), sum(a*b))`` over ``axes`` as two MXU
    dot_general contractions — bf16 inputs, fp32 accumulation, and no
    materialized ``a*b`` product. ``a``/``b`` share one non-reduced
    (channel) axis."""
    axes_t = tuple(axes)
    ch = tuple(i for i in range(a.ndim) if i not in axes)
    ones = jnp.ones([a.shape[i] for i in axes], a.dtype)
    s = lax.dot_general(
        a, ones, ((axes_t, tuple(range(len(axes)))), ((), ())),
        preferred_element_type=jnp.float32)
    sab = lax.dot_general(
        a, b, ((axes_t, axes_t), (ch, ch)),
        preferred_element_type=jnp.float32)
    return s.reshape(-1), sab.reshape(-1)


def _stats_dot(x, axes):
    n = float(np.prod([x.shape[i] for i in axes]))
    s, ssq = _dot_sums(x, x, axes)
    return s / n, ssq / n


def _bn_stats(x, axes):
    impl = _bn_stats_impl()
    if impl == "dot":
        return _stats_dot(x, axes)
    if impl == "frozen":  # diagnostic: no sweeps at all (see note above)
        ch = [i for i in range(x.ndim) if i not in axes][0]
        c = x.shape[ch]
        return jnp.zeros((c,), jnp.float32), jnp.ones((c,), jnp.float32)
    return _stats_reduce(x, axes)


# Experimental (round-4 perf lever, OFF by default): compute the forward
# batch statistics over only the first BIGDL_BN_STATS_SAMPLE rows of the
# batch. The sampled mean/var are unbiased estimators with ~batch/sample
# times the variance, applied under stop_gradient (gradients treat them
# as constants — exact for the sampled formulation, and it removes the
# backward's dx correction sweeps entirely). This deviates from the
# reference's full-batch BN semantics and from proper ghost BN (which
# normalizes each subgroup by its own stats and saves nothing).
# VALIDATED HARMFUL (round 5): ResNet-20 on the real-data digits recipe,
# sample=32/batch=128, converges to 91.9% val top-1 vs the full-batch
# control's 98.3% with a visibly unstable curve
# (perf/artifacts/r5_digits_curve.txt). The +2.3% throughput is not worth
# 6.4 accuracy points: keep OFF; retained only as a perf diagnostic.
def _bn_stats_sample():
    try:
        return int(os.environ.get("BIGDL_BN_STATS_SAMPLE", "0"))
    except ValueError:
        return 0


def bn_train_sampled(x, gamma, beta, axes, eps, sample, ch):
    """Training BN with stats over ``x[:sample]``, stop-gradient applied.

    Returns ``(y, mean, var)`` like :func:`bn_train`; plain autodiff is
    exact here (the stats are constants under stop_gradient, so the
    backward is just the per-channel scale plus the dgamma/dbeta sums).

    SPMD caveat: under a sharded batch axis the first ``sample`` GLOBAL
    rows all live on shard 0, so the stats become one shard's data (a
    biased sample if shards see non-iid data) and XLA must broadcast
    them to the other chips. A per-shard slice (strided rows) would
    avoid both; not done because the knob is experimental, off by
    default, and single-chip-motivated (advisor round-4 finding).
    """
    xs = lax.slice_in_dim(x, 0, sample, axis=0)
    mean, mean_sq = _bn_stats(xs, axes)
    mean = lax.stop_gradient(mean)
    var = lax.stop_gradient(jnp.maximum(mean_sq - mean * mean, 0.0))
    y, _ = _bn_apply(x, mean, var, gamma, beta, eps, ch)
    return y, mean, var


def _bn_train_fwd(x, gamma, beta, axes, eps):
    mean, mean_sq = _bn_stats(x, axes)
    var = jnp.maximum(mean_sq - mean * mean, 0.0)
    ch = [i for i in range(x.ndim) if i not in axes][0]
    y, inv = _bn_apply(x, mean, var, gamma, beta, eps, ch)
    return (y, mean, var), (x, gamma, mean, inv)


def _bn_train_bwd(axes, eps, res, cts):
    x, gamma, mean, inv = res
    ch = [i for i in range(x.ndim) if i not in axes][0]
    g, _, _ = cts  # cotangents for mean/var outputs are ignored (see doc)
    impl = _bn_stats_impl()
    if impl in ("frozen", "frozen_bwd"):
        # diagnostic: no backward sums (frozen_bwd keeps real fwd stats)
        k1 = _bcast(inv * gamma, x.ndim, ch).astype(x.dtype)
        zero = jnp.zeros_like(gamma)
        return k1 * g, zero, zero
    n = float(np.prod([x.shape[i] for i in axes]))
    if impl in ("bwdx", "bwdx_dot"):
        # x-based backward (round-5): never materialize xhat. Algebra:
        #   sum_g_xhat = (sum(g*x) - mean*sum(g)) * inv
        #   dx = k1*(g - mg - xhat*mgx) = k1*g + a - b*x
        # with per-channel a = k1*(mgx*inv*mean - mg), b = k1*mgx*inv —
        # the sweeps read (g, x) once and the full-size xhat/product
        # tensors of the textbook formulation simply don't exist.
        # Measured (TPU v5e, b128 ResNet-50): the textbook backward costs
        # 7.6 ms/step of the 43.97 ms step; this formulation removes most
        # of it (PERF_NOTES.md round-5).
        if impl == "bwdx_dot":
            sum_g, sum_gx = _dot_sums(g, x, axes)
        else:
            sum_g = jnp.sum(g, axis=axes, dtype=jnp.float32)
            sum_gx = jnp.sum(g * x, axis=axes, dtype=jnp.float32)
        sum_g_xhat = (sum_gx - mean * sum_g) * inv
        dgamma = sum_g_xhat
        dbeta = sum_g
        k1v = inv * gamma
        mg = sum_g / n
        mgx = sum_g_xhat / n
        a = k1v * (mgx * inv * mean - mg)
        b = k1v * mgx * inv
        k1 = _bcast(k1v, x.ndim, ch).astype(x.dtype)
        dx = k1 * g + _bcast(a, x.ndim, ch).astype(x.dtype) \
            - _bcast(b, x.ndim, ch).astype(x.dtype) * x
        return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)
    mean_c = _bcast(mean, x.ndim, ch).astype(x.dtype)
    inv_c = _bcast(inv, x.ndim, ch).astype(x.dtype)
    xhat = (x - mean_c) * inv_c
    if impl == "dot":
        sum_g, sum_g_xhat = _dot_sums(g, xhat, axes)
    else:
        # both reductions read (g, xhat) once; XLA fuses them into one pass
        sum_g = jnp.sum(g, axis=axes, dtype=jnp.float32)
        sum_g_xhat = jnp.sum((g * xhat), axis=axes, dtype=jnp.float32)
    dgamma = sum_g_xhat
    dbeta = sum_g
    k1 = _bcast(inv * gamma, x.ndim, ch).astype(x.dtype)
    mg = _bcast(sum_g / n, x.ndim, ch).astype(x.dtype)
    mgx = _bcast(sum_g_xhat / n, x.ndim, ch).astype(x.dtype)
    dx = k1 * (g - mg - xhat * mgx)
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


class BatchNormalization(Module):
    """BN over a (batch, feature) input; ``SpatialBatchNormalization``
    handles (batch, channel, H, W). ``momentum`` follows the reference:
    ``running = (1 - momentum) * running + momentum * batch_stat``."""

    reduce_axes = (0,)
    param_shape_ndim = 2

    def __init__(
        self,
        n_output: int,
        eps: float = 1e-5,
        momentum: float = 0.1,
        affine: bool = True,
        weight_init: Optional[InitializationMethod] = None,
        bias_init: Optional[InitializationMethod] = None,
        data_format: str = "NCHW",
    ):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.weight_init = weight_init or Ones()
        self.bias_init = bias_init or Zeros()
        # channel axis: 1 for NCHW (reference convention); last for NHWC
        # (the TPU-preferred layout — lanes map to channels)
        self.ch_axis = 1 if data_format == "NCHW" else -1

    def build_params(self, rng):
        if not self.affine:
            return {}
        n = self.n_output
        return {
            "weight": self.weight_init(fold_in_str(rng, "weight"), (n,), n, n),
            "bias": self.bias_init(fold_in_str(rng, "bias"), (n,), n, n),
        }

    def build_state(self):
        return {
            "running_mean": jnp.zeros((self.n_output,), jnp.float32),
            "running_var": jnp.ones((self.n_output,), jnp.float32),
        }

    def forward(self, ctx: Context, x):
        ch = self.ch_axis % x.ndim
        axes = tuple(i for i in range(x.ndim) if i != ch)
        if self.affine:
            gamma = ctx.param("weight").astype(jnp.float32)
            beta = ctx.param("bias").astype(jnp.float32)
        else:
            gamma = jnp.ones((self.n_output,), jnp.float32)
            beta = jnp.zeros((self.n_output,), jnp.float32)
        if ctx.training:
            sample = _bn_stats_sample()
            if 0 < sample < x.shape[0] and 0 in axes:
                y, mean, var = bn_train_sampled(x, gamma, beta, axes,
                                                self.eps, sample, ch)
                n_stat = sample * float(np.prod(
                    [x.shape[i] for i in axes if i != 0]))
            else:
                y, mean, var = bn_train(x, gamma, beta, axes, self.eps)
                n_stat = float(np.prod([x.shape[i] for i in axes]))
            mean = lax.stop_gradient(mean)
            var = lax.stop_gradient(var)
            m = self.momentum
            n = n_stat
            unbiased = var * (n / max(1.0, n - 1.0))
            ctx.put_state("running_mean", (1 - m) * ctx.get_state("running_mean") + m * mean)
            ctx.put_state("running_var", (1 - m) * ctx.get_state("running_var") + m * unbiased)
            return y
        mean = ctx.get_state("running_mean")
        var = ctx.get_state("running_var")
        y, _ = _bn_apply(x, mean, var, gamma, beta, self.eps, ch)
        return y


class SpatialBatchNormalization(BatchNormalization):
    """Reference: ``SpatialBatchNormalization.scala`` (NCHW, stats over
    N,H,W per channel). Same implementation — channel is axis 1."""


class LayerNormalization(Module):
    """Reference: ``DL/nn/LayerNormalization.scala`` (transformer tier):
    normalize over the last dim with learned gain/bias."""

    def __init__(self, hidden_size: int, eps: float = 1e-6):
        super().__init__()
        self.hidden_size = hidden_size
        self.eps = eps

    def build_params(self, rng):
        return {
            "weight": jnp.ones((self.hidden_size,), jnp.float32),
            "bias": jnp.zeros((self.hidden_size,), jnp.float32),
        }

    def forward(self, ctx: Context, x):
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=-1, keepdims=True)
        var = xf.var(axis=-1, keepdims=True)
        y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + self.eps))
        y = y * ctx.param("weight") + ctx.param("bias")
        return y.astype(x.dtype)


class Normalize(Module):
    """Lp-normalize along dim 1 (reference: ``DL/nn/Normalize.scala``)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p = p
        self.eps = eps

    def forward(self, ctx: Context, x):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(x), axis=1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=1, keepdims=True) ** (1.0 / self.p)
        return x / (norm + self.eps)


class SpatialCrossMapLRN(Module):
    """Local response normalization across channels (reference
    ``SpatialCrossMapLRN.scala``; AlexNet/Inception-v1 use it):
    ``y = x / (k + alpha/size * sum_{nearby c} x_c^2)^beta``.

    TPU-native: the cross-channel window sum is one avg-pool over the
    channel axis — no hand loops.
    """

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, ctx: Context, x):
        sq = jnp.square(x)
        half = (self.size - 1) // 2
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add,
            (1, self.size, 1, 1), (1, 1, 1, 1),
            [(0, 0), (half, self.size - 1 - half), (0, 0), (0, 0)],
        )
        denom = (self.k + (self.alpha / self.size) * window_sum) ** self.beta
        return x / denom


class SpatialWithinChannelLRN(Module):
    """LRN over a spatial window within each channel (reference
    ``SpatialWithinChannelLRN.scala``)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta

    def forward(self, ctx: Context, x):
        sq = jnp.square(x)
        half = (self.size - 1) // 2
        pad = [(0, 0), (0, 0), (half, self.size - 1 - half), (half, self.size - 1 - half)]
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add, (1, 1, self.size, self.size), (1, 1, 1, 1), pad,
        )
        denom = (1.0 + (self.alpha / (self.size * self.size)) * window_sum) ** self.beta
        return x / denom
