"""Normalization layers.

Reference: ``DL/nn/BatchNormalization.scala`` /
``SpatialBatchNormalization.scala`` (running stats kept per replica and
copied from replica 0, ``LocalOptimizer.scala:209``), ``DL/nn/Normalize.scala``,
``DL/nn/LayerNormalization.scala``.

Deliberate TPU deviation (documented in SURVEY.md §7 "hard parts"): under
SPMD the batch axis is sharded across chips but semantically global — batch
statistics computed with ``jnp.mean`` over a sharded batch make XLA insert
the cross-replica ``psum`` automatically, so running stats are *global*
cross-replica statistics rather than replica-0's local view.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, Ones, Zeros
from bigdl_tpu.nn.module import Context, Module


class BatchNormalization(Module):
    """BN over a (batch, feature) input; ``SpatialBatchNormalization``
    handles (batch, channel, H, W). ``momentum`` follows the reference:
    ``running = (1 - momentum) * running + momentum * batch_stat``."""

    reduce_axes = (0,)
    param_shape_ndim = 2

    def __init__(
        self,
        n_output: int,
        eps: float = 1e-5,
        momentum: float = 0.1,
        affine: bool = True,
        weight_init: Optional[InitializationMethod] = None,
        bias_init: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.weight_init = weight_init or Ones()
        self.bias_init = bias_init or Zeros()

    def build_params(self, rng):
        if not self.affine:
            return {}
        n = self.n_output
        return {
            "weight": self.weight_init(fold_in_str(rng, "weight"), (n,), n, n),
            "bias": self.bias_init(fold_in_str(rng, "bias"), (n,), n, n),
        }

    def build_state(self):
        return {
            "running_mean": jnp.zeros((self.n_output,), jnp.float32),
            "running_var": jnp.ones((self.n_output,), jnp.float32),
        }

    def _broadcast(self, v, ndim):
        shape = [1] * ndim
        shape[1] = self.n_output
        return v.reshape(shape)

    def forward(self, ctx: Context, x):
        axes = tuple(i for i in range(x.ndim) if i != 1)
        if ctx.training:
            # one-pass stats: E[x] and E[x^2] reduce over the same read of x,
            # so XLA fuses both into a single HBM pass (vs. mean-then-var's
            # two sequential passes) — the BN stages at 56x56 resolution are
            # bandwidth-bound, and this halves their stats traffic. Reducing
            # with dtype=float32 accumulates in fp32 WITHOUT materializing
            # (or saving as an autodiff residual) an fp32 copy of the
            # activation: the only residual is the bf16 x itself.
            mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
            mean_sq = jnp.mean(
                jnp.square(x.astype(jnp.float32)), axis=axes, dtype=jnp.float32
            )
            var = jnp.maximum(mean_sq - mean * mean, 0.0)
            m = self.momentum
            n = float(np.prod([x.shape[i] for i in axes]))
            unbiased = var * (n / max(1.0, n - 1.0))
            ctx.put_state("running_mean", (1 - m) * ctx.get_state("running_mean") + m * mean)
            ctx.put_state("running_var", (1 - m) * ctx.get_state("running_var") + m * unbiased)
        else:
            mean = ctx.get_state("running_mean")
            var = ctx.get_state("running_var")
        inv = jnp.reciprocal(jnp.sqrt(var + self.eps))
        if self.affine:
            scale = inv * ctx.param("weight")
            shift = ctx.param("bias") - mean * scale
        else:
            scale = inv
            shift = -mean * scale
        y = x * self._broadcast(scale, x.ndim).astype(x.dtype) + self._broadcast(
            shift, x.ndim
        ).astype(x.dtype)
        return y


class SpatialBatchNormalization(BatchNormalization):
    """Reference: ``SpatialBatchNormalization.scala`` (NCHW, stats over
    N,H,W per channel). Same implementation — channel is axis 1."""


class LayerNormalization(Module):
    """Reference: ``DL/nn/LayerNormalization.scala`` (transformer tier):
    normalize over the last dim with learned gain/bias."""

    def __init__(self, hidden_size: int, eps: float = 1e-6):
        super().__init__()
        self.hidden_size = hidden_size
        self.eps = eps

    def build_params(self, rng):
        return {
            "weight": jnp.ones((self.hidden_size,), jnp.float32),
            "bias": jnp.zeros((self.hidden_size,), jnp.float32),
        }

    def forward(self, ctx: Context, x):
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=-1, keepdims=True)
        var = xf.var(axis=-1, keepdims=True)
        y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + self.eps))
        y = y * ctx.param("weight") + ctx.param("bias")
        return y.astype(x.dtype)


class Normalize(Module):
    """Lp-normalize along dim 1 (reference: ``DL/nn/Normalize.scala``)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p = p
        self.eps = eps

    def forward(self, ctx: Context, x):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(x), axis=1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=1, keepdims=True) ** (1.0 / self.p)
        return x / (norm + self.eps)


class SpatialCrossMapLRN(Module):
    """Local response normalization across channels (reference
    ``SpatialCrossMapLRN.scala``; AlexNet/Inception-v1 use it):
    ``y = x / (k + alpha/size * sum_{nearby c} x_c^2)^beta``.

    TPU-native: the cross-channel window sum is one avg-pool over the
    channel axis — no hand loops.
    """

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, ctx: Context, x):
        sq = jnp.square(x)
        half = (self.size - 1) // 2
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add,
            (1, self.size, 1, 1), (1, 1, 1, 1),
            [(0, 0), (half, self.size - 1 - half), (0, 0), (0, 0)],
        )
        denom = (self.k + (self.alpha / self.size) * window_sum) ** self.beta
        return x / denom


class SpatialWithinChannelLRN(Module):
    """LRN over a spatial window within each channel (reference
    ``SpatialWithinChannelLRN.scala``)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta

    def forward(self, ctx: Context, x):
        sq = jnp.square(x)
        half = (self.size - 1) // 2
        pad = [(0, 0), (0, 0), (half, self.size - 1 - half), (half, self.size - 1 - half)]
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add, (1, 1, self.size, self.size), (1, 1, 1, 1), pad,
        )
        denom = (1.0 + (self.alpha / (self.size * self.size)) * window_sum) ** self.beta
        return x / denom
