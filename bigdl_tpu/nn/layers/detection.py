"""Object-detection stack: anchors, NMS, proposals, RoI pooling, FPN, heads.

Reference (all under ``DL/nn/``): ``Anchor.scala``, ``Nms.scala``,
``Proposal.scala`` / ``RegionProposal.scala``, ``RoiAlign.scala``,
``RoiPooling.scala``, ``PriorBox.scala``, ``FPN.scala``, ``BoxHead.scala``,
``MaskHead.scala``, ``Pooler.scala``, ``DetectionOutputSSD.scala`` /
``DetectionOutputFrcnn.scala`` — hand-loop CPU implementations.

TPU-native redesign principles:

- **static shapes everywhere**: NMS returns a fixed ``max_output`` set of
  indices plus a validity mask (XLA cannot produce data-dependent sizes;
  the reference returns variable-length arrays);
- **NMS as a bounded ``fori_loop``** over argmax-select-and-suppress — the
  classic O(k·N) formulation that compiles to one XLA while loop;
- **RoiAlign as vectorized bilinear gather** (one ``map_coordinates``-style
  gather per level instead of per-RoI loops);
- boxes are ``(x1, y1, x2, y2)`` in input-image coordinates, matching the
  reference's convention.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.layers.conv import SpatialConvolution, SpatialFullConvolution
from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.module import Context, Module

# --------------------------------------------------------- box utilities


def bbox_iou(boxes_a: jax.Array, boxes_b: jax.Array) -> jax.Array:
    """Pairwise IoU, (N, 4) x (M, 4) -> (N, M) (reference ``Bbox.scala``)."""
    area_a = jnp.maximum(boxes_a[:, 2] - boxes_a[:, 0], 0) * \
        jnp.maximum(boxes_a[:, 3] - boxes_a[:, 1], 0)
    area_b = jnp.maximum(boxes_b[:, 2] - boxes_b[:, 0], 0) * \
        jnp.maximum(boxes_b[:, 3] - boxes_b[:, 1], 0)
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def bbox_decode(boxes: jax.Array, deltas: jax.Array,
                weights: Sequence[float] = (1.0, 1.0, 1.0, 1.0)) -> jax.Array:
    """Apply (dx, dy, dw, dh) regression deltas to boxes
    (reference ``Bbox.bboxTransformInv``)."""
    wx, wy, ww, wh = weights
    widths = boxes[:, 2] - boxes[:, 0]
    heights = boxes[:, 3] - boxes[:, 1]
    cx = boxes[:, 0] + 0.5 * widths
    cy = boxes[:, 1] + 0.5 * heights
    dx, dy, dw, dh = (deltas[:, 0] / wx, deltas[:, 1] / wy,
                      deltas[:, 2] / ww, deltas[:, 3] / wh)
    dw = jnp.clip(dw, -1e3, math.log(1000.0 / 16))
    dh = jnp.clip(dh, -1e3, math.log(1000.0 / 16))
    pred_cx = dx * widths + cx
    pred_cy = dy * heights + cy
    pred_w = jnp.exp(dw) * widths
    pred_h = jnp.exp(dh) * heights
    return jnp.stack([
        pred_cx - 0.5 * pred_w, pred_cy - 0.5 * pred_h,
        pred_cx + 0.5 * pred_w, pred_cy + 0.5 * pred_h,
    ], axis=1)


def bbox_clip(boxes: jax.Array, height: float, width: float) -> jax.Array:
    """Clip to image bounds (reference ``Bbox.clipBoxes``)."""
    return jnp.stack([
        jnp.clip(boxes[:, 0], 0, width), jnp.clip(boxes[:, 1], 0, height),
        jnp.clip(boxes[:, 2], 0, width), jnp.clip(boxes[:, 3], 0, height),
    ], axis=1)


def nms(boxes: jax.Array, scores: jax.Array, iou_threshold: float,
        max_output: int, score_threshold: float = -jnp.inf):
    """Fixed-size NMS (reference ``Nms.scala``).

    Returns ``(indices[max_output], valid[max_output])``: greedy
    highest-score selection suppressing overlaps above ``iou_threshold``,
    as one bounded XLA loop.
    """
    n = boxes.shape[0]
    iou = bbox_iou(boxes, boxes)
    live = scores > score_threshold

    def step(i, carry):
        sel_idx, sel_valid, live = carry
        best = jnp.argmax(jnp.where(live, scores, -jnp.inf))
        ok = live[best]
        sel_idx = sel_idx.at[i].set(jnp.where(ok, best, -1))
        sel_valid = sel_valid.at[i].set(ok)
        suppress = iou[best] > iou_threshold
        live = live & ~suppress & (jnp.arange(n) != best)
        live = jnp.where(ok, live, jnp.zeros_like(live))
        return sel_idx, sel_valid, live

    sel_idx = jnp.full((max_output,), -1, jnp.int32)
    sel_valid = jnp.zeros((max_output,), bool)
    sel_idx, sel_valid, _ = lax.fori_loop(0, max_output, step,
                                          (sel_idx, sel_valid, live))
    return sel_idx, sel_valid


class Nms(Module):
    """Module wrapper over :func:`nms` (reference ``Nms.scala``)."""

    def __init__(self, iou_threshold: float = 0.5, max_output: int = 100,
                 score_threshold: float = -jnp.inf):
        super().__init__()
        self.iou_threshold = iou_threshold
        self.max_output = max_output
        self.score_threshold = score_threshold

    def forward(self, ctx: Context, x):
        boxes, scores = x
        return nms(boxes, scores, self.iou_threshold, self.max_output,
                   self.score_threshold)


# ----------------------------------------------------------------- anchors


class Anchor:
    """Anchor generation (reference ``Anchor.scala``): base anchors from
    (ratios x scales), shifted over the feature grid. Pure function-object,
    not a Module (the reference also keeps it separate)."""

    def __init__(self, ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 scales: Sequence[float] = (8.0, 16.0, 32.0),
                 base_size: float = 16.0):
        self.ratios = tuple(ratios)
        self.scales = tuple(scales)
        self.base_size = base_size

    @property
    def num_anchors(self) -> int:
        return len(self.ratios) * len(self.scales)

    def base_anchors(self, base_size: Optional[float] = None) -> jax.Array:
        """Exact reference math (``Anchor.scala:126-222``, the classic
        py-faster-rcnn enumeration): base window ``[0, 0, base-1, base-1]``
        centered at ``(base-1)/2``, ratio widths ROUNDED to integers, scale
        enum preserving the center — so reference-trained RPN weights see
        bit-identical anchors (ADVICE r3: the previous symmetric variant
        had a systematic half-pixel offset)."""
        base = float(base_size if base_size is not None else self.base_size)
        ctr = 0.5 * (base - 1)
        area = base * base
        anchors = []
        for r in self.ratios:
            # floor(v + .5) = Scala Math.round (Python round() is banker's)
            ws = float(math.floor(math.sqrt(area / r) + 0.5))
            hs = float(math.floor(ws * r + 0.5))
            for s in self.scales:
                hw = ws * s / 2 - 0.5
                hh = hs * s / 2 - 0.5
                anchors.append([ctr - hw, ctr - hh, ctr + hw, ctr + hh])
        return jnp.asarray(anchors, jnp.float32)

    def generate(self, feat_h: int, feat_w: int, stride: float) -> jax.Array:
        """(A * H * W, 4) anchors in image coordinates. Shifts are
        ``x * stride`` and the base size follows the stride when they
        differ (``Anchor.scala:39-46,59-70``)."""
        base = self.base_anchors(stride)  # (A, 4)
        shift_x = jnp.arange(feat_w) * stride
        shift_y = jnp.arange(feat_h) * stride
        sx, sy = jnp.meshgrid(shift_x, shift_y)
        shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 4)  # (H*W, 4)
        return (shifts[:, None, :] + base[None, :, :]).reshape(-1, 4)


class PriorBox(Module):
    """SSD prior boxes for one feature map (reference ``PriorBox.scala``).
    forward(feature) -> (num_priors*H*W, 4) normalized [0,1] boxes."""

    def __init__(self, min_sizes: Sequence[float],
                 max_sizes: Sequence[float] = (),
                 aspect_ratios: Sequence[float] = (2.0,),
                 flip: bool = True, clip: bool = False,
                 img_size: int = 300, step: Optional[float] = None,
                 offset: float = 0.5):
        super().__init__()
        self.min_sizes = tuple(min_sizes)
        self.max_sizes = tuple(max_sizes)
        ars = [1.0]
        for ar in aspect_ratios:
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
        self.aspect_ratios = ars
        self.clip = clip
        self.img_size = img_size
        self.step = step
        self.offset = offset

    def forward(self, ctx: Context, x):
        h, w = x.shape[-2], x.shape[-1]
        step = self.step or self.img_size / h
        whs = []
        for mn in self.min_sizes:
            whs.append((mn, mn))
            for mx in self.max_sizes:
                s = math.sqrt(mn * mx)
                whs.append((s, s))
            for ar in self.aspect_ratios:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((mn * math.sqrt(ar), mn / math.sqrt(ar)))
        cx = (jnp.arange(w) + self.offset) * step / self.img_size
        cy = (jnp.arange(h) + self.offset) * step / self.img_size
        gx, gy = jnp.meshgrid(cx, cy)
        centers = jnp.stack([gx, gy], -1).reshape(-1, 2)  # (H*W, 2)
        wh = jnp.asarray(whs, jnp.float32) / self.img_size  # (P, 2)
        boxes = jnp.concatenate([
            (centers[:, None, :] - wh[None] / 2),
            (centers[:, None, :] + wh[None] / 2),
        ], axis=-1).reshape(-1, 4)
        if self.clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        return boxes


# ------------------------------------------------------------- RoI pooling


def roi_align(features: jax.Array, rois: jax.Array, pooled_h: int,
              pooled_w: int, spatial_scale: float,
              sampling_ratio: int = 2, mode: str = "avg") -> jax.Array:
    """RoIAlign (reference ``RoiAlign.scala``): bilinear sampling on a
    regular grid inside each RoI bin, reduced by ``mode`` ("avg" or "max").

    ``features``: (C, H, W); ``rois``: (R, 4) image-coord boxes.
    Returns (R, C, pooled_h, pooled_w).
    """
    c, h, w = features.shape
    boxes = rois * spatial_scale
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    roi_w = jnp.maximum(x2 - x1, 1.0)
    roi_h = jnp.maximum(y2 - y1, 1.0)
    bin_w = roi_w / pooled_w
    bin_h = roi_h / pooled_h
    s = sampling_ratio

    # sample positions: (R, pooled, s) per axis
    def axis_points(start, bin_size, pooled):
        grid = jnp.arange(pooled)[None, :, None]          # (1, P, 1)
        sub = (jnp.arange(s)[None, None, :] + 0.5) / s    # (1, 1, s)
        return start[:, None, None] + (grid + sub) * bin_size[:, None, None]

    px = axis_points(x1, bin_w, pooled_w)  # (R, PW, s)
    py = axis_points(y1, bin_h, pooled_h)  # (R, PH, s)

    def bilinear(img, ys, xs):
        """img (H, W); ys (R,PH,s), xs (R,PW,s) -> (R, PH, s, PW, s)."""
        ys = jnp.clip(ys - 0.5, 0.0, h - 1.0)
        xs = jnp.clip(xs - 0.5, 0.0, w - 1.0)
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy1 = ys - y0
        wx1 = xs - x0
        y0 = y0.astype(jnp.int32)
        x0 = x0.astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)

        def gather(yi, xi):
            return img[yi[:, :, :, None, None], xi[:, None, None, :, :]]

        v00 = gather(y0, x0)
        v01 = gather(y0, x1i)
        v10 = gather(y1i, x0)
        v11 = gather(y1i, x1i)
        wy1b = wy1[:, :, :, None, None]
        wx1b = wx1[:, None, None, :, :]
        return (v00 * (1 - wy1b) * (1 - wx1b) + v01 * (1 - wy1b) * wx1b
                + v10 * wy1b * (1 - wx1b) + v11 * wy1b * wx1b)

    sampled = jax.vmap(lambda img: bilinear(img, py, px))(features)
    # (C, R, PH, s, PW, s) -> reduce over the s x s samples
    reduce = jnp.max if mode == "max" else jnp.mean
    return reduce(sampled, axis=(3, 5)).transpose(1, 0, 2, 3)


class RoiAlign(Module):
    """Module wrapper (reference ``RoiAlign.scala``). Input:
    ``(features (B=1, C, H, W) or (C, H, W), rois (R, 4))``."""

    def __init__(self, spatial_scale: float, sampling_ratio: int,
                 pooled_h: int, pooled_w: int):
        super().__init__()
        self.spatial_scale = spatial_scale
        self.sampling_ratio = sampling_ratio
        self.pooled_h = pooled_h
        self.pooled_w = pooled_w

    def forward(self, ctx: Context, x):
        features, rois = x
        if features.ndim == 4:
            features = features[0]
        return roi_align(features, rois, self.pooled_h, self.pooled_w,
                         self.spatial_scale, self.sampling_ratio)


class RoiPooling(Module):
    """Quantized max RoI pooling (reference ``RoiPooling.scala``) — lowered
    through the same bilinear sampler with MAX over a dense sample grid
    (documented deviation: exact hard-quantized pooling is hostile to XLA
    gathers; RoIAlign-max matches within quantization error)."""

    def __init__(self, pooled_h: int, pooled_w: int, spatial_scale: float,
                 sampling_ratio: int = 4):
        super().__init__()
        self.pooled_h = pooled_h
        self.pooled_w = pooled_w
        self.spatial_scale = spatial_scale
        self.sampling_ratio = sampling_ratio

    def forward(self, ctx: Context, x):
        features, rois = x
        if features.ndim == 4:
            features = features[0]
        return roi_align(features, rois, self.pooled_h, self.pooled_w,
                         self.spatial_scale, sampling_ratio=self.sampling_ratio,
                         mode="max")


class Pooler(Module):
    """Multi-level RoIAlign dispatcher (reference ``Pooler.scala``): each
    RoI is pooled from the FPN level matching its scale, blended by a
    one-hot level assignment (XLA-friendly: every level pools every RoI;
    the select keeps the right one — levels are few, RoIs dominate)."""

    def __init__(self, resolution: int, scales: Sequence[float],
                 sampling_ratio: int = 2):
        super().__init__()
        self.resolution = resolution
        self.scales = tuple(scales)
        self.sampling_ratio = sampling_ratio

    def forward(self, ctx: Context, x):
        features, rois = x  # features: list/tuple of (C,H,W) or (1,C,H,W)
        k_min = -math.log2(self.scales[0])
        areas = jnp.maximum(rois[:, 2] - rois[:, 0], 1e-6) * \
            jnp.maximum(rois[:, 3] - rois[:, 1], 1e-6)
        target = jnp.floor(4 + jnp.log2(jnp.sqrt(areas) / 224.0 + 1e-6))
        target = jnp.clip(target, k_min, k_min + len(self.scales) - 1) - k_min
        pooled = []
        for lvl, (feat, scale) in enumerate(zip(features, self.scales)):
            if feat.ndim == 4:
                feat = feat[0]
            p = roi_align(feat, rois, self.resolution, self.resolution,
                          scale, self.sampling_ratio)
            pooled.append(jnp.where((target == lvl)[:, None, None, None], p, 0.0))
        return sum(pooled)


# ------------------------------------------------------------------- FPN


class FPN(Module):
    """Feature Pyramid Network (reference ``FPN.scala``): lateral 1x1 convs
    + top-down nearest upsampling + 3x3 smoothing convs."""

    def __init__(self, in_channels_list: Sequence[int], out_channels: int,
                 top_blocks: int = 0):
        super().__init__()
        self.in_channels_list = tuple(in_channels_list)
        self.out_channels = out_channels
        self.top_blocks = top_blocks
        for i, cin in enumerate(self.in_channels_list):
            self.add(SpatialConvolution(cin, out_channels, 1, 1), f"lateral{i}")
            self.add(SpatialConvolution(out_channels, out_channels, 3, 3,
                                        pad_w=1, pad_h=1), f"smooth{i}")

    def forward(self, ctx: Context, x):
        """x: tuple of (B, C_i, H_i, W_i), highest resolution first."""
        n = len(self.in_channels_list)
        laterals = [self.run_child(ctx, f"lateral{i}", f) for i, f in enumerate(x)]
        outs = [None] * n
        prev = laterals[-1]
        outs[-1] = self.run_child(ctx, f"smooth{n-1}", prev)
        for i in range(n - 2, -1, -1):
            up = jnp.repeat(jnp.repeat(prev, 2, axis=2), 2, axis=3)
            up = up[:, :, : laterals[i].shape[2], : laterals[i].shape[3]]
            prev = laterals[i] + up
            outs[i] = self.run_child(ctx, f"smooth{i}", prev)
        if self.top_blocks:
            extra = outs[-1]
            for _ in range(self.top_blocks):
                extra = -lax.reduce_window(-extra, -jnp.inf, lax.max,
                                           (1, 1, 1, 1), (1, 1, 2, 2),
                                           [(0, 0)] * 4)
                outs.append(extra)
        return tuple(outs)


# ---------------------------------------------------------------- heads


class RegionProposal(Module):
    """RPN head + proposal generation (reference ``RegionProposal.scala`` /
    ``Proposal.scala``): 3x3 conv trunk, 1x1 objectness + bbox-delta heads,
    anchor decode, clip, top-k by score, NMS to ``post_nms_topn``."""

    def __init__(self, in_channels: int, anchor: Optional[Anchor] = None,
                 pre_nms_topn: int = 1000, post_nms_topn: int = 100,
                 nms_thresh: float = 0.7, min_size: float = 0.0):
        super().__init__()
        self.anchor = anchor or Anchor()
        a = self.anchor.num_anchors
        self.conv = SpatialConvolution(in_channels, in_channels, 3, 3, pad_w=1, pad_h=1)
        self.cls_logits = SpatialConvolution(in_channels, a, 1, 1)
        self.bbox_pred = SpatialConvolution(in_channels, 4 * a, 1, 1)
        self.pre_nms_topn = pre_nms_topn
        self.post_nms_topn = post_nms_topn
        self.nms_thresh = nms_thresh
        self.min_size = min_size

    def forward(self, ctx: Context, x, im_size: Tuple[int, int] = None,
                stride: float = 16.0):
        """x: (1, C, H, W) feature map. Returns (rois (post_nms_topn, 4),
        scores (post_nms_topn,), valid mask)."""
        feat = jnp.maximum(self.run_child(ctx, "conv", x), 0.0)
        logits = self.run_child(ctx, "cls_logits", feat)
        deltas = self.run_child(ctx, "bbox_pred", feat)
        _, a, fh, fw = logits.shape
        anchors = self.anchor.generate(fh, fw, stride)          # (A*H*W, 4)
        scores = logits[0].transpose(1, 2, 0).reshape(-1)        # H,W,A -> flat
        deltas = deltas[0].reshape(a, 4, fh, fw).transpose(2, 3, 0, 1).reshape(-1, 4)
        boxes = bbox_decode(anchors, deltas)
        h_im, w_im = im_size if im_size is not None else (fh * stride, fw * stride)
        boxes = bbox_clip(boxes, h_im, w_im)
        if self.min_size > 0:
            # reference Proposal.scala: drop degenerate small proposals
            keep = ((boxes[:, 2] - boxes[:, 0]) >= self.min_size) & \
                   ((boxes[:, 3] - boxes[:, 1]) >= self.min_size)
            scores = jnp.where(keep, scores, -jnp.inf)
        k = min(self.pre_nms_topn, scores.shape[0])
        top_scores, top_idx = lax.top_k(scores, k)
        top_boxes = boxes[top_idx]
        keep_idx, valid = nms(top_boxes, top_scores, self.nms_thresh,
                              self.post_nms_topn)
        rois = jnp.where(valid[:, None], top_boxes[keep_idx], 0.0)
        roi_scores = jnp.where(valid, top_scores[keep_idx], -jnp.inf)
        return rois, jax.nn.sigmoid(roi_scores), valid


class BoxHead(Module):
    """Fast R-CNN box head (reference ``BoxHead.scala``): two FCs over
    pooled RoIs + class scores + per-class box deltas."""

    def __init__(self, in_channels: int, resolution: int, num_classes: int,
                 representation: int = 1024):
        super().__init__()
        d = in_channels * resolution * resolution
        self.fc1 = Linear(d, representation)
        self.fc2 = Linear(representation, representation)
        self.cls_score = Linear(representation, num_classes)
        self.bbox_pred = Linear(representation, num_classes * 4)

    def forward(self, ctx: Context, x):
        r = x.shape[0]
        h = x.reshape(r, -1)
        h = jnp.maximum(self.run_child(ctx, "fc1", h), 0.0)
        h = jnp.maximum(self.run_child(ctx, "fc2", h), 0.0)
        return (self.run_child(ctx, "cls_score", h),
                self.run_child(ctx, "bbox_pred", h))


class MaskHead(Module):
    """Mask R-CNN mask head (reference ``MaskHead.scala``): conv trunk +
    deconv upsample + per-class 1x1 mask predictor."""

    def __init__(self, in_channels: int, num_classes: int,
                 dim_reduced: int = 256, n_convs: int = 4):
        super().__init__()
        self.n_convs = n_convs
        c = in_channels
        for i in range(n_convs):
            self.add(SpatialConvolution(c, dim_reduced, 3, 3, pad_w=1, pad_h=1),
                     f"mask_fcn{i}")
            c = dim_reduced
        self.deconv = SpatialFullConvolution(dim_reduced, dim_reduced, 2, 2, 2, 2)
        self.predictor = SpatialConvolution(dim_reduced, num_classes, 1, 1)

    def forward(self, ctx: Context, x):
        h = x
        for i in range(self.n_convs):
            h = jnp.maximum(self.run_child(ctx, f"mask_fcn{i}", h), 0.0)
        h = jnp.maximum(self.run_child(ctx, "deconv", h), 0.0)
        return self.run_child(ctx, "predictor", h)


class DetectionOutputSSD(Module):
    """SSD final assembly (reference ``DetectionOutputSSD.scala``): decode
    loc predictions against priors, per-class NMS, fixed-size output.

    Input: (loc (N*4,) or (N,4), conf (N, num_classes) probabilities,
    priors (N, 4)). Output: (boxes (K,4), scores (K,), labels (K,), valid)."""

    def __init__(self, num_classes: int, nms_thresh: float = 0.45,
                 keep_top_k: int = 100, conf_thresh: float = 0.01,
                 variances: Sequence[float] = (0.1, 0.1, 0.2, 0.2)):
        super().__init__()
        self.num_classes = num_classes
        self.nms_thresh = nms_thresh
        self.keep_top_k = keep_top_k
        self.conf_thresh = conf_thresh
        self.variances = tuple(variances)

    def forward(self, ctx: Context, x):
        loc, conf, priors = x
        loc = loc.reshape(-1, 4)
        vx, vy, vw, vh = self.variances
        # variance weights fold into the decode (caffe SSD convention)
        boxes = bbox_decode(priors, loc, weights=(1 / vx, 1 / vy, 1 / vw, 1 / vh))
        # one vmapped NMS over the foreground classes (class 0 = background)
        # instead of num_classes traced loops: boxes and the IoU matrix are
        # shared, XLA compiles a single batched loop
        fg_scores = conf[:, 1:].T  # (C-1, N)
        idx, valid = jax.vmap(
            lambda s: nms(boxes, s, self.nms_thresh, self.keep_top_k,
                          self.conf_thresh)
        )(fg_scores)
        c = fg_scores.shape[0]
        sel_boxes = jnp.where(valid[..., None], boxes[idx], 0.0).reshape(-1, 4)
        sel_scores = jnp.where(valid, jnp.take_along_axis(fg_scores, jnp.maximum(idx, 0), 1),
                               -jnp.inf).reshape(-1)
        sel_labels = jnp.broadcast_to(
            jnp.arange(1, c + 1, dtype=jnp.int32)[:, None], idx.shape).reshape(-1)
        sel_valid = valid.reshape(-1)
        top_scores, order = lax.top_k(sel_scores, self.keep_top_k)
        return (sel_boxes[order], top_scores, sel_labels[order], sel_valid[order])


def scale_bbox(boxes: jax.Array, scale_h: float, scale_w: float) -> jax.Array:
    """Scale (x1, y1, x2, y2) boxes (reference ``BboxUtil.scaleBBox``)."""
    return jnp.stack([boxes[:, 0] * scale_w, boxes[:, 1] * scale_h,
                      boxes[:, 2] * scale_w, boxes[:, 3] * scale_h], axis=1)


def bbox_vote(kept_boxes: jax.Array, cand_boxes: jax.Array,
              cand_scores: jax.Array, iou_threshold: float) -> jax.Array:
    """Box voting (reference ``BboxUtil.bboxVote``): each kept box becomes
    the score-weighted average of all candidate boxes overlapping it by
    >= ``iou_threshold``. Vectorized: one (K, N) IoU matrix instead of the
    reference's per-detection scan."""
    iou = bbox_iou(kept_boxes, cand_boxes)            # (K, N)
    w = jnp.where(iou >= iou_threshold, jnp.maximum(cand_scores, 0.0), 0.0)
    den = jnp.maximum(w.sum(axis=1, keepdims=True), 1e-12)
    return (w @ cand_boxes) / den


class Proposal(Module):
    """Faster-RCNN RPN proposal layer (reference ``Proposal.scala``):
    decode bbox deltas against (ratios x scales) anchors over the feature
    grid, clip to the image, drop boxes smaller than ``min_size`` at the
    original image scale, take the score top-k, NMS at 0.7, keep the
    post-NMS top-k.

    Input table: ``(cls_scores (1, 2A, H, W), bbox_deltas (1, 4A, H, W),
    im_info (1, 4) = [height, width, scale_h, scale_w])`` — channel
    layout matches the reference: scores = [background x A, object x A],
    deltas = A blocks of (dx, dy, dw, dh).

    TPU deviation (static shapes): returns ``(rois (K, 5), scores (K,),
    valid (K,))`` with K = the post-NMS top-k for the current mode and
    rois[:, 0] = batch index 0, instead of a variable-length tensor.
    """

    def __init__(self, pre_nms_topn_test: int = 6000,
                 post_nms_topn_test: int = 300,
                 ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 scales: Sequence[float] = (8.0, 16.0, 32.0),
                 pre_nms_topn_train: int = 12000,
                 post_nms_topn_train: int = 2000,
                 min_size: float = 16.0, nms_thresh: float = 0.7,
                 stride: float = 16.0):
        super().__init__()
        self.anchor = Anchor(ratios, scales)
        self.pre_nms_topn_test = pre_nms_topn_test
        self.post_nms_topn_test = post_nms_topn_test
        self.pre_nms_topn_train = pre_nms_topn_train
        self.post_nms_topn_train = post_nms_topn_train
        self.min_size = min_size
        self.nms_thresh = nms_thresh
        self.stride = stride

    def forward(self, ctx: Context, x):
        cls_scores, bbox_deltas, im_info = x
        a = self.anchor.num_anchors
        _, _, fh, fw = cls_scores.shape
        # object scores are the second A channels (reference narrows to
        # [A+1, 2A]); flatten in (h, w, a) order like transposeAndReshape
        scores = cls_scores[0, a:].transpose(1, 2, 0).reshape(-1)
        deltas = bbox_deltas[0].reshape(a, 4, fh, fw).transpose(2, 3, 0, 1).reshape(-1, 4)
        anchors = self.anchor.generate(fh, fw, self.stride)
        boxes = bbox_decode(anchors, deltas)
        im_h, im_w = im_info[0, 0], im_info[0, 1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, im_w - 1), jnp.clip(boxes[:, 1], 0, im_h - 1),
            jnp.clip(boxes[:, 2], 0, im_w - 1), jnp.clip(boxes[:, 3], 0, im_h - 1),
        ], axis=1)
        min_h = self.min_size * im_info[0, 2]
        min_w = self.min_size * im_info[0, 3]
        keep = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_w) & \
               ((boxes[:, 3] - boxes[:, 1] + 1) >= min_h)
        scores = jnp.where(keep, scores, -jnp.inf)

        pre = self.pre_nms_topn_train if ctx.training else self.pre_nms_topn_test
        post = self.post_nms_topn_train if ctx.training else self.post_nms_topn_test
        k = min(pre, scores.shape[0])
        top_scores, top_idx = lax.top_k(scores, k)
        top_boxes = boxes[top_idx]
        keep_idx, valid = nms(top_boxes, top_scores, self.nms_thresh, post)
        rois = jnp.where(valid[:, None], top_boxes[keep_idx], 0.0)
        roi_scores = jnp.where(valid, top_scores[keep_idx], -jnp.inf)
        rois5 = jnp.concatenate([jnp.zeros((post, 1), rois.dtype), rois], axis=1)
        return rois5, roi_scores, valid


class DetectionOutputFrcnn(Module):
    """Faster-RCNN post-processing (reference ``DetectionOutputFrcnn.scala``):
    unscale RoIs to raw image space, apply per-class box regression, clip,
    per-class score threshold + NMS (skipping background class 0),
    optional box voting, global cap at ``max_per_image`` detections.

    Input table: ``(scores (N, n_classes) softmax probabilities,
    box_deltas (N, 4*n_classes), rois (N, 5) from Proposal,
    im_info (1, 4) = [height, width, scale_h, scale_w])``.

    TPU deviation (static shapes): returns ``(boxes (K, 4), scores (K,),
    labels (K,), valid (K,))`` with K = ``max_per_image``, matching
    :class:`DetectionOutputSSD`'s convention, instead of the reference's
    packed variable-length (1, 1 + 6*count) tensor.
    """

    def __init__(self, nms_thresh: float = 0.3, n_classes: int = 21,
                 bbox_vote: bool = False, max_per_image: int = 100,
                 thresh: float = 0.05):
        super().__init__()
        self.nms_thresh = nms_thresh
        self.n_classes = n_classes
        self.bbox_vote = bbox_vote
        self.max_per_image = max_per_image
        self.thresh = thresh

    def forward(self, ctx: Context, x):
        scores, box_deltas, rois, im_info = x
        n = scores.shape[0]
        c = self.n_classes
        raw = scale_bbox(rois[:, 1:5],
                         1.0 / im_info[0, 2], 1.0 / im_info[0, 3])
        im_h = im_info[0, 0] / im_info[0, 2]
        im_w = im_info[0, 1] / im_info[0, 3]
        # per-class decode: (C, N, 4)
        deltas = box_deltas.reshape(n, c, 4).transpose(1, 0, 2)
        all_boxes = jax.vmap(lambda d: bbox_clip(bbox_decode(raw, d),
                                                 im_h, im_w))(deltas)
        fg_boxes = all_boxes[1:]                     # drop background
        fg_scores = scores[:, 1:].T                  # (C-1, N)
        k = min(self.max_per_image, n)
        idx, valid = jax.vmap(
            lambda b, s: nms(b, s, self.nms_thresh, k, self.thresh)
        )(fg_boxes, fg_scores)
        sel_boxes = jnp.take_along_axis(
            fg_boxes, jnp.maximum(idx, 0)[..., None], axis=1)   # (C-1, k, 4)
        sel_scores = jnp.where(
            valid, jnp.take_along_axis(fg_scores, jnp.maximum(idx, 0), 1),
            -jnp.inf)
        if self.bbox_vote:
            cand_scores = jnp.where(fg_scores > self.thresh, fg_scores, 0.0)
            sel_boxes = jax.vmap(bbox_vote, in_axes=(0, 0, 0, None))(
                sel_boxes, fg_boxes, cand_scores, self.nms_thresh)
        sel_labels = jnp.broadcast_to(
            jnp.arange(1, c, dtype=jnp.int32)[:, None], idx.shape)
        flat_scores = sel_scores.reshape(-1)
        kk = min(self.max_per_image, flat_scores.shape[0])
        top_scores, order = lax.top_k(flat_scores, kk)
        pad = self.max_per_image - kk
        boxes_out = jnp.pad(sel_boxes.reshape(-1, 4)[order], ((0, pad), (0, 0)))
        return (boxes_out,
                jnp.pad(top_scores, (0, pad), constant_values=-jnp.inf),
                jnp.pad(sel_labels.reshape(-1)[order], (0, pad)),
                jnp.pad(valid.reshape(-1)[order] & jnp.isfinite(top_scores),
                        (0, pad)))
