"""Binary tree-LSTM.

Reference: ``DL/nn/BinaryTreeLSTM.scala`` (binary-constituency TreeLSTM,
Tai et al. 2015 — leaf nodes embed input tokens, internal nodes compose
their two children with separate left/right gate weights; used by the
``treeLSTMSentiment`` example with ``TreeNNAccuracy``).

TPU-native encoding: the tree arrives as index arrays in TOPOLOGICAL
order (children before parents) with static shapes —
``left[i]``/``right[i]`` are child node ids (0 = none => leaf) and
``leaf_index[i]`` points into the embedding sequence for leaves.
``lax.scan`` walks the node list once; a whole batch of trees vmaps.
This replaces the reference's recursive ``composer``/``leafModule``
graph-cloning walk with one compiled program.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, Xavier
from bigdl_tpu.nn.module import Context, Module


class BinaryTreeLSTM(Module):
    """forward input: ``(embeddings, tree)`` where

    - ``embeddings``: (B, n_tokens, input_size) leaf token embeddings,
    - ``tree``: int32 (B, n_nodes, 3) rows ``[left, right, leaf_index]``
      in topological order; node ids are 1-based within the tree (0 means
      "no child"); for leaves left == right == 0 and leaf_index is the
      1-based position in ``embeddings`` (0-padded rows are ignored).

    Output: (B, n_nodes, hidden_size) node hidden states (node order as
    given; the root is the last non-padding node — ``TreeNNAccuracy``
    reads whichever node the caller selects).
    """

    def __init__(self, input_size: int, hidden_size: int,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.weight_init = weight_init or Xavier()

    def build_params(self, rng):
        h, d = self.hidden_size, self.input_size
        wi = self.weight_init

        def mk(name, shape, fan_in, fan_out):
            return wi(fold_in_str(rng, name), shape, fan_in, fan_out)

        return {
            # leaf: input -> (i, o, u) gates (leaf cells see no children)
            "leaf_w": mk("leaf_w", (d, 3 * h), d, 3 * h),
            "leaf_b": jnp.zeros((3 * h,), jnp.float32),
            # composer: left/right child h -> (i, lf, rf, o, u)
            "comp_wl": mk("comp_wl", (h, 5 * h), h, 5 * h),
            "comp_wr": mk("comp_wr", (h, 5 * h), h, 5 * h),
            "comp_b": jnp.zeros((5 * h,), jnp.float32),
        }

    def _leaf(self, p, x):
        gates = x @ p["leaf_w"] + p["leaf_b"]
        i, o, u = jnp.split(gates, 3, axis=-1)
        c = jax.nn.sigmoid(i) * jnp.tanh(u)
        hstate = jax.nn.sigmoid(o) * jnp.tanh(c)
        return hstate, c

    def _compose(self, p, hl, hr, cl, cr):
        gates = hl @ p["comp_wl"] + hr @ p["comp_wr"] + p["comp_b"]
        i, lf, rf, o, u = jnp.split(gates, 5, axis=-1)
        c = (jax.nn.sigmoid(i) * jnp.tanh(u)
             + jax.nn.sigmoid(lf) * cl + jax.nn.sigmoid(rf) * cr)
        hstate = jax.nn.sigmoid(o) * jnp.tanh(c)
        return hstate, c

    def forward(self, ctx: Context, x):
        embeddings, tree = x
        p = {k: ctx.param(k) for k in
             ("leaf_w", "leaf_b", "comp_wl", "comp_wr", "comp_b")}
        h = self.hidden_size
        n_nodes = tree.shape[1]

        def one_tree(emb, nodes):
            # slot 0 = "absent child": zeros
            h0 = jnp.zeros((n_nodes + 1, h), emb.dtype)
            c0 = jnp.zeros((n_nodes + 1, h), emb.dtype)
            emb_padded = jnp.concatenate(
                [jnp.zeros((1,) + emb.shape[1:], emb.dtype), emb], axis=0)

            def step(carry, idx):
                hs, cs = carry
                left, right, leaf = nodes[idx, 0], nodes[idx, 1], nodes[idx, 2]
                is_leaf = (left == 0) & (right == 0)
                leaf_h, leaf_c = self._leaf(p, emb_padded[leaf])
                comp_h, comp_c = self._compose(
                    p, hs[left], hs[right], cs[left], cs[right])
                node_h = jnp.where(is_leaf, leaf_h, comp_h)
                node_c = jnp.where(is_leaf, leaf_c, comp_c)
                # padding rows (leaf == 0 and no children) stay zero
                is_pad = is_leaf & (leaf == 0)
                node_h = jnp.where(is_pad, 0.0, node_h)
                node_c = jnp.where(is_pad, 0.0, node_c)
                hs = hs.at[idx + 1].set(node_h)
                cs = cs.at[idx + 1].set(node_c)
                return (hs, cs), node_h

            (_, _), out = lax.scan(step, (h0, c0), jnp.arange(n_nodes))
            return out  # (n_nodes, hidden)

        return jax.vmap(one_tree)(embeddings, tree.astype(jnp.int32))
