"""Sequence beam search decoder.

Reference: ``DL/nn/SequenceBeamSearch.scala`` (the Transformer tier's beam
decoder: beam_size candidates, ((5 + len)/6)^alpha length normalization,
EOS-terminated finished set — itself a port of the TF official
implementation).

TPU-native: one ``lax.scan`` over ``max_decode_length`` steps with fully
static shapes; alive/finished sets are fixed-size (beam_size) arrays with
scores, so the whole decode jits into a single XLA program.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Context, Module

_NEG_INF = -1.0e7


def _length_penalty(alpha: float, length) -> jnp.ndarray:
    return jnp.power((5.0 + jnp.asarray(length, jnp.float32)) / 6.0, alpha)


def _gather_beams(x, beam_indices):
    """x: (B, k, ...); beam_indices: (B, new_k) -> (B, new_k, ...)."""
    return jax.vmap(lambda row, idx: row[idx])(x, beam_indices)


def beam_search(
    symbols_to_logits_fn: Callable,
    initial_ids: jnp.ndarray,
    beam_size: int,
    vocab_size: int,
    alpha: float,
    max_decode_length: int,
    eos_id: int,
    states=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns ``(sequences (B, k, L+1), scores (B, k))`` sorted best-first.

    ``symbols_to_logits_fn(ids, i, states) -> (logits (B*k, vocab),
    states)``. ``ids`` is the FULL fixed-width (B*k, L+1) buffer (static
    shapes under scan): positions 0..i hold the decoded prefix, the rest
    are zero padding — read the latest token as ``ids[:, i]``, NOT
    ``ids[:, -1]``.
    """
    batch = initial_ids.shape[0]
    k = beam_size
    L = max_decode_length

    alive_seq = jnp.tile(initial_ids[:, None, None], (1, k, 1))  # (B, k, 1)
    alive_seq = jnp.pad(alive_seq, ((0, 0), (0, 0), (0, L)))     # (B, k, L+1)
    # only beam 0 is live initially (all beams identical otherwise)
    alive_log_probs = jnp.tile(
        jnp.asarray([[0.0] + [_NEG_INF] * (k - 1)]), (batch, 1))
    finished_seq = jnp.zeros_like(alive_seq)
    finished_scores = jnp.full((batch, k), _NEG_INF)
    finished_flags = jnp.zeros((batch, k), bool)

    def step(carry, i):
        alive_seq, alive_log_probs, fin_seq, fin_scores, fin_flags, states = carry

        flat_ids = alive_seq.reshape(batch * k, L + 1)
        logits, new_states = symbols_to_logits_fn(flat_ids, i, states)
        log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        log_probs = log_probs.reshape(batch, k, vocab_size) + alive_log_probs[..., None]

        flat = log_probs.reshape(batch, k * vocab_size)
        # 2k candidates so enough non-EOS survivors exist
        topk_lp, topk_idx = lax.top_k(flat, 2 * k)
        beam_idx = topk_idx // vocab_size
        token_idx = topk_idx % vocab_size

        cand_seq = _gather_beams(alive_seq, beam_idx)  # (B, 2k, L+1)
        cand_seq = jax.vmap(
            lambda s, t, pos: jax.vmap(
                lambda row, tok: lax.dynamic_update_index_in_dim(row, tok, pos, 0)
            )(s, t),
            in_axes=(0, 0, None),
        )(cand_seq, token_idx.astype(cand_seq.dtype), i + 1)
        cand_is_eos = token_idx == eos_id

        # alive set: best k non-EOS candidates
        alive_cand_lp = jnp.where(cand_is_eos, _NEG_INF, topk_lp)
        new_alive_lp, alive_pick = lax.top_k(alive_cand_lp, k)
        new_alive_seq = _gather_beams(cand_seq, alive_pick)

        # finished set: EOS candidates join, keep best k by normalized score
        # (penalty length i+1 = decoded tokens, reference
        # SequenceBeamSearch.scala:437)
        cand_scores = topk_lp / _length_penalty(alpha, i + 1)
        cand_scores = jnp.where(cand_is_eos, cand_scores, _NEG_INF)
        all_scores = jnp.concatenate([fin_scores, cand_scores], axis=1)
        all_flags = jnp.concatenate(
            [fin_flags, cand_is_eos], axis=1)
        all_seq = jnp.concatenate([fin_seq, cand_seq], axis=1)
        new_fin_scores, fin_pick = lax.top_k(all_scores, k)
        new_fin_seq = _gather_beams(all_seq, fin_pick)
        new_fin_flags = jnp.take_along_axis(all_flags, fin_pick, axis=1)

        return (new_alive_seq, new_alive_lp, new_fin_seq, new_fin_scores,
                new_fin_flags, new_states), None

    carry = (alive_seq, alive_log_probs, finished_seq, finished_scores,
             finished_flags, states)
    (alive_seq, alive_log_probs, finished_seq, finished_scores,
     finished_flags, _), _ = lax.scan(step, carry, jnp.arange(L))

    # fall back to alive beams where nothing finished (penalty at
    # max_decode_length, reference :151)
    alive_scores = alive_log_probs / _length_penalty(alpha, L)
    any_finished = finished_flags.any(axis=1, keepdims=True)
    seq = jnp.where(any_finished[..., None], finished_seq, alive_seq)
    scores = jnp.where(any_finished, finished_scores, alive_scores)
    return seq, scores


class SequenceBeamSearch(Module):
    """Module wrapper (reference ``SequenceBeamSearch.scala`` ctor args:
    vocab_size, beam_size, alpha, max_decode_length, eos_id). The
    ``symbols_to_logits_fn`` closes over the decoder model."""

    def __init__(self, symbols_to_logits_fn: Callable, vocab_size: int,
                 beam_size: int, alpha: float, max_decode_length: int,
                 eos_id: int):
        super().__init__()
        self.fn = symbols_to_logits_fn
        self.vocab_size = vocab_size
        self.beam_size = beam_size
        self.alpha = alpha
        self.max_decode_length = max_decode_length
        self.eos_id = eos_id

    def forward(self, ctx: Context, initial_ids):
        return beam_search(
            self.fn, initial_ids, self.beam_size, self.vocab_size,
            self.alpha, self.max_decode_length, self.eos_id)
