"""Sparse layers: embedding bags and sparse-input linear.

Reference: ``DL/nn/LookupTableSparse.scala`` (embedding over a
SparseTensor of ids with sum/mean/sqrtn combiners),
``DL/nn/SparseLinear.scala``, ``DL/nn/SparseJoinTable.scala``.

TPU-native: inputs arrive in the padded-COO device layout
``(ids, weights, mask)`` produced by ``SparseTensor.to_padded`` /
``SparseMiniBatch`` — gathers over the embedding matrix plus masked
reductions, all static-shaped so XLA tiles them; no sparse BLAS loops.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, RandomNormal, Xavier, Zeros
from bigdl_tpu.nn.module import Context, Module


class LookupTableSparse(Module):
    """Embedding bag (reference ``LookupTableSparse.scala``).

    Input: ``(ids, weights, mask)`` each (B, max_nnz); output
    (B, n_output). ``combiner``: "sum" | "mean" | "sqrtn" — identical
    semantics to the reference / TF ``embedding_lookup_sparse``: weights
    multiply the gathered rows; mean divides by ``sum(weights)`` and
    sqrtn by ``sqrt(sum(weights^2))`` over the VALID entries.
    """

    def __init__(self, n_index: int, n_output: int, combiner: str = "sum",
                 max_norm: Optional[float] = None,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError(f"unknown combiner {combiner!r}")
        self.n_index = n_index
        self.n_output = n_output
        self.combiner = combiner
        self.max_norm = max_norm
        self.weight_init = weight_init or RandomNormal(0.0, 1.0)

    def build_params(self, rng):
        return {
            "weight": self.weight_init(
                fold_in_str(rng, "weight"), (self.n_index, self.n_output),
                self.n_index, self.n_output,
            )
        }

    def forward(self, ctx: Context, x):
        ids, weights, mask = x
        table = ctx.param("weight")
        rows = table[ids]  # (B, nnz, out)
        if self.max_norm is not None:
            # clip only the GATHERED rows — norming the whole table would
            # touch n_index * n_output elements to use B * max_nnz rows
            norms = jnp.linalg.norm(rows, axis=-1, keepdims=True)
            rows = rows * jnp.minimum(1.0, self.max_norm / (norms + 1e-12))
        wv = weights * mask
        summed = (rows * wv[..., None].astype(rows.dtype)).sum(axis=1)
        if self.combiner == "sum":
            return summed
        if self.combiner == "mean":
            denom = wv.sum(axis=1, keepdims=True)
        else:  # sqrtn
            denom = jnp.sqrt(jnp.square(wv).sum(axis=1, keepdims=True))
        return summed / jnp.maximum(denom, 1e-12)


class SparseLinear(Module):
    """Linear over a padded-COO sparse input (reference
    ``SparseLinear.scala``): y = W_sparse-gather + b, i.e. for each row,
    sum_j v_j * W[:, id_j]."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None,
                 bias_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def build_params(self, rng):
        p = {
            "weight": self.weight_init(
                fold_in_str(rng, "weight"), (self.output_size, self.input_size),
                self.input_size, self.output_size,
            )
        }
        if self.with_bias:
            p["bias"] = self.bias_init(
                fold_in_str(rng, "bias"), (self.output_size,),
                self.input_size, self.output_size,
            )
        return p

    def forward(self, ctx: Context, x):
        ids, weights, mask = x
        w = ctx.param("weight")  # (out, in)
        cols = w.T[ids]  # (B, nnz, out) — gather input columns
        v = (weights * mask)[..., None].astype(cols.dtype)
        y = (cols * v).sum(axis=1)
        if self.with_bias:
            y = y + ctx.param("bias").astype(y.dtype)
        return y


class SparseJoinTable(Module):
    """Concatenate padded-COO inputs along the nnz axis with column
    offsets (reference ``SparseJoinTable.scala`` joins 2-D sparse tensors
    along dim 2)."""

    def __init__(self, input_sizes):
        super().__init__()
        self.input_sizes = list(input_sizes)

    def forward(self, ctx: Context, xs):
        ids_parts, w_parts, m_parts = [], [], []
        offset = 0
        for (ids, weights, mask), width in zip(xs, self.input_sizes):
            ids_parts.append(ids + offset)
            w_parts.append(weights)
            m_parts.append(mask)
            offset += width
        return (
            jnp.concatenate(ids_parts, axis=1),
            jnp.concatenate(w_parts, axis=1),
            jnp.concatenate(m_parts, axis=1),
        )
