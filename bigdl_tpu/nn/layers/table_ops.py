"""Table (tuple) arithmetic and glue layers.

Reference: ``DL/nn/CAddTable.scala`` and friends (CSubTable, CMulTable,
CDivTable, CMaxTable, CMinTable, CAveTable), ``JoinTable.scala``,
``SelectTable.scala``, ``SplitTable.scala``, ``FlattenTable.scala``,
``DotProduct.scala``, ``MixtureTable.scala``, ``CosineDistance.scala``.
Inputs are tuples of arrays (the ``Table`` Activity).
"""

from __future__ import annotations

import os
from functools import reduce

import jax.numpy as jnp

from bigdl_tpu.nn.module import Context, Module


class CAddTable(Module):
    def forward(self, ctx: Context, x):
        # BIGDL_RESIDUAL_ADD=pallas (read per-trace, like BIGDL_BN_STATS):
        # measured-REJECTED perf experiment kept for the record — the
        # Pallas kernel wins the standalone microbench (464 vs 269 GB/s,
        # perf/micro_resadd2.py) but LOSES 2x end-to-end (1454 vs 2808
        # img/s, perf/artifacts/r5_resadd_ab.txt): the custom-call
        # boundary forces neighbors out of the adds' fusion
        # neighborhoods. Default (plain XLA add) is the right choice.
        if (len(x) == 2
                and os.environ.get("BIGDL_RESIDUAL_ADD") == "pallas"):
            from bigdl_tpu.ops.pallas_add import residual_add
            return residual_add(x[0], x[1])
        return reduce(jnp.add, x)


class CSubTable(Module):
    def forward(self, ctx: Context, x):
        return x[0] - x[1]


class CMulTable(Module):
    def forward(self, ctx: Context, x):
        return reduce(jnp.multiply, x)


class CDivTable(Module):
    def forward(self, ctx: Context, x):
        return x[0] / x[1]


class CMaxTable(Module):
    def forward(self, ctx: Context, x):
        return reduce(jnp.maximum, x)


class CMinTable(Module):
    def forward(self, ctx: Context, x):
        return reduce(jnp.minimum, x)


class CAveTable(Module):
    def forward(self, ctx: Context, x):
        return reduce(jnp.add, x) / len(x)


class JoinTable(Module):
    """Concatenate table elements along ``dimension`` (0-indexed;
    reference: ``JoinTable.scala``).

    ``n_input_dims`` mirrors the reference's ``nInputDims``: when > 0,
    ``dimension`` refers to an *unbatched* sample of that rank, and an
    input of rank ``n_input_dims + 1`` is treated as batched — the join
    axis shifts right by one at forward time (reference
    ``getPositiveDimension``)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def forward(self, ctx: Context, x):
        axis = self.dimension
        if (self.n_input_dims > 0 and axis >= 0
                and x[0].ndim == self.n_input_dims + 1):
            axis += 1
        return jnp.concatenate(list(x), axis=axis)


class SelectTable(Module):
    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def forward(self, ctx: Context, x):
        return x[self.index]


class SplitTable(Module):
    """Split a tensor into a table along ``dimension``
    (reference: ``SplitTable.scala``)."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def forward(self, ctx: Context, x):
        n = x.shape[self.dimension]
        return tuple(jnp.take(x, i, axis=self.dimension) for i in range(n))


class FlattenTable(Module):
    def forward(self, ctx: Context, x):
        out = []

        def rec(t):
            if isinstance(t, (tuple, list)):
                for e in t:
                    rec(e)
            else:
                out.append(t)

        rec(x)
        return tuple(out)


class DotProduct(Module):
    """Row-wise dot product of two batched inputs (reference:
    ``DotProduct.scala``)."""

    def forward(self, ctx: Context, x):
        a, b = x
        return jnp.sum(a * b, axis=-1)


class MixtureTable(Module):
    """Weighted sum of expert outputs by a gater (reference:
    ``MixtureTable.scala``): input = (gates (B,E), experts table of (B,...))."""

    def forward(self, ctx: Context, x):
        gates, experts = x
        stacked = jnp.stack(list(experts), axis=1)  # (B, E, ...)
        g = gates.reshape(gates.shape + (1,) * (stacked.ndim - 2))
        return jnp.sum(stacked * g, axis=1)


class CosineDistance(Module):
    """Row-wise cosine similarity (reference: ``CosineDistance.scala``)."""

    def __init__(self, eps: float = 1e-12):
        super().__init__()
        self.eps = eps

    def forward(self, ctx: Context, x):
        a, b = x
        na = jnp.linalg.norm(a, axis=-1)
        nb = jnp.linalg.norm(b, axis=-1)
        return jnp.sum(a * b, axis=-1) / jnp.maximum(na * nb, self.eps)
