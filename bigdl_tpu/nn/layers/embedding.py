"""Embedding layers.

Reference: ``DL/nn/LookupTable.scala`` (index->vector table with optional
max-norm renorm and padding index). TPU-native: one ``jnp.take`` gather;
for TP the table is shard-able over the vocab dim (see parallel tier).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, RandomNormal
from bigdl_tpu.nn.module import Context, Module


class LookupTable(Module):
    def __init__(
        self,
        n_index: int,
        n_output: int,
        padding_value: Optional[int] = None,
        weight_init: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.weight_init = weight_init or RandomNormal(0.0, 1.0)

    def build_params(self, rng):
        w = self.weight_init(
            fold_in_str(rng, "weight"),
            (self.n_index, self.n_output),
            self.n_index,
            self.n_output,
        )
        if self.padding_value is not None:
            w = w.at[self.padding_value].set(0.0)
        return {"weight": w}

    def forward(self, ctx: Context, x):
        w = ctx.param("weight")
        return jnp.take(w, x.astype(jnp.int32), axis=0)
