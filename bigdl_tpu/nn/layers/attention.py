"""Transformer tier: multi-head attention, FFN, transformer encoder/decoder.

Reference: ``DL/nn/Attention.scala:35`` (Attention(hiddenSize, numHeads,
attentionDropout)), ``DL/nn/FeedForwardNetwork.scala:32``,
``DL/nn/Transformer.scala:53`` (vocabSize/hiddenSize/numHeads/filterSize/
numHiddenlayers/dropouts, LanguageModel | Translation) and
``TransformerOperation.scala`` (position encoding, masks, pre/post
processing: LayerNorm -> sublayer -> dropout -> residual).

TPU-native differences:
- attention math is the fused flash op (``bigdl_tpu.ops.dot_product_attention``)
  instead of a Graph of MM/SoftMax modules;
- the (B, S) padding mask / causal structure travel as an additive bias or a
  static ``causal`` flag, so everything jits with static shapes;
- incremental decoding keeps a fixed-size KV cache updated with
  ``lax.dynamic_update_slice`` (the reference grows K/V with JoinTable,
  ``Attention.scala:39-40`` — dynamic shapes would defeat XLA).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import RandomNormal
from bigdl_tpu.nn.layers.dropout import Dropout
from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.layers.norm import LayerNormalization
from bigdl_tpu.nn.module import Context, Module
from bigdl_tpu.ops.attention import (
    attention_bias_from_padding,
    dot_product_attention,
    paged_attention,
)
from bigdl_tpu.nn.int8 import dequantize_lanes, quantize_kv_rows
from bigdl_tpu.ops.flash_attention import gather_kv_lanes, gather_scale_lanes


def position_encoding(length: int, hidden_size: int, dtype=jnp.float32) -> jax.Array:
    """Sinusoidal positions (reference: ``TransformerOperation.getPositionEncode``)."""
    pos = jnp.arange(length, dtype=jnp.float32)[:, None]
    n_timescales = hidden_size // 2
    log_inc = math.log(10000.0) / max(n_timescales - 1, 1)
    inv = jnp.exp(jnp.arange(n_timescales, dtype=jnp.float32) * -log_inc)
    scaled = pos * inv[None, :]
    enc = jnp.concatenate([jnp.sin(scaled), jnp.cos(scaled)], axis=1)
    if hidden_size % 2:
        enc = jnp.pad(enc, ((0, 0), (0, 1)))
    return enc.astype(dtype)


class Attention(Module):
    """Multi-head attention, self- or cross- (reference ``Attention.scala:35``).

    Input: ``x`` or ``(x, y)`` (query source, key/value source) plus an
    optional additive ``bias``; heads = ``num_heads`` splits of
    ``hidden_size``. Projections are bias-free Linears, as in the reference
    (``TransformerOperation.dense(..., false)``).
    """

    def __init__(self, hidden_size: int, num_heads: int, attention_dropout: float = 0.0):
        super().__init__()
        if hidden_size % num_heads:
            raise ValueError(f"hidden_size {hidden_size} % num_heads {num_heads} != 0")
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.attention_dropout = attention_dropout
        init = RandomNormal(0.0, hidden_size ** -0.5)
        self.q_layer = Linear(hidden_size, hidden_size, with_bias=False, weight_init=init)
        self.k_layer = Linear(hidden_size, hidden_size, with_bias=False, weight_init=init)
        self.v_layer = Linear(hidden_size, hidden_size, with_bias=False, weight_init=init)
        self.output_layer = Linear(hidden_size, hidden_size, with_bias=False, weight_init=init)

    def _split_heads(self, t):
        b, s, _ = t.shape
        d = self.hidden_size // self.num_heads
        return t.reshape(b, s, self.num_heads, d).transpose(0, 2, 1, 3)

    def _join_heads(self, t):
        b, h, s, d = t.shape
        return t.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    def forward(self, ctx: Context, x, bias: Optional[jax.Array] = None,
                causal: bool = False, cache=None, cache_index=None,
                paged=None, write_len=None):
        if isinstance(x, (tuple, list)):
            x, y = x
        else:
            y = x
        q = self._split_heads(self.run_child(ctx, "q_layer", x))
        k = self._split_heads(self.run_child(ctx, "k_layer", y))
        v = self._split_heads(self.run_child(ctx, "v_layer", y))

        new_cache = None
        if paged is not None:
            # Block-table KV cache (vLLM-style): `paged` is a dict with
            # pools "k"/"v" of shape (num_pages, H, page_size, D) and
            # "map", the int32 physical-page ids. New K/V rows are
            # SCATTERED into the pools, then attention runs over the
            # gathered logical lanes — the same op sequence as the dense
            # slot-table path below, so outputs are bit-identical to it
            # (test-enforced); on TPU the decode step instead streams
            # pages through the Pallas gather kernel ("use_kernel").
            pk, pv = paged["k"], paged["v"]
            # int8 pools carry per-token scale pools (num_pages, page
            # _size) next to the pages: scatter quantizes the new rows
            # (one fp32 scale per row, shared across heads — see
            # nn.int8.quantize_kv_rows), gather dequantizes. A float
            # pool has no scale entries and traces the PR-6 path
            # bit-unchanged.
            pks, pvs = paged.get("k_scale"), paged.get("v_scale")
            int8_kv = pks is not None
            page_size = pk.shape[2]
            if getattr(cache_index, "ndim", 0) == 1 and q.shape[2] > 1:
                # verify (speculative decoding): W candidate rows per
                # slot at positions cache_index .. cache_index+W-1; map
                # is (S, ppn). Same scatter-then-gather sequence as the
                # decode branch below, widened to W rows — rows past the
                # lane end (a slot running out its token budget mid-
                # verify) route to the trash page so they can never land
                # in a page another slot owns. Rejected candidates'
                # rows stay in place: they sit past the slot's rewound
                # position, so they are causally masked until the next
                # verify overwrites them — the recycled-page argument.
                page_map = paged["map"]
                ppn = page_map.shape[1]
                max_len = ppn * page_size
                w = q.shape[2]
                pos = cache_index[:, None] + jnp.arange(w)[None, :]
                pg = jnp.take_along_axis(
                    page_map, jnp.clip(pos // page_size, 0, ppn - 1),
                    axis=1)
                trash = paged.get("trash")
                if trash is not None:
                    pg = jnp.where(pos < max_len, pg, trash)
                row = pos % page_size
                kr = k.transpose(0, 2, 1, 3)        # (S, W, H, D)
                vr = v.transpose(0, 2, 1, 3)
                if int8_kv:
                    kq, ksc = quantize_kv_rows(kr)
                    vq, vsc = quantize_kv_rows(vr)
                    pk = pk.at[pg, :, row].set(kq)
                    pv = pv.at[pg, :, row].set(vq)
                    pks = pks.at[pg, row].set(ksc)
                    pvs = pvs.at[pg, row].set(vsc)
                    lk = dequantize_lanes(
                        gather_kv_lanes(pk, page_map),
                        gather_scale_lanes(pks, page_map))
                    lv = dequantize_lanes(
                        gather_kv_lanes(pv, page_map),
                        gather_scale_lanes(pvs, page_map))
                else:
                    pk = pk.at[pg, :, row].set(kr.astype(pk.dtype))
                    pv = pv.at[pg, :, row].set(vr.astype(pv.dtype))
                    lk = gather_kv_lanes(pk, page_map)   # (S, H, L, D)
                    lv = gather_kv_lanes(pv, page_map)
                if bias is not None:
                    raise ValueError(
                        "paged verify attention takes no external bias")
                cols = jnp.arange(lk.shape[2])
                validity = jnp.where(
                    cols[None, None, :] <= pos[:, :, None], 0.0,
                    -1e9)[:, None]                  # (S, 1, W, L)
                out = dot_product_attention(q, lk, lv, validity)
            elif getattr(cache_index, "ndim", 0) == 1:
                # decode: one token per slot; map is (S, ppn)
                page_map = paged["map"]
                pos = cache_index
                pg = jnp.take_along_axis(
                    page_map, (pos // page_size)[:, None], axis=1)[:, 0]
                row = pos % page_size
                if int8_kv:
                    kq, ksc = quantize_kv_rows(k[:, :, 0, :])
                    vq, vsc = quantize_kv_rows(v[:, :, 0, :])
                    pk = pk.at[pg, :, row].set(kq)
                    pv = pv.at[pg, :, row].set(vq)
                    pks = pks.at[pg, row].set(ksc)
                    pvs = pvs.at[pg, row].set(vsc)
                else:
                    pk = pk.at[pg, :, row].set(k[:, :, 0, :].astype(pk.dtype))
                    pv = pv.at[pg, :, row].set(v[:, :, 0, :].astype(pv.dtype))
                if bias is not None:
                    # external-bias composition: gather the logical
                    # lanes and add the caller's bias to the position-
                    # validity mask — the same op sequence (and scale)
                    # as paged_attention_reference, so an all-zero bias
                    # is bit-identical to the unbiased path below. The
                    # bias broadcasts against (S, 1, 1, L).
                    if int8_kv:
                        lk = dequantize_lanes(
                            gather_kv_lanes(pk, page_map),
                            gather_scale_lanes(pks, page_map))
                        lv = dequantize_lanes(
                            gather_kv_lanes(pv, page_map),
                            gather_scale_lanes(pvs, page_map))
                    else:
                        lk = gather_kv_lanes(pk, page_map)  # (S, H, L, D)
                        lv = gather_kv_lanes(pv, page_map)
                    cols = jnp.arange(lk.shape[2])
                    validity = jnp.where(
                        cols[None, :] <= pos[:, None], 0.0,
                        -1e9)[:, None, None, :]         # (S, 1, 1, L)
                    out = dot_product_attention(q, lk, lv,
                                                bias + validity)
                else:
                    out3 = paged_attention(
                        q[:, :, 0, :], pk, pv, page_map, pos,
                        k_scales=pks, v_scales=pvs,
                        use_kernel=paged.get("use_kernel"))
                    out = out3[:, :, None, :]
            else:
                # prefill chunk: q rows are positions idx..idx+C-1 of ONE
                # sequence whose page ids are the (ppn,) "map" row. Rows
                # past `write_len` are bucket padding: their K/V is
                # routed to the "trash" page so pad garbage can never
                # land in a page another slot owns (the dense path writes
                # pad rows into its own private lane; a shared pool has
                # no private rows to waste).
                pages_row = paged["map"]
                ppn = pages_row.shape[0]
                idx = cache_index if cache_index is not None else 0
                n_chunk = q.shape[2]
                t = jnp.arange(n_chunk)
                pos = idx + t
                valid = t < (n_chunk if write_len is None else write_len)
                pg = jnp.where(
                    valid,
                    pages_row[jnp.clip(pos // page_size, 0, ppn - 1)],
                    paged["trash"])
                row = pos % page_size
                if int8_kv:
                    kq, ksc = quantize_kv_rows(k[0].transpose(1, 0, 2))
                    vq, vsc = quantize_kv_rows(v[0].transpose(1, 0, 2))
                    pk = pk.at[pg, :, row].set(kq)
                    pv = pv.at[pg, :, row].set(vq)
                    pks = pks.at[pg, row].set(ksc)
                    pvs = pvs.at[pg, row].set(vsc)
                    lk = dequantize_lanes(
                        gather_kv_lanes(pk, pages_row),
                        gather_scale_lanes(pks, pages_row))[None]
                    lv = dequantize_lanes(
                        gather_kv_lanes(pv, pages_row),
                        gather_scale_lanes(pvs, pages_row))[None]
                else:
                    pk = pk.at[pg, :, row].set(
                        k[0].transpose(1, 0, 2).astype(pk.dtype))
                    pv = pv.at[pg, :, row].set(
                        v[0].transpose(1, 0, 2).astype(pv.dtype))
                    lk = gather_kv_lanes(pk, pages_row)[None]
                    lv = gather_kv_lanes(pv, pages_row)[None]
                rows = idx + t[:, None]
                cols = jnp.arange(lk.shape[2])[None, :]
                validity = jnp.where(cols <= rows, 0.0, -1e9)[None, None]
                out = dot_product_attention(
                    q, lk, lv, validity if bias is None else bias + validity)
            out = self.run_child(ctx, "output_layer", self._join_heads(out))
            return out, ((pk, pv, pks, pvs) if int8_kv else (pk, pv))
        if cache is not None:
            ck, cv = cache
            idx = cache_index if cache_index is not None else 0
            if getattr(idx, "ndim", 0) == 1:
                # per-row write offsets (continuous batching: every slot in
                # the batch sits at its own decode position)
                def upd(c, kv, i):
                    return jax.lax.dynamic_update_slice(c, kv, (0, i, 0))

                ck = jax.vmap(upd)(ck, k.astype(ck.dtype), idx)
                cv = jax.vmap(upd)(cv, v.astype(cv.dtype), idx)
                rows = idx[:, None] + jnp.arange(q.shape[2])[None, :]
                cols = jnp.arange(ck.shape[2])
                validity = jnp.where(
                    cols[None, None, :] <= rows[:, :, None], 0.0, -1e9,
                )[:, None, :, :]
            else:
                ck = jax.lax.dynamic_update_slice(
                    ck, k.astype(ck.dtype), (0, 0, idx, 0))
                cv = jax.lax.dynamic_update_slice(
                    cv, v.astype(cv.dtype), (0, 0, idx, 0))
                # a cache implies decode: mask both future in-chunk positions
                # and unwritten cache slots — key col j is valid for local
                # query row i iff j <= idx + i (never rely on the caller's
                # bias for this)
                rows = idx + jnp.arange(q.shape[2])[:, None]
                cols = jnp.arange(ck.shape[2])[None, :]
                validity = jnp.where(cols <= rows, 0.0, -1e9)[None, None]
            k, v = ck, cv
            new_cache = (ck, cv)
            bias = validity if bias is None else bias + validity

        drop = self.attention_dropout if ctx.training else 0.0
        out = dot_product_attention(
            q, k, v, bias,
            causal=causal and cache is None,
            dropout_rate=drop,
            dropout_rng=ctx.rng() if drop > 0.0 else None,
        )
        out = self.run_child(ctx, "output_layer", self._join_heads(out))
        if new_cache is not None:
            return out, new_cache
        return out


class FeedForwardNetwork(Module):
    """hidden -> filter (ReLU, dropout) -> hidden
    (reference ``FeedForwardNetwork.scala:32``)."""

    def __init__(self, hidden_size: int, filter_size: int, relu_dropout: float = 0.0):
        super().__init__()
        self.filter_layer = Linear(hidden_size, filter_size)
        self.drop = Dropout(relu_dropout)
        self.output_layer = Linear(filter_size, hidden_size)

    def forward(self, ctx: Context, x):
        h = jax.nn.relu(self.run_child(ctx, "filter_layer", x))
        h = self.run_child(ctx, "drop", h)
        return self.run_child(ctx, "output_layer", h)


class _SubLayer(Module):
    """Pre/post-processing wrapper: LayerNorm -> fn -> dropout -> +residual
    (reference ``TransformerOperation.processInputLayer`` /
    ``prePostProcessingWrapper``)."""

    def __init__(self, inner: Module, hidden_size: int, dropout: float):
        super().__init__()
        self.norm = LayerNormalization(hidden_size)
        self.inner = inner
        self.drop = Dropout(dropout)

    def forward(self, ctx: Context, x, **kw):
        if isinstance(x, (tuple, list)):
            q, y = x
            normed = self.run_child(ctx, "norm", q)
            out = self.inner.forward(ctx.child("inner"), (normed, y), **kw)
            residual = q
        else:
            normed = self.run_child(ctx, "norm", x)
            out = self.inner.forward(ctx.child("inner"), normed, **kw)
            residual = x
        cache = None
        if isinstance(out, tuple):
            out, cache = out
        out = self.run_child(ctx, "drop", out)
        out = residual + out.astype(residual.dtype)
        return (out, cache) if cache is not None else out


class TransformerLayer(Module):
    """One pre-norm block: self-attn (+ optional cross-attn) + FFN."""

    def __init__(self, hidden_size: int, num_heads: int, filter_size: int,
                 attention_dropout: float = 0.0, ffn_dropout: float = 0.0,
                 residual_dropout: float = 0.0, cross_attention: bool = False):
        super().__init__()
        self.self_attention = _SubLayer(
            Attention(hidden_size, num_heads, attention_dropout),
            hidden_size, residual_dropout)
        self.cross = cross_attention
        if cross_attention:
            self.cross_attention = _SubLayer(
                Attention(hidden_size, num_heads, attention_dropout),
                hidden_size, residual_dropout)
        self.ffn = _SubLayer(
            FeedForwardNetwork(hidden_size, filter_size, ffn_dropout),
            hidden_size, residual_dropout)

    def forward(self, ctx: Context, x, bias=None, causal=False,
                encoder_output=None, encoder_bias=None, cache=None,
                cache_index=None, paged=None, write_len=None):
        out = self.self_attention.forward(
            ctx.child("self_attention"), x,
            bias=bias, causal=causal, cache=cache, cache_index=cache_index,
            paged=paged, write_len=write_len)
        new_cache = None
        if isinstance(out, tuple):
            out, new_cache = out
        if self.cross and encoder_output is not None:
            out = self.cross_attention.forward(
                ctx.child("cross_attention"), (out, encoder_output),
                bias=encoder_bias)
        out = self.ffn.forward(ctx.child("ffn"), out)
        return (out, new_cache) if new_cache is not None else out


LANGUAGE_MODEL = "language_model"
TRANSLATION = "translation"


class Transformer(Module):
    """Full transformer (reference ``DL/nn/Transformer.scala:53``).

    ``language_model``: decoder-only causal LM over token ids (B, S) ->
    logits (B, S, vocab). ``translation``: encoder-decoder; input is
    ``(src_ids, tgt_ids)``. Embedding is scaled by sqrt(hidden) and shared
    with the output projection when ``with_share_weights_linear`` (reference
    :63; standard weight tying).
    """

    def __init__(self, vocab_size: int, hidden_size: int, num_heads: int,
                 filter_size: int, num_hidden_layers: int,
                 embedding_dropout: float = 0.0, attention_dropout: float = 0.0,
                 ffn_dropout: float = 0.0, padding_value: int = 0,
                 with_share_weights_linear: bool = True,
                 transformer_type: str = LANGUAGE_MODEL):
        super().__init__()
        if transformer_type not in (LANGUAGE_MODEL, TRANSLATION):
            raise ValueError(transformer_type)
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.num_hidden_layers = num_hidden_layers
        self.padding_value = padding_value
        self.embedding_dropout = embedding_dropout
        self.transformer_type = transformer_type
        self.share_embedding = with_share_weights_linear
        self.embed_drop = Dropout(embedding_dropout)

        def make_stack(prefix, cross):
            for i in range(num_hidden_layers):
                self.add(TransformerLayer(
                    hidden_size, num_heads, filter_size,
                    attention_dropout, ffn_dropout,
                    residual_dropout=embedding_dropout,
                    cross_attention=cross,
                ), name=f"{prefix}{i}")

        if transformer_type == TRANSLATION:
            make_stack("encoder_", False)
            self.src_norm = LayerNormalization(hidden_size)
        make_stack("decoder_", transformer_type == TRANSLATION)
        self.final_norm = LayerNormalization(hidden_size)
        if not with_share_weights_linear:
            self.project = Linear(hidden_size, vocab_size, with_bias=False)

    def build_params(self, rng):
        emb = RandomNormal(0.0, self.hidden_size ** -0.5)(
            fold_in_str(rng, "embedding"),
            (self.vocab_size, self.hidden_size), self.vocab_size, self.hidden_size)
        return {"embedding": emb}

    def _embed(self, ctx: Context, ids):
        emb = ctx.param("embedding")
        x = emb[ids] * (self.hidden_size ** 0.5)
        x = x + position_encoding(ids.shape[1], self.hidden_size, x.dtype)[None]
        return self.run_child(ctx, "embed_drop", x)

    def _logits(self, ctx: Context, h):
        if self.share_embedding:
            if "embedding_q" in ctx.params:
                # int8 lm head (quantize_for_serving): the float
                # embedding keeps doing lookups; the GEMM against it
                # runs s8 x s8 -> s32 with per-vocab-row rescale
                from bigdl_tpu.nn.int8 import int8_linear

                return int8_linear(h, ctx.param("embedding_q"),
                                   ctx.param("lm_scale"))
            emb = ctx.param("embedding").astype(h.dtype)
            return jnp.einsum("bsh,vh->bsv", h, emb)
        return self.run_child(ctx, "project", h)

    def _padding_bias(self, ids):
        return attention_bias_from_padding((ids == self.padding_value))

    # ---------------------------------------------- incremental decoding ----
    # The serving tier's step API (bigdl_tpu/serving/engine.py): a slot-table
    # KV cache of FIXED shapes so one jitted decode step serves every
    # admission/retirement pattern without recompiling. All three methods are
    # pure functions of (params, cache, ...) — jit/donate them freely.

    def _decoder_names(self):
        return [n for n in self._modules if n.startswith("decoder_")]

    def init_cache(self, max_slots: int, max_len: int, dtype=jnp.float32):
        """Zeroed per-layer KV slot table:
        ``{layer: (K, V)}`` with K/V of shape
        ``(max_slots, num_heads, max_len, head_dim)``. Slot contents are
        only ever read through the causal/position mask, so a freed slot's
        stale keys are invisible until a prefill overwrites them."""
        if self.transformer_type != LANGUAGE_MODEL:
            raise ValueError("incremental decoding needs a language_model "
                             "transformer (decoder-only)")
        head_dim = self.hidden_size // self.num_heads
        shape = (max_slots, self.num_heads, max_len, head_dim)
        return {name: (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for name in self._decoder_names()}

    def prefill(self, params, cache, slot, tokens, length):
        """Run one PADDED prompt ``tokens`` (P,) through the decoder,
        writing its keys/values into rows 0..P-1 of ``slot``'s cache lane;
        returns ``(next-token logits (vocab,), new_cache)`` where the logits
        are read at position ``length - 1`` (the last REAL token — pad
        garbage beyond it is causally masked now and overwritten by later
        decode steps before it could ever be attended)."""
        ctx = Context(params, {}, False, None)
        h = self._embed(ctx, tokens[None])
        new_cache = dict(cache)
        for name in self._decoder_names():
            ck, cv = cache[name]
            lane = (jax.lax.dynamic_slice_in_dim(ck, slot, 1, axis=0),
                    jax.lax.dynamic_slice_in_dim(cv, slot, 1, axis=0))
            h, (nk, nv) = self._modules[name].forward(
                ctx.child(name), h, cache=lane, cache_index=0)
            new_cache[name] = (
                jax.lax.dynamic_update_slice_in_dim(ck, nk, slot, axis=0),
                jax.lax.dynamic_update_slice_in_dim(cv, nv, slot, axis=0))
        h = self.run_child(ctx, "final_norm", h)
        logits = self._logits(ctx, h)
        last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=1,
                                            keepdims=False)
        return last[0], new_cache

    # ------------------------------------------------- paged decoding ----
    # Block-table variant of the slot-table API above (vLLM-style paged
    # KV): the cache is a shared pool of fixed-size pages per layer and
    # each sequence owns a row of int32 page ids, so KV memory scales
    # with ACTUAL token counts instead of max_slots x max_len. The
    # logical-lane view a page map reconstitutes is bit-identical to a
    # dense lane, so these produce the same logits as prefill/decode_step
    # (test-enforced). Prefill takes a `start` offset: long prompts run
    # as a sequence of chunks interleaved with decode steps (chunked
    # prefill), each chunk attending to the already-cached prefix.

    def init_paged_cache(self, num_pages: int, page_size: int,
                         dtype=jnp.float32):
        """Zeroed per-layer KV page pools ``{layer: (K, V)}`` with K/V of
        shape ``(num_pages, num_heads, page_size, head_dim)``. Page ids
        are the caller's to manage (the serving tier's ``PagePool``
        reserves one physical page as the trash page for masked
        writes).

        ``dtype="int8"`` (or ``jnp.int8``) stores pages int8 with
        per-token fp32 scale pools of shape ``(num_pages, page_size)``
        riding alongside: the entry becomes ``(K, V, K_scale, V_scale)``
        and the attention layer quantizes on scatter / dequantizes on
        gather (``nn.int8``) — half the bf16 KV bytes plus a
        ``4 / (num_heads * head_dim * itemsize)`` scale overhead."""
        if self.transformer_type != LANGUAGE_MODEL:
            raise ValueError("incremental decoding needs a language_model "
                             "transformer (decoder-only)")
        head_dim = self.hidden_size // self.num_heads
        shape = (num_pages, self.num_heads, page_size, head_dim)
        if jnp.dtype(dtype) == jnp.int8:
            sshape = (num_pages, page_size)
            return {name: (jnp.zeros(shape, jnp.int8),
                           jnp.zeros(shape, jnp.int8),
                           jnp.zeros(sshape, jnp.float32),
                           jnp.zeros(sshape, jnp.float32))
                    for name in self._decoder_names()}
        return {name: (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for name in self._decoder_names()}

    def prefill_paged(self, params, cache, pages_row, tokens, start,
                      length, trash, need_logits: bool = True):
        """Run one prompt chunk ``tokens`` (C,) through the decoder at
        positions ``start .. start+C-1`` of the sequence whose physical
        page ids are ``pages_row`` (ppn,). ``length`` is the number of
        REAL tokens in the chunk (the rest is bucket padding, routed to
        the ``trash`` page); with ``need_logits`` (the FINAL chunk)
        returns ``(next-token logits (vocab,), new_cache)`` read at chunk
        row ``length - 1``, otherwise just ``new_cache``."""
        ctx = Context(params, {}, False, None)
        n_chunk = tokens.shape[0]
        emb = ctx.param("embedding")
        x = emb[tokens][None] * (self.hidden_size ** 0.5)
        page_size = jax.tree_util.tree_leaves(cache)[0].shape[2]
        max_len = pages_row.shape[0] * page_size
        pe = position_encoding(max_len, self.hidden_size, x.dtype)
        x = x + pe[jnp.clip(start + jnp.arange(n_chunk), 0, max_len - 1)][None]
        x = self.run_child(ctx, "embed_drop", x)
        new_cache = dict(cache)
        for name in self._decoder_names():
            entry = cache[name]
            pk, pv = entry[0], entry[1]
            pks, pvs = (entry[2], entry[3]) if len(entry) == 4 else (None,
                                                                     None)
            x, new_cache[name] = self._modules[name].forward(
                ctx.child(name), x, cache_index=start,
                paged={"k": pk, "v": pv, "k_scale": pks, "v_scale": pvs,
                       "map": pages_row, "trash": trash},
                write_len=length)
        if not need_logits:
            return new_cache
        h = self.run_child(ctx, "final_norm", x)
        logits = self._logits(ctx, h)
        last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=1,
                                            keepdims=False)
        return last[0], new_cache

    def decode_step_paged(self, params, cache, tokens, positions, page_map,
                          use_kernel: Optional[bool] = None):
        """One decode step for every slot over the paged pools:
        ``tokens``/``positions`` as in :meth:`decode_step`, ``page_map``
        (S, ppn) int32 physical pages per slot. Returns
        ``(logits (S, vocab), new_cache)``; ``use_kernel`` routes the
        attention through the Pallas paged kernel (TPU) instead of the
        jnp gather reference."""
        ctx = Context(params, {}, False, None)
        emb = ctx.param("embedding")
        x = emb[tokens][:, None, :] * (self.hidden_size ** 0.5)
        page_size = jax.tree_util.tree_leaves(cache)[0].shape[2]
        max_len = page_map.shape[1] * page_size
        pe = position_encoding(max_len, self.hidden_size, x.dtype)
        x = x + pe[positions][:, None, :]
        new_cache = dict(cache)
        for name in self._decoder_names():
            entry = cache[name]
            pk, pv = entry[0], entry[1]
            pks, pvs = (entry[2], entry[3]) if len(entry) == 4 else (None,
                                                                     None)
            x, new_cache[name] = self._modules[name].forward(
                ctx.child(name), x, cache_index=positions,
                paged={"k": pk, "v": pv, "k_scale": pks, "v_scale": pvs,
                       "map": page_map, "use_kernel": use_kernel})
        x = self.run_child(ctx, "final_norm", x)
        return self._logits(ctx, x)[:, 0, :], new_cache

    def decode_verify_paged(self, params, cache, tokens, positions,
                            page_map, trash):
        """The verify step of speculative decoding: a positioned
        multi-token prefill over EVERY slot at once. ``tokens`` (S, W)
        is each slot's last accepted token followed by its W-1 draft
        candidates; ``positions`` (S,) the cache row the first of them
        writes. Writes K/V rows ``positions .. positions+W-1`` into the
        paged pools (rows past the lane end route to ``trash``) and
        returns ``(logits (S, W, vocab), new_cache)`` — row ``i`` is the
        next-token distribution after the candidate at position
        ``positions + i``, so one call scores all W candidate
        continuations that plain decode would take W sequential steps to
        score. Rows are per-slot independent exactly like
        :meth:`decode_step_paged` (retire-and-readmit stays safe)."""
        ctx = Context(params, {}, False, None)
        emb = ctx.param("embedding")
        w = tokens.shape[1]
        x = emb[tokens] * (self.hidden_size ** 0.5)          # (S, W, h)
        page_size = jax.tree_util.tree_leaves(cache)[0].shape[2]
        max_len = page_map.shape[1] * page_size
        pe = position_encoding(max_len, self.hidden_size, x.dtype)
        pos = positions[:, None] + jnp.arange(w)[None, :]
        x = x + pe[jnp.clip(pos, 0, max_len - 1)]
        new_cache = dict(cache)
        for name in self._decoder_names():
            entry = cache[name]
            pk, pv = entry[0], entry[1]
            pks, pvs = (entry[2], entry[3]) if len(entry) == 4 else (None,
                                                                     None)
            x, new_cache[name] = self._modules[name].forward(
                ctx.child(name), x, cache_index=positions,
                paged={"k": pk, "v": pv, "k_scale": pks, "v_scale": pvs,
                       "map": page_map, "trash": trash})
        x = self.run_child(ctx, "final_norm", x)
        return self._logits(ctx, x), new_cache

    def decode_step(self, params, cache, tokens, positions):
        """One decode step for EVERY slot at once: ``tokens`` (S,) are each
        slot's current token, ``positions`` (S,) the cache row it occupies.
        Returns ``(logits (S, vocab), new_cache)``. Rows are independent —
        a slot's output never depends on what other slots hold, which is
        what makes retire-and-readmit between steps safe."""
        ctx = Context(params, {}, False, None)
        emb = ctx.param("embedding")
        x = emb[tokens][:, None, :] * (self.hidden_size ** 0.5)
        max_len = jax.tree_util.tree_leaves(cache)[0].shape[2]
        pe = position_encoding(max_len, self.hidden_size, x.dtype)
        x = x + pe[positions][:, None, :]
        new_cache = dict(cache)
        for name in self._decoder_names():
            x, new_cache[name] = self._modules[name].forward(
                ctx.child(name), x, cache=cache[name], cache_index=positions)
        x = self.run_child(ctx, "final_norm", x)
        return self._logits(ctx, x)[:, 0, :], new_cache

    def forward(self, ctx: Context, x):
        if self.transformer_type == LANGUAGE_MODEL:
            ids = x
            h = self._embed(ctx, ids)
            for name in self._modules:
                if name.startswith("decoder_"):
                    h = self._modules[name].forward(ctx.child(name), h, causal=True)
            h = self.run_child(ctx, "final_norm", h)
            return self._logits(ctx, h)

        src, tgt = x
        src_bias = self._padding_bias(src)
        enc = self._embed(ctx, src)
        for name in self._modules:
            if name.startswith("encoder_"):
                enc = self._modules[name].forward(ctx.child(name), enc, bias=src_bias)
        enc = self.run_child(ctx, "src_norm", enc)

        dec = self._embed(ctx, tgt)
        for name in self._modules:
            if name.startswith("decoder_"):
                dec = self._modules[name].forward(
                    ctx.child(name), dec, causal=True,
                    encoder_output=enc, encoder_bias=src_bias)
        dec = self.run_child(ctx, "final_norm", dec)
        return self._logits(ctx, dec)
