"""Remaining reference zoo layers (coverage sweep, round 2).

Reference (all under ``DL/nn/``): ``ActivityRegularization``,
``NegativeEntropyPenalty``, ``BinaryThreshold``, ``HardShrink``,
``SoftShrink``, ``TanhShrink``, ``LogSigmoid``, ``SoftMin``,
``GaussianSampler``, ``Highway``, ``PairwiseDistance``, ``CrossProduct``,
``MM``, ``MV``, ``Tile``, ``ExpandSize``, ``Pack``, ``Reverse``,
``InferReshape``, ``ResizeBilinear``, ``NormalizeScale``,
``BifurcateSplitTable``, ``NarrowTable``, ``DenseToSparse``,
``SpatialSubtractiveNormalization``, ``SpatialDivisiveNormalization``,
``SpatialContrastiveNormalization``.

Each class cites its reference file; implementations are single fused
XLA expressions (the reference hand-loops most of these on CPU).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.rng import fold_in_str, np_rng
from bigdl_tpu.nn.init import InitializationMethod, Xavier
from bigdl_tpu.nn.module import Context, Module


# -- penalties (identity forward, loss stored in state) ----------------------

class ActivityRegularization(Module):
    """Reference ``ActivityRegularization.scala``: identity forward; adds
    ``l1*sum|x| + l2*sum(x^2)`` to the training loss. The penalty is
    published in module state under ``"loss"`` (the reference exposes a
    ``loss`` field the criterion wrapper reads)."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        super().__init__()
        self.l1, self.l2 = l1, l2

    def build_state(self):
        return {"loss": jnp.zeros((), jnp.float32)}

    def forward(self, ctx: Context, x):
        xf = x.astype(jnp.float32)
        loss = self.l1 * jnp.sum(jnp.abs(xf)) + self.l2 * jnp.sum(xf * xf)
        ctx.put_state("loss", loss)
        return x


class NegativeEntropyPenalty(Module):
    """Reference ``NegativeEntropyPenalty.scala``: identity forward,
    penalty ``beta * sum(p * log p)`` over probabilities (encourages
    exploration in RL); published in state ``"loss"``."""

    def __init__(self, beta: float = 0.01):
        super().__init__()
        self.beta = beta

    def build_state(self):
        return {"loss": jnp.zeros((), jnp.float32)}

    def forward(self, ctx: Context, x):
        p = x.astype(jnp.float32)
        ctx.put_state("loss", self.beta * jnp.sum(p * jnp.log(p + 1e-12)))
        return x


# -- activations --------------------------------------------------------------

class BinaryThreshold(Module):
    """Reference ``BinaryThreshold.scala``: 1 where x > th else 0."""

    def __init__(self, th: float = 1e-6):
        super().__init__()
        self.th = th

    def forward(self, ctx: Context, x):
        return (x > self.th).astype(x.dtype)


class HardShrink(Module):
    """Reference ``HardShrink.scala``: x if |x| > lambda else 0."""

    def __init__(self, lambda_: float = 0.5):
        super().__init__()
        self.lambda_ = lambda_

    def forward(self, ctx: Context, x):
        return jnp.where(jnp.abs(x) > self.lambda_, x, 0).astype(x.dtype)


class SoftShrink(Module):
    """Reference ``SoftShrink.scala``: shrink toward 0 by lambda."""

    def __init__(self, lambda_: float = 0.5):
        super().__init__()
        self.lambda_ = lambda_

    def forward(self, ctx: Context, x):
        return (jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.lambda_, 0)).astype(x.dtype)


class TanhShrink(Module):
    """Reference ``TanhShrink.scala``: x - tanh(x)."""

    def forward(self, ctx: Context, x):
        return x - jnp.tanh(x)


class LogSigmoid(Module):
    """Reference ``LogSigmoid.scala``: log(1/(1+exp(-x))), stable."""

    def forward(self, ctx: Context, x):
        return -jax.nn.softplus(-x)


class SoftMin(Module):
    """Reference ``SoftMin.scala``: softmax of -x along ``dim``."""

    def __init__(self, dim: int = -1):
        super().__init__()
        self.dim = dim

    def forward(self, ctx: Context, x):
        return jax.nn.softmax(-x, axis=self.dim)


# -- sampling / structured ----------------------------------------------------

class GaussianSampler(Module):
    """Reference ``GaussianSampler.scala`` (VAE reparameterization):
    input table (mean, log_var) -> mean + exp(0.5*log_var) * eps."""

    def forward(self, ctx: Context, x):
        mean, log_var = x
        eps = jax.random.normal(ctx.rng(), mean.shape, mean.dtype)
        return mean + jnp.exp(0.5 * log_var) * eps


class Highway(Module):
    """Reference ``Highway.scala``: y = T(x) * H(x) + (1 - T(x)) * x with
    T = sigmoid(Linear), H = activation(Linear) (defaults to tanh)."""

    def __init__(self, size: int, with_bias: bool = True,
                 activation: Optional[Module] = None,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        from bigdl_tpu.nn.layers.activation import Tanh
        from bigdl_tpu.nn.layers.linear import Linear

        self.size = size
        self.activation = activation or Tanh()
        self._modules["gate"] = Linear(size, size, with_bias=with_bias,
                                       weight_init=weight_init)
        self._modules["transform"] = Linear(size, size, with_bias=with_bias,
                                            weight_init=weight_init)

    def forward(self, ctx: Context, x):
        t = jax.nn.sigmoid(
            self._modules["gate"].forward(ctx.child("gate"), x))
        # 'activation' matches the auto-registered child key so a
        # parameterized activation (e.g. PReLU) finds its params
        h = self.activation.forward(
            ctx.child("activation"),
            self._modules["transform"].forward(ctx.child("transform"), x))
        return t * h + (1 - t) * x


class PairwiseDistance(Module):
    """Reference ``PairwiseDistance.scala``: p-norm distance between the
    two table entries, per batch row."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def forward(self, ctx: Context, x):
        a, b = x
        d = jnp.abs(a - b) ** self.norm
        return jnp.sum(d, axis=-1) ** (1.0 / self.norm)


class CrossProduct(Module):
    """Reference ``CrossProduct.scala``: pairwise dot products of a table
    of k (B, d) tensors -> (B, k*(k-1)/2) in row-scan order; optional
    ``num_tensor`` validation and ``embedding_size`` check."""

    def __init__(self, num_tensor: int = 0, embedding_size: int = 0):
        super().__init__()
        self.num_tensor = num_tensor
        self.embedding_size = embedding_size

    def forward(self, ctx: Context, x):
        xs = list(x)
        if self.num_tensor and len(xs) != self.num_tensor:
            raise ValueError(f"expected {self.num_tensor} tensors, got {len(xs)}")
        if self.embedding_size and xs[0].shape[-1] != self.embedding_size:
            raise ValueError("embedding size mismatch")
        outs = []
        for i in range(len(xs)):
            for j in range(i + 1, len(xs)):
                outs.append(jnp.sum(xs[i] * xs[j], axis=-1))
        return jnp.stack(outs, axis=-1)


class MM(Module):
    """Reference ``MM.scala``: batched/unbatched matmul of a 2-tensor
    table with optional transposes."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a, self.trans_b = trans_a, trans_b

    def forward(self, ctx: Context, x):
        a, b = x
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


class MV(Module):
    """Reference ``MV.scala``: (batched) matrix-vector product."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def forward(self, ctx: Context, x):
        m, v = x
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


# -- shape / structural -------------------------------------------------------

class Tile(Module):
    """Reference ``Tile.scala``: repeat ``copies`` times along ``dim``
    (0-indexed over the batched shape)."""

    def __init__(self, dim: int = 0, copies: int = 2):
        super().__init__()
        self.dim, self.copies = dim, copies

    def forward(self, ctx: Context, x):
        reps = [1] * x.ndim
        reps[self.dim] = self.copies
        return jnp.tile(x, reps)


class ExpandSize(Module):
    """Reference ``ExpandSize.scala``: broadcast singleton dims to
    ``sizes`` (-1 keeps the input dim)."""

    def __init__(self, sizes: Sequence[int]):
        super().__init__()
        self.sizes = tuple(sizes)

    def forward(self, ctx: Context, x):
        target = tuple(x.shape[i] if s == -1 else s
                       for i, s in enumerate(self.sizes))
        return jnp.broadcast_to(x, target)


class Pack(Module):
    """Reference ``Pack.scala``: stack a table along a new ``dim``
    (0-indexed over the batched shape)."""

    def __init__(self, dim: int = 0):
        super().__init__()
        self.dim = dim

    def forward(self, ctx: Context, x):
        xs = list(x) if isinstance(x, (tuple, list)) else [x]
        return jnp.stack(xs, axis=self.dim)


class Reverse(Module):
    """Reference ``Reverse.scala``: flip along ``dim``."""

    def __init__(self, dim: int = 0):
        super().__init__()
        self.dim = dim

    def forward(self, ctx: Context, x):
        return jnp.flip(x, axis=self.dim)


class InferReshape(Module):
    """Reference ``InferReshape.scala``: reshape where 0 copies the input
    dim and -1 is inferred; ``batch_mode`` prepends the batch dim."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def forward(self, ctx: Context, x):
        in_shape = x.shape[1:] if self.batch_mode else x.shape
        out = []
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(in_shape[i])
            else:
                out.append(s)
        if self.batch_mode:
            out = [x.shape[0]] + out
        return jnp.reshape(x, tuple(out))


class ResizeBilinear(Module):
    """Reference ``ResizeBilinear.scala``: bilinear spatial resize
    (``jax.image.resize``; align_corners matches the TF semantics the
    reference wraps)."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, data_format: str = "NCHW"):
        super().__init__()
        self.oh, self.ow = output_height, output_width
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, ctx: Context, x):
        if self.data_format == "NCHW":
            shape = (x.shape[0], x.shape[1], self.oh, self.ow)
        else:
            shape = (x.shape[0], self.oh, self.ow, x.shape[3])
        if not self.align_corners:
            return jax.image.resize(x, shape, "bilinear")
        # align_corners: linspace over exact corner points
        h_ax, w_ax = (2, 3) if self.data_format == "NCHW" else (1, 2)
        ih, iw = x.shape[h_ax], x.shape[w_ax]
        rows = jnp.linspace(0, ih - 1, self.oh)
        cols = jnp.linspace(0, iw - 1, self.ow)
        r0 = jnp.floor(rows).astype(jnp.int32)
        c0 = jnp.floor(cols).astype(jnp.int32)
        r1 = jnp.minimum(r0 + 1, ih - 1)
        c1 = jnp.minimum(c0 + 1, iw - 1)
        fr = (rows - r0).astype(x.dtype)
        fc = (cols - c0).astype(x.dtype)

        def gather_h(arr, idx):
            return jnp.take(arr, idx, axis=h_ax)

        def gather_w(arr, idx):
            return jnp.take(arr, idx, axis=w_ax)

        top = gather_h(x, r0)
        bot = gather_h(x, r1)
        frb = fr.reshape(tuple(len(rows) if i == h_ax else 1 for i in range(x.ndim)))
        rows_mixed = top * (1 - frb) + bot * frb
        left = gather_w(rows_mixed, c0)
        right = gather_w(rows_mixed, c1)
        fcb = fc.reshape(tuple(len(cols) if i == w_ax else 1 for i in range(x.ndim)))
        return left * (1 - fcb) + right * fcb


class NormalizeScale(Module):
    """Reference ``NormalizeScale.scala`` (SSD conv4_3 path): p-norm
    normalize then multiply by a learnable per-channel scale initialized
    to ``scale``."""

    def __init__(self, p: float = 2.0, scale: float = 20.0,
                 size: Sequence[int] = (), eps: float = 1e-10):
        super().__init__()
        self.p, self.scale_init, self.size, self.eps = p, scale, tuple(size), eps

    def build_params(self, rng):
        return {"weight": jnp.full(self.size, self.scale_init, jnp.float32)}

    def forward(self, ctx: Context, x):
        norm = jnp.sum(jnp.abs(x.astype(jnp.float32)) ** self.p,
                       axis=1, keepdims=True) ** (1.0 / self.p)
        y = x / (norm + self.eps).astype(x.dtype)
        return y * ctx.param("weight").astype(x.dtype)


# -- table ops ----------------------------------------------------------------

class BifurcateSplitTable(Module):
    """Reference ``BifurcateSplitTable.scala``: split a tensor into two
    halves along ``dim`` (0-indexed over the batched shape)."""

    def __init__(self, dim: int):
        super().__init__()
        self.dim = dim

    def forward(self, ctx: Context, x):
        half = x.shape[self.dim] // 2
        left = lax.slice_in_dim(x, 0, half, axis=self.dim)
        right = lax.slice_in_dim(x, half, x.shape[self.dim], axis=self.dim)
        return (left, right)


class NarrowTable(Module):
    """Reference ``NarrowTable.scala``: select ``length`` table entries
    starting at ``offset`` (1-based, as the reference; length -1 = rest)."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset, self.length = offset, length

    def forward(self, ctx: Context, x):
        xs = list(x)
        start = self.offset - 1
        end = len(xs) if self.length == -1 else start + self.length
        out = xs[start:end]
        return out[0] if len(out) == 1 else tuple(out)


class DenseToSparse(Module):
    """Reference ``DenseToSparse.scala``: dense (B, n) -> padded-COO
    sparse representation (ids, values, mask) matching
    ``bigdl_tpu.core.sparse`` conventions; nnz per row is bounded by the
    static width (XLA needs static shapes — the reference emits a truly
    dynamic SparseTensor, here the mask carries the dynamic count)."""

    def forward(self, ctx: Context, x):
        n = x.shape[-1]
        idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
        mask = (x != 0)
        order = jnp.argsort(~mask, axis=-1, stable=True)
        ids = jnp.take_along_axis(idx, order, axis=-1)
        vals = jnp.take_along_axis(x, order, axis=-1)
        smask = jnp.take_along_axis(mask, order, axis=-1)
        return ids, jnp.where(smask, vals, 0), smask


# -- local normalization family ----------------------------------------------

def _smoothing_kernel(kernel: Optional[np.ndarray], size: int) -> np.ndarray:
    if kernel is None:
        k = np.ones((size, size), np.float32)
    else:
        k = np.asarray(kernel, np.float32)
        if k.ndim == 1:
            k = np.outer(k, k)
    return k / k.sum()


class SpatialSubtractiveNormalization(Module):
    """Reference ``SpatialSubtractiveNormalization.scala``: subtract the
    kernel-weighted local mean (computed across channels) from each
    pixel; SAME-size output via zero padding with edge-effect
    correction (the coef map)."""

    def __init__(self, n_input_plane: int = 1, kernel=None, size: int = 9):
        super().__init__()
        self.n = n_input_plane
        self.kernel = _smoothing_kernel(kernel, size)

    def _local_mean(self, x):
        k = jnp.asarray(self.kernel, x.dtype)[None, None] / self.n
        kh, kw = self.kernel.shape
        pad = [(kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2)]
        mean = lax.conv_general_dilated(
            jnp.mean(x, axis=1, keepdims=True) * self.n, k, (1, 1), pad,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        ones = jnp.ones((1, 1) + x.shape[2:], x.dtype)
        coef = lax.conv_general_dilated(
            ones, k * self.n, (1, 1), pad,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return mean / coef

    def forward(self, ctx: Context, x):
        return x - self._local_mean(x)


class SpatialDivisiveNormalization(Module):
    """Reference ``SpatialDivisiveNormalization.scala``: divide by the
    local standard deviation, floored by its mean and ``threshold``."""

    def __init__(self, n_input_plane: int = 1, kernel=None, size: int = 9,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel, size)
        self.threshold, self.thresval = threshold, thresval

    def forward(self, ctx: Context, x):
        local_var = self.sub._local_mean(x * x)
        local_std = jnp.sqrt(jnp.maximum(local_var, 0))
        mean_std = jnp.mean(local_std, axis=(2, 3), keepdims=True)
        denom = jnp.maximum(jnp.maximum(local_std, mean_std), self.threshold)
        return x / denom


class SpatialContrastiveNormalization(Module):
    """Reference ``SpatialContrastiveNormalization.scala``: subtractive
    then divisive normalization with the same kernel."""

    def __init__(self, n_input_plane: int = 1, kernel=None, size: int = 9,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel, size)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel, size,
                                                threshold, thresval)

    def forward(self, ctx: Context, x):
        return self.div.forward(ctx, self.sub.forward(ctx, x))


class SpatialConvolutionMap(Module):
    """Torch-legacy connection-table conv (reference
    ``SpatialConvolutionMap.scala``): each output plane connects to a
    subset of input planes given by ``conn_table`` rows ``(in, out)``
    (0-based here; the reference/Torch tables are 1-based).

    TPU-native: the per-connection (kH, kW) kernels scatter into a dense
    (O, I, kH, kW) weight at trace time (the table is static), and the
    whole layer runs as ONE full convolution on the MXU — the sparsity
    becomes structural zeros instead of the reference's per-connection
    accumulation loops.

    Tables: ``full_table(i, o)``, ``one_to_one_table(n)``,
    ``random_table(i, o, fanin)`` mirror the reference's builders.
    """

    def __init__(self, conn_table, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.conn_table = np.asarray(conn_table, np.int32).reshape(-1, 2)
        self.n_input_plane = int(self.conn_table[:, 0].max()) + 1
        self.n_output_plane = int(self.conn_table[:, 1].max()) + 1
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.weight_init = weight_init or Xavier()

    @staticmethod
    def full_table(n_in: int, n_out: int) -> np.ndarray:
        return np.asarray([(i, o) for o in range(n_out) for i in range(n_in)],
                          np.int32)

    @staticmethod
    def one_to_one_table(n: int) -> np.ndarray:
        return np.asarray([(i, i) for i in range(n)], np.int32)

    @staticmethod
    def random_table(n_in: int, n_out: int, fanin: int,
                     seed: int = 0) -> np.ndarray:
        rng = np_rng(seed)
        rows = []
        for o in range(n_out):
            for i in rng.choice(n_in, size=min(fanin, n_in), replace=False):
                rows.append((int(i), o))
        return np.asarray(rows, np.int32)

    def build_params(self, rng):
        kh, kw = self.kernel
        n_conn = len(self.conn_table)
        fanin = max(1, n_conn // max(1, self.n_output_plane))
        return {
            "weight": self.weight_init(
                fold_in_str(rng, "weight"), (n_conn, kh, kw),
                fanin * kh * kw, fanin * kh * kw),
            "bias": jnp.zeros((self.n_output_plane,), jnp.float32),
        }

    def forward(self, ctx: Context, x):
        kh, kw = self.kernel
        w = ctx.param("weight").astype(x.dtype)  # (n_conn, kh, kw)
        dense = jnp.zeros(
            (self.n_output_plane, self.n_input_plane, kh, kw), x.dtype)
        dense = dense.at[self.conn_table[:, 1], self.conn_table[:, 0]].set(w)
        y = lax.conv_general_dilated(
            x, dense, self.stride,
            [(self.pad[0], self.pad[0]), (self.pad[1], self.pad[1])],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return y + ctx.param("bias").astype(x.dtype)[:, None, None]
