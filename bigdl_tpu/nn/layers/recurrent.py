"""Recurrent stack: cells + scan-based containers.

Reference: ``DL/nn/Recurrent.scala`` (857 LoC BPTT container cloning the
cell per timestep), ``Cell.scala`` (abstract cell), ``RnnCell`` in
``RNN.scala``, ``LSTM.scala``, ``LSTMPeephole.scala``, ``GRU.scala``,
``ConvLSTMPeephole.scala``, ``MultiRNNCell.scala``, ``BiRecurrent.scala``,
``TimeDistributed.scala``, ``RecurrentDecoder.scala``.

TPU-native redesign: the reference unrolls time in Scala and clones the
cell module per step (hidden state is mutable module state). Here a cell is
a pure step function ``(carry, x_t) -> (carry, y_t)`` and ``Recurrent`` is
one ``lax.scan`` — XLA compiles the whole sequence into a single fused
loop, weights stay resident, and the backward pass is scan's transpose (no
hand-written BPTT). Gate matmuls are packed into one ``(input_size +
hidden, 4*hidden)``-style gemm so the MXU sees few large matmuls instead
of many small ones.

Layout: inputs are (batch, time, feature) — the reference's default
``batchNormParams == null`` NCHW-ish (B, T, D) layout. Internally scan runs
over a (time, batch, feature) transpose.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, RandomUniform, Xavier, Zeros
from bigdl_tpu.nn.module import Context, Module


class Cell(Module):
    """Recurrent cell base (reference: ``Cell.scala``).

    Subclasses define ``build_params``, ``init_carry(batch) -> carry`` and
    ``step(ctx, carry, x) -> (new_carry, output)``. Cells are also usable
    as plain modules on a single timestep input (carry defaults to zeros).
    """

    hidden_size: int

    def init_carry(self, batch: int, dtype=jnp.float32, input_shape=None):
        """Zero carry. ``input_shape`` is the per-timestep input shape
        (without batch), needed by conv cells to size spatial state."""
        raise NotImplementedError

    def step(self, ctx: Context, carry, x):
        raise NotImplementedError

    def forward(self, ctx: Context, x):
        carry = self.init_carry(x.shape[0], x.dtype, x.shape[1:])
        _, y = self.step(ctx, carry, x)
        return y


def _uniform_std(hidden_size: float) -> RandomUniform:
    bound = 1.0 / (hidden_size ** 0.5)
    return RandomUniform(-bound, bound)


_CELL_ACTS = {"tanh": jnp.tanh, "relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid}


def _cell_act(name: str):
    try:
        return _CELL_ACTS[name]
    except KeyError:
        raise ValueError(
            f"unknown cell activation {name!r}; known: {sorted(_CELL_ACTS)}"
        ) from None


class RnnCell(Cell):
    """Vanilla RNN cell: ``act(W x + U h + b)`` (reference ``RNN.scala``)."""

    def __init__(self, input_size: int, hidden_size: int, activation: str = "tanh",
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = _cell_act(activation)
        self.weight_init = weight_init or _uniform_std(hidden_size)

    def build_params(self, rng):
        i, h = self.input_size, self.hidden_size
        init = self.weight_init
        return {
            "weight": init(fold_in_str(rng, "w"), (i + h, h), i + h, h),
            "bias": init(fold_in_str(rng, "b"), (h,), i + h, h),
        }

    def init_carry(self, batch, dtype=jnp.float32, input_shape=None):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def step(self, ctx: Context, carry, x):
        w = ctx.param("weight").astype(x.dtype)
        b = ctx.param("bias").astype(x.dtype)
        h = self.activation(jnp.concatenate([x, carry], axis=-1) @ w + b)
        return h, h


class LSTMCell(Cell):
    """LSTM (reference ``LSTM.scala``): gates packed into ONE gemm of
    shape (input+hidden, 4*hidden); gate order i, f, g, o."""

    def __init__(self, input_size: int, hidden_size: int,
                 forget_bias: float = 0.0, activation: str = "tanh",
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias
        self.activation = _cell_act(activation)
        self.weight_init = weight_init or _uniform_std(hidden_size)

    def build_params(self, rng):
        i, h = self.input_size, self.hidden_size
        init = self.weight_init
        b = init(fold_in_str(rng, "b"), (4 * h,), i + h, h)
        if self.forget_bias:
            b = b.at[h:2 * h].add(self.forget_bias)
        return {
            "weight": init(fold_in_str(rng, "w"), (i + h, 4 * h), i + h, h),
            "bias": b,
        }

    def init_carry(self, batch, dtype=jnp.float32, input_shape=None):
        return (
            jnp.zeros((batch, self.hidden_size), dtype),  # h
            jnp.zeros((batch, self.hidden_size), dtype),  # c
        )

    def step(self, ctx: Context, carry, x):
        h_prev, c_prev = carry
        w = ctx.param("weight").astype(x.dtype)
        b = ctx.param("bias").astype(x.dtype)
        z = jnp.concatenate([x, h_prev], axis=-1) @ w + b
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * self.activation(g)
        h = jax.nn.sigmoid(o) * self.activation(c)
        return (h, c), h


class LSTMPeepholeCell(LSTMCell):
    """LSTM with peephole connections from the cell state into i/f/o
    (reference ``LSTMPeephole.scala``)."""

    def build_params(self, rng):
        p = super().build_params(rng)
        h = self.hidden_size
        init = self.weight_init
        p["peep_i"] = init(fold_in_str(rng, "pi"), (h,), h, h)
        p["peep_f"] = init(fold_in_str(rng, "pf"), (h,), h, h)
        p["peep_o"] = init(fold_in_str(rng, "po"), (h,), h, h)
        return p

    def step(self, ctx: Context, carry, x):
        h_prev, c_prev = carry
        w = ctx.param("weight").astype(x.dtype)
        b = ctx.param("bias").astype(x.dtype)
        z = jnp.concatenate([x, h_prev], axis=-1) @ w + b
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i + c_prev * ctx.param("peep_i").astype(x.dtype))
        f = jax.nn.sigmoid(f + c_prev * ctx.param("peep_f").astype(x.dtype))
        c = f * c_prev + i * self.activation(g)
        o = jax.nn.sigmoid(o + c * ctx.param("peep_o").astype(x.dtype))
        h = o * self.activation(c)
        return (h, c), h


class GRUCell(Cell):
    """GRU (reference ``GRU.scala``): r/z packed into one gemm; candidate
    uses torch convention ``n = tanh(W_n x + r * (U_n h + b_hn))``."""

    def __init__(self, input_size: int, hidden_size: int, activation: str = "tanh",
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = _cell_act(activation)
        self.weight_init = weight_init or _uniform_std(hidden_size)

    def build_params(self, rng):
        i, h = self.input_size, self.hidden_size
        init = self.weight_init
        return {
            "weight_rz": init(fold_in_str(rng, "wrz"), (i + h, 2 * h), i + h, h),
            "bias_rz": init(fold_in_str(rng, "brz"), (2 * h,), i + h, h),
            "weight_in": init(fold_in_str(rng, "wn"), (i, h), i, h),
            "bias_in": init(fold_in_str(rng, "bin"), (h,), i, h),
            "weight_hn": init(fold_in_str(rng, "un"), (h, h), h, h),
            "bias_hn": init(fold_in_str(rng, "bhn"), (h,), h, h),
        }

    def init_carry(self, batch, dtype=jnp.float32, input_shape=None):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def step(self, ctx: Context, carry, x):
        dt = x.dtype
        rz = jnp.concatenate([x, carry], axis=-1) @ ctx.param("weight_rz").astype(dt) \
            + ctx.param("bias_rz").astype(dt)
        r, z = jnp.split(jax.nn.sigmoid(rz), 2, axis=-1)
        n = self.activation(
            x @ ctx.param("weight_in").astype(dt) + ctx.param("bias_in").astype(dt)
            + r * (carry @ ctx.param("weight_hn").astype(dt) + ctx.param("bias_hn").astype(dt))
        )
        h = (1.0 - z) * n + z * carry
        return h, h


class ConvLSTMPeepholeCell(Cell):
    """2-D convolutional LSTM with peepholes (reference
    ``ConvLSTMPeephole.scala``). State is (batch, channels, H, W); the
    gate convs are packed into one conv producing 4*out channels."""

    def __init__(self, input_size: int, output_size: int, kernel: int = 3,
                 stride: int = 1, with_peephole: bool = True,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        assert stride == 1, "ConvLSTM state must keep spatial dims (stride 1)"
        self.input_size = input_size
        self.hidden_size = output_size
        self.kernel = kernel
        self.with_peephole = with_peephole
        self.weight_init = weight_init or Xavier()

    def build_params(self, rng):
        k, cin, cout = self.kernel, self.input_size, self.hidden_size
        fan_in = (cin + cout) * k * k
        fan_out = 4 * cout * k * k
        p = {
            "weight": self.weight_init(
                fold_in_str(rng, "w"), (4 * cout, cin + cout, k, k), fan_in, fan_out
            ),
            "bias": Zeros()(fold_in_str(rng, "b"), (4 * cout,), fan_in, fan_out),
        }
        if self.with_peephole:
            p["peep_i"] = Zeros()(fold_in_str(rng, "pi"), (cout,), cout, cout)
            p["peep_f"] = Zeros()(fold_in_str(rng, "pf"), (cout,), cout, cout)
            p["peep_o"] = Zeros()(fold_in_str(rng, "po"), (cout,), cout, cout)
        return p

    def init_carry(self, batch, dtype=jnp.float32, input_shape=None):
        assert input_shape is not None and len(input_shape) == 3, (
            "ConvLSTM needs the (C, H, W) per-step input shape to size its state"
        )
        shape = (batch, self.hidden_size) + tuple(input_shape[-2:])
        return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)

    def step(self, ctx: Context, carry, x):
        h_prev, c_prev = carry
        w = ctx.param("weight").astype(x.dtype)
        b = ctx.param("bias").astype(x.dtype)
        # asymmetric SAME padding so EVEN kernels also preserve the
        # spatial state dims (symmetric k//2 grows them and the second
        # timestep's carry add fails)
        k = self.kernel
        pad = (k // 2, (k - 1) - k // 2)
        z = lax.conv_general_dilated(
            jnp.concatenate([x, h_prev], axis=1), w, (1, 1),
            [pad, pad],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        ) + b[None, :, None, None]
        i, f, g, o = jnp.split(z, 4, axis=1)

        def peep(name):
            return ctx.param(name).astype(x.dtype)[None, :, None, None]

        if self.with_peephole:
            i = i + peep("peep_i") * c_prev
            f = f + peep("peep_f") * c_prev
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
        if self.with_peephole:
            o = o + peep("peep_o") * c
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h


class ConvLSTMPeephole3DCell(Cell):
    """3-D convolutional LSTM with peepholes (reference
    ``ConvLSTMPeephole3D.scala``). State is (batch, channels, D, H, W).

    Matches the reference's structure: a biased input convolution
    (``kernel_i``) and an UNbiased recurrent convolution (``kernel_c``),
    both SAME-padded stride 1 (the reference's ``padding = -1``), with
    multiplicative peepholes from the cell state into i/f/o (its
    ``CMul(Array(1, outputSize, 1, 1, 1))``). Gates are packed into
    4*out channels per conv so the MXU sees two large convolutions per
    step instead of eight small ones.
    """

    def __init__(self, input_size: int, output_size: int, kernel_i: int = 3,
                 kernel_c: int = 3, stride: int = 1, with_peephole: bool = True,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        assert stride == 1, "ConvLSTM state must keep spatial dims (stride 1)"
        self.input_size = input_size
        self.hidden_size = output_size
        self.kernel_i = kernel_i
        self.kernel_c = kernel_c
        self.with_peephole = with_peephole
        self.weight_init = weight_init or Xavier()

    def build_params(self, rng):
        ki, kc = self.kernel_i, self.kernel_c
        cin, cout = self.input_size, self.hidden_size
        init = self.weight_init
        fan_i, fan_c = cin * ki ** 3, cout * kc ** 3
        p = {
            "weight_i": init(fold_in_str(rng, "wi"),
                             (4 * cout, cin, ki, ki, ki), fan_i, 4 * cout * ki ** 3),
            "bias": Zeros()(fold_in_str(rng, "b"), (4 * cout,), fan_i, cout),
            # recurrent conv is bias-free in the reference (withBias = false)
            "weight_h": init(fold_in_str(rng, "wh"),
                             (4 * cout, cout, kc, kc, kc), fan_c, 4 * cout * kc ** 3),
        }
        if self.with_peephole:
            p["peep_i"] = Zeros()(fold_in_str(rng, "pi"), (cout,), cout, cout)
            p["peep_f"] = Zeros()(fold_in_str(rng, "pf"), (cout,), cout, cout)
            p["peep_o"] = Zeros()(fold_in_str(rng, "po"), (cout,), cout, cout)
        return p

    def init_carry(self, batch, dtype=jnp.float32, input_shape=None):
        assert input_shape is not None and len(input_shape) == 4, (
            "ConvLSTM3D needs the (C, D, H, W) per-step input shape to size its state"
        )
        shape = (batch, self.hidden_size) + tuple(input_shape[-3:])
        return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)

    @staticmethod
    def _conv3d_same(x, w, k):
        pad = [(k // 2, (k - 1) - k // 2)] * 3
        return lax.conv_general_dilated(
            x, w, (1, 1, 1), pad,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )

    def step(self, ctx: Context, carry, x):
        h_prev, c_prev = carry
        wi = ctx.param("weight_i").astype(x.dtype)
        wh = ctx.param("weight_h").astype(x.dtype)
        b = ctx.param("bias").astype(x.dtype)
        z = (self._conv3d_same(x, wi, self.kernel_i)
             + self._conv3d_same(h_prev, wh, self.kernel_c)
             + b[None, :, None, None, None])
        i, f, g, o = jnp.split(z, 4, axis=1)

        def peep(name):
            return ctx.param(name).astype(x.dtype)[None, :, None, None, None]

        if self.with_peephole:
            i = i + peep("peep_i") * c_prev
            f = f + peep("peep_f") * c_prev
        c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
        if self.with_peephole:
            o = o + peep("peep_o") * c
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h


class MultiRNNCell(Cell):
    """Stack of cells applied at each timestep (reference
    ``MultiRNNCell.scala``)."""

    def __init__(self, cells: Sequence[Cell]):
        super().__init__()
        self.cells = list(cells)
        for idx, c in enumerate(self.cells):
            self.add(c, name=f"cell{idx}")
        self.hidden_size = self.cells[-1].hidden_size

    def init_carry(self, batch, dtype=jnp.float32, input_shape=None):
        carries = []
        shape = tuple(input_shape) if input_shape is not None else None
        for c in self.cells:
            carries.append(c.init_carry(batch, dtype, shape))
            if shape is not None:
                # next cell sees this cell's output: hidden_size features,
                # spatial dims preserved (conv cells are stride 1)
                shape = (c.hidden_size,) + shape[1:] if len(shape) > 1 else (c.hidden_size,)
        return tuple(carries)

    def step(self, ctx: Context, carry, x):
        new_carry = []
        for idx, cell in enumerate(self.cells):
            c, x = cell.step(ctx.child(f"cell{idx}"), carry[idx], x)
            new_carry.append(c)
        return tuple(new_carry), x


class Recurrent(Module):
    """Run a cell over (batch, time, feature) via ``lax.scan`` (reference:
    ``Recurrent.scala`` — its per-step module cloning and BPTT collapse
    into the scan and its transpose).

    ``return_sequences=False`` returns only the last output (the reference
    keeps full sequences; Keras-tier uses last-output mode).
    """

    def __init__(self, cell: Cell, return_sequences: bool = True, reverse: bool = False):
        super().__init__()
        self.cell = cell  # registers child under 'cell'
        self.return_sequences = return_sequences
        self.reverse = reverse

    def _scan(self, ctx: Context, x, carry):
        cell = self.cell
        cell_ctx = ctx.child("cell")

        def step_fn(carry, x_t):
            new_carry, y = cell.step(cell_ctx, carry, x_t)
            return new_carry, y

        xs = jnp.moveaxis(x, 1, 0)  # (T, B, ...)
        carry, ys = lax.scan(step_fn, carry, xs, reverse=self.reverse)
        return carry, jnp.moveaxis(ys, 0, 1)

    def forward(self, ctx: Context, x):
        carry = self.cell.init_carry(x.shape[0], x.dtype, x.shape[2:])
        _, ys = self._scan(ctx, x, carry)
        if self.return_sequences:
            return ys
        return ys[:, -1] if not self.reverse else ys[:, 0]


class BiRecurrent(Module):
    """Bidirectional wrapper (reference ``BiRecurrent.scala``): forward and
    backward passes concatenated (or merged by sum) on the feature dim."""

    def __init__(self, fwd_cell: Cell, bwd_cell: Cell, merge: str = "concat"):
        super().__init__()
        self.fwd = Recurrent(fwd_cell, return_sequences=True, reverse=False)
        self.bwd = Recurrent(bwd_cell, return_sequences=True, reverse=True)
        if merge not in ("concat", "sum"):
            raise ValueError(f"unknown merge mode {merge}")
        self.merge = merge

    def forward(self, ctx: Context, x):
        yf = self.fwd.forward(ctx.child("fwd"), x)
        yb = self.bwd.forward(ctx.child("bwd"), x)
        if self.merge == "concat":
            return jnp.concatenate([yf, yb], axis=-1)
        return yf + yb


class TimeDistributed(Module):
    """Apply a module independently at every timestep (reference
    ``TimeDistributed.scala``). Implemented as a reshape (merge batch and
    time) rather than a loop — one big gemm for the MXU."""

    def __init__(self, inner: Module):
        super().__init__()
        self.inner = inner  # registers child under 'inner'

    def forward(self, ctx: Context, x):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y = self.inner.forward(ctx.child("inner"), flat)
        return y.reshape((b, t) + y.shape[1:])


class RecurrentDecoder(Module):
    """Decode ``seq_length`` steps feeding each output back as the next
    input (reference ``RecurrentDecoder.scala``). Input is the first-step
    input (batch, feature)."""

    def __init__(self, cell: Cell, seq_length: int):
        super().__init__()
        self.cell = cell  # registers child under 'cell'
        self.seq_length = seq_length

    def forward(self, ctx: Context, x):
        cell = self.cell
        cell_ctx = ctx.child("cell")
        carry = cell.init_carry(x.shape[0], x.dtype, x.shape[1:])

        def step_fn(state, _):
            carry, inp = state
            new_carry, y = cell.step(cell_ctx, carry, inp)
            return (new_carry, y), y

        _, ys = lax.scan(step_fn, (carry, x), None, length=self.seq_length)
        return jnp.moveaxis(ys, 0, 1)


# convenience aliases mirroring the reference's layer names
def LSTM(input_size, hidden_size, **kw) -> Recurrent:
    return Recurrent(LSTMCell(input_size, hidden_size, **kw))


def GRU(input_size, hidden_size, **kw) -> Recurrent:
    return Recurrent(GRUCell(input_size, hidden_size, **kw))


def SimpleRNN(input_size, hidden_size, **kw) -> Recurrent:
    return Recurrent(RnnCell(input_size, hidden_size, **kw))


def ConvLSTMPeephole(input_size, output_size, **kw) -> Recurrent:
    """Sequence-level 2-D conv-LSTM over (B, T, C, H, W) (reference
    ``ConvLSTMPeephole.scala`` wrapped in ``Recurrent``)."""
    return Recurrent(ConvLSTMPeepholeCell(input_size, output_size, **kw))


def ConvLSTMPeephole3D(input_size, output_size, **kw) -> Recurrent:
    """Sequence-level 3-D conv-LSTM over (B, T, C, D, H, W) (reference
    ``ConvLSTMPeephole3D.scala`` wrapped in ``Recurrent``)."""
    return Recurrent(ConvLSTMPeephole3DCell(input_size, output_size, **kw))
