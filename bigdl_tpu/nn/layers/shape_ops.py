"""Shape/glue layers.

Reference: ``DL/nn/Reshape.scala``, ``View.scala``, ``Squeeze.scala``,
``Unsqueeze.scala``, ``Transpose.scala``, ``Select.scala``, ``Narrow.scala``,
``Contiguous.scala``, ``Padding.scala``, ``Replicate.scala``, ``Mean.scala``,
``Max.scala``, ``Min.scala``, ``Sum.scala``. Dims here are 0-indexed Python
axes over the batched shape (the reference is 1-indexed Torch dims, usually
with an implicit batch in front).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

from bigdl_tpu.nn.module import Context, Module


class Reshape(Module):
    """Reshape the non-batch dims (reference semantic: size excludes batch
    when ``batch_mode`` is None/True)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = True):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def forward(self, ctx: Context, x):
        if self.batch_mode:
            return x.reshape((x.shape[0],) + self.size)
        return x.reshape(self.size)


class View(Module):
    """Reshape allowing one -1 wildcard, batch preserved
    (reference: ``View.scala``)."""

    def __init__(self, *sizes: int):
        super().__init__()
        self.sizes = sizes if sizes else (-1,)

    def forward(self, ctx: Context, x):
        return x.reshape((x.shape[0],) + tuple(self.sizes))


class Squeeze(Module):
    def __init__(self, dim: Optional[int] = None):
        super().__init__()
        self.dim = dim

    def forward(self, ctx: Context, x):
        return jnp.squeeze(x, axis=self.dim) if self.dim is not None else jnp.squeeze(x)


class Unsqueeze(Module):
    def __init__(self, dim: int):
        super().__init__()
        self.dim = dim

    def forward(self, ctx: Context, x):
        return jnp.expand_dims(x, self.dim)


class Transpose(Module):
    """Swap listed axis pairs in order (reference: ``Transpose.scala``)."""

    def __init__(self, *pairs: Tuple[int, int]):
        super().__init__()
        self.pairs = pairs

    def forward(self, ctx: Context, x):
        for a, b in self.pairs:
            x = jnp.swapaxes(x, a, b)
        return x


class Select(Module):
    """Select index along dim, squeezing it (reference: ``Select.scala``)."""

    def __init__(self, dim: int, index: int):
        super().__init__()
        self.dim, self.index = dim, index

    def forward(self, ctx: Context, x):
        return jnp.take(x, self.index, axis=self.dim)


class Narrow(Module):
    """Slice [offset, offset+length) along dim (reference: ``Narrow.scala``).
    ``length=-1`` means to the end."""

    def __init__(self, dim: int, offset: int, length: int = 1):
        super().__init__()
        self.dim, self.offset, self.length = dim, offset, length

    def forward(self, ctx: Context, x):
        end = x.shape[self.dim] if self.length == -1 else self.offset + self.length
        idx = [slice(None)] * x.ndim
        idx[self.dim] = slice(self.offset, end)
        return x[tuple(idx)]


class Contiguous(Module):
    """No-op in XLA (reference: ``Contiguous.scala``)."""

    def forward(self, ctx: Context, x):
        return x


class Padding(Module):
    """Pad ``pad`` entries (negative = before, positive = after) along dim
    with ``value`` (reference: ``Padding.scala``)."""

    def __init__(self, dim: int, pad: int, value: float = 0.0):
        super().__init__()
        self.dim, self.pad, self.value = dim, pad, value

    def forward(self, ctx: Context, x):
        widths = [(0, 0)] * x.ndim
        widths[self.dim] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value)


class Replicate(Module):
    """Insert a new dim of size n_features at ``dim`` by replication
    (reference: ``Replicate.scala``)."""

    def __init__(self, n_features: int, dim: int = 0):
        super().__init__()
        self.n_features, self.dim = n_features, dim

    def forward(self, ctx: Context, x):
        return jnp.repeat(jnp.expand_dims(x, self.dim), self.n_features, axis=self.dim)


class _Reduce(Module):
    def __init__(self, dimension: int = 0, squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.squeeze = squeeze


class Mean(_Reduce):
    def forward(self, ctx: Context, x):
        return x.mean(axis=self.dimension, keepdims=not self.squeeze)


class Sum(_Reduce):
    def forward(self, ctx: Context, x):
        return x.sum(axis=self.dimension, keepdims=not self.squeeze)


class Max(_Reduce):
    def forward(self, ctx: Context, x):
        return x.max(axis=self.dimension, keepdims=not self.squeeze)


class Min(_Reduce):
    def forward(self, ctx: Context, x):
        return x.min(axis=self.dimension, keepdims=not self.squeeze)
