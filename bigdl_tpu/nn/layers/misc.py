"""Misc parameterized & structural layers.

Reference (all under ``DL/nn/``): ``CMul``/``CAdd`` (broadcast learnable
scale/offset), ``Mul``/``Add`` (scalar/bias), ``Scale`` (CMul+CAdd),
``Bilinear``, ``Cosine``, ``Euclidean``, ``Masking``, ``MaskedSelect``,
``Index``, ``GradientReversal``, ``L1Penalty``, ``Maxout``, ``SReLU``,
``RReLU``, ``SpatialDropout1D/2D/3D``, ``LocallyConnected1D/2D``,
``SpatialSeparableConvolution``, ``SpatialUpSampling*``,
``SpatialZeroPadding``, ``Cropping2D/3D``, ``UpSampling1D/2D/3D``.

Each docstring cites its reference file. Implementations are single XLA
ops where possible (the reference hand-loops most of these).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, Ones, RandomUniform, Xavier, Zeros
from bigdl_tpu.nn.layers.conv import SpatialConvolution
from bigdl_tpu.nn.module import Context, Module


class CMul(Module):
    """Learnable componentwise scale, broadcast over the batch
    (reference ``CMul.scala``; ``size`` includes broadcast 1-dims)."""

    def __init__(self, size: Sequence[int],
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.size = tuple(size)
        self.weight_init = weight_init or Ones()

    def build_params(self, rng):
        n = int(jnp.prod(jnp.asarray(self.size)))
        return {"weight": self.weight_init(fold_in_str(rng, "w"), self.size, n, n)}

    def forward(self, ctx: Context, x):
        return x * ctx.param("weight").astype(x.dtype)


class CAdd(Module):
    """Learnable componentwise bias (reference ``CAdd.scala``)."""

    def __init__(self, size: Sequence[int],
                 bias_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.size = tuple(size)
        self.bias_init = bias_init or Zeros()

    def build_params(self, rng):
        n = int(jnp.prod(jnp.asarray(self.size)))
        return {"bias": self.bias_init(fold_in_str(rng, "b"), self.size, n, n)}

    def forward(self, ctx: Context, x):
        return x + ctx.param("bias").astype(x.dtype)


class Mul(Module):
    """Single learnable scalar gain (reference ``Mul.scala``)."""

    def build_params(self, rng):
        return {"weight": RandomUniform(-1.0, 1.0)(fold_in_str(rng, "w"), (1,), 1, 1)}

    def forward(self, ctx: Context, x):
        return x * ctx.param("weight").astype(x.dtype)


class Add(Module):
    """Learnable bias vector over the last dim (reference ``Add.scala``)."""

    def __init__(self, input_size: int):
        super().__init__()
        self.input_size = input_size

    def build_params(self, rng):
        return {"bias": Zeros()(fold_in_str(rng, "b"), (self.input_size,), 1, 1)}

    def forward(self, ctx: Context, x):
        return x + ctx.param("bias").astype(x.dtype)


class Scale(Module):
    """CMul then CAdd (reference ``Scale.scala`` — the caffe Scale layer)."""

    def __init__(self, size: Sequence[int]):
        super().__init__()
        self.cmul = CMul(size)
        self.cadd = CAdd(size)

    def forward(self, ctx: Context, x):
        return self.run_child(ctx, "cadd", self.run_child(ctx, "cmul", x))


class Bilinear(Module):
    """Bilinear form over an input pair: ``y_k = x1^T W_k x2 (+ b_k)``
    (reference ``Bilinear.scala``)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size1 = input_size1
        self.input_size2 = input_size2
        self.output_size = output_size
        self.bias_res = bias_res
        self.weight_init = weight_init or Xavier()

    def build_params(self, rng):
        fan_in = self.input_size1 * self.input_size2
        p = {
            "weight": self.weight_init(
                fold_in_str(rng, "w"),
                (self.output_size, self.input_size1, self.input_size2),
                fan_in, self.output_size,
            )
        }
        if self.bias_res:
            p["bias"] = Zeros()(fold_in_str(rng, "b"), (self.output_size,), fan_in, 1)
        return p

    def forward(self, ctx: Context, x):
        x1, x2 = x
        w = ctx.param("weight").astype(x1.dtype)
        y = jnp.einsum("bi,kij,bj->bk", x1, w, x2)
        if self.bias_res:
            y = y + ctx.param("bias").astype(x1.dtype)
        return y


class Cosine(Module):
    """Cosine similarity of the input to each of ``output_size`` learned
    prototype rows (reference ``Cosine.scala``)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size

    def build_params(self, rng):
        return {
            "weight": RandomUniform(-1.0, 1.0)(
                fold_in_str(rng, "w"), (self.output_size, self.input_size),
                self.input_size, self.output_size,
            )
        }

    def forward(self, ctx: Context, x):
        w = ctx.param("weight").astype(x.dtype)
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
        wn = w / jnp.maximum(jnp.linalg.norm(w, axis=-1, keepdims=True), 1e-12)
        return xn @ wn.T


class Euclidean(Module):
    """Distance of the input to ``output_size`` learned centers
    (reference ``Euclidean.scala``)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size

    def build_params(self, rng):
        bound = 1.0 / (self.input_size ** 0.5)
        return {
            "weight": RandomUniform(-bound, bound)(
                fold_in_str(rng, "w"), (self.output_size, self.input_size),
                self.input_size, self.output_size,
            )
        }

    def forward(self, ctx: Context, x):
        w = ctx.param("weight").astype(x.dtype)
        diff = x[:, None, :] - w[None, :, :]
        return jnp.sqrt(jnp.sum(jnp.square(diff), axis=-1) + 1e-12)


class Masking(Module):
    """Zero timesteps whose features all equal ``mask_value``
    (reference ``Masking.scala``)."""

    def __init__(self, mask_value: float = 0.0):
        super().__init__()
        self.mask_value = mask_value

    def forward(self, ctx: Context, x):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, x, 0.0)


class MaskedSelect(Module):
    """Select input elements where a (same-shape) mask is nonzero
    (reference ``MaskedSelect.scala``). Output keeps the input shape with
    unselected entries zeroed: a dynamic-size gather has no place under
    XLA's static shapes, so the reference's compacted vector becomes a
    masked tensor (documented deviation)."""

    def forward(self, ctx: Context, x):
        t, mask = x
        return jnp.where(mask != 0, t, 0.0)


class Index(Module):
    """Index along ``dimension`` with an integer index tensor
    (reference ``Index.scala``)."""

    def __init__(self, dimension: int = 0):
        super().__init__()
        self.dimension = dimension

    def forward(self, ctx: Context, x):
        t, idx = x
        return jnp.take(t, idx.astype(jnp.int32), axis=self.dimension)


@jax.custom_vjp
def _grad_reverse(x, lam):
    return x


def _grad_reverse_fwd(x, lam):
    return x, lam


def _grad_reverse_bwd(lam, g):
    return (-lam * g, None)


_grad_reverse.defvjp(_grad_reverse_fwd, _grad_reverse_bwd)


class GradientReversal(Module):
    """Identity forward, ``-lambda * grad`` backward (reference
    ``GradientReversal.scala`` — domain-adversarial training)."""

    def __init__(self, the_lambda: float = 1.0):
        super().__init__()
        self.the_lambda = the_lambda

    def forward(self, ctx: Context, x):
        return _grad_reverse(x, self.the_lambda)


@jax.custom_vjp
def _l1_penalty(x, scale):
    return x


def _l1_penalty_fwd(x, scale):
    return x, (jnp.sign(x), scale)


def _l1_penalty_bwd(res, g):
    sign, scale = res
    return (g + scale * sign.astype(g.dtype), None)


_l1_penalty.defvjp(_l1_penalty_fwd, _l1_penalty_bwd)


class L1Penalty(Module):
    """Identity forward that injects an L1 sparsity gradient on the
    activations (reference ``L1Penalty.scala``)."""

    def __init__(self, l1weight: float):
        super().__init__()
        self.l1weight = float(l1weight)

    def forward(self, ctx: Context, x):
        return _l1_penalty(x, self.l1weight)


class Maxout(Module):
    """Max over ``maxout_number`` linear maps (reference ``Maxout.scala``)."""

    def __init__(self, input_size: int, output_size: int, maxout_number: int,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.maxout_number = maxout_number
        self.weight_init = weight_init or Xavier()

    def build_params(self, rng):
        k = self.maxout_number * self.output_size
        return {
            "weight": self.weight_init(
                fold_in_str(rng, "w"), (self.input_size, k), self.input_size, k),
            "bias": Zeros()(fold_in_str(rng, "b"), (k,), self.input_size, k),
        }

    def forward(self, ctx: Context, x):
        z = x @ ctx.param("weight").astype(x.dtype) + ctx.param("bias").astype(x.dtype)
        z = z.reshape(z.shape[:-1] + (self.maxout_number, self.output_size))
        return jnp.max(z, axis=-2)


class SReLU(Module):
    """S-shaped ReLU with four learnable per-channel params
    (reference ``SReLU.scala``)."""

    def __init__(self, shape: Sequence[int]):
        super().__init__()
        self.shape = tuple(shape)

    def build_params(self, rng):
        n = 1
        return {
            "t_right": Ones()(fold_in_str(rng, "tr"), self.shape, n, n),
            "a_right": Ones()(fold_in_str(rng, "ar"), self.shape, n, n),
            "t_left": Zeros()(fold_in_str(rng, "tl"), self.shape, n, n),
            "a_left": Zeros()(fold_in_str(rng, "al"), self.shape, n, n),
        }

    def forward(self, ctx: Context, x):
        dt = x.dtype
        tr = ctx.param("t_right").astype(dt)
        ar = ctx.param("a_right").astype(dt)
        tl = ctx.param("t_left").astype(dt)
        al = ctx.param("a_left").astype(dt)
        y_high = tr + ar * (x - tr)
        y_low = tl + al * (x - tl)
        return jnp.where(x >= tr, y_high, jnp.where(x <= tl, y_low, x))


class RReLU(Module):
    """Randomized leaky ReLU (reference ``RReLU.scala``): slope sampled
    in [lower, upper) in training, fixed to the mean at inference."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, ctx: Context, x):
        if ctx.training:
            a = jax.random.uniform(
                ctx.rng(), x.shape, jnp.float32, self.lower, self.upper
            ).astype(x.dtype)
        else:
            a = jnp.asarray((self.lower + self.upper) / 2, x.dtype)
        return jnp.where(x >= 0, x, a * x)


class _SpatialDropoutND(Module):
    """Drop whole feature channels (reference ``SpatialDropout1D/2D/3D.scala``)."""

    spatial_dims = 2

    def __init__(self, init_p: float = 0.5):
        super().__init__()
        self.p = init_p

    def _mask_shape(self, x):
        # channel-first (NCHW / NCDHW): keep (B, C), broadcast over space
        return x.shape[: x.ndim - self.spatial_dims] + (1,) * self.spatial_dims

    def forward(self, ctx: Context, x):
        if not ctx.training or self.p <= 0.0:
            return x
        keep = jax.random.bernoulli(ctx.rng(), 1.0 - self.p, self._mask_shape(x))
        return jnp.where(keep, x / (1.0 - self.p), 0.0)


class SpatialDropout1D(_SpatialDropoutND):
    spatial_dims = 1

    def _mask_shape(self, x):
        # 1-D sequences are channel-LAST (B, T, D): drop whole feature
        # channels, broadcast over time
        return (x.shape[0], 1, x.shape[2])


class SpatialDropout2D(_SpatialDropoutND):
    spatial_dims = 2


class SpatialDropout3D(_SpatialDropoutND):
    spatial_dims = 3


class LocallyConnected2D(Module):
    """Unshared-weight conv (reference ``LocallyConnected2D.scala``):
    every output pixel owns its own kernel. Lowered as patch extraction +
    one batched einsum (MXU-friendly) instead of per-pixel loops."""

    def __init__(self, n_input_plane: int, input_width: int, input_height: int,
                 n_output_plane: int, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, with_bias: bool = True,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.n_input_plane = n_input_plane
        self.input_width = input_width
        self.input_height = input_height
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.with_bias = with_bias
        self.weight_init = weight_init or Xavier()
        self.out_h = (input_height + 2 * pad_h - kernel_h) // stride_h + 1
        self.out_w = (input_width + 2 * pad_w - kernel_w) // stride_w + 1

    def build_params(self, rng):
        kh, kw = self.kernel
        fan_in = self.n_input_plane * kh * kw
        p = {
            "weight": self.weight_init(
                fold_in_str(rng, "w"),
                (self.out_h, self.out_w, self.n_output_plane,
                 self.n_input_plane, kh, kw),
                fan_in, self.n_output_plane,
            )
        }
        if self.with_bias:
            p["bias"] = Zeros()(
                fold_in_str(rng, "b"),
                (self.n_output_plane, self.out_h, self.out_w), fan_in, 1,
            )
        return p

    def forward(self, ctx: Context, x):
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        if ph or pw:
            x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        # patches: (B, C*kh*kw, out_h, out_w)
        patches = lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), [(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        b = x.shape[0]
        patches = patches.reshape(b, self.n_input_plane, kh, kw, self.out_h, self.out_w)
        w = ctx.param("weight").astype(x.dtype)
        y = jnp.einsum("bcklhw,hwockl->bohw", patches, w)
        if self.with_bias:
            y = y + ctx.param("bias").astype(x.dtype)
        return y


class LocallyConnected1D(Module):
    """Reference ``LocallyConnected1D.scala`` — per-step unshared temporal
    conv over (B, T, D) inputs."""

    def __init__(self, n_input_frame: int, input_frame_size: int,
                 output_frame_size: int, kernel_w: int, stride_w: int = 1,
                 weight_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.n_input_frame = n_input_frame
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.weight_init = weight_init or Xavier()
        self.out_frames = (n_input_frame - kernel_w) // stride_w + 1

    def build_params(self, rng):
        fan_in = self.input_frame_size * self.kernel_w
        return {
            "weight": self.weight_init(
                fold_in_str(rng, "w"),
                (self.out_frames, self.kernel_w * self.input_frame_size,
                 self.output_frame_size),
                fan_in, self.output_frame_size,
            ),
            "bias": Zeros()(
                fold_in_str(rng, "b"),
                (self.out_frames, self.output_frame_size), fan_in, 1,
            ),
        }

    def forward(self, ctx: Context, x):
        idx = jnp.arange(self.out_frames) * self.stride_w
        windows = x[:, idx[:, None] + jnp.arange(self.kernel_w)[None, :], :]
        b = x.shape[0]
        windows = windows.reshape(b, self.out_frames, -1)
        w = ctx.param("weight").astype(x.dtype)
        y = jnp.einsum("btk,tko->bto", windows, w)
        return y + ctx.param("bias").astype(x.dtype)


class SpatialSeparableConvolution(Module):
    """Depthwise conv + 1x1 pointwise conv (reference
    ``SpatialSeparableConvolution.scala``)."""

    def __init__(self, n_input_channel: int, n_output_channel: int,
                 depth_multiplier: int, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, with_bias: bool = True):
        super().__init__()
        mid = n_input_channel * depth_multiplier
        self.depthwise = SpatialConvolution(
            n_input_channel, mid, kernel_w, kernel_h, stride_w, stride_h,
            pad_w, pad_h, n_group=n_input_channel, with_bias=False,
        )
        self.pointwise = SpatialConvolution(
            mid, n_output_channel, 1, 1, with_bias=with_bias,
        )

    def forward(self, ctx: Context, x):
        return self.run_child(ctx, "pointwise", self.run_child(ctx, "depthwise", x))


# ----------------------------------------------------- resizing / padding


class UpSampling1D(Module):
    """Repeat timesteps (reference ``UpSampling1D.scala``)."""

    def __init__(self, length: int = 2):
        super().__init__()
        self.length = length

    def forward(self, ctx: Context, x):
        return jnp.repeat(x, self.length, axis=1)


class UpSampling2D(Module):
    """Nearest-neighbor spatial upsampling on NCHW (reference
    ``UpSampling2D.scala``)."""

    def __init__(self, size: Tuple[int, int] = (2, 2)):
        super().__init__()
        self.size = tuple(size)

    def forward(self, ctx: Context, x):
        return jnp.repeat(jnp.repeat(x, self.size[0], axis=2), self.size[1], axis=3)


class UpSampling3D(Module):
    """Reference ``UpSampling3D.scala`` (NCDHW)."""

    def __init__(self, size: Tuple[int, int, int] = (2, 2, 2)):
        super().__init__()
        self.size = tuple(size)

    def forward(self, ctx: Context, x):
        for i, s in enumerate(self.size):
            x = jnp.repeat(x, s, axis=2 + i)
        return x


class SpatialUpSamplingNearest(Module):
    """Reference ``SpatialUpSamplingNearest.scala``."""

    def __init__(self, scale: int):
        super().__init__()
        self.scale = scale

    def forward(self, ctx: Context, x):
        return jnp.repeat(jnp.repeat(x, self.scale, axis=2), self.scale, axis=3)


class SpatialUpSamplingBilinear(Module):
    """Bilinear resize (reference ``SpatialUpSamplingBilinear.scala``,
    align_corners semantics of the reference's default=false)."""

    def __init__(self, out_height: int, out_width: int):
        super().__init__()
        self.out_height = out_height
        self.out_width = out_width

    def forward(self, ctx: Context, x):
        b, c, h, w = x.shape
        return jax.image.resize(
            x, (b, c, self.out_height, self.out_width), method="bilinear"
        )


class SpatialZeroPadding(Module):
    """Reference ``SpatialZeroPadding.scala`` (negative pad crops)."""

    def __init__(self, pad_left: int, pad_right: int, pad_top: int, pad_bottom: int):
        super().__init__()
        self.pads = (pad_left, pad_right, pad_top, pad_bottom)

    def forward(self, ctx: Context, x):
        l, r, t, b = self.pads
        if min(self.pads) < 0:
            h, w = x.shape[2], x.shape[3]
            x = x[:, :, max(0, -t): h - max(0, -b), max(0, -l): w - max(0, -r)]
            l, r, t, b = (max(0, v) for v in (l, r, t, b))
        return jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r)))


class Cropping2D(Module):
    """Reference ``Cropping2D.scala`` (NCHW)."""

    def __init__(self, height_crop: Tuple[int, int], width_crop: Tuple[int, int]):
        super().__init__()
        self.height_crop = tuple(height_crop)
        self.width_crop = tuple(width_crop)

    def forward(self, ctx: Context, x):
        (t, b), (l, r) = self.height_crop, self.width_crop
        return x[:, :, t: x.shape[2] - b, l: x.shape[3] - r]


class Cropping3D(Module):
    """Reference ``Cropping3D.scala`` (NCDHW)."""

    def __init__(self, dim1_crop: Tuple[int, int], dim2_crop: Tuple[int, int],
                 dim3_crop: Tuple[int, int]):
        super().__init__()
        self.crops = (tuple(dim1_crop), tuple(dim2_crop), tuple(dim3_crop))

    def forward(self, ctx: Context, x):
        (a0, a1), (b0, b1), (c0, c1) = self.crops
        return x[:, :, a0: x.shape[2] - a1, b0: x.shape[3] - b1, c0: x.shape[4] - c1]
