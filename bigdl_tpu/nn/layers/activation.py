"""Activation and elementwise layers.

Reference: the activation slice of ``DL/nn/`` (ReLU, ReLU6, Tanh, Sigmoid,
SoftMax, LogSoftMax, ELU, LeakyReLU, PReLU, SReLU, SoftPlus, SoftSign,
HardTanh, HardSigmoid, Threshold, Power, Square, Sqrt, Abs, Clamp, Log, Exp,
Negative, AddConstant, MulConstant). All are single XLA elementwise ops that
fuse into adjacent matmuls/convs — the reference needed MKL-DNN post-op
fusion (``Fusion.scala``) to get the same effect.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Context, Module


class ReLU(Module):
    def __init__(self, ip: bool = False):  # ip (in-place) kept for API parity; meaningless in JAX
        super().__init__()

    def forward(self, ctx: Context, x):
        return jax.nn.relu(x)


class ReLU6(Module):
    def forward(self, ctx: Context, x):
        return jnp.clip(x, 0.0, 6.0)


class Tanh(Module):
    def forward(self, ctx: Context, x):
        return jnp.tanh(x)


class Sigmoid(Module):
    def forward(self, ctx: Context, x):
        return jax.nn.sigmoid(x)


class SoftMax(Module):
    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, ctx: Context, x):
        return jax.nn.softmax(x, axis=self.axis)


class LogSoftMax(Module):
    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, ctx: Context, x):
        return jax.nn.log_softmax(x, axis=self.axis)


class ELU(Module):
    def __init__(self, alpha: float = 1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, ctx: Context, x):
        return jax.nn.elu(x, self.alpha)


class LeakyReLU(Module):
    def __init__(self, negval: float = 0.01):
        super().__init__()
        self.negval = negval

    def forward(self, ctx: Context, x):
        return jax.nn.leaky_relu(x, self.negval)


class PReLU(Module):
    """Learned per-channel negative slope (reference: ``PReLU.scala``;
    ``n_output_plane=0`` -> one shared slope)."""

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self.n_output_plane = n_output_plane

    def build_params(self, rng):
        n = max(1, self.n_output_plane)
        return {"weight": jnp.full((n,), 0.25, jnp.float32)}

    def forward(self, ctx: Context, x):
        a = ctx.param("weight").astype(x.dtype)
        if self.n_output_plane > 0 and x.ndim > 2:
            shape = [1] * x.ndim
            shape[1] = self.n_output_plane
            a = a.reshape(shape)
        return jnp.where(x >= 0, x, a * x)


class SoftPlus(Module):
    def __init__(self, beta: float = 1.0):
        super().__init__()
        self.beta = beta

    def forward(self, ctx: Context, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(Module):
    def forward(self, ctx: Context, x):
        return x / (1.0 + jnp.abs(x))


class HardTanh(Module):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0):
        super().__init__()
        self.min_value, self.max_value = min_value, max_value

    def forward(self, ctx: Context, x):
        return jnp.clip(x, self.min_value, self.max_value)


class HardSigmoid(Module):
    def forward(self, ctx: Context, x):
        return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class Threshold(Module):
    def __init__(self, th: float = 1e-6, v: float = 0.0):
        super().__init__()
        self.th, self.v = th, v

    def forward(self, ctx: Context, x):
        return jnp.where(x > self.th, x, jnp.asarray(self.v, x.dtype))


class GELU(Module):
    def forward(self, ctx: Context, x):
        return jax.nn.gelu(x)


class SiLU(Module):
    def forward(self, ctx: Context, x):
        return jax.nn.silu(x)


class Power(Module):
    """(shift + scale * x) ** power (reference: ``Power.scala``)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0):
        super().__init__()
        self.power, self.scale, self.shift = power, scale, shift

    def forward(self, ctx: Context, x):
        return (self.shift + self.scale * x) ** self.power


class Square(Module):
    def forward(self, ctx: Context, x):
        return x * x


class Sqrt(Module):
    def forward(self, ctx: Context, x):
        return jnp.sqrt(x)


class Abs(Module):
    def forward(self, ctx: Context, x):
        return jnp.abs(x)


class Clamp(Module):
    def __init__(self, min_value: float, max_value: float):
        super().__init__()
        self.min_value, self.max_value = min_value, max_value

    def forward(self, ctx: Context, x):
        return jnp.clip(x, self.min_value, self.max_value)


class Log(Module):
    def forward(self, ctx: Context, x):
        return jnp.log(x)


class Exp(Module):
    def forward(self, ctx: Context, x):
        return jnp.exp(x)


class Negative(Module):
    def forward(self, ctx: Context, x):
        return -x


class AddConstant(Module):
    def __init__(self, constant: float):
        super().__init__()
        self.constant = constant

    def forward(self, ctx: Context, x):
        return x + self.constant


class MulConstant(Module):
    def __init__(self, constant: float):
        super().__init__()
        self.constant = constant

    def forward(self, ctx: Context, x):
        return x * self.constant
