"""Pooling layers.

Reference: ``DL/nn/SpatialMaxPooling.scala``, ``SpatialAveragePooling.scala``
(with ``ceilMode`` and ``countIncludePad``), ``TemporalMaxPooling.scala``.
TPU-native: ``lax.reduce_window`` — XLA lowers it to vectorized windowed
reductions; no pooling-index bookkeeping is needed because gradients come
from autodiff, not a hand-written ``updateGradInput``.

Argument order keeps the reference's W-before-H convention.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Context, Module


def _pool_padding(in_size, k, s, pad, ceil_mode):
    """(lo, hi) padding for one spatial dim, Torch floor/ceil semantics."""
    if ceil_mode:
        out = int(np.ceil((in_size + 2 * pad - k) / s)) + 1
        # Torch: last window must start inside the (left-padded) input
        if (out - 1) * s >= in_size + pad:
            out -= 1
    else:
        out = int(np.floor((in_size + 2 * pad - k) / s)) + 1
    needed = max(0, (out - 1) * s + k - in_size - pad)
    return pad, needed


class _Pool2D(Module):
    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0, data_format="NCHW"):
        super().__init__()
        self.kernel = (kh, kw)
        self.stride = (dh or kh, dw or kw)
        self.pad = (pad_h, pad_w)
        self.ceil_mode = False
        self.data_format = data_format

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _window(self, x):
        if self.data_format == "NCHW":
            h_ax, w_ax = 2, 3
        else:
            h_ax, w_ax = 1, 2
        dims = [1] * x.ndim
        strides = [1] * x.ndim
        pads = [(0, 0)] * x.ndim
        dims[h_ax], dims[w_ax] = self.kernel
        strides[h_ax], strides[w_ax] = self.stride
        pads[h_ax] = _pool_padding(x.shape[h_ax], self.kernel[0], self.stride[0], self.pad[0], self.ceil_mode)
        pads[w_ax] = _pool_padding(x.shape[w_ax], self.kernel[1], self.stride[1], self.pad[1], self.ceil_mode)
        return tuple(dims), tuple(strides), tuple(pads)




@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _maxpool2d(x, window, strides, pads):
    """Max-pool with a hand-written backward.

    XLA lowers the gradient of ``reduce_window_max`` to SelectAndScatter,
    which is ~4x slower than the arithmetic around it on TPU. The custom
    backward instead scatter-adds ``g * (x_window == y)`` over the
    ``kh*kw`` window offsets — strided elementwise ops that XLA fuses.

    Tie semantics deviation (documented): positions EQUAL to the window
    max all receive the gradient (SelectAndScatter picks one). Ties are
    measure-zero for continuous activations; for post-ReLU zeros the
    upstream ReLU gradient mask kills the extra contributions.
    """
    neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, neg_inf, lax.max, window, strides, pads)


def _maxpool2d_fwd(x, window, strides, pads):
    y = _maxpool2d(x, window, strides, pads)
    return y, (x, y)


def _maxpool2d_bwd(window, strides, pads, res, g):
    x, y = res
    # spatial dims are the trailing two of the 4-tuples
    kh, kw = window[2], window[3]
    sh, sw = strides[2], strides[3]
    (plo_h, phi_h), (plo_w, phi_w) = pads[2], pads[3]
    oh, ow = y.shape[2], y.shape[3]
    # pad x out to the full strided extent the windows touch
    need_h = plo_h + (oh - 1) * sh + kh
    need_w = plo_w + (ow - 1) * sw + kw
    xp = jnp.pad(
        x,
        ((0, 0), (0, 0), (plo_h, max(0, need_h - x.shape[2] - plo_h)),
         (plo_w, max(0, need_w - x.shape[3] - plo_w))),
        constant_values=-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else 0,
    )
    dxp = jnp.zeros(xp.shape, g.dtype)
    for di in range(kh):
        for dj in range(kw):
            xs = lax.slice(
                xp,
                (0, 0, di, dj),
                (xp.shape[0], xp.shape[1], di + (oh - 1) * sh + 1, dj + (ow - 1) * sw + 1),
                (1, 1, sh, sw),
            )
            contrib = g * (xs == y).astype(g.dtype)
            dxp = dxp.at[:, :, di:di + (oh - 1) * sh + 1:sh,
                         dj:dj + (ow - 1) * sw + 1:sw].add(contrib)
    dx = dxp[:, :, plo_h:plo_h + x.shape[2], plo_w:plo_w + x.shape[3]]
    return (dx.astype(x.dtype),)


_maxpool2d.defvjp(_maxpool2d_fwd, _maxpool2d_bwd)


class SpatialMaxPooling(_Pool2D):
    #: opt-in alternative gradient. In isolation the equality-mask backward
    #: is ~4x faster than SelectAndScatter on TPU (8.0 -> 2.1 ms on the
    #: ResNet stem pool), but inside the full ResNet-50 step it measured
    #: NET SLOWER (94.8 -> 103.3 ms/step): XLA overlaps SelectAndScatter
    #: with neighboring conv work while the 9-offset scatter chain
    #: serializes. Default off; flip on for pool-dominated models.
    fused_backward = False

    def forward(self, ctx: Context, x):
        dims, strides, pads = self._window(x)
        if self.fused_backward and x.ndim == 4 and self.data_format == "NCHW":
            return _maxpool2d(x, dims, strides, pads)
        # scalar init (not an array) so lax picks the reduce_window_max
        # primitive, which has a reverse-mode autodiff rule
        neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, neg_inf, lax.max, dims, strides, pads)


class SpatialAveragePooling(_Pool2D):
    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0,
                 count_include_pad: bool = True, data_format="NCHW"):
        super().__init__(kw, kh, dw, dh, pad_w, pad_h, data_format)
        self.count_include_pad = count_include_pad

    def forward(self, ctx: Context, x):
        dims, strides, pads = self._window(x)
        summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        # Divisor semantics (torch oracle): count_include_pad counts the
        # official padding but never the ceil-mode extension; the mask is 1
        # over the (optionally padded) input extent and 0 over the extension.
        if self.data_format == "NCHW":
            h_ax, w_ax = 2, 3
        else:
            h_ax, w_ax = 1, 2
        if self.count_include_pad:
            mask_widths = [(0, 0)] * x.ndim
            mask_widths[h_ax] = (self.pad[0], self.pad[0])
            mask_widths[w_ax] = (self.pad[1], self.pad[1])
            mask = jnp.pad(jnp.ones(x.shape, x.dtype), mask_widths, constant_values=1.0)
            mask_pads = list(pads)
            mask_pads[h_ax] = (0, pads[h_ax][1] - self.pad[0])
            mask_pads[w_ax] = (0, pads[w_ax][1] - self.pad[1])
            counts = lax.reduce_window(mask, 0.0, lax.add, dims, strides, tuple(mask_pads))
        else:
            counts = lax.reduce_window(
                jnp.ones(x.shape, x.dtype), 0.0, lax.add, dims, strides, pads
            )
        return summed / counts


class TemporalMaxPooling(Module):
    """Max pooling over (batch, time, feature) (reference:
    ``TemporalMaxPooling.scala``)."""

    def __init__(self, k_w: int, d_w: int = None):
        super().__init__()
        self.k_w = k_w
        self.d_w = d_w or k_w

    def forward(self, ctx: Context, x):
        neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(
            x, neg_inf, lax.max, (1, self.k_w, 1), (1, self.d_w, 1), [(0, 0)] * 3
        )


class GlobalAveragePooling2D(Module):
    """Mean over spatial dims (keras-tier helper; reference keras
    ``GlobalAveragePooling2D``)."""

    def __init__(self, data_format="NCHW"):
        super().__init__()
        self.axes = (2, 3) if data_format == "NCHW" else (1, 2)

    def forward(self, ctx: Context, x):
        return x.mean(axis=self.axes)


class GlobalMaxPooling2D(Module):
    """Max over spatial dims (keras ``GlobalMaxPooling2D``; also the
    caffe ``Pooling(global_pooling=true, pool=MAX)`` mapping)."""

    def __init__(self, data_format="NCHW"):
        super().__init__()
        self.axes = (2, 3) if data_format == "NCHW" else (1, 2)

    def forward(self, ctx: Context, x):
        return x.max(axis=self.axes)
