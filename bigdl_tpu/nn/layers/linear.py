"""Linear (fully-connected) layer and trivial passthroughs.

Reference: ``DL/nn/Linear.scala`` (weight (out,in), optional bias, gemm via
MKL — here a single ``jnp.dot`` that XLA maps straight onto the MXU;
bfloat16 inputs keep the systolic array fed while params stay fp32 masters).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, RandomUniform, Zeros
from bigdl_tpu.nn.module import Context, Module


class Linear(Module):
    def __init__(
        self,
        input_size: int,
        output_size: int,
        with_bias: bool = True,
        weight_init: Optional[InitializationMethod] = None,
        bias_init: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.weight_init = weight_init or RandomUniform()
        self.bias_init = bias_init or RandomUniform()

    def set_init_method(self, weight_init=None, bias_init=None) -> "Linear":
        if weight_init:
            self.weight_init = weight_init
        if bias_init:
            self.bias_init = bias_init
        return self

    def build_params(self, rng):
        fan_in, fan_out = self.input_size, self.output_size
        p = {
            "weight": self.weight_init(
                fold_in_str(rng, "weight"), (self.output_size, self.input_size), fan_in, fan_out
            )
        }
        if self.with_bias:
            p["bias"] = self.bias_init(fold_in_str(rng, "bias"), (self.output_size,), fan_in, fan_out)
        return p

    def forward(self, ctx: Context, x):
        if "weight_q" in ctx.params:
            # int8 serving tree (nn.quantized.quantize_for_serving): the
            # params CARRY the quantization, so every caller — full
            # forward, prefill/decode_step, their paged twins — runs the
            # s8 x s8 -> s32 MXU path with zero signature changes. The
            # branch resolves at trace time (dict membership), so float
            # trees trace exactly the code below, bit-unchanged.
            from bigdl_tpu.nn.int8 import int8_linear

            return int8_linear(
                x, ctx.param("weight_q"), ctx.param("scale"),
                ctx.param("bias") if self.with_bias else None)
        w = ctx.param("weight").astype(x.dtype)
        y = jnp.dot(x, w.T)
        if self.with_bias:
            y = y + ctx.param("bias").astype(x.dtype)
        return y


class Identity(Module):
    """Reference: ``DL/nn/Identity.scala``."""

    def forward(self, ctx: Context, x):
        return x


class Echo(Module):
    """Debug passthrough that prints activation shape at trace time
    (reference: ``DL/nn/Echo.scala``)."""

    def forward(self, ctx: Context, x):
        import jax

        shapes = jax.tree_util.tree_map(lambda a: getattr(a, "shape", None), x)
        print(f"[Echo {self.get_name() or ''}] {shapes}")
        return x
