"""Stochastic regularization layers.

Reference: ``DL/nn/Dropout.scala`` (inverted dropout: scale by 1/(1-p) at
train time), ``GaussianNoise.scala``, ``GaussianDropout.scala``. RNG is a
deterministic per-module-path stream derived from the key passed to
``apply`` (see ``Context.rng``), replacing the reference's per-thread
mersenne twister.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Context, Module


class Dropout(Module):
    def __init__(self, init_p: float = 0.5, scale: bool = True):
        super().__init__()
        self.p = init_p
        self.scale = scale

    def forward(self, ctx: Context, x):
        if not ctx.training or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(ctx.rng(), keep, x.shape)
        y = jnp.where(mask, x, jnp.zeros((), x.dtype))
        return y / keep if self.scale else y


class GaussianNoise(Module):
    def __init__(self, stddev: float):
        super().__init__()
        self.stddev = stddev

    def forward(self, ctx: Context, x):
        if not ctx.training:
            return x
        return x + self.stddev * jax.random.normal(ctx.rng(), x.shape, x.dtype)


class GaussianDropout(Module):
    def __init__(self, rate: float):
        super().__init__()
        self.rate = rate

    def forward(self, ctx: Context, x):
        if not ctx.training:
            return x
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        return x * (1.0 + stddev * jax.random.normal(ctx.rng(), x.shape, x.dtype))
