"""Convolution layers.

Reference: ``DL/nn/SpatialConvolution.scala:253`` (im2col + MKL gemm, hand
loops in ``NNPrimitive.scala``), ``SpatialDilatedConvolution.scala``,
``SpatialFullConvolution.scala`` (deconvolution), ``TemporalConvolution.scala``.
TPU-native: one ``lax.conv_general_dilated`` per layer — XLA tiles it onto
the MXU and fuses surrounding elementwise ops; there is no im2col, no
layout "reorder" pass (the reference's ``ReorderManager``), and no manual
fusion (the reference's ``Fusion.scala`` conv+bn/conv+relu post-ops).

Argument order keeps the reference's W-before-H convention
(``kernelW, kernelH, strideW, strideH, padW, padH``). ``pad_w = pad_h = -1``
selects TF-style SAME padding, as in the reference.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, Xavier, Zeros
from bigdl_tpu.nn.module import Context, Module


def _dimension_numbers(data_format: str, kernel_format: str = "OIHW"):
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unknown data_format {data_format}")
    if kernel_format not in ("OIHW", "HWIO"):
        raise ValueError(f"unknown kernel_format {kernel_format}")
    return (data_format, kernel_format, data_format)


def _padding(pad_h: int, pad_w: int):
    if pad_h == -1 or pad_w == -1:
        return "SAME"
    return [(pad_h, pad_h), (pad_w, pad_w)]


class SpatialConvolution(Module):
    """2-D convolution (reference ``SpatialConvolution.scala``; groups via
    ``feature_group_count`` replace the reference's per-group gemm loop)."""

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        n_group: int = 1,
        with_bias: bool = True,
        data_format: str = "NCHW",
        weight_init: Optional[InitializationMethod] = None,
        bias_init: Optional[InitializationMethod] = None,
        kernel_format: str = "OIHW",
    ):
        super().__init__()
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.n_group = n_group
        self.with_bias = with_bias
        self.data_format = data_format
        # kernel storage layout. "HWIO" stores the weight as (kh, kw, in/g,
        # out): its row-major layout is exactly the TPU conv kernel's
        # internal layout (O minor, I next), so XLA elides the per-step
        # fp32 layout copy that an OIHW-stored weight pays after every
        # optimizer update (~5 ms/step on the ResNet-50 bench, see
        # PERF_NOTES.md). OIHW stays the default: it is the reference's
        # wire layout (SpatialConvolution.scala) and what every
        # serializer/converter in interop/ expects.
        self.kernel_format = kernel_format
        self.dilation = (1, 1)
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init:
            self.weight_init = weight_init
        if bias_init:
            self.bias_init = bias_init
        return self

    def weight_as_oihw(self, w):
        """Export view: every wire format (reference proto, caffe, t7,
        ONNX) stores conv weights OIHW; HWIO storage transposes on the
        way out so serialized files are layout-independent."""
        return w.transpose(3, 2, 0, 1) if self.kernel_format == "HWIO" else w

    def weight_from_oihw(self, w):
        """Import view: map an OIHW wire tensor into this module's
        storage ``kernel_format``."""
        return w.transpose(2, 3, 1, 0) if self.kernel_format == "HWIO" else w

    def build_params(self, rng):
        kh, kw = self.kernel
        cin = self.n_input_plane // self.n_group
        fan_in = cin * kh * kw
        fan_out = (self.n_output_plane // self.n_group) * kh * kw
        w = self.weight_init(
            fold_in_str(rng, "weight"),
            (self.n_output_plane, cin, kh, kw),
            fan_in,
            fan_out,
        )
        if self.kernel_format == "HWIO":
            # same draw as OIHW (layout-only difference), transposed once
            w = jnp.transpose(w, (2, 3, 1, 0))
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = self.bias_init(
                fold_in_str(rng, "bias"), (self.n_output_plane,), fan_in, fan_out
            )
        return p

    def _add_bias(self, ctx: Context, y, dtype):
        if self.with_bias:
            b = ctx.param("bias").astype(dtype)
            y = y + (b[:, None, None] if self.data_format == "NCHW" else b)
        return y

    def forward(self, ctx: Context, x):
        w = ctx.param("weight").astype(x.dtype)
        y = lax.conv_general_dilated(
            x,
            w,
            window_strides=self.stride,
            padding=_padding(*self.pad),
            rhs_dilation=self.dilation,
            feature_group_count=self.n_group,
            dimension_numbers=_dimension_numbers(self.data_format,
                                                 self.kernel_format),
        )
        return self._add_bias(ctx, y, x.dtype)


class SpatialDilatedConvolution(SpatialConvolution):
    """Reference: ``SpatialDilatedConvolution.scala``. Same lowering as the
    base conv with ``rhs_dilation`` set."""

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        dilation_w: int = 1,
        dilation_h: int = 1,
        **kw,
    ):
        super().__init__(
            n_input_plane, n_output_plane, kernel_w, kernel_h, stride_w, stride_h, pad_w, pad_h, **kw
        )
        self.dilation = (dilation_h, dilation_w)


class SpatialFullConvolution(Module):
    """Transposed convolution (reference: ``SpatialFullConvolution.scala``).

    Implemented as ``lax.conv_transpose``; ``adj_w/adj_h`` add extra output
    size as in the reference.
    """

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        adj_w: int = 0,
        adj_h: int = 0,
        with_bias: bool = True,
        data_format: str = "NCHW",
        weight_init: Optional[InitializationMethod] = None,
        bias_init: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.adj = (adj_h, adj_w)
        self.with_bias = with_bias
        self.data_format = data_format
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def build_params(self, rng):
        kh, kw = self.kernel
        fan_in = self.n_input_plane * kh * kw
        fan_out = self.n_output_plane * kh * kw
        p = {
            "weight": self.weight_init(
                fold_in_str(rng, "weight"),
                (self.n_output_plane, self.n_input_plane, kh, kw),
                fan_in,
                fan_out,
            )
        }
        if self.with_bias:
            p["bias"] = self.bias_init(fold_in_str(rng, "bias"), (self.n_output_plane,), fan_in, fan_out)
        return p

    def forward(self, ctx: Context, x):
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        ah, aw = self.adj
        w = ctx.param("weight").astype(x.dtype)
        # gradient-of-conv formulation: lhs-dilate input by stride, pad by k-1-p
        y = lax.conv_general_dilated(
            x,
            jnp.flip(w, (-2, -1)),  # stored (out, in, kh, kw): flip spatial only
            window_strides=(1, 1),
            padding=[(kh - 1 - ph, kh - 1 - ph + ah), (kw - 1 - pw, kw - 1 - pw + aw)],
            lhs_dilation=(sh, sw),
            dimension_numbers=_dimension_numbers(self.data_format),
        )
        if self.with_bias:
            b = ctx.param("bias").astype(x.dtype)
            y = y + (b[:, None, None] if self.data_format == "NCHW" else b)
        return y


class TemporalConvolution(Module):
    """1-D convolution over (batch, time, feature) input
    (reference: ``TemporalConvolution.scala``)."""

    def __init__(
        self,
        input_frame_size: int,
        output_frame_size: int,
        kernel_w: int,
        stride_w: int = 1,
        weight_init: Optional[InitializationMethod] = None,
        bias_init: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def build_params(self, rng):
        fan_in = self.input_frame_size * self.kernel_w
        fan_out = self.output_frame_size * self.kernel_w
        return {
            "weight": self.weight_init(
                fold_in_str(rng, "weight"),
                (self.output_frame_size, self.input_frame_size, self.kernel_w),
                fan_in,
                fan_out,
            ),
            "bias": self.bias_init(
                fold_in_str(rng, "bias"), (self.output_frame_size,), fan_in, fan_out
            ),
        }

    def forward(self, ctx: Context, x):
        # x: (batch, time, feature) -> NCW for lax
        w = ctx.param("weight").astype(x.dtype)  # (out, in, k)
        y = lax.conv_general_dilated(
            x.swapaxes(1, 2),
            w,
            window_strides=(self.stride_w,),
            padding="VALID",
            dimension_numbers=("NCH", "OIH", "NCH"),
        )
        y = y.swapaxes(1, 2)
        return y + ctx.param("bias").astype(x.dtype)


class SpatialShareConvolution(SpatialConvolution):
    """Reference: ``SpatialShareConvolution.scala`` — identical math to
    SpatialConvolution; the reference variant exists to share im2col
    buffers across JVM threads, which has no analogue under XLA (buffers
    are compiler-managed), so this is a documented alias kept for API
    parity and model-zoo compatibility."""
