"""Volumetric (3-D) convolution / pooling layers.

Reference: ``DL/nn/VolumetricConvolution.scala``,
``VolumetricFullConvolution.scala``, ``VolumetricMaxPooling.scala``,
``VolumetricAveragePooling.scala`` — hand-written loops over (T, H, W)
volumes. TPU-native: one ``lax.conv_general_dilated`` /
``lax.reduce_window`` over NCDHW, which XLA tiles onto the MXU exactly like
the 2-D case.

Argument order keeps the reference's (kT, kW, kH) / (dT, dW, dH) /
(padT, padW, padH) convention.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import InitializationMethod, Xavier, Zeros
from bigdl_tpu.nn.module import Context, Module

_DNUMS = ("NCDHW", "OIDHW", "NCDHW")


def _pad3(pad_t: int, pad_w: int, pad_h: int):
    if -1 in (pad_t, pad_w, pad_h):
        return "SAME"
    return [(pad_t, pad_t), (pad_h, pad_h), (pad_w, pad_w)]


class VolumetricConvolution(Module):
    """3-D conv over (N, C, D, H, W) (reference
    ``VolumetricConvolution.scala``)."""

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        k_t: int,
        k_w: int,
        k_h: int,
        d_t: int = 1,
        d_w: int = 1,
        d_h: int = 1,
        pad_t: int = 0,
        pad_w: int = 0,
        pad_h: int = 0,
        with_bias: bool = True,
        weight_init: Optional[InitializationMethod] = None,
        bias_init: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_w, pad_h)
        self.with_bias = with_bias
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def build_params(self, rng):
        kt, kh, kw = self.kernel
        fan_in = self.n_input_plane * kt * kh * kw
        fan_out = self.n_output_plane * kt * kh * kw
        p = {
            "weight": self.weight_init(
                fold_in_str(rng, "weight"),
                (self.n_output_plane, self.n_input_plane, kt, kh, kw),
                fan_in, fan_out,
            )
        }
        if self.with_bias:
            p["bias"] = self.bias_init(
                fold_in_str(rng, "bias"), (self.n_output_plane,), fan_in, fan_out
            )
        return p

    def forward(self, ctx: Context, x):
        w = ctx.param("weight").astype(x.dtype)
        y = lax.conv_general_dilated(
            x, w,
            window_strides=self.stride,
            padding=_pad3(*self.pad),
            dimension_numbers=_DNUMS,
        )
        if self.with_bias:
            y = y + ctx.param("bias").astype(x.dtype)[:, None, None, None]
        return y


class VolumetricFullConvolution(Module):
    """3-D transposed conv (reference ``VolumetricFullConvolution.scala``):
    lowered as input-dilated conv with a spatially-flipped kernel."""

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        k_t: int,
        k_w: int,
        k_h: int,
        d_t: int = 1,
        d_w: int = 1,
        d_h: int = 1,
        pad_t: int = 0,
        pad_w: int = 0,
        pad_h: int = 0,
        adj_t: int = 0,
        adj_w: int = 0,
        adj_h: int = 0,
        with_bias: bool = True,
        weight_init: Optional[InitializationMethod] = None,
        bias_init: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.adj = (adj_t, adj_h, adj_w)
        self.with_bias = with_bias
        self.weight_init = weight_init or Xavier()
        self.bias_init = bias_init or Zeros()

    def build_params(self, rng):
        kt, kh, kw = self.kernel
        fan_in = self.n_input_plane * kt * kh * kw
        fan_out = self.n_output_plane * kt * kh * kw
        p = {
            "weight": self.weight_init(
                fold_in_str(rng, "weight"),
                (self.n_input_plane, self.n_output_plane, kt, kh, kw),
                fan_in, fan_out,
            )
        }
        if self.with_bias:
            p["bias"] = self.bias_init(
                fold_in_str(rng, "bias"), (self.n_output_plane,), fan_in, fan_out
            )
        return p

    def forward(self, ctx: Context, x):
        w = ctx.param("weight").astype(x.dtype)
        # transpose conv: lhs_dilation = stride, kernel flipped, IO swapped
        w = jnp.flip(w, axis=(2, 3, 4)).swapaxes(0, 1)
        kt, kh, kw = self.kernel
        pt, ph, pw = self.pad
        at, ah, aw = self.adj
        pads = [
            (kt - 1 - pt, kt - 1 - pt + at),
            (kh - 1 - ph, kh - 1 - ph + ah),
            (kw - 1 - pw, kw - 1 - pw + aw),
        ]
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(1, 1, 1),
            padding=pads,
            lhs_dilation=self.stride,
            dimension_numbers=_DNUMS,
        )
        if self.with_bias:
            y = y + ctx.param("bias").astype(x.dtype)[:, None, None, None]
        return y


class _Pool3D(Module):
    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None,
                 d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)

    def _window(self):
        return (1, 1) + self.kernel, (1, 1) + self.stride, \
            [(0, 0), (0, 0)] + [(p, p) for p in self.pad]


class VolumetricMaxPooling(_Pool3D):
    """Reference ``VolumetricMaxPooling.scala``."""

    def forward(self, ctx: Context, x):
        win, stride, pads = self._window()
        # scalar init value keeps the max-reduce_window differentiable
        return lax.reduce_window(x, -jnp.inf, lax.max, win, stride, pads)


class VolumetricAveragePooling(_Pool3D):
    """Reference ``VolumetricAveragePooling.scala`` (count includes pad,
    matching the reference's default countIncludePad=true)."""

    def forward(self, ctx: Context, x):
        win, stride, pads = self._window()
        summed = lax.reduce_window(x, 0.0, lax.add, win, stride, pads)
        kt, kh, kw = self.kernel
        return summed / float(kt * kh * kw)
