"""Torch-style NN layer library on pure-functional JAX.

Reference: ``DL/nn/`` (227 layer classes + ~40 criterions; SURVEY.md §2.2).
"""

from bigdl_tpu.nn.module import Module, Criterion, Context, LambdaLayer, Params, State
from bigdl_tpu.nn.containers import (
    Container,
    Sequential,
    Concat,
    ConcatTable,
    ParallelTable,
    MapTable,
    Bottle,
)
from bigdl_tpu.nn.graph import Graph, Input, Node
from bigdl_tpu.nn.layers import *  # noqa: F401,F403
from bigdl_tpu.nn.criterion import (
    ClassNLLCriterion,
    CrossEntropyCriterion,
    MSECriterion,
    AbsCriterion,
    SmoothL1Criterion,
    BCECriterion,
    BCECriterionWithLogits,
    MarginCriterion,
    DistKLDivCriterion,
    HingeEmbeddingCriterion,
    L1Cost,
    MultiLabelSoftMarginCriterion,
    ParallelCriterion,
    MultiCriterion,
    TimeDistributedCriterion,
    CosineEmbeddingCriterion,
    MarginRankingCriterion,
    MultiLabelMarginCriterion,
    MultiMarginCriterion,
    SoftMarginCriterion,
    L1HingeEmbeddingCriterion,
    KLDCriterion,
    GaussianCriterion,
    PoissonCriterion,
    CosineProximityCriterion,
    DiceCoefficientCriterion,
    ClassSimplexCriterion,
    CategoricalCrossEntropy,
    TransformerCriterion,
    CosineDistanceCriterion,
    DotProductCriterion,
    PGCriterion,
    KullbackLeiblerDivergenceCriterion,
    MeanAbsolutePercentageCriterion,
    MeanSquaredLogarithmicCriterion,
    SmoothL1CriterionWithWeights,
    SoftmaxWithCriterion,
    TimeDistributedMaskCriterion,
)
from bigdl_tpu.nn import init
from bigdl_tpu.nn.layers.recurrent import (
    Cell,
    RnnCell,
    LSTMCell,
    LSTMPeepholeCell,
    GRUCell,
    ConvLSTMPeepholeCell,
    ConvLSTMPeephole3DCell,
    ConvLSTMPeephole,
    ConvLSTMPeephole3D,
    MultiRNNCell,
    Recurrent,
    BiRecurrent,
    TimeDistributed,
    RecurrentDecoder,
    LSTM,
    GRU,
    SimpleRNN,
)
from bigdl_tpu.nn.quantized import QuantizedLinear, QuantizedSpatialConvolution, quantize
