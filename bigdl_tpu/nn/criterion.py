"""Criterions (loss functions).

Reference: the ~40 criterions under ``DL/nn/`` (``ClassNLLCriterion.scala``,
``CrossEntropyCriterion.scala``, ``MSECriterion.scala``, ``AbsCriterion.scala``,
``SmoothL1Criterion.scala``, ``BCECriterion.scala``, ``MarginCriterion.scala``,
``DistKLDivCriterion.scala``, ``HingeEmbeddingCriterion.scala``,
``ParallelCriterion.scala``, ``TimeDistributedCriterion.scala``,
``MultiCriterion.scala``, ``L1Cost.scala``, ``MultiLabelSoftMarginCriterion``).

Deviation from the reference: class labels are **0-based** integer arrays
(the reference uses 1-based Torch labels). Losses are pure functions of
(output, target); gradients come from ``jax.grad`` over the composed step.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Criterion


def _reduce(loss, size_average: bool):
    return jnp.mean(loss) if size_average else jnp.sum(loss)


def _bce_with_logits(output, t):
    """Numerically-stable elementwise sigmoid cross-entropy."""
    return jnp.maximum(output, 0) - output * t + jnp.log1p(jnp.exp(-jnp.abs(output)))


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probabilities
    (reference: ``ClassNLLCriterion.scala``). ``logProbAsInput=True`` expects
    LogSoftMax output; with ``False`` it expects probabilities."""

    def __init__(
        self,
        weights: Optional[jnp.ndarray] = None,
        size_average: bool = True,
        log_prob_as_input: bool = True,
    ):
        self.weights = weights
        self.size_average = size_average
        self.log_prob_as_input = log_prob_as_input

    def forward(self, output, target):
        logp = output if self.log_prob_as_input else jnp.log(jnp.clip(output, 1e-8))
        t = target.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]
        if self.weights is not None:
            w = jnp.take(self.weights, t)
            total = -jnp.sum(picked * w)
            return total / jnp.sum(w) if self.size_average else total
        return _reduce(-picked, self.size_average)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (reference: ``CrossEntropyCriterion.scala``).
    Takes raw logits."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average
        self.inner = ClassNLLCriterion(weights, size_average)

    def forward(self, output, target):
        return self.inner.forward(jax.nn.log_softmax(output, axis=-1), target)


class MSECriterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        return _reduce((output - target.astype(output.dtype)) ** 2, self.size_average)


class AbsCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        return _reduce(jnp.abs(output - target), self.size_average)


class SmoothL1Criterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        d = jnp.abs(output - target)
        loss = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(loss, self.size_average)


class BCECriterion(Criterion):
    """Binary cross entropy over probabilities (reference: ``BCECriterion.scala``)."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def forward(self, output, target):
        eps = 1e-12
        t = target.astype(output.dtype)
        loss = -(t * jnp.log(output + eps) + (1 - t) * jnp.log(1 - output + eps))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss, self.size_average)


class BCECriterionWithLogits(Criterion):
    """Numerically-stable sigmoid+BCE (TPU-friendly fused form)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        return _reduce(_bce_with_logits(output, target.astype(output.dtype)), self.size_average)


class MarginCriterion(Criterion):
    """Hinge loss, targets in {-1, 1} (reference: ``MarginCriterion.scala``).
    ``squared=True`` gives L2-SVM."""

    def __init__(self, margin: float = 1.0, size_average: bool = True, squared: bool = False):
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def forward(self, output, target):
        h = jnp.maximum(0.0, self.margin - output * target)
        if self.squared:
            h = h * h
        return _reduce(h, self.size_average)


class DistKLDivCriterion(Criterion):
    """KL(target || output) with output = log-probs (reference:
    ``DistKLDivCriterion.scala``)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        t = target.astype(output.dtype)
        loss = jnp.where(t > 0, t * (jnp.log(jnp.clip(t, 1e-12)) - output), 0.0)
        if self.size_average:
            return jnp.sum(loss) / output.shape[0]
        return jnp.sum(loss)


class HingeEmbeddingCriterion(Criterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, output, target):
        loss = jnp.where(target > 0, output, jnp.maximum(0.0, self.margin - output))
        return _reduce(loss, self.size_average)


class L1Cost(Criterion):
    def forward(self, output, target=None):
        return jnp.sum(jnp.abs(output))


class MultiLabelSoftMarginCriterion(Criterion):
    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def forward(self, output, target):
        loss = _bce_with_logits(output, target.astype(output.dtype))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss.mean(axis=-1), self.size_average)


class ParallelCriterion(Criterion):
    """Weighted sum of criterions over a table of (output, target) pairs
    (reference: ``ParallelCriterion.scala``)."""

    def __init__(self, repeat_target: bool = False):
        self.criterions = []
        self.weights = []
        self.repeat_target = repeat_target

    def add(self, criterion: Criterion, weight: float = 1.0) -> "ParallelCriterion":
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, output, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.forward(output[i], t)
        return total


class MultiCriterion(Criterion):
    """Sum of criterions on the same (output, target)
    (reference: ``MultiCriterion.scala``)."""

    def __init__(self):
        self.criterions = []
        self.weights = []

    def add(self, criterion: Criterion, weight: float = 1.0) -> "MultiCriterion":
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, output, target):
        return sum(w * c.forward(output, target) for c, w in zip(self.criterions, self.weights))


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (batch, time, ...) output
    (reference: ``TimeDistributedCriterion.scala``)."""

    def __init__(self, criterion: Criterion, size_average: bool = False, dimension: int = 1):
        self.criterion = criterion
        self.size_average = size_average
        self.dimension = dimension

    def forward(self, output, target):
        # Vectorized: flatten (batch, time) into one batch and rescale so the
        # result equals the reference's per-timestep loop (sum over steps of
        # criterion(output_t, target_t)).
        steps = output.shape[self.dimension]
        o = jnp.moveaxis(output, self.dimension, 1)
        t = jnp.moveaxis(target, self.dimension, 1) if target.ndim >= 2 else target
        o_flat = o.reshape((-1,) + o.shape[2:])
        t_flat = t.reshape((-1,) + t.shape[2:]) if t.ndim >= 2 else t
        flat = self.criterion.forward(o_flat, t_flat)
        total = flat * steps if getattr(self.criterion, "size_average", True) else flat
        return total / steps if self.size_average else total
