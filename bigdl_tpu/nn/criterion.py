"""Criterions (loss functions).

Reference: the ~40 criterions under ``DL/nn/`` (``ClassNLLCriterion.scala``,
``CrossEntropyCriterion.scala``, ``MSECriterion.scala``, ``AbsCriterion.scala``,
``SmoothL1Criterion.scala``, ``BCECriterion.scala``, ``MarginCriterion.scala``,
``DistKLDivCriterion.scala``, ``HingeEmbeddingCriterion.scala``,
``ParallelCriterion.scala``, ``TimeDistributedCriterion.scala``,
``MultiCriterion.scala``, ``L1Cost.scala``, ``MultiLabelSoftMarginCriterion``).

Deviation from the reference: class labels are **0-based** integer arrays
(the reference uses 1-based Torch labels). Losses are pure functions of
(output, target); gradients come from ``jax.grad`` over the composed step.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Criterion


def _reduce(loss, size_average: bool):
    return jnp.mean(loss) if size_average else jnp.sum(loss)


def _bce_with_logits(output, t):
    """Numerically-stable elementwise sigmoid cross-entropy."""
    return jnp.maximum(output, 0) - output * t + jnp.log1p(jnp.exp(-jnp.abs(output)))


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probabilities
    (reference: ``ClassNLLCriterion.scala``). ``logProbAsInput=True`` expects
    LogSoftMax output; with ``False`` it expects probabilities."""

    def __init__(
        self,
        weights: Optional[jnp.ndarray] = None,
        size_average: bool = True,
        log_prob_as_input: bool = True,
    ):
        self.weights = weights
        self.size_average = size_average
        self.log_prob_as_input = log_prob_as_input

    def forward(self, output, target):
        logp = output if self.log_prob_as_input else jnp.log(jnp.clip(output, 1e-8))
        t = target.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]
        if self.weights is not None:
            w = jnp.take(self.weights, t)
            total = -jnp.sum(picked * w)
            return total / jnp.sum(w) if self.size_average else total
        return _reduce(-picked, self.size_average)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (reference: ``CrossEntropyCriterion.scala``).
    Takes raw logits."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average
        self.inner = ClassNLLCriterion(weights, size_average)

    def forward(self, output, target):
        return self.inner.forward(jax.nn.log_softmax(output, axis=-1), target)


class MSECriterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        return _reduce((output - target.astype(output.dtype)) ** 2, self.size_average)


class AbsCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        return _reduce(jnp.abs(output - target), self.size_average)


class SmoothL1Criterion(Criterion):
    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        d = jnp.abs(output - target)
        loss = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(loss, self.size_average)


class BCECriterion(Criterion):
    """Binary cross entropy over probabilities (reference: ``BCECriterion.scala``)."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def forward(self, output, target):
        eps = 1e-12
        t = target.astype(output.dtype)
        loss = -(t * jnp.log(output + eps) + (1 - t) * jnp.log(1 - output + eps))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss, self.size_average)


class BCECriterionWithLogits(Criterion):
    """Numerically-stable sigmoid+BCE (TPU-friendly fused form)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        return _reduce(_bce_with_logits(output, target.astype(output.dtype)), self.size_average)


class MarginCriterion(Criterion):
    """Hinge loss, targets in {-1, 1} (reference: ``MarginCriterion.scala``).
    ``squared=True`` gives L2-SVM."""

    def __init__(self, margin: float = 1.0, size_average: bool = True, squared: bool = False):
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def forward(self, output, target):
        h = jnp.maximum(0.0, self.margin - output * target)
        if self.squared:
            h = h * h
        return _reduce(h, self.size_average)


class DistKLDivCriterion(Criterion):
    """KL(target || output) with output = log-probs (reference:
    ``DistKLDivCriterion.scala``)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        t = target.astype(output.dtype)
        loss = jnp.where(t > 0, t * (jnp.log(jnp.clip(t, 1e-12)) - output), 0.0)
        if self.size_average:
            return jnp.sum(loss) / output.shape[0]
        return jnp.sum(loss)


class HingeEmbeddingCriterion(Criterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, output, target):
        loss = jnp.where(target > 0, output, jnp.maximum(0.0, self.margin - output))
        return _reduce(loss, self.size_average)


class L1Cost(Criterion):
    def forward(self, output, target=None):
        return jnp.sum(jnp.abs(output))


class MultiLabelSoftMarginCriterion(Criterion):
    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def forward(self, output, target):
        loss = _bce_with_logits(output, target.astype(output.dtype))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss.mean(axis=-1), self.size_average)


class ParallelCriterion(Criterion):
    """Weighted sum of criterions over a table of (output, target) pairs
    (reference: ``ParallelCriterion.scala``)."""

    def __init__(self, repeat_target: bool = False):
        self.criterions = []
        self.weights = []
        self.repeat_target = repeat_target

    def add(self, criterion: Criterion, weight: float = 1.0) -> "ParallelCriterion":
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, output, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.forward(output[i], t)
        return total


class MultiCriterion(Criterion):
    """Sum of criterions on the same (output, target)
    (reference: ``MultiCriterion.scala``)."""

    def __init__(self):
        self.criterions = []
        self.weights = []

    def add(self, criterion: Criterion, weight: float = 1.0) -> "MultiCriterion":
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, output, target):
        return sum(w * c.forward(output, target) for c, w in zip(self.criterions, self.weights))


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of (batch, time, ...) output
    (reference: ``TimeDistributedCriterion.scala``)."""

    def __init__(self, criterion: Criterion, size_average: bool = False, dimension: int = 1):
        self.criterion = criterion
        self.size_average = size_average
        self.dimension = dimension

    def forward(self, output, target):
        # Vectorized: flatten (batch, time) into one batch and rescale so the
        # result equals the reference's per-timestep loop (sum over steps of
        # criterion(output_t, target_t)).
        steps = output.shape[self.dimension]
        o = jnp.moveaxis(output, self.dimension, 1)
        t = jnp.moveaxis(target, self.dimension, 1) if target.ndim >= 2 else target
        o_flat = o.reshape((-1,) + o.shape[2:])
        t_flat = t.reshape((-1,) + t.shape[2:]) if t.ndim >= 2 else t
        flat = self.criterion.forward(o_flat, t_flat)
        total = flat * steps if getattr(self.criterion, "size_average", True) else flat
        return total / steps if self.size_average else total


class CosineEmbeddingCriterion(Criterion):
    """Reference ``CosineEmbeddingCriterion.scala``: for input pair (x1, x2)
    and target y in {1, -1}: ``1 - cos`` for y=1, ``max(0, cos - margin)``
    for y=-1."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, output, target):
        x1, x2 = output
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12
        )
        loss = jnp.where(target > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(loss, self.size_average)


class MarginRankingCriterion(Criterion):
    """Reference ``MarginRankingCriterion.scala``:
    ``max(0, -y*(x1 - x2) + margin)``."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, output, target):
        x1, x2 = output
        loss = jnp.maximum(0.0, -target * (x1 - x2) + self.margin)
        return _reduce(loss, self.size_average)


class MultiLabelMarginCriterion(Criterion):
    """Reference ``MultiLabelMarginCriterion.scala``: multi-class multi-label
    hinge. ``target`` is a 0/1 indicator matrix shaped like ``output``
    (deviation: the reference packs 1-based label indices; an indicator mask
    is the XLA-friendly equivalent)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        t = target.astype(bool)
        # hinge between every (positive, other) pair
        pos = jnp.where(t, output, jnp.inf)[..., None]        # (B, C, 1)
        neg = jnp.where(t, -jnp.inf, output)[..., None, :]    # (B, 1, C)
        pair = jnp.maximum(0.0, 1.0 - (pos - neg))
        pair = jnp.where(jnp.isfinite(pair), pair, 0.0)
        loss = jnp.sum(pair, axis=(-2, -1)) / output.shape[-1]
        return _reduce(loss, self.size_average)


class MultiMarginCriterion(Criterion):
    """Reference ``MultiMarginCriterion.scala``: multi-class hinge
    ``sum_j max(0, margin - x_y + x_j)^p / C``."""

    def __init__(self, p: int = 1, margin: float = 1.0, size_average: bool = True):
        self.p = p
        self.margin = margin
        self.size_average = size_average

    def forward(self, output, target):
        t = target.astype(jnp.int32)
        x_y = jnp.take_along_axis(output, t[..., None], axis=-1)
        m = jnp.maximum(0.0, self.margin - x_y + output) ** self.p
        m = m * (1 - jax.nn.one_hot(t, output.shape[-1], dtype=output.dtype))
        loss = jnp.sum(m, -1) / output.shape[-1]
        return _reduce(loss, self.size_average)


class SoftMarginCriterion(Criterion):
    """Reference ``SoftMarginCriterion.scala``:
    ``mean(log(1 + exp(-y*x)))``."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        # logaddexp(0, z) = stable log(1 + e^z)
        return _reduce(jnp.logaddexp(0.0, -target * output), self.size_average)


class L1HingeEmbeddingCriterion(Criterion):
    """Reference ``L1HingeEmbeddingCriterion.scala``: L1 distance of a pair,
    hinged for dissimilar (y=-1) targets."""

    def __init__(self, margin: float = 1.0):
        self.margin = margin

    def forward(self, output, target):
        x1, x2 = output
        d = jnp.sum(jnp.abs(x1 - x2), axis=-1)
        loss = jnp.where(target > 0, d, jnp.maximum(0.0, self.margin - d))
        return jnp.mean(loss)


class KLDCriterion(Criterion):
    """Reference ``KLDCriterion.scala``: KL(q(z|x) || N(0,1)) from
    (mean, log_variance) — the VAE latent loss."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target=None):
        mean, log_var = output
        kld = 0.5 * jnp.sum(
            jnp.square(mean) + jnp.exp(log_var) - 1.0 - log_var, axis=-1
        )
        return jnp.mean(kld) if self.size_average else jnp.sum(kld)


class GaussianCriterion(Criterion):
    """Reference ``GaussianCriterion.scala``: negative log-likelihood of
    target under a diagonal Gaussian given (mean, log_variance)."""

    def forward(self, output, target):
        mean, log_var = output
        nll = 0.5 * (
            jnp.log(2 * jnp.pi) + log_var
            + jnp.square(target - mean) / jnp.exp(log_var)
        )
        return jnp.sum(nll)


class PoissonCriterion(Criterion):
    """Reference ``PoissonCriterion.scala``: mean(pred - target*log(pred))."""

    def forward(self, output, target):
        return jnp.mean(output - target * jnp.log(jnp.clip(output, 1e-8)))


class CosineProximityCriterion(Criterion):
    """Reference ``CosineProximityCriterion.scala`` (Keras cosine_proximity):
    ``-mean(cos(output, target))``."""

    def forward(self, output, target):
        o = output / jnp.maximum(jnp.linalg.norm(output, axis=-1, keepdims=True), 1e-12)
        t = target / jnp.maximum(jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-12)
        return -jnp.mean(jnp.sum(o * t, axis=-1))


class DiceCoefficientCriterion(Criterion):
    """Reference ``DiceCoefficientCriterion.scala``: 1 - Dice overlap
    (segmentation loss)."""

    def __init__(self, epsilon: float = 1.0):
        self.epsilon = epsilon

    def forward(self, output, target):
        axes = tuple(range(1, output.ndim))
        inter = jnp.sum(output * target, axis=axes)
        union = jnp.sum(output, axis=axes) + jnp.sum(target, axis=axes)
        dice = (2.0 * inter + self.epsilon) / (union + self.epsilon)
        return jnp.mean(1.0 - dice)


class ClassSimplexCriterion(Criterion):
    """Reference ``ClassSimplexCriterion.scala``: MSE against learned-free
    regular-simplex embeddings of the classes."""

    def __init__(self, n_classes: int):
        self.n_classes = n_classes
        self.simplex = self._build_simplex(n_classes)

    @staticmethod
    def _build_simplex(n):
        # regular simplex: centered identity rows e_i - 1/n are pairwise
        # equidistant; a uniform row normalization preserves that
        import numpy as _np

        a = _np.eye(n, dtype=_np.float32) - 1.0 / n
        scale = _np.linalg.norm(a[0])
        return jnp.asarray(a / max(scale, 1e-12))

    def forward(self, output, target):
        t = target.astype(jnp.int32)
        goal = jnp.take(self.simplex, t, axis=0)
        return jnp.mean(jnp.square(output - goal))


class CategoricalCrossEntropy(Criterion):
    """Cross-entropy over probabilities with one-hot OR int targets
    (reference: Keras ``categorical_crossentropy`` mapping in
    ``DL/nn/keras``)."""

    def __init__(self, from_logits: bool = False):
        self.from_logits = from_logits

    def forward(self, output, target):
        if self.from_logits:
            logp = output - jax.nn.logsumexp(output, axis=-1, keepdims=True)
        else:
            logp = jnp.log(jnp.clip(output, 1e-8, 1.0))
        if target.ndim == output.ndim:
            target = jnp.argmax(target, axis=-1)
        picked = jnp.take_along_axis(logp, target[..., None].astype(jnp.int32), axis=-1)
        return -jnp.mean(picked)


class TransformerCriterion(Criterion):
    """Apply transforms to output/target before an inner criterion
    (reference ``TransformerCriterion.scala``)."""

    def __init__(self, criterion: Criterion, input_transformer=None,
                 target_transformer=None):
        self.criterion = criterion
        self.input_transformer = input_transformer
        self.target_transformer = target_transformer

    def forward(self, output, target):
        if self.input_transformer is not None:
            output = self.input_transformer(output)
        if self.target_transformer is not None:
            target = self.target_transformer(target)
        return self.criterion.forward(output, target)


class CosineDistanceCriterion(Criterion):
    """1 - cos(output, target) (reference:
    ``CosineDistanceCriterion.scala``)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        t = target.astype(output.dtype)
        dot = jnp.sum(output * t, axis=-1)
        denom = jnp.linalg.norm(output, axis=-1) * jnp.linalg.norm(t, axis=-1)
        loss = 1.0 - dot / jnp.maximum(denom, 1e-12)
        return _reduce(loss, self.size_average)


class DotProductCriterion(Criterion):
    """Dot product of output and target (reference:
    ``DotProductCriterion.scala``; the PG building block). Positive —
    maximizing semantics come from the PGCriterion wrapper."""

    def __init__(self, size_average: bool = False):
        self.size_average = size_average

    def forward(self, output, target):
        dot = jnp.sum(output * target.astype(output.dtype))
        if self.size_average and output.ndim == 2:
            return dot / output.shape[0]
        return dot


class PGCriterion(Criterion):
    """Policy-gradient loss: sum(-log(pi(a|s)) * advantage) (reference:
    ``PGCriterion.scala`` = TransformerCriterion(Log >> MulConstant(-1),
    DotProductCriterion)). ``output`` are action probabilities, ``target``
    carries the (one-hot x advantage) credit."""

    def __init__(self, size_average: bool = False):
        self.size_average = size_average

    def forward(self, output, target):
        neg_logp = -jnp.log(jnp.clip(output, 1e-12))
        dot = jnp.sum(neg_logp * target.astype(output.dtype))
        if self.size_average and output.ndim == 2:
            return dot / output.shape[0]
        return dot


class KullbackLeiblerDivergenceCriterion(Criterion):
    """Keras-style KL divergence over probability rows (reference:
    ``KullbackLeiblerDivergenceCriterion.scala``): mean over samples of
    sum(y_true * log(y_true / y_pred)) with [eps, 1] clipping."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, output, target):
        eps = 1e-7
        y_t = jnp.clip(target.astype(output.dtype), eps, 1.0)
        y_p = jnp.clip(output, eps, 1.0)
        loss = jnp.sum(y_t * jnp.log(y_t / y_p), axis=-1)
        return _reduce(loss, self.size_average)


class MeanAbsolutePercentageCriterion(Criterion):
    """Keras MAPE (reference: ``MeanAbsolutePercentageCriterion.scala``):
    100 * mean(|y_t - y_p| / clip(|y_t|, eps, inf))."""

    def forward(self, output, target):
        t = target.astype(output.dtype)
        diff = jnp.abs(t - output) / jnp.clip(jnp.abs(t), 1e-7)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(Criterion):
    """Keras MSLE (reference: ``MeanSquaredLogarithmicCriterion.scala``):
    mean((log(y_p + 1) - log(y_t + 1))^2) with [eps, inf) clipping."""

    def forward(self, output, target):
        eps = 1e-7
        t = jnp.log1p(jnp.clip(target.astype(output.dtype), eps))
        p = jnp.log1p(jnp.clip(output, eps))
        return jnp.mean((p - t) ** 2)


class SmoothL1CriterionWithWeights(Criterion):
    """Fast-RCNN bbox regression loss (reference:
    ``SmoothL1CriterionWithWeights.scala``): smooth-L1 of
    (output - gt) * w_inside, scaled by w_outside, with transition point
    1/sigma^2. ``target`` is (gt,) or (gt, inside_w, outside_w)."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        self.sigma2 = sigma * sigma
        self.num = num

    def forward(self, output, target):
        if isinstance(target, (tuple, list)):
            gt = target[0]
            inside = target[1] if len(target) > 1 else None
            outside = target[2] if len(target) > 2 else None
        else:
            gt, inside, outside = target, None, None
        d = output - gt.astype(output.dtype)
        if inside is not None:
            d = d * inside
        ad = jnp.abs(d)
        loss = jnp.where(ad < 1.0 / self.sigma2,
                         0.5 * self.sigma2 * d * d,
                         ad - 0.5 / self.sigma2)
        if outside is not None:
            loss = loss * outside
        total = jnp.sum(loss)
        return total / self.num if self.num > 0 else total


class SoftmaxWithCriterion(Criterion):
    """Caffe SoftmaxWithLoss over (N, C, ...) maps (reference:
    ``SoftmaxWithCriterion.scala``): per-pixel CE with optional
    ignore_label and normalize mode VALID (default) | FULL | BATCH_SIZE |
    NONE. Labels 0-based (repo-wide deviation)."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "VALID"):
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def forward(self, output, target):
        logp = jax.nn.log_softmax(output, axis=1)
        t = target.astype(jnp.int32)
        # clamp before the gather: an ignore_label outside [0, C) would
        # otherwise hit take_along_axis's NaN fill mode
        t_safe = jnp.clip(t, 0, output.shape[1] - 1)
        picked = jnp.take_along_axis(logp, t_safe[:, None], axis=1)[:, 0]
        if self.ignore_label is not None:
            valid = (t != self.ignore_label).astype(output.dtype)
        else:
            valid = jnp.ones_like(picked, output.dtype)
        total = -jnp.sum(picked * valid)
        n, inner = output.shape[0], picked[0].size
        if self.normalize_mode == "VALID":
            return total / jnp.maximum(jnp.sum(valid), 1.0)
        if self.normalize_mode == "FULL":
            return total / (n * inner)
        if self.normalize_mode == "BATCH_SIZE":
            return total / n
        return total  # NONE


class TimeDistributedMaskCriterion(Criterion):
    """Time-distributed criterion with a padding mask (reference:
    ``TimeDistributedMaskCriterion.scala``): apply the inner criterion per
    step, ignoring positions where target == padding_value, and normalize
    by the number of unmasked positions."""

    def __init__(self, criterion: Criterion, padding_value: int = -1):
        # NOTE: labels here are 0-based (unlike the 1-based reference where
        # padding 0 is safe), so the default padding marker is -1
        self.criterion = criterion
        self.padding_value = padding_value

    def forward(self, output, target):
        b, t = output.shape[0], output.shape[1]
        flat_out = output.reshape((b * t,) + output.shape[2:])
        flat_tgt = target.reshape((b * t,) + target.shape[2:])
        mask = (flat_tgt != self.padding_value).astype(flat_out.dtype)
        mask = mask.reshape(b * t, -1)[:, 0]
        losses = jax.vmap(
            lambda o, tt: self.criterion.forward(o[None], tt[None])
        )(flat_out, flat_tgt)
        total = jnp.sum(losses * mask)
        return total / jnp.maximum(jnp.sum(mask), 1.0)
