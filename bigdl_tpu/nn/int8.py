"""Functional int8 building blocks for the quantized serving tier.

Leaf module on purpose (imports nothing from ``bigdl_tpu.nn``): both the
reference-tier module rewrite (``nn/quantized.py``) and the serving hot
path (``nn/layers/linear.py`` / the ``Transformer`` lm head / the paged
KV pools) call these, and a ``layers -> quantized -> layers`` cycle must
not exist.

Numerics contract (the tests pin all of it against numpy oracles):

- **weights**: symmetric per-output-channel int8 —
  ``scale = max|w| / 127`` per row of the (out, in) weight,
  ``w_q = clip(round(w / scale), -127, 127)``. ``jnp.round`` is
  round-half-to-even, bitwise ``np.round`` — the oracle replays it
  exactly.
- **activations**: symmetric PER-TOKEN (per-row) int8, computed
  dynamically INSIDE the jitted step. Per-row, not per-tensor, is
  load-bearing for the serving tier: a decode batch holds every active
  slot's activations, and a batch-wide absmax would make one request's
  quantization — and therefore its logits and its sampled stream —
  depend on who its neighbours are, breaking the engine's
  schedule-invariance contract (caught by the order-reversal tests).
  One scale per row keeps each request a pure function of itself, and
  is the more accurate choice anyway; the VPU absmax is noise next to
  the MXU GEMM either way.
- **matmul**: a TRUE ``s8 x s8 -> s32`` ``lax.dot_general``
  (``preferred_element_type=int32``) — on TPU this is the MXU's native
  int8 path at ~1.9x the bf16 rate (350-373 TOP/s measured,
  ``perf/micro_int8.py`` round 5). Integer accumulation is exact, so
  the jitted GEMM matches an int64-safe numpy oracle BIT-for-bit; the
  fp32 rescale ``acc * (scale_x * scale_w)`` is the only rounding.
- **KV rows**: per-token (per-row) scales shared across heads — one
  fp32 scale per written K (and V) row. Write-local by construction:
  no page ever needs requantizing, a recycled page carries no stale
  scale state, and chunked prefill stays bitwise equal to whole-prompt
  prefill even at int8 (each row's quantization depends only on the
  row itself).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# floor for every scale: keeps all-zero tensors/rows well-defined
# (q = 0, dequant = 0) without a division guard in the hot path
EPS = 1e-8


def quantize_weight(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(out, in) float weight -> (int8 weight, (out,) fp32 scales),
    symmetric per-output-channel (reference ``Desc.scala`` scales)."""
    w = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=1)
    scale = jnp.maximum(absmax, EPS) / 127.0
    wq = jnp.clip(jnp.round(w / scale[:, None]), -127, 127).astype(jnp.int8)
    return wq, scale.astype(jnp.float32)


def quantize_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Dynamic symmetric per-token int8: (M, K) float -> (int8 x,
    (M,) fp32 scales), one scale per row. Runs inside the jitted step;
    see the module docstring for why serving activations quantize
    per row, never per batch."""
    x = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=-1), EPS) / 127.0
    xq = jnp.clip(jnp.round(x / scale[:, None]), -127, 127).astype(jnp.int8)
    return xq, scale


def int8_accum(xq: jax.Array, wq: jax.Array) -> jax.Array:
    """The raw MXU op: (M, K) s8 x (N, K) s8 -> (M, N) s32, contracting
    K. Exact integer accumulation — no silent upcast (test-asserted on
    the jaxpr)."""
    return lax.dot_general(
        xq, wq, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)


def int8_linear(x: jax.Array, wq: jax.Array, w_scale: jax.Array,
                bias: Optional[jax.Array] = None) -> jax.Array:
    """Quantized GEMM for a (out, in) int8 weight: dynamic per-token
    activation quantization, ``s8 x s8 -> s32`` dot, fp32
    (row-scale x channel-scale) rescale. ``x`` is (..., in); returns
    (..., out) in ``x.dtype``."""
    shape = x.shape
    xq, x_scale = quantize_rows(x.reshape(-1, shape[-1]))
    acc = int8_accum(xq, wq)
    y = acc.astype(jnp.float32) * (
        x_scale[:, None] * w_scale[None, :].astype(jnp.float32))
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.reshape(shape[:-1] + (wq.shape[0],)).astype(x.dtype)


# ------------------------------------------------------------- KV rows ----


def quantize_kv_rows(rows: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-token KV quantization: ``rows`` (..., H, D) float ->
    (int8 rows, (...,) fp32 scales), one scale per row across all heads.
    Shared-across-heads keeps the scale pool free of a heads axis, so
    it replicates cleanly under tensor parallelism while the int8 pages
    shard on heads; the cross-head absmax is an exact max, so sharded
    and single-device quantization agree bitwise."""
    rows = rows.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(rows), axis=(-2, -1))
    scale = jnp.maximum(absmax, EPS) / 127.0
    q = jnp.clip(jnp.round(rows / scale[..., None, None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_lanes(lanes: jax.Array, scales: jax.Array,
                     dtype=jnp.float32) -> jax.Array:
    """int8 lanes (..., H, L, D) x per-row scales (..., L) -> float
    lanes. The inverse of :func:`quantize_kv_rows` after a page
    gather."""
    return lanes.astype(dtype) * scales[..., None, :, None].astype(dtype)
