"""Image classification over an image folder / DataFrame.

Reference: ``DL/example/imageclassification/ImagePredictor.scala`` (+
``MlUtils``, ``RowToByteRecords``) — load a trained model, read images
into a DataFrame, transform, batch-predict, show predictions; and
``imageFrame/InceptionValidation.scala`` (ImageFrame-based Top-1/Top-5
validation of Inception-v1).

TPU-native: ``DLImageReader`` -> vision transformer chain ->
``Predictor.predict_class``; ``--validate`` switches to the
ImageFrame-validation app using labeled subfolders.
"""

from __future__ import annotations

import argparse

import numpy as np
import jax


def _load_model(model_path, class_num):
    if model_path:
        from bigdl_tpu.utils.serializer import load_module

        return load_module(model_path)
    from bigdl_tpu.models import inception

    model = inception.build(class_num)
    params, state = model.init(jax.random.key(0))
    return model, params, state


def _chain(size: int = 224):
    from bigdl_tpu.vision import (
        AspectScale, CenterCrop, ChannelNormalize, MatToTensor,
    )

    return (AspectScale(256) >> CenterCrop(size, size)
            >> ChannelNormalize((123.0, 117.0, 104.0)) >> MatToTensor())


def _synthetic_df(n: int = 8):
    import pandas as pd

    rng = np.random.RandomState(0)
    return pd.DataFrame({
        "uri": [f"synthetic_{i}" for i in range(n)],
        "image": [rng.rand(256, 256, 3).astype(np.float32) * 255
                  for i in range(n)],
    })


def predict(args):
    """ImagePredictor: DataFrame of images -> prediction column."""
    from bigdl_tpu.dlframes import DLImageReader, DLImageTransformer
    from bigdl_tpu.optim.predictor import Predictor

    model, params, state = _load_model(args.modelPath, args.classNum)
    df = (DLImageReader.read_images(args.folder) if args.folder
          else _synthetic_df())
    df = DLImageTransformer(_chain()).transform(df)
    x = np.stack(df["transformed"].to_list())
    classes = Predictor(model, params, state,
                        batch_size=args.batchSize).predict_class(x)
    out = df[["uri"]].assign(prediction=classes)
    print(out.to_string(index=False))
    return out


def validate(args):
    """InceptionValidation: labeled ImageFrame -> Top-1/Top-5."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy
    from bigdl_tpu.optim.predictor import Evaluator
    from bigdl_tpu.vision import ImageFrame

    model, params, state = _load_model(args.modelPath, args.classNum)
    if args.folder:
        frame = ImageFrame.read(args.folder, with_label=True).transform(_chain())
        x = np.stack([f["tensor"] for f in frame])
        y = np.asarray([f["label"] for f in frame], np.int32)
    else:
        rng = np.random.RandomState(0)
        x = rng.rand(16, 3, 224, 224).astype(np.float32)
        y = rng.randint(0, args.classNum, (16,)).astype(np.int32)
    res = Evaluator(model, params, state, batch_size=args.batchSize).test(
        DataSet.tensors(x, y), [Top1Accuracy(), Top5Accuracy()])
    print(f"Top1: {res[0]}  Top5: {res[1]}")
    return res


def main(argv=None):
    ap = argparse.ArgumentParser("image-classification")
    ap.add_argument("-f", "--folder", default=None,
                    help="image dir (synthetic if absent)")
    ap.add_argument("--modelPath", default=None,
                    help=".bigdl model (random-weight Inception if absent)")
    ap.add_argument("-b", "--batchSize", type=int, default=8)
    ap.add_argument("--classNum", type=int, default=1000)
    ap.add_argument("--validate", action="store_true",
                    help="labeled-folder Top-1/Top-5 validation instead of predict")
    args = ap.parse_args(argv)
    return validate(args) if args.validate else predict(args)


if __name__ == "__main__":
    main()
