"""Elastic-fleet demo: an SLO-driven autoscaler absorbing a burst.

A :class:`~bigdl_tpu.serving.DisaggregatedFleet` starts at its minimum
size — one prefill member, one decode member — behind a single
``submit`` front door. An :class:`~bigdl_tpu.serving.AutoscaleController`
polls the fleet's gauges through a
:class:`~bigdl_tpu.obs.MetricsRegistry` and steers each role's
:class:`~bigdl_tpu.serving.EnginePool` independently: prompt-queue
pressure grows the prefill pool, decode queue/occupancy pressure grows
the decode pool, and sustained quiet (after cooldowns) drains members
back out through the scale-down gate — no stream is ever failed to
shrink.

The demo offers an OPEN-LOOP burst (arrivals on an absolute Poisson
schedule, never waiting for completions) sized past one member's
modeled capacity, then goes quiet. Watch the decision log: the pools
grow asymmetrically under the burst and give the capacity back in the
calm. Kernel costs are modeled with per-call sleeps so one CPU core
can show the scheduling story.

Run: ``python -m bigdl_tpu.examples.elastic_fleet_demo``
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np


class _CostedKernels:
    """Paged kernels plus a fixed per-call sleep — a stand-in for chip
    step time, priced per role (prompt chunks on prefill members,
    decode steps on decode members)."""

    def __init__(self, inner, step_s=0.0, prompt_s=0.0):
        self.inner = inner
        self.step_s = step_s
        self.prompt_s = prompt_s
        self.cache_sharding = getattr(inner, "cache_sharding", None)

    def prefill(self, *a, **kw):
        time.sleep(self.prompt_s)
        return self.inner.prefill(*a, **kw)

    def chunk(self, *a, **kw):
        time.sleep(self.prompt_s)
        return self.inner.chunk(*a, **kw)

    def decode(self, *a, **kw):
        time.sleep(self.step_s)
        return self.inner.decode(*a, **kw)

    @property
    def prefill_traces(self):
        return self.inner.prefill_traces

    @property
    def chunk_traces(self):
        return self.inner.chunk_traces

    @property
    def decode_traces(self):
        return self.inner.decode_traces


def main(argv=None):
    from bigdl_tpu.nn.layers.attention import Transformer
    from bigdl_tpu.obs import MetricsRegistry
    from bigdl_tpu.serving import (
        AutoscaleController,
        DisaggregatedFleet,
        EnginePool,
        GenerationEngine,
        Overloaded,
        PagedDecodeKernels,
        ReplicaUnavailable,
        ScalingPolicy,
        ServingMetrics,
    )
    from bigdl_tpu.serving.autoscale import above, all_of, any_of, below

    ap = argparse.ArgumentParser("elastic-fleet-demo")
    ap.add_argument("--rps", type=float, default=60.0,
                    help="burst arrival rate (req/s) — sized past one "
                         "member's modeled capacity")
    ap.add_argument("--burst-s", type=float, default=2.5,
                    help="burst duration")
    ap.add_argument("--calm-s", type=float, default=3.0,
                    help="quiet tail (where scale-down shows)")
    ap.add_argument("--calm-rps", type=float, default=8.0)
    ap.add_argument("--step-ms", type=float, default=4.0,
                    help="modeled decode-step cost per call")
    ap.add_argument("--new", type=int, default=24,
                    help="generated tokens per request")
    args = ap.parse_args(argv)

    vocab, page, slots, chunks = 64, 8, 4, 2
    prompt_len = chunks * page
    prompt_ms = 2.5 * args.step_ms
    # capacity arithmetic the burst is sized against
    decode_cap = slots / (args.new * args.step_ms / 1e3)
    prefill_cap = 1.0 / (chunks * prompt_ms / 1e3)
    print(f"modeled capacity/member: prefill ~{prefill_cap:.0f} rps, "
          f"decode ~{decode_cap:.0f} rps; burst offers {args.rps:.0f} rps")

    model = Transformer(vocab_size=vocab, hidden_size=32, num_heads=2,
                        filter_size=64, num_hidden_layers=1)
    params, _ = model.init(jax.random.key(0))
    kernels = PagedDecodeKernels(model)  # shared: scale-ups compile nothing
    eng_kw = dict(max_slots=slots, max_len=prompt_len + args.new,
                  max_prompt_len=prompt_len, page_size=page,
                  prefill_chunk=page, max_queue=32)

    def make_role(role):
        def make():
            k = (_CostedKernels(kernels, prompt_s=prompt_ms / 1e3)
                 if role == "prefill"
                 else _CostedKernels(kernels, step_s=args.step_ms / 1e3))
            return GenerationEngine(
                model, params, role=role, kernels=k,
                metrics=ServingMetrics(recent_window_s=2.0), **eng_kw)
        return make

    fleet = DisaggregatedFleet(make_role("prefill"), make_role("decode"),
                               n_prefill=1, n_decode=1, warm=True)
    registry = MetricsRegistry()
    registry.register("fleet", fleet)
    ctrl = AutoscaleController({
        "prefill": (EnginePool(fleet, "prefill", drain_timeout=10.0),
                    ScalingPolicy(
                        min_replicas=1, max_replicas=2,
                        up_when=above("fleet.prefill.queue_depth", 3),
                        down_when=below("fleet.prefill.queue_depth", 1),
                        breach_up=2, breach_down=3,
                        cooldown_up_s=0.6, cooldown_down_s=1.2)),
        "decode": (EnginePool(fleet, "decode", drain_timeout=10.0),
                   ScalingPolicy(
                       min_replicas=1, max_replicas=2,
                       up_when=any_of(
                           above("fleet.decode.queue_depth", 2),
                           above("fleet.decode.page_occupancy", 0.85)),
                       down_when=all_of(
                           below("fleet.decode.queue_depth", 1),
                           below("fleet.decode.page_occupancy", 0.5)),
                       breach_up=2, breach_down=3,
                       cooldown_up_s=0.6, cooldown_down_s=1.2)),
    }, registry=registry, interval_s=0.2)
    ctrl.start()

    # open-loop offered load: absolute schedule, no waiting on results
    rs = np.random.RandomState(0)
    sched, t = [], 0.0
    while t < args.burst_s + args.calm_s:
        rate = args.rps if t < args.burst_s else args.calm_rps
        t += rs.exponential(1.0 / rate)
        sched.append(t)
    prompts = [rs.randint(1, vocab, (prompt_len,)).tolist()
               for _ in range(16)]

    streams, shed = [], 0
    t0 = time.monotonic()  # same clock as the controller's decision log
    for i, at in enumerate(sched):
        delay = t0 + at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            streams.append(fleet.submit(prompts[i % len(prompts)],
                                        max_new_tokens=args.new))
        except (Overloaded, ReplicaUnavailable):
            shed += 1  # open loop: the fleet sheds, the clients keep coming

    served = 0
    for s in streams:
        s.result(timeout=120)
        served += 1
    ctrl.stop()

    peak = {"prefill": 1, "decode": 1}
    for _, sizes in ctrl.size_history:
        for pool, n in sizes.items():
            peak[pool] = max(peak[pool], n)
    snap = ctrl.snapshot()
    print(ctrl.format_table())
    for when, pool, action, member in ctrl.history:
        print(f"  t+{when - t0:5.2f}s  {pool:<8} {action:<11} {member}")
    pages_left = fleet.pages_in_use()
    fleet.close()

    out = {
        "offered": len(sched),
        "served": served,
        "shed": shed,
        "scale_ups": sum(p["scale_ups"] for p in snap["pools"].values()),
        "scale_downs": sum(p["scale_downs"]
                           for p in snap["pools"].values()),
        "peak_prefill": peak["prefill"],
        "peak_decode": peak["decode"],
        "pages_in_use": pages_left,
    }
    print(f"offered {out['offered']} served {out['served']} shed "
          f"{out['shed']}; peak sizes prefill={out['peak_prefill']} "
          f"decode={out['peak_decode']}; pages left {pages_left}")
    return out


if __name__ == "__main__":
    main()
