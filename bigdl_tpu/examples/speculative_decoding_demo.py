"""Speculative-decoding demo: a draft+target pair behind the router,
head-to-head against the plain paged engine.

A cheap draft model proposes ``k`` tokens per round; ONE target forward
(the verify step) scores all of them and the rejection sampler keeps the
longest prefix the target agrees with, plus one token the target chose
itself. Greedy speculative output is token-identical to plain greedy
decode — the demo asserts it — and the target runs far fewer forwards
than it emits tokens, which is the whole win on bandwidth-bound
hardware (every decode step streams the full KV cache + all GEMM
weights; see the expected-speedup formula in the README).

``--draft self`` (default) runs the target as its own draft — the
acceptance UPPER bound, standing in for a well-distilled family member.
``--draft small`` runs a fresh random quarter-size draft instead: with
untrained weights the two models rarely agree, which is the acceptance
FLOOR — the demo is honest about both ends.

Run: ``python -m bigdl_tpu.examples.speculative_decoding_demo -n 12``
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np


def build_lm(vocab_size: int = 128, small: bool = False):
    from bigdl_tpu.nn.layers.attention import Transformer

    if small:
        return Transformer(vocab_size=vocab_size, hidden_size=40,
                           num_heads=2, filter_size=80,
                           num_hidden_layers=1)
    return Transformer(vocab_size=vocab_size, hidden_size=160, num_heads=4,
                       filter_size=320, num_hidden_layers=2)


def main(argv=None):
    from bigdl_tpu.serving import GenerationEngine, ModelRouter

    ap = argparse.ArgumentParser("speculative-decoding-demo")
    ap.add_argument("-n", "--requests", type=int, default=12,
                    help="total generation requests")
    ap.add_argument("-k", "--speculate", type=int, default=3,
                    help="draft tokens proposed per verify round")
    ap.add_argument("-s", "--slots", type=int, default=4,
                    help="engine slot-table size")
    ap.add_argument("--max-len", type=int, default=96,
                    help="KV cache length (prompt + generation)")
    ap.add_argument("--new", type=int, default=24,
                    help="max_new_tokens per request")
    ap.add_argument("--draft", choices=("self", "small"), default="self",
                    help="'self' = the target drafts for itself "
                         "(acceptance upper bound); 'small' = a fresh "
                         "random quarter-size draft (the floor)")
    args = ap.parse_args(argv)

    vocab = 128
    model = build_lm(vocab)
    params, _ = model.init(jax.random.key(0))
    if args.draft == "self":
        draft, dparams = model, params
    else:
        draft = build_lm(vocab, small=True)
        dparams, _ = draft.init(jax.random.key(1))

    rs = np.random.RandomState(0)
    requests = [(rs.randint(1, vocab, (int(rs.randint(2, 13)),)).tolist(),
                 args.new) for _ in range(args.requests)]

    # one family behind one front door: the plain engine serves "lm",
    # the draft+target pair serves "lm-spec" — both greedy, so their
    # outputs MUST match token for token (speculation is lossless)
    plain = GenerationEngine(
        model, params, max_slots=args.slots, max_len=args.max_len,
        max_prompt_len=16, max_queue=max(64, 2 * args.requests),
        page_size=8)
    spec = GenerationEngine(
        model, params, max_slots=args.slots, max_len=args.max_len,
        max_prompt_len=16, max_queue=max(64, 2 * args.requests),
        page_size=8, speculate=(draft, dparams, args.speculate))
    plain.warmup()
    spec.warmup()
    router = ModelRouter()
    router.register("lm", plain)
    router.register("lm-spec", spec)

    def run(name):
        t0 = time.monotonic()
        streams = [router.submit(name, p, max_new_tokens=m)
                   for p, m in requests]
        outs = [s.result(timeout=300) for s in streams]
        return outs, time.monotonic() - t0

    plain_outs, plain_wall = run("lm")
    spec_outs, spec_wall = run("lm-spec")
    psnap = plain.metrics.snapshot()
    ssnap = spec.metrics.snapshot()
    print(spec.metrics.format_table())
    router.close()

    mismatches = sum(1 for a, b in zip(plain_outs, spec_outs) if a != b)
    assert mismatches == 0, (
        f"{mismatches} streams diverged — speculative greedy decode "
        f"must be lossless")

    tokens = sum(len(o) for o in spec_outs)
    plain_tps = sum(len(o) for o in plain_outs) / plain_wall
    spec_tps = tokens / spec_wall
    acc = ssnap["acceptance_rate"]
    amort = tokens / max(ssnap["verify_steps"], 1)
    print(f"plain      : {plain_tps:7.0f} tok/s "
          f"({psnap['decode_steps']} target forwards for {tokens} tokens)")
    print(f"speculative: {spec_tps:7.0f} tok/s "
          f"({ssnap['verify_steps']} target forwards for {tokens} tokens "
          f"= {amort:.2f} tokens per verify)")
    print(f"acceptance : {acc * 100:.0f}% of {ssnap['draft_tokens']} "
          f"drafted tokens (k={args.speculate}, draft={args.draft}); "
          f"0 greedy mismatches")
    print("the wall-clock win needs a chip (or the bench's modeled "
          "per-model step costs): on CPU the draft is not actually "
          "cheaper, but the target amortization above is the real lever")
    ssnap["speculative_vs_plain"] = spec_tps / plain_tps
    ssnap["mismatches"] = mismatches
    ssnap["tokens_per_verify"] = amort
    return ssnap


if __name__ == "__main__":
    main()
