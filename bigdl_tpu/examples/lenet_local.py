"""Local LeNet train / test / predict trio.

Reference: ``DL/example/lenetLocal/{Train,Test,Predict}.scala`` — the
single-node workflow: train LeNet on MNIST and checkpoint, evaluate a
saved model, predict classes for a few samples.

TPU-native: one CLI with ``--mode train|test|predict``; the model is
persisted through ``utils/serializer`` and evaluated with
``Evaluator``/``Predictor`` on the single chip.
"""

from __future__ import annotations

import argparse
import os

import numpy as np

import bigdl_tpu.nn as nn


def _model_file(folder: str) -> str:
    return os.path.join(folder, "lenet.bigdl")


def train(args) -> str:
    from bigdl_tpu.models import lenet
    from bigdl_tpu.utils.serializer import save_module

    params, state = lenet.main([
        "-b", str(args.batchSize), "-e", str(args.maxEpoch),
        "--learningRate", str(args.learningRate),
    ] + (["--maxIteration", str(args.maxIteration)] if args.maxIteration else [])
      + (["-f", args.folder] if args.folder else []))
    os.makedirs(args.modelDir, exist_ok=True)
    path = save_module(_model_file(args.modelDir), lenet.build(), params, state)
    print(f"saved model to {path}")
    return path


def _load(args):
    from bigdl_tpu.utils.serializer import load_module

    return load_module(_model_file(args.modelDir))


def test(args):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.models import lenet
    from bigdl_tpu.optim import Top1Accuracy
    from bigdl_tpu.optim.predictor import Evaluator

    model, params, state = _load(args)
    ds = lenet.mnist_train_pipeline(args.folder, train=False)
    res = Evaluator(model, params, state, batch_size=args.batchSize).test(
        ds, [Top1Accuracy()])
    print(f"Top1Accuracy: {res[0]}")
    return res


def predict(args):
    from bigdl_tpu.dataset.datasets import load_mnist
    from bigdl_tpu.models import lenet
    from bigdl_tpu.optim.predictor import Predictor
    from bigdl_tpu.dataset.datasets import MNIST_TRAIN_MEAN, MNIST_TRAIN_STD

    model, params, state = _load(args)
    x, _ = load_mnist(args.folder, train=False)
    x = ((x - MNIST_TRAIN_MEAN) / MNIST_TRAIN_STD)[:args.nPredict, None]
    classes = Predictor(model, params, state).predict_class(
        x.astype(np.float32))
    print(f"predicted classes: {classes.tolist()}")
    return classes


def main(argv=None):
    ap = argparse.ArgumentParser("lenet-local")
    ap.add_argument("--mode", choices=["train", "test", "predict"],
                    default="train")
    ap.add_argument("-f", "--folder", default=None,
                    help="mnist dir (synthetic if absent)")
    ap.add_argument("--modelDir", default="/tmp/bigdl_tpu_lenet")
    ap.add_argument("-b", "--batchSize", type=int, default=128)
    ap.add_argument("-e", "--maxEpoch", type=int, default=2)
    ap.add_argument("--maxIteration", type=int, default=0)
    ap.add_argument("--learningRate", type=float, default=0.05)
    ap.add_argument("--nPredict", type=int, default=8)
    args = ap.parse_args(argv)
    return {"train": train, "test": test, "predict": predict}[args.mode](args)


if __name__ == "__main__":
    main()
