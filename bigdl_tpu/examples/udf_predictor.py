"""Serve a text classifier as a DataFrame UDF.

Reference: ``DL/example/udfpredictor/DataframePredictor.scala`` — train
(or load) the text classifier, register it as a Spark SQL UDF, and query
a DataFrame of documents with ``df.withColumn("class", udf(col))`` /
SQL ``SELECT``.

TPU-native: the "UDF" is a plain Python callable closed over a jitted
``Predictor`` — applied to a pandas column. The query surface is
``DataFrame.assign`` (and ``DataFrame.query`` for the SQL-filter step),
the direct pandas equivalents of the reference's withColumn + WHERE.
"""

from __future__ import annotations

import argparse
from typing import Callable, List

import numpy as np

from bigdl_tpu.dataset.text import Dictionary, tokenize


def make_udf(model, params, state, dictionary: Dictionary,
             seq_len: int, batch_size: int = 32) -> Callable[[List[str]], np.ndarray]:
    """Vectorized UDF: list of raw documents -> predicted class ids."""
    from bigdl_tpu.examples.text_classification import to_arrays
    from bigdl_tpu.optim.predictor import Predictor

    predictor = Predictor(model, params, state, batch_size=batch_size)

    def udf(texts: List[str]) -> np.ndarray:
        toks = [tokenize(t) for t in texts]
        x, _ = to_arrays(toks, [0] * len(toks), dictionary, seq_len)
        return predictor.predict_class(x)

    return udf


def main(argv=None):
    import pandas as pd

    from bigdl_tpu.examples.text_classification import (
        build, load_corpus, to_arrays,
    )

    ap = argparse.ArgumentParser("udf-predictor")
    ap.add_argument("-b", "--baseDir", default=None,
                    help="news20-layout corpus (synthetic if absent)")
    ap.add_argument("-s", "--maxSequenceLength", type=int, default=500)
    ap.add_argument("-z", "--batchSize", type=int, default=32)
    ap.add_argument("-e", "--maxEpoch", type=int, default=1)
    ap.add_argument("--filterClass", type=int, default=0,
                    help="the WHERE-clause class of the reference's SQL query")
    args = ap.parse_args(argv)

    # train the classifier (reference: loads or trains via TextClassifier)
    import jax

    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim import Adagrad, Trigger, optimizer
    import bigdl_tpu.nn as nn

    texts, labels = load_corpus(args.baseDir)
    dictionary = Dictionary(texts, vocab_size=5000)
    x, y = to_arrays(texts, labels, dictionary, args.maxSequenceLength)
    class_num = int(y.max()) + 1
    model = build(class_num, dictionary.vocab_size,
                  seq_len=args.maxSequenceLength)
    ds = DataSet.tensors(x, y) >> SampleToMiniBatch(args.batchSize)
    opt = optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=args.batchSize)
    opt.set_optim_method(Adagrad(learning_rate=0.01))
    opt.set_end_when(Trigger.max_epoch(args.maxEpoch))
    params, state = opt.optimize()

    # register + query (reference: df.withColumn then SQL WHERE)
    udf = make_udf(model, params, state, dictionary, args.maxSequenceLength,
                   args.batchSize)
    docs = pd.DataFrame({"text": [" ".join(t) for t in texts[:16]]})
    docs = docs.assign(predicted=udf(docs["text"].tolist()))
    hits = docs.query(f"predicted == {args.filterClass}")
    print(f"{len(hits)}/{len(docs)} documents predicted class {args.filterClass}")
    return docs


if __name__ == "__main__":
    main()
