"""Text classification with a word-embedding CNN.

Reference: ``DL/example/textclassification/TextClassifier.scala`` +
``DL/example/utils/TextClassifier.scala`` (news20 corpus + GloVe
embeddings -> token windows -> temporal conv/pooling stack -> 20-way
softmax, trained with an Optimizer).

TPU-native: the tokenizer/Dictionary pipeline feeds fixed-length int32
token ids; the embedding table is a ``LookupTable`` initialized from
GloVe vectors when ``--embeddingFile`` is given (random otherwise), and
the conv stack is ``TemporalConvolution``/``TemporalMaxPooling`` — one
statically-shaped program, no per-sentence shapes.
"""

from __future__ import annotations

import argparse
import glob
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset.text import Dictionary, tokenize


def build(class_num: int, vocab_size: int, embed_dim: int = 50,
          seq_len: int = 500) -> nn.Sequential:
    """Embedding -> [conv5/relu/pool5] x2 -> conv5/relu -> global max pool
    -> Linear(128) -> Linear(class_num) (reference
    ``TextClassifier.buildModel``)."""
    model = nn.Sequential()
    # +1: the Dictionary maps unknown words past the vocab
    model.add(nn.LookupTable(vocab_size + 1, embed_dim))
    model.add(nn.TemporalConvolution(embed_dim, 128, 5))
    model.add(nn.ReLU())
    model.add(nn.TemporalMaxPooling(5, 5))
    model.add(nn.TemporalConvolution(128, 128, 5))
    model.add(nn.ReLU())
    model.add(nn.TemporalMaxPooling(5, 5))
    model.add(nn.TemporalConvolution(128, 128, 5))
    model.add(nn.ReLU())
    # global max over the remaining time axis
    remaining = ((seq_len - 4) // 5 - 4) // 5 - 4
    if remaining < 1:
        raise ValueError(
            f"maxSequenceLength={seq_len} too short for the conv stack "
            "(needs >= 149)")
    model.add(nn.TemporalMaxPooling(remaining, remaining))
    model.add(nn.Squeeze(1))
    model.add(nn.Linear(128, 100))
    model.add(nn.ReLU())
    model.add(nn.Linear(100, class_num))
    model.add(nn.LogSoftMax())
    return model


def load_corpus(base_dir: Optional[str], n_classes: int = 4,
                n_per_class: int = 64) -> Tuple[List[List[str]], List[int]]:
    """news20 layout: one subdirectory per category, one file per post.
    Synthetic class-separable token streams when ``base_dir`` is absent."""
    if base_dir and os.path.isdir(base_dir):
        texts, labels = [], []
        cats = sorted(d for d in os.listdir(base_dir)
                      if os.path.isdir(os.path.join(base_dir, d)))
        for li, cat in enumerate(cats):
            for path in sorted(glob.glob(os.path.join(base_dir, cat, "*"))):
                with open(path, errors="ignore") as f:
                    texts.append(tokenize(f.read()))
                labels.append(li)
        return texts, labels
    rng = np.random.RandomState(0)
    vocab = [f"w{i}" for i in range(200)]
    texts, labels = [], []
    for li in range(n_classes):
        marker = [f"class{li}marker{j}" for j in range(8)]
        for _ in range(n_per_class):
            length = int(rng.randint(20, 60))
            toks = [vocab[rng.randint(200)] for _ in range(length)]
            for m in marker:  # class-identifying tokens
                toks.insert(int(rng.randint(len(toks))), m)
            texts.append(toks)
            labels.append(li)
    return texts, labels


def load_glove(path: str, dictionary: Dictionary,
               embed_dim: int) -> np.ndarray:
    """GloVe text format -> (vocab+1, dim) table; missing words stay at
    their random init (reference ``buildWord2VecMap``)."""
    table = np.random.RandomState(1).uniform(
        -0.05, 0.05, (dictionary.vocab_size + 1, embed_dim)).astype(np.float32)
    with open(path, errors="ignore") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            idx = dictionary.word2index.get(parts[0])
            if idx is not None and len(parts) == embed_dim + 1:
                table[idx] = np.asarray(parts[1:], np.float32)
    return table


def to_arrays(texts: Sequence[List[str]], labels: Sequence[int],
              dictionary: Dictionary, seq_len: int) -> Tuple[np.ndarray, np.ndarray]:
    x = np.full((len(texts), seq_len), dictionary.unk_index(), np.int32)
    for i, toks in enumerate(texts):
        idx = dictionary.indices(toks[:seq_len])
        x[i, :len(idx)] = idx
    return x, np.asarray(labels, np.int32)


def main(argv=None):
    import jax

    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.models.cli import fit
    from bigdl_tpu.optim import Adagrad, Top1Accuracy, Trigger, optimizer

    ap = argparse.ArgumentParser("text-classifier")
    ap.add_argument("-b", "--baseDir", default=None,
                    help="news20-layout corpus dir (synthetic if absent)")
    ap.add_argument("--embeddingFile", default=None,
                    help="GloVe .txt vectors (random init if absent)")
    ap.add_argument("-s", "--maxSequenceLength", type=int, default=500)
    ap.add_argument("-w", "--maxWordsNum", type=int, default=5000)
    ap.add_argument("-l", "--trainingSplit", type=float, default=0.8)
    ap.add_argument("-z", "--batchSize", type=int, default=32)
    ap.add_argument("--learningRate", type=float, default=0.01)
    ap.add_argument("--embedDim", type=int, default=50)
    ap.add_argument("-e", "--maxEpoch", type=int, default=2)
    ap.add_argument("--maxIteration", type=int, default=0)
    ap.add_argument("--checkpoint", default=None)
    args = ap.parse_args(argv)

    texts, labels = load_corpus(args.baseDir)
    dictionary = Dictionary(texts, vocab_size=args.maxWordsNum)
    x, y = to_arrays(texts, labels, dictionary, args.maxSequenceLength)
    perm = np.random.RandomState(42).permutation(len(x))
    x, y = x[perm], y[perm]
    split = int(len(x) * args.trainingSplit)
    class_num = int(y.max()) + 1

    model = build(class_num, dictionary.vocab_size,
                  args.embedDim, args.maxSequenceLength)
    params, state = model.init(jax.random.key(0))
    if args.embeddingFile:
        table = load_glove(args.embeddingFile, dictionary, args.embedDim)
        params = dict(params)
        lookup_key = next(iter(params))
        params[lookup_key] = dict(params[lookup_key], weight=table)

    train = DataSet.tensors(x[:split], y[:split]) >> SampleToMiniBatch(args.batchSize)
    val = DataSet.tensors(x[split:], y[split:])

    opt = optimizer(model, train, nn.ClassNLLCriterion(),
                    batch_size=args.batchSize)
    opt.set_model_and_state(params, state)
    opt.set_optim_method(Adagrad(learning_rate=args.learningRate))
    opt.set_validation(Trigger.every_epoch(), val, [Top1Accuracy()],
                       args.batchSize)
    return fit(opt, args)


if __name__ == "__main__":
    main()
