"""Model validator: load a saved model in any supported format and
evaluate it.

Reference: ``DL/example/loadmodel/ModelValidator.scala`` — one CLI that
loads a BigDL / Caffe / Torch model (``-t bigdl|caffe|torch``) and runs
Top-1/Top-5 validation over an image folder.

TPU-native: formats map to ``utils/serializer.load_module`` (repo
format), ``interop.caffe.load_caffe`` (prototxt + caffemodel) and
``utils/torch_file.load_t7``; the image folder is read through the
vision ImageFrame pipeline; synthetic data stands in when no folder is
given.
"""

from __future__ import annotations

import argparse
from typing import Optional, Tuple

import numpy as np


def load_any(model_type: str, model_path: str,
             caffe_def_path: Optional[str] = None):
    """Returns (module, params, state) for any supported format
    (``torch`` converts the legacy Sequential zoo via ``t7_to_module``)."""
    if model_type == "bigdl":
        from bigdl_tpu.utils.serializer import load_module

        return load_module(model_path)
    if model_type == "bigdl-proto":
        from bigdl_tpu.interop.bigdl import load_bigdl

        return load_bigdl(model_path)
    if model_type == "caffe":
        from bigdl_tpu.interop.caffe import load_caffe

        if not caffe_def_path:
            raise ValueError("caffe models need --caffeDefPath (prototxt)")
        return load_caffe(caffe_def_path, model_path)
    if model_type == "torch":
        from bigdl_tpu.utils.torch_file import load_t7, t7_to_module

        return t7_to_module(load_t7(model_path))
    raise ValueError("modelType must be bigdl, bigdl-proto, caffe or torch")


def load_images(folder: Optional[str], batch: int,
                n_synth: int = 64) -> Tuple[np.ndarray, np.ndarray]:
    """ImageFolder layout (subdir per class) -> normalized NCHW batch
    arrays; synthetic when absent (reference reads the ImageNet val
    set)."""
    if folder:
        from bigdl_tpu.vision import (
            AspectScale, CenterCrop, ChannelNormalize, ImageFrame, MatToTensor,
        )

        frame = ImageFrame.read(folder, with_label=True)
        chain = (AspectScale(256) >> CenterCrop(224, 224)
                 >> ChannelNormalize((123.0, 117.0, 104.0)) >> MatToTensor())
        frame = frame.transform(chain)
        x = np.stack([f["tensor"] for f in frame])
        y = np.asarray([f["label"] for f in frame], np.int32)
        return x, y
    rng = np.random.RandomState(0)
    x = rng.rand(n_synth, 3, 224, 224).astype(np.float32)
    return x, rng.randint(0, 1000, (n_synth,)).astype(np.int32)


def main(argv=None):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy
    from bigdl_tpu.optim.predictor import Evaluator

    ap = argparse.ArgumentParser("load-model-validator")
    ap.add_argument("-t", "--modelType", required=True,
                    choices=["bigdl", "bigdl-proto", "caffe", "torch"])
    ap.add_argument("--modelPath", required=True)
    ap.add_argument("--caffeDefPath", default=None)
    ap.add_argument("-f", "--folder", default=None,
                    help="ImageFolder-layout validation images (synthetic if absent)")
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    args = ap.parse_args(argv)

    model, params, state = load_any(args.modelType, args.modelPath,
                                    args.caffeDefPath)
    x, y = load_images(args.folder, args.batchSize)
    results = Evaluator(model, params, state, batch_size=args.batchSize).test(
        DataSet.tensors(x, y), [Top1Accuracy(), Top5Accuracy()])
    for method, res in zip(("Top1Accuracy", "Top5Accuracy"), results):
        print(f"{method}: {res}")
    return results


if __name__ == "__main__":
    main()
