"""TensorFlow interop: load/save a frozen graph; transfer learning on a
loaded TF feature extractor.

Reference: ``DL/example/tensorflow/loadandsave/{Load,Save}.scala`` (load
a frozen TF model, run it; export a BigDL model as a TF graph) and
``DL/example/tensorflow/transferlearning/TransferLearning.scala``
(run an Inception feature extractor loaded from TF, train a fresh
classifier head on the extracted features).

TPU-native: the frozen GraphDef imports as one pure ``TFGraphModule``
(one XLA program); transfer learning = extract features once on-device,
then fit a small head with the ordinary optimizer — no Session/queue
machinery needed (the reference's queue runners exist to feed Spark
partitions; here the host pipeline feeds the chip directly).
"""

from __future__ import annotations

import argparse
import os

import numpy as np
import jax

import bigdl_tpu.nn as nn


def demo_feature_graph(path: str, in_ch: int = 4, feat: int = 16) -> str:
    """Build a small conv feature extractor, export it as a frozen TF
    GraphDef (stand-in for a downloaded slim checkpoint)."""
    from bigdl_tpu.interop.tf import save_tf_graph

    model = nn.Sequential(
        nn.SpatialConvolution(in_ch, 8, 3, 3, pad_w=1, pad_h=1),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([8 * 4 * 4]),
        nn.Linear(8 * 4 * 4, feat),
        nn.Tanh(),
    )
    params, state = model.init(jax.random.key(0))
    save_tf_graph(model, params, state, path, input_shape=(-1, in_ch, 8, 8))
    return path


def main(argv=None):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.interop.tf import load_tf_graph
    from bigdl_tpu.optim import SGD, Top1Accuracy, Trigger, optimizer
    from bigdl_tpu.optim.predictor import Predictor

    ap = argparse.ArgumentParser("tf-transfer-learning")
    ap.add_argument("--graph", default=None,
                    help="frozen GraphDef .pb (a demo extractor is built if absent)")
    ap.add_argument("--inputs", default=None,
                    help="comma-separated input node names (demo default)")
    ap.add_argument("--outputs", default=None,
                    help="comma-separated output node names (demo default)")
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("-e", "--maxEpoch", type=int, default=3)
    ap.add_argument("--nSamples", type=int, default=256)
    ap.add_argument("--classNum", type=int, default=4)
    args = ap.parse_args(argv)

    graph_path = args.graph or demo_feature_graph("/tmp/bigdl_tpu_tf_feat.pb")
    inputs = args.inputs.split(",") if args.inputs else ["input"]
    outputs = args.outputs.split(",") if args.outputs else ["output"]
    extractor, ext_params, ext_state = load_tf_graph(graph_path, inputs, outputs)

    # synthetic labeled data in the extractor's input shape
    rng = np.random.RandomState(0)
    y = rng.randint(0, args.classNum, (args.nSamples,)).astype(np.int32)
    x = rng.rand(args.nSamples, 4, 8, 8).astype(np.float32)
    x += y[:, None, None, None] * 0.5  # class-separable

    # 1) run the TF graph on-device to extract features (Load.scala)
    feats = Predictor(extractor, ext_params, ext_state,
                      batch_size=args.batchSize).predict(x)
    feats = np.stack([np.asarray(f, np.float32) for f in feats])

    # 2) train a fresh head on the frozen features (TransferLearning.scala)
    head = nn.Sequential(nn.Linear(feats.shape[-1], args.classNum),
                         nn.LogSoftMax())
    ds = DataSet.tensors(feats, y) >> SampleToMiniBatch(args.batchSize)
    opt = optimizer(head, ds, nn.ClassNLLCriterion(), batch_size=args.batchSize)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(Trigger.max_epoch(args.maxEpoch))
    opt.set_validation(Trigger.every_epoch(), DataSet.tensors(feats, y),
                       [Top1Accuracy()], args.batchSize)
    params, state = opt.optimize()
    print("transfer-learning head trained")
    return params, state


if __name__ == "__main__":
    main()
