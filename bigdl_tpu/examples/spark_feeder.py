"""Spark-executor -> TPU-host feeding: the producer side of the feeder.

Reference / north star: the reference keeps training data in Spark
executors (``CachedDistriDataSet``, ``DL/dataset/DataSet.scala:247``) and
moves batches to the compute through the BlockManager; the north star
names "Spark-executor x TPU" configs. Here the executor side is a plain
``mapPartitions`` closure that streams its partition through
:class:`bigdl_tpu.dataset.feeder.BatchFeedClient` to the TPU host, which
trains from a :class:`SocketFeedDataSet`.

Runs in two modes:

- with pyspark installed: a real ``SparkContext`` fans partitions over
  executors, each executor task opens one socket to the host;
- without pyspark (this image): ``multiprocessing`` processes stand in
  for executor tasks — same closure, same wire protocol, same
  backpressure path.

The JVM framing (for Scala/Java executors that do not run Python) is 30
lines; a reference implementation is in
``bigdl_tpu/examples/JvmFeedProducer.java`` and the byte layout is pinned
by ``tests/test_feeder.py::test_wire_format_conformance``.
"""

from __future__ import annotations

import argparse
import multiprocessing


def partition_producer(host: str, port: int, seed: int, n_batches: int,
                       batch: int):
    """The mapPartitions closure: runs INSIDE the executor process.

    In real use the iterator yields the partition's (features, labels)
    records; here it synthesizes MNIST-shaped batches."""
    import numpy as np

    from bigdl_tpu.dataset.feeder import push_batches

    rng = np.random.RandomState(seed)

    def batches():
        for _ in range(n_batches):
            x = rng.rand(batch, 784).astype(np.float32)
            y = (rng.randint(0, 10, (batch,))).astype(np.int32)
            yield x, y

    return push_batches((host, port), batches())


def run_spark(sc, host, port, n_partitions, n_batches, batch):
    """Real Spark path: one feed connection per partition task. UNTESTED
    in this image (no pyspark): only the multiprocessing fallback below
    and the JVM byte-layout conformance test exercise the wire protocol;
    this branch's Spark-specific plumbing has never run here. A real
    job would iterate the partition's records inside the closure; the
    synthetic producer only needs the partition index for a distinct
    seed."""
    counts = (
        sc.parallelize(range(n_partitions), n_partitions)
        .mapPartitionsWithIndex(lambda idx, it: [partition_producer(
            host, port, seed=100 + idx, n_batches=n_batches, batch=batch)])
        .collect()
    )
    return sum(counts)


def main(argv=None):
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.feeder import SocketFeedDataSet
    from bigdl_tpu.optim import SGD, Trigger, optimizer

    ap = argparse.ArgumentParser("spark_feeder")
    ap.add_argument("--nProducers", type=int, default=2,
                    help="executor tasks (partitions)")
    ap.add_argument("--nBatches", type=int, default=4, help="batches/task")
    ap.add_argument("--batchSize", type=int, default=32)
    ap.add_argument("--maxEpoch", type=int, default=1)
    ap.add_argument("--bindHost", default="127.0.0.1",
                    help="host interface to listen on (use 0.0.0.0 for "
                         "remote Spark executors)")
    ap.add_argument("--feedHost", default=None,
                    help="address executors connect to (this host's "
                         "routable name when executors are remote)")
    args = ap.parse_args(argv)

    # host side: bind first so producers have a live port to hit
    ds = SocketFeedDataSet((args.bindHost, 0), n_producers=args.nProducers,
                           epoch_size=args.nProducers * args.nBatches)
    host, port = ds.bound_address

    try:
        from pyspark import SparkContext  # noqa: F401

        sc = SparkContext.getOrCreate()
        spawn = None
        # the Spark action must run CONCURRENTLY with the consumer:
        # producers block in send() once the host queue + TCP buffers
        # fill (backpressure), so a foreground collect() would deadlock
        # before optimize() ever starts draining
        import threading

        if args.bindHost in ("0.0.0.0", "::") and not args.feedHost:
            raise SystemExit(
                "--bindHost is a wildcard: remote executors cannot "
                "connect to it — pass --feedHost <this host's routable "
                "address>")
        spark_err: list = []

        def spark_action():
            try:
                run_spark(sc, args.feedHost or host, port, args.nProducers,
                          args.nBatches, args.batchSize)
            except BaseException as e:  # surfaced after optimize/join
                spark_err.append(e)
                # poison the feed so optimize() unblocks instead of
                # waiting forever on a stream no producer will ever feed
                ds.fail(e)

        spark_thread = threading.Thread(target=spark_action, daemon=True)
        spark_thread.start()
    except ImportError:
        sc = None
        spark_thread = None
        # stand-in executors: separate PROCESSES, same closure
        ctx = multiprocessing.get_context("spawn")
        spawn = [
            ctx.Process(target=partition_producer,
                        args=(host, port, 100 + i, args.nBatches,
                              args.batchSize))
            for i in range(args.nProducers)
        ]
        for p in spawn:
            p.start()

    model = nn.Sequential(
        nn.Linear(784, 64), nn.ReLU(), nn.Linear(64, 10), nn.LogSoftMax())
    opt = optimizer(model, ds, nn.ClassNLLCriterion(),
                    batch_size=args.batchSize)
    opt.set_optim_method(SGD(learning_rate=0.05))
    opt.set_end_when(Trigger.max_epoch(args.maxEpoch))
    try:
        params, state = opt.optimize()
    except Exception:
        if spark_err:
            raise RuntimeError("Spark feed job failed") from spark_err[0]
        raise

    if spawn:
        for p in spawn:
            p.join(timeout=30)
    if sc is not None and spark_thread is not None:
        spark_thread.join(timeout=60)
        if spark_err:
            raise RuntimeError("Spark feed job failed") from spark_err[0]

    # sanity: the model saw real data (loss finite, params moved)
    leaf = np.asarray(params["0"]["weight"])
    assert np.all(np.isfinite(leaf))
    print(f"trained from {args.nProducers} producer processes "
          f"x {args.nBatches} batches")
    return params, state


if __name__ == "__main__":
    main()
