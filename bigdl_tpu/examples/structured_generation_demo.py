"""Structured-generation demo: a JSON tool-call schema through the
router, every stream guaranteed to parse.

N client threads submit prompts through a
:class:`~bigdl_tpu.serving.ModelRouter` front door with a compiled
grammar attached: a JSON schema for a tool call (``{"tool": ...,
"ok": ...}``) lowered to a token-level automaton over the model's
vocabulary (PR 20). Every step of a constrained stream samples under
the automaton's current-state mask inside the jitted step — greedy is
argmax over the LEGAL set — so the untrained toy model still emits
syntactically perfect tool calls. The run ends with the metrics table
(``constrained_streams`` / ``grammar_compile_cache_hits`` /
``masked_vocab_frac``), the observed parse rate, and a few decoded
calls.

Run: ``python -m bigdl_tpu.examples.structured_generation_demo -n 12``
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import jax
import numpy as np

# the demo's toy tokenizer: one printable character per token id (ids
# 2..), id 0 = pad, id 1 = EOS — enough alphabet to spell a tool call
_CHARS = ("abcdefghijklmnopqrstuvwxyz"
          "ABCDEFGHIJKLMNOPQRSTUVWXYZ"
          "0123456789{}\":,.-_[]() ")
EOS_ID = 1

TOOL_SCHEMA = {
    "type": "object",
    "properties": {
        "tool": {"enum": ["search", "calculator", "weather"]},
        "ok": {"type": "boolean"},
    },
    "required": ["tool", "ok"],
}


def build_lm(vocab_size: int = 128):
    from bigdl_tpu.nn.layers.attention import Transformer

    return Transformer(vocab_size=vocab_size, hidden_size=160, num_heads=4,
                       filter_size=320, num_hidden_layers=2)


def make_vocab(n: int = 128):
    vocab = [f"<{i}>" for i in range(n)]
    for j, ch in enumerate(_CHARS):
        vocab[j + 2] = ch
    return vocab


def main(argv=None):
    from bigdl_tpu.grammar import compile_grammar, json_schema_grammar
    from bigdl_tpu.serving import (
        GenerationEngine, ModelRouter, PagedDecodeKernels,
    )

    ap = argparse.ArgumentParser("structured-generation-demo")
    ap.add_argument("-n", "--requests", type=int, default=12,
                    help="total tool-call requests")
    ap.add_argument("-c", "--concurrency", type=int, default=4,
                    help="client threads")
    ap.add_argument("-s", "--slots", type=int, default=4,
                    help="engine slot-table size")
    ap.add_argument("--max-new", type=int, default=64,
                    help="token budget per call (the grammar terminates "
                         "via EOS well inside it)")
    ap.add_argument("--temperature", type=float, default=0.8,
                    help="sampling temperature over the LEGAL set "
                         "(0 = constrained greedy)")
    args = ap.parse_args(argv)

    vocab_size = 128
    model = build_lm(vocab_size)
    params, _ = model.init(jax.random.key(0))
    kernels = PagedDecodeKernels(model)

    # one compile per distinct grammar — every request below shares it
    grammar = compile_grammar(json_schema_grammar(TOOL_SCHEMA),
                              make_vocab(vocab_size), eos_id=EOS_ID)
    print(f"grammar: {grammar.n_states} automaton states over "
          f"{grammar.vocab_size} tokens, start-state mask excludes "
          f"{grammar.masked_frac(grammar.start_state) * 100:.1f}% of "
          f"the vocabulary")

    rs = np.random.RandomState(0)
    requests = [rs.randint(2, vocab_size, (int(rs.randint(2, 10)),)).tolist()
                for _ in range(args.requests)]

    engine = GenerationEngine(
        model, params, max_slots=args.slots, max_len=96,
        max_prompt_len=16, max_queue=max(64, 2 * args.requests),
        kernels=kernels, page_size=16, seed=0, eos_id=EOS_ID)
    engine.warmup()

    router = ModelRouter()
    router.register("lm", engine)

    outs = [None] * args.requests

    def client(cid: int) -> None:
        time.sleep(0.002 * cid)
        streams = {}
        for i in range(cid, args.requests, args.concurrency):
            streams[i] = router.submit(
                "lm", requests[i], max_new_tokens=args.max_new,
                temperature=args.temperature, top_k=8, seed=100 + i,
                grammar=grammar)
        for i, stream in streams.items():
            outs[i] = [tok for tok in stream]

    t0 = time.monotonic()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    snap = engine.metrics.snapshot()
    print(engine.metrics.format_table())
    router.close()

    served = [o for o in outs if o is not None]
    parsed = [o for o in served if grammar.matches(o)]
    parse_rate = len(parsed) / max(len(served), 1)
    tokens = sum(len(o) for o in served)
    print(f"{len(served)} constrained streams, {tokens} tokens in "
          f"{wall * 1e3:.0f} ms — parse rate "
          f"{parse_rate * 100:.0f}%, mean masked-vocab fraction "
          f"{snap['masked_vocab_frac'] * 100:.1f}%")
    for o in served[:3]:
        call = json.loads(grammar.text_of(o))
        print(f"  tool call: {call}")
    snap["parse_rate"] = parse_rate
    return snap


if __name__ == "__main__":
    main()
