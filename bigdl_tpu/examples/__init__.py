"""End-to-end example applications.

Reference: ``DL/example/*`` (~15 Spark apps, ~3,000 LoC of scopt CLI
mains). Each module here is the TPU-native counterpart of one reference
app — an ``argparse`` main over the same framework surface (models, data
pipeline, dlframes, interop, quantization), runnable standalone with a
synthetic-data fallback when the real dataset directory is absent (the
reference's unit strategy: tiny fixtures, no network downloads).

| module                      | reference app                                   |
|-----------------------------|-------------------------------------------------|
| ``text_classification``     | ``example/textclassification/TextClassifier``   |
| ``udf_predictor``           | ``example/udfpredictor/DataframePredictor``     |
| ``tree_lstm_sentiment``     | ``example/treeLSTMSentiment/Train``             |
| ``load_model``              | ``example/loadmodel/ModelValidator``            |
| ``image_classification``    | ``example/imageclassification/ImagePredictor``  |
| ``lenet_local``             | ``example/lenetLocal/{Train,Test,Predict}``     |
| ``ml_pipeline``             | ``example/MLPipeline/DLClassifierLeNet`` etc.   |
| ``int8_inference``          | ``example/mkldnn/int8/{GenerateInt8Scales,ImageNetInference}`` |
| ``tf_transfer_learning``    | ``example/tensorflow/{transferlearning,loadandsave}`` |
| ``dlframes_image``          | ``example/dlframes/{imageInference,imageTransferLearning}`` |
| ``keras_train``             | ``example/keras/Train``                         |
| ``language_model``          | ``example/languagemodel/PTBWordLM``             |
| ``recommendation``          | NCF over movielens (LookupTable + HitRatio/NDCG) |
| ``parallel_training``       | ``ParallelOptimizer``/ZeRO-style sync + pipeline (beyond-reference axes) |
"""
