"""Int8 quantized inference.

Reference: ``DL/example/mkldnn/int8/{GenerateInt8Scales,ImageNetInference}.scala``
— compute per-channel int8 scales for a trained ResNet-50, then validate
the quantized model on ImageNet.

TPU-native: ``nn.quantized.quantize`` rewrites the module tree to true
int8×int8→int32 ``dot_general`` layers with per-channel symmetric scales
(weights are quantized from the params themselves, so there is no
separate scale-generation pass to run offline — this CLI reports the
scale ranges the reference's GenerateInt8Scales step would have written,
then validates fp32 vs int8 accuracy side by side).
"""

from __future__ import annotations

import argparse

import numpy as np
import jax


def main(argv=None):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.examples.load_model import load_images
    from bigdl_tpu.models import resnet, vgg
    from bigdl_tpu.nn.quantized import quantize
    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy
    from bigdl_tpu.optim.predictor import Evaluator
    from bigdl_tpu.utils.serializer import load_module

    ap = argparse.ArgumentParser("int8-inference")
    ap.add_argument("--model", default=None,
                    help="saved .bigdl model (fresh resnet/vgg when absent)")
    ap.add_argument("--arch", choices=["resnet50", "vgg16"],
                    default="resnet50")
    ap.add_argument("-f", "--folder", default=None,
                    help="ImageFolder validation images (synthetic if absent)")
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("--classNum", type=int, default=1000)
    args = ap.parse_args(argv)

    if args.model:
        model, params, state = load_module(args.model)
    else:
        model = (resnet.build_imagenet(50, args.classNum)
                 if args.arch == "resnet50"
                 else vgg.build_vgg16(class_num=args.classNum))
        params, state = model.init(jax.random.key(0))

    qmodel, qparams = quantize(model, params)

    # the GenerateInt8Scales report: per-layer weight scale ranges
    for path, leaf in jax.tree_util.tree_flatten_with_path(qparams)[0]:
        keys = [getattr(k, "key", str(k)) for k in path]
        if keys and keys[-1] == "scale":
            arr = np.asarray(leaf)
            print(f"scales {'/'.join(keys[:-1])}: "
                  f"min={arr.min():.3e} max={arr.max():.3e} n={arr.size}")

    x, y = load_images(args.folder, args.batchSize, n_synth=2 * args.batchSize)
    y = y % args.classNum
    methods = [Top1Accuracy(), Top5Accuracy()]
    ds = DataSet.tensors(x, y)
    fp = Evaluator(model, params, state, batch_size=args.batchSize).test(ds, methods)
    q = Evaluator(qmodel, qparams, state, batch_size=args.batchSize).test(ds, methods)
    for name, a, b in zip(("Top1", "Top5"), fp, q):
        print(f"{name}: fp32 {a} | int8 {b}")
    return fp, q


if __name__ == "__main__":
    main()
