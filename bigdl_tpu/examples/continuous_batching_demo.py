"""Continuous-batching demo: staggered generation clients against a
GenerationEngine, routed by model name, vs the static baseline.

N client threads submit mixed-length prompts with mixed generation
targets through a :class:`~bigdl_tpu.serving.ModelRouter` front door; the
:class:`~bigdl_tpu.serving.GenerationEngine` behind it admits each prompt
into a free KV slot BETWEEN decode steps and retires finished sequences
mid-flight, so short requests never wait for long ones. The run ends with
the token-level metrics table (TTFT, tokens/sec, slot occupancy) and a
head-to-head against run-to-completion static batching over the same
jitted kernels — the scheduling win shows even on one CPU core.

Run: ``python -m bigdl_tpu.examples.continuous_batching_demo -n 24``
"""

from __future__ import annotations

import argparse
import threading
import time

import jax
import numpy as np


def build_lm(vocab_size: int = 128):
    from bigdl_tpu.nn.layers.attention import Transformer

    # large enough that the jitted step dwarfs host bookkeeping — with a
    # toy model the scheduler's Python overhead would drown the win
    return Transformer(vocab_size=vocab_size, hidden_size=160, num_heads=4,
                       filter_size=320, num_hidden_layers=2)


def main(argv=None):
    from bigdl_tpu.serving import (
        GenerationEngine, ModelRouter, Overloaded, PagedDecodeKernels,
        static_generate,
    )

    ap = argparse.ArgumentParser("continuous-batching-demo")
    ap.add_argument("-n", "--requests", type=int, default=24,
                    help="total generation requests")
    ap.add_argument("-c", "--concurrency", type=int, default=6,
                    help="client threads")
    ap.add_argument("-s", "--slots", type=int, default=4,
                    help="engine slot-table size")
    ap.add_argument("--max-len", type=int, default=96,
                    help="KV cache length (prompt + generation)")
    ap.add_argument("--short", type=int, default=4,
                    help="short requests' max_new_tokens")
    ap.add_argument("--long", type=int, default=48,
                    help="long requests' max_new_tokens")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="sampling temperature (0 = greedy; sampling runs "
                         "inside the jitted step, seeded per request)")
    args = ap.parse_args(argv)

    vocab = 128
    model = build_lm(vocab)
    params, _ = model.init(jax.random.key(0))
    # paged kernels (PR 6): block-table KV cache + in-step sampling —
    # KV memory scales with each request's token budget, not max_len
    kernels = PagedDecodeKernels(model)

    rs = np.random.RandomState(0)
    requests = []
    for i in range(args.requests):
        plen = int(rs.randint(2, 13))
        prompt = rs.randint(1, vocab, (plen,)).tolist()
        requests.append((prompt, args.short if i % 2 == 0 else args.long))

    engine = GenerationEngine(
        model, params, max_slots=args.slots, max_len=args.max_len,
        max_prompt_len=16, max_queue=max(64, 2 * args.requests),
        kernels=kernels)
    engine.warmup()  # compile decode + every prompt bucket before traffic

    router = ModelRouter()
    router.register("lm", engine)

    outs = [None] * args.requests
    rejected = [0] * args.concurrency

    def client(cid: int) -> None:
        time.sleep(0.002 * cid)  # clients come up out of phase: the
        # engine demonstrably admits latecomers into a RUNNING loop
        # stride partition: exactly `requests` total across all clients;
        # submit the whole stride first (streams are futures — the engine
        # packs them into slots as they free up), then consume each
        streams = {}
        for i in range(cid, args.requests, args.concurrency):
            prompt, mnt = requests[i]
            try:
                streams[i] = router.submit("lm", prompt, max_new_tokens=mnt,
                                           temperature=args.temperature)
            except Overloaded:
                rejected[cid] += 1
        for i, stream in streams.items():
            outs[i] = [tok for tok in stream]  # tokens arrive per step

    t0 = time.monotonic()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    cont_wall = time.monotonic() - t0
    snap = engine.metrics.snapshot()
    print(engine.metrics.format_table())
    router.close()

    served = [o for o in outs if o is not None]
    cont_tokens = sum(len(o) for o in served)

    t0 = time.monotonic()
    souts, static_steps = static_generate(
        model, params, requests, max_slots=args.slots, max_len=args.max_len,
        kernels=kernels, prompt_buckets=engine.prompt_buckets,
        sampling=[dict(temperature=args.temperature)] * args.requests
        if args.temperature > 0 else None)
    static_wall = time.monotonic() - t0
    static_tokens = sum(len(o) for o in souts)

    cont_tps = cont_tokens / cont_wall
    static_tps = static_tokens / static_wall
    print(f"continuous: {cont_tokens} tokens in {cont_wall * 1e3:.0f} ms "
          f"({cont_tps:.0f} tok/s, {snap['decode_steps']} decode steps, "
          f"occupancy {snap['slot_occupancy'] * 100:.0f}%)")
    print(f"static    : {static_tokens} tokens in {static_wall * 1e3:.0f} ms "
          f"({static_tps:.0f} tok/s, {static_steps} decode steps)")
    print(f"continuous batching = {cont_tps / static_tps:.2f}x static "
          f"run-to-completion")
    snap["continuous_vs_static"] = cont_tps / static_tps
    snap["rejected_clients"] = sum(rejected)
    return snap


if __name__ == "__main__":
    main()
