"""Parallel host input pipeline, end to end.

Reference: ``DL/example/imageclassification`` feeds its trainer through
``MTLabeledBGRImgToBatch`` — a multi-threaded transformer pool batching
augmented images faster than any single thread can. This example drives
the TPU-native equivalent (``bigdl_tpu.dataset.parallel_pipeline``):

1. a synthetic uint8 image dataset runs through a pad-4-crop + flip
   augment chain fanned across ``--workers`` pool workers
   (``Transformer.parallel`` — one call opts any ``>>`` chain in);
2. a small CNN trains on the pooled stream via
   ``Optimizer.set_data_pipeline`` (the chain's elementwise run is
   pooled automatically; batching stays serial);
3. the per-stage ``PipelineStats`` table (items, MB, rates, queue
   occupancy, stall/starve) is printed — the observability layer that
   makes input-side regressions visible next to the step metrics.

Determinism: augmentation is seeded per element from the stream index,
so the emitted batches are bit-identical whatever ``--workers`` is.
"""

from __future__ import annotations

import argparse

import numpy as np


def _normalize(t):
    # module-level (not a lambda): process mode ships the chain to
    # spawned workers by pickle
    return (np.float32(t[0]) - 127.0) / 128.0, t[1]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--processes", action="store_true",
                    help="process pool + shared-memory batch handoff "
                         "(for Python-bound transforms threads can't scale)")
    ap.add_argument("-z", "--batchSize", type=int, default=16)
    ap.add_argument("--maxIteration", type=int, default=8)
    ap.add_argument("-s", "--size", type=int, default=128,
                    help="synthetic dataset size")
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.core.rng import RandomGenerator
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.image import BGRImgToSample, HFlip, RandomCropper
    from bigdl_tpu.dataset.transformer import FunctionTransformer

    rs = np.random.RandomState(0)
    side = 24
    elems = [(rs.randint(0, 255, (3, side + 4, side + 4)).astype(np.uint8),
              rs.randint(0, 4))
             for _ in range(args.size)]

    # the augment chain: pad-crop + flip + to-Sample, then batch. The
    # optimizer pools the elementwise prefix; SampleToMiniBatch stays
    # serial on the consumer side.
    chain = (RandomCropper(side, side, pad=2, rng=RandomGenerator(7))
             >> HFlip(rng=RandomGenerator(9))
             >> FunctionTransformer(_normalize)
             >> BGRImgToSample()
             >> SampleToMiniBatch(args.batchSize))
    ds = DataSet.array(elems, rng=RandomGenerator(5)) >> chain

    feat = (side - 2) // 2  # valid 3x3 conv, then 2x2 pool
    model = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2), nn.Reshape([8 * feat * feat]),
        nn.Linear(8 * feat * feat, 4), nn.LogSoftMax())

    opt = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                               batch_size=args.batchSize)
    opt.set_optim_method(optim.SGD(learning_rate=0.05))
    opt.set_end_when(optim.Trigger.max_iteration(args.maxIteration))
    opt.set_data_pipeline(args.workers, processes=args.processes, chunk=4)
    params, state = opt.optimize()

    print(f"trained {args.maxIteration} iterations, final loss "
          f"{opt.state.loss:.4f}, pipeline ({'processes' if args.processes else 'threads'} x{args.workers}):")
    print(opt.pipeline_stats.format_table())
    return params, opt.pipeline_stats


if __name__ == "__main__":
    main()
