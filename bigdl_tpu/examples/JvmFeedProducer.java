/*
 * Executor-side feed producer for JVM (Scala/Java) Spark tasks.
 *
 * Wire protocol (see bigdl_tpu/dataset/feeder.py; byte layout pinned by
 * tests/test_feeder.py::test_wire_format_conformance):
 *
 *   handshake:  8 bytes  "BDLFEED1"
 *   per batch:  uint32 BE n_arrays, then per array: uint64 BE length +
 *               that many bytes of a .npy (v1.0) serialization
 *   end:        uint32 BE 0
 *
 * The .npy payloads here are C-order little-endian float32 / int32 with
 * the standard 10/6-byte magic+header; numpy on the host reads them with
 * np.load. Call fromPartition() inside rdd.mapPartitions.
 */
import java.io.DataOutputStream;
import java.net.Socket;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;

public final class JvmFeedProducer implements AutoCloseable {
    private final Socket sock;
    private final DataOutputStream out;

    public JvmFeedProducer(String host, int port) throws Exception {
        sock = new Socket(host, port);
        out = new DataOutputStream(sock.getOutputStream());
        out.write("BDLFEED1".getBytes(StandardCharsets.US_ASCII));
    }

    /** One batch = one float32 feature array + one int32 label array. */
    public void push(float[] features, int[] featShape,
                     int[] labels) throws Exception {
        out.writeInt(2);                       // n_arrays, uint32 BE
        byte[] f = npy(featShape, features, null);
        out.writeLong(f.length);               // uint64 BE
        out.write(f);
        byte[] l = npy(new int[]{labels.length}, null, labels);
        out.writeLong(l.length);
        out.write(l);
    }

    @Override public void close() throws Exception {
        out.writeInt(0);                       // end-of-stream frame
        out.flush();
        sock.close();
    }

    /** Minimal .npy v1.0 writer (C-order, little-endian). */
    private static byte[] npy(int[] shape, float[] f, int[] i) {
        StringBuilder dims = new StringBuilder();
        for (int d : shape) dims.append(d).append(",");
        String hdr = "{'descr': '" + (f != null ? "<f4" : "<i4")
                + "', 'fortran_order': False, 'shape': (" + dims + "), }";
        int pad = 64 - ((10 + hdr.length() + 1) % 64);
        hdr = hdr + " ".repeat(pad) + "\n";
        int n = f != null ? f.length : i.length;
        ByteBuffer buf = ByteBuffer.allocate(10 + hdr.length() + 4 * n);
        buf.put((byte) 0x93).put("NUMPY".getBytes(StandardCharsets.US_ASCII));
        buf.put((byte) 1).put((byte) 0);
        buf.order(ByteOrder.LITTLE_ENDIAN).putShort((short) hdr.length());
        buf.put(hdr.getBytes(StandardCharsets.US_ASCII));
        if (f != null) for (float v : f) buf.putFloat(v);
        else for (int v : i) buf.putInt(v);
        return buf.array();
    }
}
