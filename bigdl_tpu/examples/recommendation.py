"""Neural collaborative filtering on MovieLens.

Reference: the recommendation capability axis — ``LookupTable``
embeddings + the ranking metrics (``HitRatio``/``NDCG``,
``DL/optim/ValidationMethod.scala``) over the ``PY/dataset/movielens``
corpus (the reference ships these pieces; this example wires them into
the standard NCF recipe: user/item embeddings -> MLP -> score, trained
on implicit feedback with sampled negatives, evaluated leave-one-out
with HR@10/NDCG@10).
"""

from __future__ import annotations

import argparse

import numpy as np
import jax

import bigdl_tpu.nn as nn


def build(n_users: int, n_items: int, embed_dim: int = 16) -> nn.Graph:
    """(user_ids, item_ids) -> match score."""
    users = nn.Input()
    items = nn.Input()
    u = nn.LookupTable(n_users + 1, embed_dim)(users)
    i = nn.LookupTable(n_items + 1, embed_dim)(items)
    x = nn.JoinTable(1)(nn.Squeeze(1)(u), nn.Squeeze(1)(i))
    x = nn.Linear(2 * embed_dim, 32)(x)
    x = nn.ReLU()(x)
    x = nn.Linear(32, 16)(x)
    x = nn.ReLU()(x)
    out = nn.Sigmoid()(nn.Linear(16, 1)(x))
    return nn.Graph([users, items], out)


def implicit_split(rows: np.ndarray):
    """Leave-one-out per user: last rated item held out for eval."""
    by_user = {}
    for u, i, _ in rows:
        by_user.setdefault(int(u), []).append(int(i))
    train_pairs, test_pairs = [], []
    for u, items in by_user.items():
        if len(items) < 2:
            train_pairs.extend((u, i) for i in items)
            continue
        train_pairs.extend((u, i) for i in items[:-1])
        test_pairs.append((u, items[-1]))
    return train_pairs, test_pairs, by_user


def main(argv=None):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.datasets import load_movielens
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch
    from bigdl_tpu.models.cli import fit
    from bigdl_tpu.optim import Adam, HitRatio, NDCG, optimizer
    from bigdl_tpu.optim.predictor import Predictor

    ap = argparse.ArgumentParser("ncf-recommendation")
    ap.add_argument("-f", "--folder", default=None,
                    help="ml-1m dir with ratings.dat (synthetic if absent)")
    ap.add_argument("-b", "--batchSize", type=int, default=256)
    ap.add_argument("--embedDim", type=int, default=16)
    ap.add_argument("--negNum", type=int, default=4,
                    help="sampled negatives per positive (train)")
    ap.add_argument("--evalNeg", type=int, default=50,
                    help="sampled negatives per positive (eval ranking)")
    ap.add_argument("--learningRate", type=float, default=1e-3)
    ap.add_argument("-e", "--maxEpoch", type=int, default=2)
    ap.add_argument("--maxIteration", type=int, default=0)
    ap.add_argument("--checkpoint", default=None)
    args = ap.parse_args(argv)

    rows = load_movielens(args.folder)
    n_users = int(rows[:, 0].max())
    n_items = int(rows[:, 1].max())
    train_pairs, test_pairs, by_user = implicit_split(rows)

    rng = np.random.RandomState(0)
    samples = []
    for u, i in train_pairs:
        samples.append(Sample((np.asarray([u], np.int32),
                               np.asarray([i], np.int32)),
                              np.asarray([1.0], np.float32)))
        seen = set(by_user[u])
        for _ in range(args.negNum):
            j = int(rng.randint(1, n_items + 1))
            while j in seen:
                j = int(rng.randint(1, n_items + 1))
            samples.append(Sample((np.asarray([u], np.int32),
                                   np.asarray([j], np.int32)),
                                  np.asarray([0.0], np.float32)))
    rng.shuffle(samples)

    model = build(n_users, n_items, args.embedDim)
    ds = DataSet.array(samples) >> SampleToMiniBatch(args.batchSize)
    opt = optimizer(model, ds, nn.BCECriterion(), batch_size=args.batchSize)
    opt.set_optim_method(Adam(learning_rate=args.learningRate))
    params, state = fit(opt, args)

    # leave-one-out ranking eval: positive at column 0 + sampled negatives
    predictor = Predictor(model, params, state, batch_size=args.batchSize)
    users_e, items_e = [], []
    for u, pos in test_pairs:
        cands = [pos]
        seen = set(by_user[u])
        while len(cands) < args.evalNeg + 1:
            j = int(rng.randint(1, n_items + 1))
            if j not in seen:
                cands.append(j)
        users_e.append(np.full(len(cands), u, np.int32))
        items_e.append(np.asarray(cands, np.int32))
    uu = np.concatenate(users_e)[:, None]
    ii = np.concatenate(items_e)[:, None]
    scores = predictor.predict((uu, ii), flatten=False)
    scores = np.concatenate([np.asarray(s).reshape(-1) for s in scores])
    scores = scores.reshape(len(test_pairs), args.evalNeg + 1)

    hr = HitRatio(10, args.evalNeg)
    ndcg = NDCG(10, args.evalNeg)
    import jax.numpy as jnp

    hits, n = hr.batch(jnp.asarray(scores), None)
    gain, _ = ndcg.batch(jnp.asarray(scores), None)
    print(f"HR@10: {float(hits)/float(n):.4f}  "
          f"NDCG@10: {float(gain)/float(n):.4f}  ({n} users)")
    return float(hits) / float(n)


if __name__ == "__main__":
    main()
