"""Fault-tolerant training demo: async checkpoints, preemption, resume.

The ``bigdl_tpu.ckpt`` workflow end to end on synthetic data:

1. ``--preempt-at K`` simulates a TPU eviction by SIGTERM-ing the process
   from the input pipeline at batch K. The armed preemption hook
   (``set_checkpoint(handle_preemption=True)``) turns that into a final
   synchronous save marked ``preempted`` in ``MANIFEST.json``, and
   ``optimize()`` returns cleanly instead of dying mid-step.
2. Rerunning the SAME command resumes: ``auto_resume=True`` restores the
   newest committed checkpoint before the first step and trains on to
   ``--iters``. ``--corrupt`` truncates the newest blob first to show the
   verified restore falling back to the previous good checkpoint.

Reference: the driver retry window (``DistriOptimizer.scala:881-960``)
recovers the same way, but from blocking unverified saves; here the saves
are async (the step loop pays only a device->host snapshot) and each
restore is checksum-verified.

Run it twice to see both phases::

    python -m bigdl_tpu.examples.fault_tolerant_training --preempt-at 6
    python -m bigdl_tpu.examples.fault_tolerant_training
"""

from __future__ import annotations

import argparse
import os
import signal

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.ckpt import load_manifest
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import TensorDataSet


def _data(n=512, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 8).astype(np.float32)
    w = rs.randn(1, 8).astype(np.float32)
    y = (x @ w.T > 0).astype(np.int32)[:, 0]
    return x, y


class _EvictingDataSet(TensorDataSet):
    """Sends this process a real SIGTERM before batch N — the same signal
    a TPU preemption notice delivers."""

    def __init__(self, x, y, at):
        super().__init__(x, y)
        self.at = at
        self.count = 0

    def batches(self, batch_size, train, partial_batch=False):
        for b in super().batches(batch_size, train, partial_batch):
            self.count += 1
            if self.at and self.count == self.at:
                print(f"[demo] simulating preemption: SIGTERM at batch {self.count}")
                os.kill(os.getpid(), signal.SIGTERM)
            yield b


def main(argv=None):
    ap = argparse.ArgumentParser("fault-tolerant-training")
    ap.add_argument("--workdir", default="/tmp/bigdl_tpu_ft_demo")
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--preempt-at", type=int, default=0,
                    help="SIGTERM self before batch N (0 = train to --iters)")
    ap.add_argument("--corrupt", action="store_true",
                    help="truncate the newest blob before resuming, to "
                         "demonstrate checksum-verified fallback")
    args = ap.parse_args(argv)

    if args.corrupt:
        entries = load_manifest(args.workdir)
        if entries:
            blob = os.path.join(args.workdir, entries[-1].file)
            with open(blob, "r+b") as fh:
                fh.truncate(16)
            print(f"[demo] truncated {entries[-1].tag} — restore must fall back")

    x, y = _data()
    if args.preempt_at:
        ds = _EvictingDataSet(x, y, args.preempt_at)
    else:
        ds = DataSet.tensors(x, y) >> SampleToMiniBatch(args.batchSize)

    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2),
                          nn.LogSoftMax())
    opt = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                               batch_size=args.batchSize)
    # keep the input pipeline on the training thread so the simulated
    # eviction lands near the batch that triggers it (the feeder thread
    # otherwise races several batches ahead on tiny data)
    opt.host_prefetch_depth = 0
    opt.set_optim_method(optim.SGD(learning_rate=0.5, momentum=0.9))
    opt.set_end_when(optim.Trigger.max_iteration(args.iters))
    opt.set_checkpoint(
        args.workdir, optim.Trigger.several_iteration(args.save_every),
        keep_last_n=3, keep_every_k_steps=10,
        handle_preemption=True, auto_resume=True)

    params, _ = opt.optimize()
    opt.checkpoint_manager.close()

    entries = load_manifest(args.workdir)
    tail = [(e.tag, e.step, "preempted" if e.preempted else "committed")
            for e in entries[-3:]]
    print(f"[demo] stopped at iteration {opt.state.iteration}, "
          f"loss {opt.state.loss:.4f}; manifest tail: {tail}")
    return opt


if __name__ == "__main__":
    main()
