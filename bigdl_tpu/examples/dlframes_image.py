"""dlframes image apps: inference and transfer learning over DataFrames.

Reference: ``DL/example/dlframes/imageInference/ImageInference.scala``
(DLImageReader -> DLImageTransformer -> DLModel.transform appends
predictions) and ``imageTransferLearning/ImageTransferLearning.scala``
(pretrained conv features -> DLClassifier fit on a small labeled frame).

TPU-native: same two apps over pandas frames via ``bigdl_tpu.dlframes``.
"""

from __future__ import annotations

import argparse

import numpy as np
import jax

import bigdl_tpu.nn as nn
from bigdl_tpu.dlframes import (
    DLClassifier, DLImageReader, DLImageTransformer, DLModel,
)


def _transform_chain(size: int = 224):
    from bigdl_tpu.vision import (
        AspectScale, CenterCrop, ChannelNormalize, MatToTensor,
    )

    return (AspectScale(256) >> CenterCrop(size, size)
            >> ChannelNormalize((123.0, 117.0, 104.0)) >> MatToTensor())


def _frame(args):
    import pandas as pd

    if args.folder:
        return DLImageReader.read_images(args.folder)
    rng = np.random.RandomState(0)
    return pd.DataFrame({
        "uri": [f"synthetic_{i}" for i in range(args.nSamples)],
        "image": [rng.rand(256, 256, 3).astype(np.float32) * 255
                  for _ in range(args.nSamples)],
    })


def inference(args):
    """ImageInference: model.transform appends a prediction column."""
    from bigdl_tpu.models import resnet

    model = resnet.build_imagenet(18, args.classNum)
    params, state = model.init(jax.random.key(0))
    df = DLImageTransformer(_transform_chain()).transform(_frame(args))
    dl = DLModel(model, params, state, features_col="transformed",
                 batch_size=args.batchSize, feature_size=(3, 224, 224))
    out = dl.transform(df)
    print(out[["uri"]].assign(
        top1=[int(np.argmax(p)) for p in out["prediction"]]).to_string(index=False))
    return out


def transfer_learning(args):
    """ImageTransferLearning: frozen conv features + trained classifier."""
    from bigdl_tpu.optim.predictor import Predictor

    # feature extractor = small conv stack (stands in for a pretrained
    # model's convolutional body, which --modelPath would load)
    extractor = nn.Sequential(
        nn.SpatialConvolution(3, 8, 7, 7, 4, 4, 3, 3), nn.ReLU(),
        nn.SpatialMaxPooling(4, 4, 4, 4), nn.GlobalAveragePooling2D(),
    )
    eparams, estate = extractor.init(jax.random.key(0))

    df = DLImageTransformer(_transform_chain()).transform(_frame(args))
    x = np.stack(df["transformed"].to_list())
    rng = np.random.RandomState(1)
    labels = rng.randint(0, 2, (len(x),))
    x += labels[:, None, None, None] * 0.8  # make classes separable

    feats = Predictor(extractor, eparams, estate,
                      batch_size=args.batchSize).predict(x)
    feats = np.stack([np.asarray(f, np.float32) for f in feats])
    import pandas as pd

    train = pd.DataFrame({"features": list(feats), "label": labels})
    clf = DLClassifier(
        nn.Sequential(nn.Linear(feats.shape[-1], 2), nn.LogSoftMax()),
        nn.ClassNLLCriterion(), feature_size=[feats.shape[-1]]).set_batch_size(args.batchSize).set_max_epoch(args.maxEpoch).set_learning_rate(0.5)
    model = clf.fit(train)
    out = model.transform(train)
    acc = float((out["prediction"].to_numpy() == labels).mean())
    print(f"transfer-learning accuracy: {acc:.3f}")
    return acc


def main(argv=None):
    ap = argparse.ArgumentParser("dlframes-image")
    ap.add_argument("--app", choices=["inference", "transfer"],
                    default="inference")
    ap.add_argument("-f", "--folder", default=None,
                    help="image dir (synthetic if absent)")
    ap.add_argument("-b", "--batchSize", type=int, default=8)
    ap.add_argument("-e", "--maxEpoch", type=int, default=5)
    ap.add_argument("--classNum", type=int, default=1000)
    ap.add_argument("--nSamples", type=int, default=8)
    args = ap.parse_args(argv)
    return inference(args) if args.app == "inference" else transfer_learning(args)


if __name__ == "__main__":
    main()
