"""Tree-LSTM sentiment classification.

Reference: ``DL/example/treeLSTMSentiment/{Train,TreeSentiment,Utils}.scala``
— Stanford Sentiment Treebank constituency trees + GloVe embeddings ->
``BinaryTreeLSTM`` -> per-node sentiment softmax, validated with
``TreeNNAccuracy`` (root-node accuracy).

TPU-native: trees are encoded as static-shape int32 ``[left, right,
leaf_index]`` node arrays in topological order (see
``nn/layers/tree_lstm.py``); an SST-format s-expression parser produces
them, and a synthetic corpus stands in when no dataset directory is
given. The whole batch is one ``lax.scan``-over-nodes program.
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional, Tuple

import numpy as np

import bigdl_tpu.nn as nn


def parse_sst(line: str) -> Tuple[List[str], List[Tuple[int, int, int]], int]:
    """Parse one SST s-expression ``(3 (2 word) (2 word))`` into
    (tokens, nodes, root_label). Nodes are ``[left, right, leaf_index]``
    rows in topological (children-first) order, ids 1-based, 0 = none."""
    pos = 0

    def parse() -> Tuple[int, int]:  # returns (node_id, label)
        nonlocal pos
        assert line[pos] == "(", f"expected '(' at {pos}"
        pos += 1
        label_end = line.index(" ", pos)
        label = int(line[pos:label_end])
        pos = label_end + 1
        if line[pos] == "(":  # internal: two children
            left, _ = parse()
            assert line[pos] == " ", f"expected ' ' at {pos}"
            pos += 1
            right, _ = parse()
            assert line[pos] == ")", f"expected ')' at {pos}"
            pos += 1
            nodes.append((left, right, 0))
            return len(nodes), label
        end = line.index(")", pos)  # leaf: a token
        tokens.append(line[pos:end])
        pos = end + 1
        nodes.append((0, 0, len(tokens)))
        return len(nodes), label

    tokens: List[str] = []
    nodes: List[Tuple[int, int, int]] = []
    _, root_label = parse()
    return tokens, nodes, root_label


def synthetic_corpus(n: int = 128, n_classes: int = 3,
                     seed: int = 0) -> List[str]:
    """Class-separable synthetic SST lines: sentiment decided by which
    marker words appear."""
    rng = np.random.RandomState(seed)
    lines = []
    for _ in range(n):
        label = int(rng.randint(n_classes))
        words = [f"c{label}w{rng.randint(4)}" for _ in range(4)]
        lines.append(
            f"({label} ({label} ({label} {words[0]}) ({label} {words[1]}))"
            f" ({label} ({label} {words[2]}) ({label} {words[3]})))")
    return lines


def load_trees(folder: Optional[str], split: str) -> List[str]:
    if folder:
        path = os.path.join(folder, f"{split}.txt")
        if os.path.exists(path):
            with open(path) as f:
                return [ln.strip() for ln in f if ln.strip()]
    return synthetic_corpus(seed=0 if split == "train" else 1)


def build(vocab_size: int, embed_dim: int, hidden: int,
          class_num: int) -> nn.Graph:
    """tokens+tree -> embeddings -> BinaryTreeLSTM -> root hidden ->
    class log-probs (reference ``TreeSentiment.scala``)."""
    tokens = nn.Input()
    tree = nn.Input()
    emb = nn.LookupTable(vocab_size + 1, embed_dim)(tokens)
    hiddens = nn.BinaryTreeLSTM(embed_dim, hidden)(emb, tree)
    root = nn.Select(1, -1)(hiddens)  # topological order: root is last
    out = nn.LogSoftMax()(nn.Linear(hidden, class_num)(root))
    return nn.Graph([tokens, tree], out)


def encode(lines: List[str], word2index, n_tokens: int,
           n_nodes: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    toks = np.zeros((len(lines), n_tokens), np.int32)
    trees = np.zeros((len(lines), n_nodes, 3), np.int32)
    labels = np.zeros((len(lines),), np.int32)
    for i, line in enumerate(lines):
        tk, nd, root = parse_sst(line)
        tk, nd = tk[:n_tokens], nd[:n_nodes]
        toks[i, :len(tk)] = [word2index.get(w, 0) for w in tk]
        trees[i, :len(nd)] = nd
        # shift the root to the LAST row so Select(1, -1) reads it
        if len(nd) < n_nodes:
            trees[i, -1] = trees[i, len(nd) - 1]
            trees[i, len(nd) - 1] = 0
        labels[i] = root
    return toks, trees, labels


def main(argv=None):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.cli import fit
    from bigdl_tpu.optim import Adagrad, Top1Accuracy, Trigger, optimizer

    ap = argparse.ArgumentParser("tree-lstm-sentiment")
    ap.add_argument("-f", "--folder", default=None,
                    help="dir with train.txt/dev.txt SST trees (synthetic if absent)")
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("--hiddenSize", type=int, default=64)
    ap.add_argument("--embedDim", type=int, default=32)
    ap.add_argument("--learningRate", type=float, default=0.05)
    ap.add_argument("-e", "--maxEpoch", type=int, default=2)
    ap.add_argument("--maxIteration", type=int, default=0)
    ap.add_argument("--checkpoint", default=None)
    args = ap.parse_args(argv)

    train_lines = load_trees(args.folder, "train")
    dev_lines = load_trees(args.folder, "dev")
    vocab = {}
    max_tok = max_node = 0
    for line in train_lines + dev_lines:
        tk, nd, _ = parse_sst(line)
        for w in tk:
            vocab.setdefault(w, len(vocab) + 1)  # 0 is the pad id
        max_tok, max_node = max(max_tok, len(tk)), max(max_node, len(nd))

    xt, xr, y = encode(train_lines, vocab, max_tok, max_node)
    vt, vr, vy = encode(dev_lines, vocab, max_tok, max_node)
    class_num = int(max(y.max(), vy.max())) + 1

    model = build(len(vocab), args.embedDim, args.hiddenSize, class_num)
    train = (DataSet.array([Sample((a, b), c) for a, b, c in zip(xt, xr, y)])
             >> SampleToMiniBatch(args.batchSize))
    val = DataSet.array([Sample((a, b), c) for a, b, c in zip(vt, vr, vy)])

    opt = optimizer(model, train, nn.ClassNLLCriterion(),
                    batch_size=args.batchSize)
    opt.set_optim_method(Adagrad(learning_rate=args.learningRate))
    opt.set_validation(Trigger.every_epoch(), val, [Top1Accuracy()],
                       args.batchSize)
    return fit(opt, args)


if __name__ == "__main__":
    main()
