"""Keras-API training example.

Reference: ``DL/example/keras/Train.scala`` (compile/fit a Keras-style
Sequential on MNIST with the BigDL Keras tier).

TPU-native: the ``bigdl_tpu.keras`` tier — shape-inferring layers,
``compile``/``fit``/``evaluate``/``predict``.
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    from bigdl_tpu import keras
    from bigdl_tpu.dataset.datasets import (
        MNIST_TRAIN_MEAN, MNIST_TRAIN_STD, load_mnist,
    )

    ap = argparse.ArgumentParser("keras-train")
    ap.add_argument("-f", "--folder", default=None,
                    help="mnist dir (synthetic if absent)")
    ap.add_argument("-b", "--batchSize", type=int, default=128)
    ap.add_argument("-e", "--maxEpoch", type=int, default=2)
    ap.add_argument("--nSamples", type=int, default=0,
                    help="cap training samples (0 = all)")
    args = ap.parse_args(argv)

    x, y = load_mnist(args.folder, train=True)
    x = ((x - MNIST_TRAIN_MEAN) / MNIST_TRAIN_STD)[:, None].astype(np.float32)
    if args.nSamples:
        x, y = x[:args.nSamples], y[:args.nSamples]
    vx, vy = load_mnist(args.folder, train=False)
    vx = ((vx - MNIST_TRAIN_MEAN) / MNIST_TRAIN_STD)[:, None].astype(np.float32)

    model = keras.Sequential()
    model.add(keras.Convolution2D(32, 3, 3, activation="relu",
                                  input_shape=(1, 28, 28)))
    model.add(keras.MaxPooling2D())
    model.add(keras.Convolution2D(64, 3, 3, activation="relu"))
    model.add(keras.MaxPooling2D())
    model.add(keras.Flatten())
    model.add(keras.Dense(128, activation="relu"))
    model.add(keras.Dropout(0.25))
    model.add(keras.Dense(10, activation="softmax"))

    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=args.batchSize, nb_epoch=args.maxEpoch)
    scores = model.evaluate(vx, vy, batch_size=args.batchSize)
    print(f"evaluate: {scores}")
    return scores


if __name__ == "__main__":
    main()
