"""Serving demo: multi-threaded clients against a dynamic-batching service.

The reference's closest analogue is the ``udfpredictor`` example (one
request per forward through a pooled model). Here N client threads fire
single-sample requests at an :class:`bigdl_tpu.serving.InferenceService`;
the service aggregates them into bucket-padded micro-batches behind one
jitted forward and the run ends with the SLO metrics table —
demonstrating that concurrent traffic costs far fewer forwards than
requests.

Run: ``python -m bigdl_tpu.examples.serving_demo -c 16 -n 128``
"""

from __future__ import annotations

import argparse
import threading
import time

import jax
import numpy as np


def build_model(n_features: int, n_classes: int):
    from bigdl_tpu.nn import Linear, LogSoftMax, ReLU, Sequential

    return (Sequential()
            .add(Linear(n_features, 64)).add(ReLU())
            .add(Linear(64, n_classes)).add(LogSoftMax()))


def main(argv=None):
    from bigdl_tpu.serving import (
        DeadlineExceeded, InferenceService, Overloaded,
    )

    ap = argparse.ArgumentParser("serving-demo")
    ap.add_argument("-c", "--concurrency", type=int, default=16,
                    help="client threads")
    ap.add_argument("-n", "--requests", type=int, default=128,
                    help="total requests across all clients")
    ap.add_argument("-b", "--max-batch-size", type=int, default=8)
    ap.add_argument("-w", "--max-wait-ms", type=float, default=5.0)
    ap.add_argument("-q", "--max-queue", type=int, default=256)
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request deadline (0 = none)")
    args = ap.parse_args(argv)

    n_features, n_classes = 32, 10
    model = build_model(n_features, n_classes)
    params, state = model.init(jax.random.key(0))
    rs = np.random.RandomState(0)
    data = rs.rand(args.requests, n_features).astype("float32")

    svc = InferenceService(
        model, params, state,
        max_batch_size=args.max_batch_size, max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue)
    svc.warmup(data[0])  # pre-compile every bucket before traffic

    deadline = args.deadline_ms / 1e3 or None
    rejected = [0] * args.concurrency

    def client(cid: int) -> None:
        # stride partition: exactly `requests` total and every client busy,
        # whatever the requests/concurrency ratio
        for i in range(cid, args.requests, args.concurrency):
            try:
                svc.predict(data[i], timeout=30, deadline=deadline)
            except (Overloaded, DeadlineExceeded):
                # both are expected under load; the metrics table reports
                # them — a client thread must survive to finish its stride
                rejected[cid] += 1

    t0 = time.monotonic()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    svc.close()

    snap = svc.metrics.snapshot()
    print(svc.metrics.format_table())
    print(f"{snap['served']} requests in {snap['forwards']} forwards "
          f"({snap['served'] / wall:.1f} req/s at concurrency "
          f"{args.concurrency})")
    return snap


if __name__ == "__main__":
    main()
