"""Telemetry smoke: a traced engine behind the /metrics + /healthz
endpoint, scraped over real HTTP under traffic.

The end-to-end drive of the obs tier (and the CI "Telemetry smoke"
step): boot a small paged :class:`GenerationEngine` with a
:class:`Tracer`, wire its metrics + page pool + timeline + the fault
injector + the flight recorder into one :class:`MetricsRegistry`,
serve it through a :class:`MetricsEndpoint`, then

- scrape ``/metrics`` twice with traffic in between and assert the
  served/tokens counters are MONOTONIC between scrapes,
- assert ``/healthz`` reports healthy while the engine serves,
- dump the request traces as JSONL and assert every request produced a
  finished, non-empty trace,
- close everything and assert no ``bigdl-obs`` thread survives.

Exits nonzero on any violation; prints one JSON summary line.

Run: ``python -m bigdl_tpu.examples.telemetry_demo``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import urllib.request


def main(argv=None):
    import jax
    import numpy as np

    from bigdl_tpu import faults
    from bigdl_tpu.nn.layers.attention import Transformer
    from bigdl_tpu.obs import (
        MetricsEndpoint,
        MetricsRegistry,
        Tracer,
        engine_health,
        flight_recorder,
    )
    from bigdl_tpu.serving import GenerationEngine, ServingMetrics

    ap = argparse.ArgumentParser("telemetry-demo")
    ap.add_argument("-n", "--requests", type=int, default=12)
    ap.add_argument("--trace-out", type=str, default=None,
                    help="trace JSONL path (default: a temp file)")
    args = ap.parse_args(argv)

    violations = []
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=4,
                        filter_size=64, num_hidden_layers=2)
    params, _ = model.init(jax.random.key(0))
    tracer = Tracer()
    engine = GenerationEngine(model, params, max_slots=4, max_len=48,
                              max_prompt_len=12, page_size=8,
                              prefill_chunk=4, tracer=tracer,
                              metrics=ServingMetrics())
    engine.warmup()

    registry = (MetricsRegistry()
                .register("serving", engine.metrics)
                .register("pages", engine._pool)
                .register("timeline", engine.timeline)
                .register("traces", tracer)
                .register("faults", faults.default())
                .register("flight_recorder", flight_recorder()))
    endpoint = MetricsEndpoint(
        registry, health={"engine": engine_health(engine)})

    def scrape(path="/metrics"):
        resp = urllib.request.urlopen(endpoint.url(path), timeout=10)
        return resp.status, resp.read().decode()

    def sample(body, name):
        for line in body.splitlines():
            if line.startswith(f"bigdl_{name} "):
                return float(line.split()[1])
        return None

    rs = np.random.RandomState(0)

    def wave(n):
        streams = [engine.submit(
            rs.randint(1, 60, (int(rs.randint(2, 12)),)).tolist(),
            max_new_tokens=int(rs.randint(2, 8))) for _ in range(n)]
        for s in streams:
            s.result(timeout=120)

    wave(args.requests // 2)
    status1, body1 = scrape()
    wave(args.requests - args.requests // 2)
    status2, body2 = scrape()

    if status1 != 200 or status2 != 200:
        violations.append(f"/metrics status {status1}/{status2}")
    for counter in ("serving_served", "serving_tokens_out",
                    "serving_engine_steps", "traces_finished"):
        v1, v2 = sample(body1, counter), sample(body2, counter)
        if v1 is None or v2 is None:
            violations.append(f"counter {counter} missing from scrape")
        elif not 0 < v1 <= v2:
            violations.append(
                f"counter {counter} not monotonic under traffic: "
                f"{v1} -> {v2}")

    hz_status, hz_body = scrape("/healthz")
    hz = json.loads(hz_body)
    if hz_status != 200 or not hz["ok"]:
        violations.append(f"/healthz unhealthy while serving: {hz}")

    if args.trace_out:
        trace_path = args.trace_out
    else:
        fd, trace_path = tempfile.mkstemp(prefix="bigdl_traces_",
                                          suffix=".jsonl")
        os.close(fd)
    n_traces = tracer.dump_jsonl(trace_path)
    if n_traces < args.requests:
        violations.append(
            f"trace JSONL has {n_traces} traces for {args.requests} "
            f"requests")
    with open(trace_path) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec["outcome"] != "done" or not rec["spans"]:
                violations.append(f"bad trace: {rec['id']}")
                break

    engine.close()
    endpoint.close()
    if any(t.name == "bigdl-obs-endpoint" and t.is_alive()
           for t in threading.enumerate()):
        violations.append("endpoint thread leaked after close()")
    if engine.pages_in_use:
        violations.append("engine leaked KV pages")

    print(json.dumps({
        "metric": "telemetry_smoke_pass",
        "value": 0.0 if violations else 1.0,
        "requests": args.requests,
        "traces": n_traces,
        "trace_jsonl": trace_path,
        "served": engine.metrics.snapshot()["served"],
        "engine_steps": engine.metrics.snapshot()["engine_steps"],
        "violations": violations,
    }))
    if violations:
        raise SystemExit("telemetry smoke FAILED:\n  - "
                         + "\n  - ".join(violations))


if __name__ == "__main__":
    sys.exit(main())
