"""PTB word language model.

Reference: ``DL/example/languagemodel/{PTBModel,PTBWordLM}.scala`` —
LSTM LM over PTB with the Dictionary/tokenizer pipeline.

TPU-native: delegates the model + train loop to
``bigdl_tpu.models.rnn`` (the reference's ``models/rnn`` and
``example/languagemodel`` share the same recipe); this wrapper adds the
corpus plumbing: raw text file -> SentenceTokenizer -> Dictionary ->
next-word windows, matching the example's data path.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

import numpy as np


def corpus_to_ids(path: Optional[str], vocab_size: int) -> np.ndarray:
    """Raw text -> flat int32 id stream via the text pipeline (reference
    ``SentenceTokenizer``/``Dictionary``); synthetic ids when absent."""
    from bigdl_tpu.dataset.text import Dictionary, tokenize

    if path and os.path.exists(path):
        with open(path, errors="ignore") as f:
            sentences = [tokenize(line) for line in f if line.strip()]
        d = Dictionary(sentences, vocab_size=vocab_size)
        return np.concatenate([d.indices(s) for s in sentences])
    rng = np.random.RandomState(0)
    return rng.randint(0, vocab_size, (20000,)).astype(np.int32)


def main(argv=None):
    from bigdl_tpu.models import rnn

    ap = argparse.ArgumentParser("ptb-word-lm")
    ap.add_argument("-f", "--dataFile", default=None,
                    help="raw text corpus (synthetic if absent)")
    ap.add_argument("--vocabSize", type=int, default=10000)
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("--seqLength", type=int, default=35)
    ap.add_argument("--hiddenSize", type=int, default=256)
    ap.add_argument("-e", "--maxEpoch", type=int, default=2)
    ap.add_argument("--maxIteration", type=int, default=0)
    args = ap.parse_args(argv)

    forwarded = [
        "-b", str(args.batchSize), "-e", str(args.maxEpoch),
        "--seqLength", str(args.seqLength),
        "--hiddenSize", str(args.hiddenSize),
        "--vocabSize", str(args.vocabSize),
    ]
    if args.maxIteration:
        forwarded += ["--maxIteration", str(args.maxIteration)]
    if args.dataFile:
        # hand the tokenized stream to the model main via a temp npy file
        ids = corpus_to_ids(args.dataFile, args.vocabSize)
        tmp = "/tmp/bigdl_tpu_ptb_ids.npy"
        np.save(tmp, ids)
        forwarded += ["--idsFile", tmp]
    return rnn.main(forwarded)


if __name__ == "__main__":
    main()
