"""Parallel-training walkthrough: overlapped gradient sync and the
stateful heterogeneous pipeline, on whatever devices are available.

The reference's distributed story is one strategy (synchronous data
parallelism over the BlockManager PS with layer-wise async sync,
``DL/optim/DistriOptimizer.scala`` + ``ParallelOptimizer.scala``); here
each strategy is a mesh axis. This example runs, on a dp mesh:

  1. ``DistriOptimizer(overlap_buckets=K)`` — the reference's layer-wise
     overlapped sync as bucketed in-backward collectives, with optional
     bf16 wire compression (its fp16 blocks);
  2. the ZeRO-1 overlap step (gradient reduce-scatter in the backward,
     1/n chunked optimizer state, weight all-gather — the reference's
     PS partitioning as XLA collectives);

and, on a pp mesh, a BatchNorm-containing heterogeneous pipeline
(``HeteroPipeline``) training with microbatch state threading.

Usage: python -m bigdl_tpu.examples.parallel_training [--steps N]
(On CPU set XLA_FLAGS=--xla_force_host_platform_device_count=8 to get a
multi-device mesh, as tests/conftest.py does.)
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=None,
                    help="default: 4 rows per device")
    args = ap.parse_args(argv)
    if args.steps < 1:
        ap.error("--steps must be >= 1")

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.core.rng import RandomGenerator
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.parallel import (HeteroPipeline, make_pp_train_step,
                                    make_zero1_overlap_step,
                                    zero1_init_state, zero1_state_sharding)

    n_dev = len(jax.devices())
    batch = args.batch or 4 * n_dev
    rs = np.random.RandomState(0)
    x = rs.randn(8 * batch, 16).astype("float32")
    y = (x @ rs.randn(16, 1) > 0).astype("int32")[:, 0]

    # -- 1. DistriOptimizer with overlapped bucketed gradient sync -----
    ds = DataSet.tensors(x, y, rng=RandomGenerator(1)) >> SampleToMiniBatch(batch)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 2),
                          nn.LogSoftMax())
    opt = optim.DistriOptimizer(
        model, ds, nn.ClassNLLCriterion(), batch_size=batch,
        overlap_buckets=2, overlap_wire_dtype=jnp.bfloat16)
    opt.set_optim_method(SGD(learning_rate=0.2, momentum=0.9))
    opt.set_end_when(optim.Trigger.max_iteration(args.steps))
    params, _ = opt.optimize()
    print(f"[overlap-ddp] trained {args.steps} steps on a "
          f"{n_dev}-device dp mesh (bf16 wire, 2 buckets)")

    # -- 2. ZeRO-1 overlap step (chunked optimizer state) --------------
    mesh = Mesh(np.array(jax.devices()).reshape(n_dev), ("dp",))
    method = SGD(learning_rate=0.2, momentum=0.9)
    p, ms = model.init(jax.random.key(0))
    oz = zero1_state_sharding(
        zero1_init_state(method, p, mesh, num_buckets=2), mesh)
    # the model ends in LogSoftMax, so pair it with ClassNLLCriterion
    # (CrossEntropyCriterion expects raw logits and would double-log-softmax)
    zstep = make_zero1_overlap_step(
        model, nn.ClassNLLCriterion(), method, mesh, oz, num_buckets=2)
    xb = jnp.asarray(x[:batch])
    yb = jnp.asarray(y[:batch])
    for it in range(args.steps):
        p, ms, oz, loss = zstep(p, ms, oz, xb, yb, jnp.int32(it))
    print(f"[overlap-zero1] {args.steps} steps, final loss {float(loss):.4f} "
          f"(optimizer state sharded 1/{n_dev} per chip)")

    # -- 3. heterogeneous stateful pipeline ----------------------------
    pmesh = Mesh(np.array(jax.devices()).reshape(n_dev), ("pp",))
    F = 16
    stages = [nn.Sequential(nn.Linear(F, F), nn.BatchNormalization(F),
                            nn.ReLU())] + \
             [nn.Sequential(nn.Linear(F, F), nn.Tanh())
              for _ in range(n_dev - 1)]
    pipe = HeteroPipeline(stages, pmesh, n_micro=2)
    pp, pst = pipe.init(jax.random.key(1))
    pstep = make_pp_train_step(pipe, nn.CrossEntropyCriterion(),
                               SGD(learning_rate=0.1))
    po = SGD(learning_rate=0.1).init_state(pp)
    yb16 = jnp.asarray(rs.randint(0, F, (batch,)))
    for it in range(args.steps):
        pp, pst, po, loss = pstep(pp, pst, po, xb, yb16, jnp.int32(it))
    print(f"[pipeline] {len(stages)}-stage BN pipeline trained "
          f"{args.steps} steps under pp={n_dev}, final loss {float(loss):.4f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
