"""ML-pipeline (dlframes) examples: LeNet classifier, logistic
regression, multi-label linear regression.

Reference: ``DL/example/MLPipeline/{DLClassifierLeNet,
DLClassifierLogisticRegression, DLEstimatorMultiLabelLR}.scala`` — the
Spark-ML estimator/transformer workflow over DataFrames.

TPU-native: same workflow over pandas frames through
``bigdl_tpu.dlframes`` (see that module's docstring for why the frame
engine is pandas here).
"""

from __future__ import annotations

import argparse

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.dlframes import DLClassifier, DLEstimator


def lenet_classifier(args):
    """DLClassifierLeNet: fit LeNet on MNIST rows, report accuracy."""
    import pandas as pd

    from bigdl_tpu.dataset.datasets import (
        MNIST_TRAIN_MEAN, MNIST_TRAIN_STD, load_mnist,
    )
    from bigdl_tpu.models import lenet

    x, y = load_mnist(args.folder, train=True)
    x = ((x - MNIST_TRAIN_MEAN) / MNIST_TRAIN_STD).reshape(len(x), -1)
    n = min(len(x), args.nSamples)
    df = pd.DataFrame({"features": list(x[:n].astype(np.float32)),
                       "label": y[:n].astype(np.int64)})

    clf = DLClassifier(
        lenet.build(),  # starts with Reshape([1, 28, 28]) over the 784 rows
        nn.ClassNLLCriterion(), feature_size=[784],
    ).set_batch_size(args.batchSize).set_max_epoch(args.maxEpoch).set_learning_rate(0.05)
    model = clf.fit(df)
    out = model.transform(df)
    acc = float((out["prediction"].to_numpy() == out["label"].to_numpy()).mean())
    print(f"LeNet pipeline train accuracy: {acc:.3f}")
    return acc


def logistic_regression(args):
    """DLClassifierLogisticRegression: 2-feature binary LR."""
    import pandas as pd

    rng = np.random.RandomState(0)
    n = args.nSamples
    x = rng.randn(n, 2).astype(np.float32)
    y = (x[:, 0] + 2 * x[:, 1] > 0).astype(np.int64)
    df = pd.DataFrame({"features": list(x), "label": y})

    clf = DLClassifier(
        nn.Sequential(nn.Linear(2, 2), nn.LogSoftMax()),
        nn.ClassNLLCriterion(), feature_size=[2]).set_batch_size(args.batchSize).set_max_epoch(args.maxEpoch).set_learning_rate(1.0)
    model = clf.fit(df)
    out = model.transform(df)
    acc = float((out["prediction"].to_numpy() == y).mean())
    print(f"logistic-regression pipeline accuracy: {acc:.3f}")
    return acc


def multilabel_lr(args):
    """DLEstimatorMultiLabelLR: 2-in 2-out linear regression with MSE."""
    import pandas as pd

    rng = np.random.RandomState(1)
    n = args.nSamples
    x = rng.randn(n, 2).astype(np.float32)
    w = np.asarray([[2.0, -1.0], [0.5, 3.0]], np.float32)
    t = x @ w
    df = pd.DataFrame({"features": list(x), "label": list(t)})

    est = DLEstimator(nn.Linear(2, 2), nn.MSECriterion(),
                      feature_size=[2], label_size=[2]).set_batch_size(args.batchSize).set_max_epoch(args.maxEpoch).set_learning_rate(0.1)
    model = est.fit(df)
    out = model.transform(df)
    pred = np.stack(out["prediction"].to_list())
    mse = float(np.mean((pred - t) ** 2))
    print(f"multi-label LR pipeline MSE: {mse:.4f}")
    return mse


def main(argv=None):
    ap = argparse.ArgumentParser("ml-pipeline")
    ap.add_argument("--app", choices=["lenet", "lr", "multilabel"],
                    default="lr")
    ap.add_argument("-f", "--folder", default=None)
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("-e", "--maxEpoch", type=int, default=5)
    ap.add_argument("--nSamples", type=int, default=256)
    args = ap.parse_args(argv)
    return {"lenet": lenet_classifier, "lr": logistic_regression,
            "multilabel": multilabel_lr}[args.app](args)


if __name__ == "__main__":
    main()
