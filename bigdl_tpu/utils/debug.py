"""Determinism / NaN / transfer sanitizers.

Reference: SURVEY §5 "race detection / sanitizers" — the reference has
none (closest: ``Engine.checkSingleton``); the TPU build is told to
"lean on JAX determinism + donation/aliasing checks" instead. This
module is that tier:

- ``check_deterministic``: run a jitted fn twice, assert bitwise-equal
  results (catches nondeterministic reductions/rng misuse — the SPMD
  analogue of a race detector).
- ``nan_guard``: wrap a step fn; raises with the offending leaf path on
  the first non-finite output (cheaper and jit-compatible vs global
  ``jax_debug_nans``).
- ``no_transfers``: context manager asserting no implicit host<->device
  transfers happen inside (wraps ``jax.transfer_guard``) — catches the
  classic "numpy op inside the hot loop silently pulls the array back"
  throughput bug.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Sequence

import numpy as np
import jax
import jax.numpy as jnp


def check_deterministic(fn: Callable, *args, runs: int = 2) -> Any:
    """Call ``fn(*args)`` ``runs`` times; raise if any pair of results
    differs bitwise. Returns the (verified) first result."""
    results = [fn(*args) for _ in range(runs)]
    first = jax.tree_util.tree_leaves(results[0])
    for r, result in enumerate(results[1:], start=2):
        leaves = jax.tree_util.tree_leaves(result)
        for i, (a, b) in enumerate(zip(first, leaves)):
            a, b = np.asarray(a), np.asarray(b)
            if a.tobytes() != b.tobytes():
                diff = np.max(np.abs(a.astype(np.float64) - b.astype(np.float64)))
                raise AssertionError(
                    f"non-deterministic result: leaf {i} differs between run 1 "
                    f"and run {r} (max abs diff {diff:.3e})")
    return results[0]


def nan_guard(fn: Callable, name: str = "step") -> Callable:
    """Wrap ``fn``: after each call, check every floating leaf of the
    result is finite; raise naming the leaf path otherwise."""

    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        flat, _ = jax.tree_util.tree_flatten_with_path(out)
        for path, leaf in flat:
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
                if not bool(jnp.all(jnp.isfinite(leaf))):
                    keys = "/".join(getattr(k, "key", str(k)) for k in path)
                    raise FloatingPointError(
                        f"{name}: non-finite values in output leaf '{keys}'")
        return out

    return wrapped


@contextlib.contextmanager
def no_transfers(level: str = "disallow"):
    """Assert no implicit host<->device transfers inside the block
    (explicit ``jax.device_put``/``np.asarray`` fetches still allowed at
    level 'log'; 'disallow' raises on any implicit transfer)."""
    with jax.transfer_guard(level):
        yield
