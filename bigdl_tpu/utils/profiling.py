"""Per-module timing (profiling hook).

Reference: ``DL/nn/abstractnn/AbstractModule.scala:255-289`` — wall-time
counters accumulated inside every forward/backward, read by
``getTimes()``/``resetTimes()`` (summed for graphs at
``IRGraph.scala:137-143``).

TPU-native deviation: under ``jit`` the whole step fuses into one XLA
program, so per-module wall times cannot be observed from inside it.
``module_times`` therefore drives each TOP-LEVEL child as its own jitted
program (compile excluded, block_until_ready timed) — the same
layer-attribution information the reference counters give, produced by
measurement runs instead of per-call instrumentation. For kernel-level
timelines use ``jax.profiler.trace`` (TensorBoard), the analogue the
reference lacks (SURVEY notes "no sampled profiler, no chrome-trace").
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import jax
import numpy as np


def _timed(fn, *args, reps: int = 3) -> float:
    out = fn(*args)
    jax.block_until_ready(out)  # warmup/compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def module_times(model, params, state, x, reps: int = 3,
                 backward: bool = True) -> List[Tuple[str, float, Optional[float]]]:
    """[(child_name, forward_seconds, backward_seconds)] for each direct
    child of a Sequential-style model (reference ``getTimes()`` rows).

    ``backward`` adds the grad-of-sum time per child (None for
    parameter-less children).
    """
    import jax.numpy as jnp

    out: List[Tuple[str, float, Optional[float]]] = []
    h = x
    state = state or {}
    for name, child in model._modules.items():
        p = (params or {}).get(name, {})
        s = state.get(name, {})

        def fwd(p, h):
            y, _ = child.apply(p, h, state=s, training=False)
            return y

        fwd_jit = jax.jit(fwd)
        t_fwd = _timed(fwd_jit, p, h, reps=reps)

        t_bwd = None
        if backward and jax.tree_util.tree_leaves(p):
            def loss(p, h):
                return jnp.sum(jnp.square(jnp.float32(fwd(p, h))))

            g_jit = jax.jit(jax.grad(loss))
            # grad re-runs the forward; report backward-only like the
            # reference counters (clamped: fusion can make the combined
            # program faster than the naive sum)
            t_bwd = max(0.0, _timed(g_jit, p, h, reps=reps) - t_fwd)
        h = fwd_jit(p, h)
        out.append((name, t_fwd, t_bwd))
    return out


def format_times(rows: List[Tuple[str, float, Optional[float]]]) -> str:
    """Pretty table like the reference's getTimes log dump."""
    lines = [f"{'module':<28} {'forward(ms)':>12} {'backward(ms)':>13}"]
    for name, f, b in rows:
        bs = f"{b * 1e3:13.3f}" if b is not None else f"{'-':>13}"
        lines.append(f"{name:<28} {f * 1e3:12.3f} {bs}")
    total_f = sum(f for _, f, _ in rows)
    total_b = sum(b for _, _, b in rows if b is not None)
    lines.append(f"{'TOTAL':<28} {total_f * 1e3:12.3f} {total_b * 1e3:13.3f}")
    return "\n".join(lines)


import contextlib


@contextlib.contextmanager
def trace(log_dir: str):
    """Kernel-level timeline capture (chrome-trace / TensorBoard xplane;
    the analogue SURVEY §5 notes the reference LACKS — "no sampled
    profiler, no chrome-trace export"). Wraps ``jax.profiler``:

        with profiling.trace("/tmp/tb"):
            train_step(...)

    produces ``plugins/profile/<ts>/*.trace.json.gz`` viewable in
    chrome://tracing or TensorBoard."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
