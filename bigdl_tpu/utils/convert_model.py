"""Model format converter CLI.

Reference: ``DL/utils/ConvertModel.scala`` — converts models between
bigdl / caffe / tensorflow / torch formats from the command line.

Usage::

    python -m bigdl_tpu.utils.convert_model \
        --from caffe --input deploy.prototxt,weights.caffemodel \
        --to bigdl --output model.bin
"""

from __future__ import annotations

import argparse


def main(argv=None):
    parser = argparse.ArgumentParser("convert-model")
    parser.add_argument("--from", dest="src", required=True,
                        choices=["bigdl", "bigdl-proto", "caffe", "tensorflow", "onnx", "torch"])
    parser.add_argument("--to", dest="dst", required=True,
                        choices=["bigdl", "bigdl-proto", "caffe", "tensorflow", "onnx"])
    parser.add_argument("--input", required=True,
                        help="source path; caffe takes 'prototxt,caffemodel', "
                             "tensorflow takes 'graph.pb,input:output'")
    parser.add_argument("--output", required=True,
                        help="destination path; caffe writes "
                             "'prototxt,caffemodel'")
    parser.add_argument("--input-shape", default=None,
                        help="comma ints, e.g. 1,3,224,224 (needed for "
                             "caffe/tf/onnx export)")
    args = parser.parse_args(argv)

    shape = (tuple(int(d) for d in args.input_shape.split(","))
             if args.input_shape else None)

    # -- load ------------------------------------------------------------
    if args.src == "bigdl":
        from bigdl_tpu.utils.serializer import load_module

        model, params, state = load_module(args.input)
    elif args.src == "bigdl-proto":
        # reference wire format (Bigdl.proto, Module.saveModule files)
        from bigdl_tpu.interop.bigdl import load_bigdl

        model, params, state = load_bigdl(args.input)
    elif args.src == "caffe":
        from bigdl_tpu.interop.caffe import load_caffe

        proto, weights = args.input.split(",")
        model, params, state = load_caffe(proto, weights)
    elif args.src == "tensorflow":
        from bigdl_tpu.interop.tf import load_tf_graph

        path, io = args.input.split(",")
        inp, out = io.split(":")
        model, params, state = load_tf_graph(path, [inp], [out])
    elif args.src == "torch":
        from bigdl_tpu.utils.torch_file import load_t7, t7_to_module

        model, params, state = t7_to_module(load_t7(args.input))
    else:  # onnx
        from bigdl_tpu.interop.onnx import load_onnx

        model, params, state = load_onnx(args.input)

    # -- save ------------------------------------------------------------
    if args.dst == "bigdl":
        from bigdl_tpu.utils.serializer import save_module

        save_module(args.output, model, params, state)
    elif args.dst == "bigdl-proto":
        from bigdl_tpu.interop.bigdl import save_bigdl

        save_bigdl(args.output, model, params, state)
    elif args.dst == "caffe":
        from bigdl_tpu.interop.caffe import save_caffe

        proto, weights = args.output.split(",")
        save_caffe(model, params, state, proto, weights, input_shape=shape)
    elif args.dst == "tensorflow":
        from bigdl_tpu.interop.tf import save_tf_graph

        save_tf_graph(model, params, state, args.output, input_shape=shape)
    else:
        from bigdl_tpu.interop.onnx import save_onnx

        save_onnx(model, params, state, args.output, input_shape=shape)
    print(f"converted {args.src} -> {args.dst}: {args.output}")


if __name__ == "__main__":
    main()
