"""Checkpoint FORMAT layer (serialization + legacy single-file API).

Reference: ``Optimizer.setCheckpoint`` (``DL/optim/Optimizer.scala:198``),
``AbstractOptimizer.checkpoint`` (``AbstractOptimizer.scala:205``) saving
(a) the model and (b) each OptimMethod with its state; resume via
``Module.load`` + ``OptimMethod.load`` (``models/lenet/Train.scala:48,65``);
``getLatestFile`` discovery (``DistriOptimizer.scala:986``).

TPU-native: a checkpoint is the (params, module-state, optim-state) pytree
triple serialized with flax's msgpack (+ a JSON sidecar for host counters:
epoch, iteration, records-processed — the reference's ``endEpoch``/
``recordsProcessedThisEpoch`` state keys).

This module is the stable FORMAT core: :func:`serialize_payload` /
:func:`deserialize_payload` define the bytes, and the thin
``save_checkpoint``/``load_checkpoint``/``latest_checkpoint`` trio remains
as the legacy single-file API. Fault tolerance — async saves, verified
atomic manifest commits, restore fallback, retention, preemption — lives
one tier up in ``bigdl_tpu.ckpt.CheckpointManager``, which writes this
same format (every ``CheckpointManager`` blob is loadable with
:func:`load_checkpoint` and vice versa).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization


def _to_numpy(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def serialize_payload(params: Any, module_state: Any = None,
                      optim_state: Any = None) -> bytes:
    """The checkpoint wire format: the (params, module_state, optim_state)
    triple as flax msgpack bytes. Device arrays are fetched to host here."""
    return serialization.to_bytes({
        "params": _to_numpy(params),
        "module_state": _to_numpy(module_state or {}),
        "optim_state": _to_numpy(optim_state or {}),
    })


def deserialize_payload(blob: bytes, template: Optional[Dict[str, Any]] = None):
    """Inverse of :func:`serialize_payload`. With a ``template`` (pytrees
    from a fresh ``init``), leaves come back with the correct tree
    structure; without, raw nested dicts."""
    target = None
    if template is not None:
        target = {
            "params": template.get("params"),
            "module_state": template.get("module_state") or {},
            "optim_state": template.get("optim_state") or {},
        }
    return serialization.from_bytes(target, blob)


def save_checkpoint(
    path: str,
    tag: str,
    params: Any,
    module_state: Any = None,
    optim_state: Any = None,
    meta: Optional[Dict[str, Any]] = None,
) -> str:
    """Write ``<path>/<tag>.ckpt`` (+ ``.meta.json``). Returns the file path."""
    os.makedirs(path, exist_ok=True)
    blob = serialize_payload(params, module_state, optim_state)
    f = os.path.join(path, f"{tag}.ckpt")
    tmp = f + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(blob)
    os.replace(tmp, f)
    meta = dict(meta or {})
    meta.setdefault("wall_time", time.time())
    with open(os.path.join(path, f"{tag}.meta.json"), "w") as fh:
        json.dump(meta, fh)
    return f


def load_checkpoint(file: str, template: Optional[Dict[str, Any]] = None):
    """Load a checkpoint. With a ``template`` (same-structure pytrees from a
    fresh ``init``), leaves are restored with correct tree structure;
    without, returns raw nested dicts."""
    with open(file, "rb") as fh:
        blob = fh.read()
    payload = deserialize_payload(blob, template)
    meta_path = file[: -len(".ckpt")] + ".meta.json"
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as fh:
            meta = json.load(fh)
    return payload, meta


def latest_checkpoint(path: str, prefix: str = "") -> Optional[str]:
    """Newest ``*.ckpt`` by embedded iteration number then mtime
    (reference: ``getLatestFile``).

    Hardened against the debris a crashed save leaves behind: staging
    files (``*.tmp``) are never candidates, a blob whose ``.meta.json``
    sidecar is missing is skipped (the legacy writer commits blob-then-
    sidecar, so a sidecar-less blob is a torn save with unknowable
    epoch/iteration counters), and a file vanishing mid-scan (concurrent
    retention GC) is ignored rather than crashing the scan."""
    if not os.path.isdir(path):
        return None
    try:
        names = os.listdir(path)
    except OSError:
        return None
    best: Tuple[int, float, Optional[str]] = (-1, -1.0, None)
    for name in names:
        if (name.endswith(".tmp") or not name.endswith(".ckpt")
                or not name.startswith(prefix)):
            continue
        full = os.path.join(path, name)
        if not os.path.exists(full[: -len(".ckpt")] + ".meta.json"):
            continue
        m = re.search(r"(\d+)", name)
        it = int(m.group(1)) if m else 0
        try:
            mtime = os.path.getmtime(full)
        except OSError:
            continue
        if (it, mtime) > (best[0], best[1]):
            best = (it, mtime, full)
    return best[2]


# -- orbax backend -----------------------------------------------------------

def save_checkpoint_orbax(path: str, tag: str, params: Any,
                          module_state: Any = None, optim_state: Any = None,
                          meta: Optional[Dict[str, Any]] = None) -> str:
    """Orbax-backed checkpoint (atomic directory commit, multi-host-safe
    — the production-durability tier the module docstring promises;
    payload layout matches :func:`save_checkpoint` so the same resume
    logic applies). Writes ``<path>/<tag>.orbax/``."""
    import orbax.checkpoint as ocp

    target = os.path.abspath(os.path.join(path, f"{tag}.orbax"))
    payload = {
        "params": _to_numpy(params),
        "module_state": _to_numpy(module_state or {}),
        "optim_state": _to_numpy(optim_state or {}),
        "meta": dict(meta or {}, wall_time=time.time()),
    }
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(target, payload, force=True)
    return target


def load_checkpoint_orbax(path_or_dir: str, tag: Optional[str] = None):
    """Load an orbax checkpoint written by :func:`save_checkpoint_orbax`.
    Returns (params, module_state, optim_state, meta)."""
    import orbax.checkpoint as ocp

    target = os.path.abspath(
        os.path.join(path_or_dir, f"{tag}.orbax") if tag else path_or_dir)
    with ocp.PyTreeCheckpointer() as ckptr:
        payload = ckptr.restore(target)
    return (payload["params"], payload["module_state"],
            payload["optim_state"], payload.get("meta", {}))


# -- async checkpointing ------------------------------------------------------

class AsyncCheckpoint:
    """Handle for an in-flight background checkpoint write."""

    def __init__(self, thread, holder):
        self._thread = thread
        self._holder = holder

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self, timeout: Optional[float] = None) -> str:
        """Block until the write finishes; returns the path (or raises
        the worker's exception)."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint write still in flight")
        if "error" in self._holder:
            raise self._holder["error"]
        return self._holder["path"]


def save_checkpoint_async(
    path: str,
    tag: str,
    params: Any,
    module_state: Any = None,
    optim_state: Any = None,
    meta: Optional[Dict[str, Any]] = None,
) -> AsyncCheckpoint:
    """Non-blocking checkpoint write (the TPU-native answer to the
    reference's checkpoint stall: ``AbstractOptimizer.checkpoint``
    blocks the driver between iterations, ``AbstractOptimizer.scala:205``).

    .. deprecated:: kept as the thin legacy shim only. New code should use
       ``bigdl_tpu.ckpt.CheckpointManager``, which adds verified manifest
       commits, in-flight guards, backpressure, retention GC, and
       preemption handling on top of this same file format.

    jax arrays are immutable, so the live (params, state) pytrees are
    snapshotted by reference for free — the device->host transfer and the
    file write both happen on a worker thread while training continues.
    The atomic tmp-file rename in :func:`save_checkpoint` keeps partial
    writes invisible; call ``.result()`` before shutdown (or rely on
    ``get_latest_checkpoint`` skipping torn files).
    """
    import threading

    holder: Dict[str, Any] = {}

    def work():
        try:
            holder["path"] = save_checkpoint(
                path, tag, params, module_state, optim_state, meta)
        except BaseException as e:  # surfaced via .result()
            holder["error"] = e

    t = threading.Thread(target=work, daemon=True,
                         name=f"ckpt-{tag}")
    t.start()
    return AsyncCheckpoint(t, holder)
