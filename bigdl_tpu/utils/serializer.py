"""Model structure serialization: save/load full modules without code.

Reference: ``DL/utils/serializer/`` — ``ModuleSerializer`` (:36) maps each
class to a serializer, defaulting to a reflection-driven
``ModuleSerializable`` that persists constructor params + weights into the
protobuf schema (``Bigdl.java``); ``ModuleLoader`` rebuilds the tree.

TPU-native design: constructor calls are captured automatically on every
``Module``/``Criterion``/``OptimMethod``/... subclass
(``capture_init_args``, ``nn/module.py``) — that record IS the reflective
spec. A saved model file is::

    b"BDLTPU1\\0" | u64 json_len | spec JSON | flax-msgpack weights blob

The JSON spec nests: class path, encoded constructor args, children added
after construction, plus custom sections for ``Graph`` (node DAG with
shared-module dedup) and ``KerasLayer`` (input shape; the inner module is
rebuilt deterministically). ``LambdaLayer`` and other function-carrying
modules are rejected with a clear error (the reference likewise has
unserializable ops).
"""

from __future__ import annotations

import importlib
import json
import os
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np
from flax import serialization as flax_ser

from bigdl_tpu.nn.graph import Graph, Node
from bigdl_tpu.nn.module import Criterion, LambdaLayer, Module

_MAGIC = b"BDLTPU1\x00"
# v2: container children encoded as post-ctor patches ({'spec'|'patch'})
# instead of full nested specs
FORMAT_VERSION = 2


def _check_version(header, file):
    v = header.get("format_version")
    if v != FORMAT_VERSION:
        raise ValueError(
            f"{file} uses model format version {v}; this build reads "
            f"version {FORMAT_VERSION} — re-save the model with the "
            f"current library"
        )


class SerializationError(TypeError):
    pass


# ------------------------------------------------------------ value codec


def _class_path(obj) -> str:
    cls = type(obj)
    if "<locals>" in cls.__qualname__:
        raise SerializationError(
            f"cannot serialize locally-defined class {cls.__qualname__} "
            f"(define it at module scope, or use a Keras-tier layer which "
            f"serializes by its builder config)"
        )
    return f"{cls.__module__}:{cls.__qualname__}"


def _resolve(path: str):
    mod, _, qual = path.partition(":")
    obj = importlib.import_module(mod)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


def _has_spec_bases(v) -> bool:
    from bigdl_tpu.nn.init import InitializationMethod
    from bigdl_tpu.optim.optim_method import OptimMethod
    from bigdl_tpu.optim.schedules import LearningRateSchedule

    return isinstance(v, (Module, Criterion, InitializationMethod,
                          OptimMethod, LearningRateSchedule))


def encode_value(v) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, tuple):
        return {"__tuple__": [encode_value(x) for x in v]}
    if isinstance(v, list):
        return [encode_value(x) for x in v]
    if isinstance(v, dict):
        return {"__dict__": {str(k): encode_value(x) for k, x in v.items()}}
    if isinstance(v, np.ndarray):
        return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
    if isinstance(v, Module):
        return {"__module__": module_to_spec(v)}
    if _has_spec_bases(v):
        return {"__object__": object_to_spec(v)}
    raise SerializationError(
        f"cannot serialize constructor argument of type {type(v).__name__}: {v!r}"
    )


def decode_value(v) -> Any:
    if isinstance(v, dict):
        if "__tuple__" in v:
            return tuple(decode_value(x) for x in v["__tuple__"])
        if "__dict__" in v:
            return {k: decode_value(x) for k, x in v["__dict__"].items()}
        if "__ndarray__" in v:
            return np.asarray(v["__ndarray__"], dtype=v["dtype"])
        if "__module__" in v:
            return module_from_spec(v["__module__"])
        if "__object__" in v:
            return object_from_spec(v["__object__"])
    if isinstance(v, list):
        return [decode_value(x) for x in v]
    return v


# ------------------------------------------------------- object (non-module)


def object_to_spec(obj) -> Dict[str, Any]:
    if hasattr(obj, "serial_config"):
        # object overrides its spec (e.g. state accumulated after __init__)
        args, kwargs = obj.serial_config()
    else:
        args, kwargs = getattr(obj, "_init_config", ((), {}))
    return {
        "cls": _class_path(obj),
        "args": [encode_value(a) for a in args],
        "kwargs": {k: encode_value(v) for k, v in kwargs.items()},
    }


def object_from_spec(spec: Dict[str, Any]):
    cls = _resolve(spec["cls"])
    args = [decode_value(a) for a in spec.get("args", [])]
    kwargs = {k: decode_value(v) for k, v in spec.get("kwargs", {}).items()}
    return cls(*args, **kwargs)


# ------------------------------------------------------------- module spec


def module_to_spec(m: Module) -> Dict[str, Any]:
    from bigdl_tpu.keras.engine import KerasLayer
    from bigdl_tpu.keras.topology import Model as KModel
    from bigdl_tpu.keras.topology import Sequential as KSequential

    if isinstance(m, LambdaLayer):
        raise SerializationError(
            "LambdaLayer wraps an arbitrary Python function and cannot be "
            "serialized; use a named layer class instead"
        )

    # graph-like modules hold Node objects in their captured ctor args;
    # they serialize through the DAG spec instead
    if isinstance(m, KModel):
        return _named(m, {"cls": _class_path(m),
                          "keras_model_graph": _graph_to_spec(m._graph),
                          "keras_output_shapes": encode_value(
                              [tuple(s) if s is not None else None
                               for s in m._output_shapes])})
    if isinstance(m, Graph):
        return _named(m, {"cls": _class_path(m), "graph": _graph_to_spec(m)})

    spec = object_to_spec(m)
    if m.get_name():
        spec["name"] = m.get_name()

    if isinstance(m, KSequential):
        spec["args"] = []
        spec["kwargs"] = {}
        spec["keras_sequential"] = [module_to_spec(l) for l in m._layers]
        return spec
    if isinstance(m, KerasLayer):
        # the inner module is a deterministic function of (config, shape)
        spec["keras_input_shape"] = encode_value(m.input_shape)
        return spec

    patch = _children_patch(m)
    if patch:
        spec["children"] = patch
    return spec


def _children_patch(m: Module) -> Dict[str, Any]:
    """Spec only for children the constructor did NOT create (added via
    ``add()`` afterwards), plus nested patches inside ctor-created children.
    Ctor-created children are reachable from the encoded constructor args,
    so re-encoding them here would double the spec per nesting level."""
    ctor = getattr(m, "_ctor_children", frozenset())
    out: Dict[str, Any] = {}
    for name, child in m.modules.items():
        if name in ctor:
            sub = _children_patch(child)
            if sub:
                out[name] = {"patch": sub}
        else:
            out[name] = {"spec": module_to_spec(child)}
    return out


def module_from_spec(spec: Dict[str, Any]) -> Module:
    from bigdl_tpu.keras.engine import KerasLayer

    cls = _resolve(spec["cls"])

    if "keras_sequential" in spec:
        inst = cls()
        for lspec in spec["keras_sequential"]:
            inst.add(module_from_spec(lspec))
        _maybe_name(inst, spec)
        return inst
    if "keras_model_graph" in spec:
        g = _graph_from_spec(spec["keras_model_graph"])
        inst = cls(g.inputs, g.outputs)
        shapes = decode_value(spec.get("keras_output_shapes"))
        if shapes:
            inst._output_shapes = list(shapes)
        _maybe_name(inst, spec)
        return inst
    if "graph" in spec:
        g = _graph_from_spec(spec["graph"])
        if cls is not Graph:  # Graph subclass: rewire via Graph ctor contract
            inst = cls(g.inputs, g.outputs)
        else:
            inst = g
        _maybe_name(inst, spec)
        return inst

    inst = object_from_spec(spec)
    if isinstance(inst, KerasLayer):
        shape = decode_value(spec.get("keras_input_shape"))
        if shape is not None:
            inst.ensure_built(shape)
    _replay_children(inst, spec.get("children", {}))
    _maybe_name(inst, spec)
    return inst


def _named(m: Module, spec: Dict[str, Any]) -> Dict[str, Any]:
    if m.get_name():
        spec["name"] = m.get_name()
    return spec


def _maybe_name(inst: Module, spec) -> None:
    if spec.get("name"):
        inst.set_name(spec["name"])


def _replay_children(inst: Module, patch: Dict[str, Any]) -> None:
    """Re-attach post-construction children from a ``_children_patch``."""
    for name, entry in patch.items():
        if "spec" in entry:
            inst.add(module_from_spec(entry["spec"]), name)
        else:
            _replay_children(inst.modules[name], entry["patch"])


# ----------------------------------------------------------------- graphs


def _graph_to_spec(g: Graph) -> Dict[str, Any]:
    nodes = list(g._topo)
    index = {id(n): i for i, n in enumerate(nodes)}
    elements = []  # dedup shared modules
    elem_index: Dict[int, int] = {}
    node_specs = []
    for n in nodes:
        if n.element is None:
            ei = -1
        else:
            mid = id(n.element)
            if mid not in elem_index:
                elem_index[mid] = len(elements)
                elements.append(module_to_spec(n.element))
            ei = elem_index[mid]
        node_specs.append({"element": ei, "prev": [index[id(p)] for p in n.prev]})
    return {
        "elements": elements,
        "nodes": node_specs,
        "inputs": [index[id(n)] for n in g.inputs],
        "outputs": [index[id(n)] for n in g.outputs],
    }


def _graph_from_spec(spec: Dict[str, Any]) -> Graph:
    elements = [module_from_spec(e) for e in spec["elements"]]
    nodes = []
    for ns in spec["nodes"]:
        elem = None if ns["element"] < 0 else elements[ns["element"]]
        nodes.append(Node(elem, [nodes[i] for i in ns["prev"]]))
    return Graph(
        [nodes[i] for i in spec["inputs"]],
        [nodes[i] for i in spec["outputs"]],
    )


# ------------------------------------------------------------ file format


def _to_numpy(tree):
    import jax

    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def save_module(file: str, module: Module, params=None, state=None,
                overwrite: bool = True, extra: Optional[Dict] = None) -> str:
    """Persist structure (+ optional weights) to one file
    (reference ``AbstractModule.saveModule``, ``AbstractModule.scala:548``)."""
    if os.path.exists(file) and not overwrite:
        raise FileExistsError(f"{file} exists (pass overwrite=True)")
    header = {
        "format_version": FORMAT_VERSION,
        "spec": module_to_spec(module),
        "has_weights": params is not None,
        "extra": extra or {},
    }
    blob = b""
    if params is not None:
        blob = flax_ser.to_bytes({
            "params": _to_numpy(params),
            "state": _to_numpy(state or {}),
        })
    hjson = json.dumps(header).encode("utf-8")
    tmp = file + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(file)), exist_ok=True)
    with open(tmp, "wb") as fh:
        fh.write(_MAGIC)
        fh.write(struct.pack("<Q", len(hjson)))
        fh.write(hjson)
        fh.write(blob)
    os.replace(tmp, file)
    return file


def load_module(file: str) -> Tuple[Module, Any, Any]:
    """Load (module, params, state); params/state are None when the file was
    saved without weights (reference ``Module.loadModule``)."""
    with open(file, "rb") as fh:
        magic = fh.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{file} is not a bigdl_tpu model file")
        (hlen,) = struct.unpack("<Q", fh.read(8))
        header = json.loads(fh.read(hlen).decode("utf-8"))
        blob = fh.read()
    _check_version(header, file)
    module = module_from_spec(header["spec"])
    params = state = None
    if header.get("has_weights"):
        # restore against a freshly-initialized template for exact treedefs
        import jax

        t_params, t_state = module.init(jax.random.key(0))
        payload = flax_ser.from_bytes({"params": t_params, "state": t_state}, blob)
        params, state = payload["params"], payload["state"]
    return module, params, state


# ----------------------------------------------------------- optim methods


def save_optim_method(file: str, method, state=None) -> str:
    """Reference: ``OptimMethod.save`` (Java serialization there; a spec +
    msgpack state blob here)."""
    header = {
        "format_version": FORMAT_VERSION,
        "spec": object_to_spec(method),
        "has_state": state is not None,
    }
    blob = flax_ser.to_bytes(_to_numpy(state)) if state is not None else b""
    hjson = json.dumps(header).encode("utf-8")
    tmp = file + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(file)), exist_ok=True)
    with open(tmp, "wb") as fh:
        fh.write(_MAGIC)
        fh.write(struct.pack("<Q", len(hjson)))
        fh.write(hjson)
        fh.write(blob)
    os.replace(tmp, file)
    return file


def load_optim_method(file: str):
    """Returns (method, state_or_None)."""
    with open(file, "rb") as fh:
        if fh.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{file} is not a bigdl_tpu file")
        (hlen,) = struct.unpack("<Q", fh.read(8))
        header = json.loads(fh.read(hlen).decode("utf-8"))
        blob = fh.read()
    _check_version(header, file)
    method = object_from_spec(header["spec"])
    state = flax_ser.msgpack_restore(blob) if header.get("has_state") else None
    return method, state
