"""Scheme-dispatching file I/O: local, hdfs://, s3://.

Reference: ``DL/utils/File.scala:26`` — ``save``/``load`` of serialized
objects to local paths, HDFS (:68-176) and S3, used by checkpointing.

TPU-native: local paths use plain file handles; ``hdfs://`` and ``s3://``
dispatch to ``fsspec``/``pyarrow``/``boto3`` WHEN INSTALLED and raise an
actionable error otherwise (this image has no cluster filesystems — the
interface keeps checkpoint code cloud-portable without hard deps).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, BinaryIO


def _open(path: str, mode: str) -> BinaryIO:
    if path.startswith("hdfs://"):
        try:
            import fsspec

            return fsspec.open(path, mode).open()
        except Exception:
            pass
        try:
            import pyarrow.fs as pafs

            fs, p = pafs.FileSystem.from_uri(path)
            return (fs.open_input_stream(p) if "r" in mode
                    else fs.open_output_stream(p))
        except Exception as e:  # missing driver/JVM/libs all land here
            raise ImportError(
                "hdfs:// paths need a working `fsspec` hdfs driver or "
                f"`pyarrow` HDFS (libjvm) setup: {e}"
            ) from None
    if path.startswith("s3://"):
        try:
            import fsspec

            return fsspec.open(path, mode).open()
        except Exception as e:
            raise ImportError(
                "s3:// paths need `fsspec` with the s3fs driver installed: "
                f"{e}"
            ) from None
    if "w" in mode:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
    return open(path, mode)


def save_bytes(data: bytes, path: str, overwrite: bool = True) -> None:
    """Reference ``File.saveBytes``."""
    if not overwrite and not path.startswith(("hdfs://", "s3://")) \
            and os.path.exists(path):
        raise FileExistsError(f"{path} exists and overwrite=False")
    with _open(path, "wb") as f:
        f.write(data)


def load_bytes(path: str) -> bytes:
    """Reference ``File.readBytes``."""
    with _open(path, "rb") as f:
        return f.read()


def save(obj: Any, path: str, overwrite: bool = True) -> None:
    """Pickle-serialize to any supported scheme (reference ``File.save``)."""
    save_bytes(pickle.dumps(obj), path, overwrite)


def load(path: str) -> Any:
    """Reference ``File.load``."""
    return pickle.loads(load_bytes(path))
