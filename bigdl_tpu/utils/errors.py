"""Exception-identity hygiene helpers.

Raising one shared exception *object* from more than one site is a
cross-thread hazard this repo has been bitten by twice (PR 8: a fault
plan's armed instance; PR 17: a stream's terminal error raised from both
``__iter__`` and every ``result()`` call): each raise mutates the
object's ``__traceback__``/``__context__`` in place, corrupting what a
concurrent consumer already captured.  graftlint rule GL001 flags the
pattern statically; this helper is the standard fix — a fresh shallow
copy per raise site.
"""

from __future__ import annotations

import copy


def fresh_exception(exc: BaseException,
                    keep_traceback: bool = True) -> BaseException:
    """A per-raise shallow copy of ``exc``.

    The copy carries the original's ``__cause__`` and (when
    ``keep_traceback``) its ``__traceback__``, so diagnostics are
    unchanged — but raising the copy appends frames to the COPY's
    traceback, never to the object other threads hold.  An exception
    whose constructor defeats ``copy.copy`` (required kwargs lost by
    ``__reduce__``) degrades to the original object rather than raising
    a different error than the caller stored.
    """
    try:
        fresh = copy.copy(exc)
    except Exception:
        return exc
    if type(fresh) is not type(exc):  # exotic __reduce__; don't trust it
        return exc
    fresh.__traceback__ = exc.__traceback__ if keep_traceback else None
    fresh.__cause__ = exc.__cause__
    fresh.__suppress_context__ = exc.__suppress_context__
    return fresh
