"""Torch7 ``.t7`` file reader.

Reference: ``DL/utils/TorchFile.scala`` — reads legacy Torch serialization
(the binary format of ``torch.save`` from Lua Torch7) so reference models
and test fixtures stored as .t7 can be consumed. Read-only here (the
write path has no consumers in a TPU-native stack); covers numbers,
strings, booleans, tables, and the dense Float/Double/Long/Int/Byte
tensor + storage classes.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5
TYPE_FUNCTION = 6
TYPE_RECUR_FUNCTION = 8
LEGACY_TYPE_RECUR_FUNCTION = 7

_STORAGE_DTYPES = {
    "torch.DoubleStorage": (np.float64, 8),
    "torch.FloatStorage": (np.float32, 4),
    "torch.LongStorage": (np.int64, 8),
    "torch.IntStorage": (np.int32, 4),
    "torch.ShortStorage": (np.int16, 2),
    "torch.ByteStorage": (np.uint8, 1),
    "torch.CharStorage": (np.int8, 1),
}
_TENSOR_CLASSES = {
    "torch.DoubleTensor": "torch.DoubleStorage",
    "torch.FloatTensor": "torch.FloatStorage",
    "torch.LongTensor": "torch.LongStorage",
    "torch.IntTensor": "torch.IntStorage",
    "torch.ShortTensor": "torch.ShortStorage",
    "torch.ByteTensor": "torch.ByteStorage",
    "torch.CharTensor": "torch.CharStorage",
}


class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: Dict[int, Any] = {}

    def _read(self, fmt: str):
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, self.f.read(size))[0]

    def read_int(self) -> int:
        return self._read("<i")

    def read_long(self) -> int:
        return self._read("<q")

    def read_double(self) -> float:
        return self._read("<d")

    def read_string(self) -> str:
        n = self.read_int()
        return self.f.read(n).decode("latin-1")

    def read_object(self) -> Any:
        t = self.read_int()
        if t == TYPE_NIL:
            return None
        if t == TYPE_NUMBER:
            v = self.read_double()
            return int(v) if v.is_integer() else v
        if t == TYPE_STRING:
            return self.read_string()
        if t == TYPE_BOOLEAN:
            return bool(self.read_int())
        if t == TYPE_TABLE:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            table: Dict[Any, Any] = {}
            self.memo[idx] = table
            n = self.read_int()
            for _ in range(n):
                k = self.read_object()
                table[k] = self.read_object()
            return table
        if t == TYPE_TORCH:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            version = self.read_string()
            if version.startswith("V "):
                class_name = self.read_string()
            else:  # pre-versioning files: the string IS the class name
                class_name = version
            obj = self._read_torch_class(class_name, idx)
            return obj
        raise ValueError(f"unsupported t7 type tag {t}")

    def _read_torch_class(self, class_name: str, idx: int) -> Any:
        if class_name in _STORAGE_DTYPES:
            dtype, width = _STORAGE_DTYPES[class_name]
            n = self.read_long()
            data = np.frombuffer(self.f.read(n * width), dtype=dtype).copy()
            self.memo[idx] = data
            return data
        if class_name in _TENSOR_CLASSES:
            ndim = self.read_int()
            size = [self.read_long() for _ in range(ndim)]
            stride = [self.read_long() for _ in range(ndim)]
            offset = self.read_long() - 1  # 1-based
            self.memo[idx] = None  # placeholder for cycles
            storage = self.read_object()
            if storage is None or ndim == 0:
                arr = np.zeros(size, _STORAGE_DTYPES[_TENSOR_CLASSES[class_name]][0])
            else:
                arr = np.lib.stride_tricks.as_strided(
                    storage[offset:],
                    shape=size,
                    strides=[s * storage.itemsize for s in stride],
                ).copy()
            self.memo[idx] = arr
            return arr
        # unknown torch class: read as a table payload (module objects)
        obj = {"__torch_class__": class_name, "fields": self.read_object()}
        self.memo[idx] = obj
        return obj


def load_t7(path: str) -> Any:
    """Read one serialized object from a .t7 file (reference
    ``TorchFile.load``): tensors as numpy arrays, tables as dicts,
    unknown torch classes as {'__torch_class__', 'fields'} wrappers."""
    with open(path, "rb") as f:
        return _Reader(f).read_object()
