"""Torch7 ``.t7`` file reader.

Reference: ``DL/utils/TorchFile.scala`` — reads legacy Torch serialization
(the binary format of ``torch.save`` from Lua Torch7) so reference models
and test fixtures stored as .t7 can be consumed. Read-only here (the
write path has no consumers in a TPU-native stack); covers numbers,
strings, booleans, tables, and the dense Float/Double/Long/Int/Byte
tensor + storage classes.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5
TYPE_FUNCTION = 6
TYPE_RECUR_FUNCTION = 8
LEGACY_TYPE_RECUR_FUNCTION = 7

_STORAGE_DTYPES = {
    "torch.DoubleStorage": (np.float64, 8),
    "torch.FloatStorage": (np.float32, 4),
    "torch.LongStorage": (np.int64, 8),
    "torch.IntStorage": (np.int32, 4),
    "torch.ShortStorage": (np.int16, 2),
    "torch.ByteStorage": (np.uint8, 1),
    "torch.CharStorage": (np.int8, 1),
}
_TENSOR_CLASSES = {
    "torch.DoubleTensor": "torch.DoubleStorage",
    "torch.FloatTensor": "torch.FloatStorage",
    "torch.LongTensor": "torch.LongStorage",
    "torch.IntTensor": "torch.IntStorage",
    "torch.ShortTensor": "torch.ShortStorage",
    "torch.ByteTensor": "torch.ByteStorage",
    "torch.CharTensor": "torch.CharStorage",
}


class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: Dict[int, Any] = {}

    def _read(self, fmt: str):
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, self.f.read(size))[0]

    def read_int(self) -> int:
        return self._read("<i")

    def read_long(self) -> int:
        return self._read("<q")

    def read_double(self) -> float:
        return self._read("<d")

    def read_string(self) -> str:
        n = self.read_int()
        return self.f.read(n).decode("latin-1")

    def read_object(self) -> Any:
        t = self.read_int()
        if t == TYPE_NIL:
            return None
        if t == TYPE_NUMBER:
            v = self.read_double()
            return int(v) if v.is_integer() else v
        if t == TYPE_STRING:
            return self.read_string()
        if t == TYPE_BOOLEAN:
            return bool(self.read_int())
        if t == TYPE_TABLE:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            table: Dict[Any, Any] = {}
            self.memo[idx] = table
            n = self.read_int()
            for _ in range(n):
                k = self.read_object()
                table[k] = self.read_object()
            return table
        if t == TYPE_TORCH:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            version = self.read_string()
            if version.startswith("V "):
                class_name = self.read_string()
            else:  # pre-versioning files: the string IS the class name
                class_name = version
            obj = self._read_torch_class(class_name, idx)
            return obj
        raise ValueError(f"unsupported t7 type tag {t}")

    def _read_torch_class(self, class_name: str, idx: int) -> Any:
        if class_name in _STORAGE_DTYPES:
            dtype, width = _STORAGE_DTYPES[class_name]
            n = self.read_long()
            data = np.frombuffer(self.f.read(n * width), dtype=dtype).copy()
            self.memo[idx] = data
            return data
        if class_name in _TENSOR_CLASSES:
            ndim = self.read_int()
            size = [self.read_long() for _ in range(ndim)]
            stride = [self.read_long() for _ in range(ndim)]
            offset = self.read_long() - 1  # 1-based
            self.memo[idx] = None  # placeholder for cycles
            storage = self.read_object()
            if storage is None or ndim == 0:
                arr = np.zeros(size, _STORAGE_DTYPES[_TENSOR_CLASSES[class_name]][0])
            else:
                arr = np.lib.stride_tricks.as_strided(
                    storage[offset:],
                    shape=size,
                    strides=[s * storage.itemsize for s in stride],
                ).copy()
            self.memo[idx] = arr
            return arr
        # unknown torch class: read as a table payload (module objects)
        obj = {"__torch_class__": class_name, "fields": self.read_object()}
        self.memo[idx] = obj
        return obj


def load_t7(path: str) -> Any:
    """Read one serialized object from a .t7 file (reference
    ``TorchFile.load``): tensors as numpy arrays, tables as dicts,
    unknown torch classes as {'__torch_class__', 'fields'} wrappers."""
    with open(path, "rb") as f:
        return _Reader(f).read_object()


# -- writer (reference ``TorchFile.scala`` write path) ------------------------

class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self._next_idx = 1

    def _write(self, fmt: str, v) -> None:
        self.f.write(struct.pack(fmt, v))

    def write_object(self, obj: Any) -> None:
        if obj is None:
            self._write("<i", TYPE_NIL)
        elif isinstance(obj, bool):
            self._write("<i", TYPE_BOOLEAN)
            self._write("<i", int(obj))
        elif isinstance(obj, (int, float)):
            self._write("<i", TYPE_NUMBER)
            self._write("<d", float(obj))
        elif isinstance(obj, str):
            self._write("<i", TYPE_STRING)
            data = obj.encode("latin-1")
            self._write("<i", len(data))
            self.f.write(data)
        elif isinstance(obj, np.ndarray):
            self._write("<i", TYPE_TORCH)
            self._write("<i", self._idx())
            cls = {"float32": "torch.FloatTensor", "float64": "torch.DoubleTensor",
                   "int64": "torch.LongTensor", "int32": "torch.IntTensor",
                   "uint8": "torch.ByteTensor"}[str(obj.dtype)]
            self._write_versioned(cls)
            arr = np.ascontiguousarray(obj)
            self._write("<i", arr.ndim)
            for d in arr.shape:
                self._write("<q", d)
            stride = [int(s // arr.itemsize) for s in arr.strides]
            for s in stride:
                self._write("<q", s)
            self._write("<q", 1)  # 1-based storage offset
            # inline storage object
            self._write("<i", TYPE_TORCH)
            self._write("<i", self._idx())
            self._write_versioned(_TENSOR_CLASSES[cls])
            self._write("<q", arr.size)
            self.f.write(arr.tobytes())
        elif isinstance(obj, dict) and "__torch_class__" in obj:
            self._write("<i", TYPE_TORCH)
            self._write("<i", self._idx())
            self._write_versioned(obj["__torch_class__"])
            self.write_object(obj.get("fields", {}))
        elif isinstance(obj, dict):
            self._write("<i", TYPE_TABLE)
            self._write("<i", self._idx())
            self._write("<i", len(obj))
            for k, v in obj.items():
                self.write_object(k)
                self.write_object(v)
        elif isinstance(obj, (list, tuple)):
            self.write_object({i + 1: v for i, v in enumerate(obj)})
        else:
            raise TypeError(f"cannot serialize {type(obj).__name__} to t7")

    def _idx(self) -> int:
        i = self._next_idx
        self._next_idx += 1
        return i

    def _write_versioned(self, class_name: str) -> None:
        for s in ("V 1", class_name):
            data = s.encode("latin-1")
            self._write("<i", len(data))
            self.f.write(data)


def save_t7(path: str, obj: Any) -> str:
    """Write a Torch7 file readable by :func:`load_t7` (and Lua Torch).
    Shared references are not deduplicated (each occurrence serializes
    its own copy) — fine for module trees."""
    with open(path, "wb") as f:
        _Writer(f).write_object(obj)
    return path


# -- legacy torch module tree -> bigdl_tpu module -----------------------------

def _lua_list(table) -> list:
    """Lua array table {1: a, 2: b, ...} -> [a, b, ...]."""
    if table is None:
        return []
    if isinstance(table, (list, tuple)):
        return list(table)
    return [table[k] for k in sorted(k for k in table if isinstance(k, (int, float)))]


def t7_to_module(obj):
    """Convert a loaded legacy-Torch module tree (``load_t7`` output) to
    ``(module, params, state)`` (reference: the ``loadmodel`` example's
    Torch path + ``TorchFile.scala``). Covers the legacy Sequential zoo:
    conv/linear/pooling/BN/LRN/activations/dropout/reshape/view/concat."""
    import jax

    import bigdl_tpu.nn as nn

    loaded_params: Dict[str, Any] = {}

    def conv(module_obj, path):
        f = module_obj["fields"]
        m = nn.SpatialConvolution(
            int(f["nInputPlane"]), int(f["nOutputPlane"]),
            int(f["kW"]), int(f["kH"]), int(f.get("dW", 1)), int(f.get("dH", 1)),
            int(f.get("padW", 0)), int(f.get("padH", 0)))
        w = np.asarray(f["weight"], np.float32)
        if w.ndim == 2:  # MM variant stores (nOut, nIn*kH*kW)
            w = w.reshape(int(f["nOutputPlane"]), int(f["nInputPlane"]),
                          int(f["kH"]), int(f["kW"]))
        entry = {"weight": w}
        if f.get("bias") is not None:
            entry["bias"] = np.asarray(f["bias"], np.float32)
        loaded_params[path] = entry
        return m

    def linear(module_obj, path):
        f = module_obj["fields"]
        w = np.asarray(f["weight"], np.float32)
        m = nn.Linear(w.shape[1], w.shape[0],
                      with_bias=f.get("bias") is not None)
        entry = {"weight": w}
        if f.get("bias") is not None:
            entry["bias"] = np.asarray(f["bias"], np.float32)
        loaded_params[path] = entry
        return m

    def bn(module_obj, path, spatial):
        f = module_obj["fields"]
        n = int(np.asarray(f["running_mean"]).shape[0])
        cls = nn.SpatialBatchNormalization if spatial else nn.BatchNormalization
        m = cls(n, eps=float(f.get("eps", 1e-5)),
                momentum=float(f.get("momentum", 0.1)),
                affine=f.get("weight") is not None)
        entry = {}
        if f.get("weight") is not None:
            entry["weight"] = np.asarray(f["weight"], np.float32)
            entry["bias"] = np.asarray(f["bias"], np.float32)
        if entry:
            loaded_params[path] = entry
        return m

    def pool(module_obj, path, kind):
        f = module_obj["fields"]
        cls = nn.SpatialMaxPooling if kind == "max" else nn.SpatialAveragePooling
        m = cls(int(f["kW"]), int(f["kH"]), int(f.get("dW", 1)),
                int(f.get("dH", 1)), int(f.get("padW", 0)), int(f.get("padH", 0)))
        if f.get("ceil_mode"):
            m.ceil()
        return m

    SIMPLE = {
        "nn.ReLU": lambda o, p: nn.ReLU(),
        "nn.Tanh": lambda o, p: nn.Tanh(),
        "nn.Sigmoid": lambda o, p: nn.Sigmoid(),
        "nn.SoftMax": lambda o, p: nn.SoftMax(),
        "nn.LogSoftMax": lambda o, p: nn.LogSoftMax(),
        "nn.Identity": lambda o, p: nn.Identity(),
        "nn.Dropout": lambda o, p: nn.Dropout(float(o["fields"].get("p", 0.5))),
        "nn.Reshape": lambda o, p: nn.Reshape(
            [int(d) for d in np.asarray(o["fields"]["size"]).reshape(-1)]),
        "nn.View": lambda o, p: nn.View(
            *[int(d) for d in np.asarray(o["fields"]["size"]).reshape(-1)]),
        "nn.SpatialZeroPadding": lambda o, p: nn.SpatialZeroPadding(
            int(o["fields"]["pad_l"]), int(o["fields"]["pad_r"]),
            int(o["fields"]["pad_t"]), int(o["fields"]["pad_b"])),
        "nn.SpatialCrossMapLRN": lambda o, p: nn.SpatialCrossMapLRN(
            int(o["fields"].get("size", 5)),
            float(o["fields"].get("alpha", 1.0)),
            float(o["fields"].get("beta", 0.75)),
            float(o["fields"].get("k", 1.0))),
        "nn.SpatialConvolution": conv,
        "nn.SpatialConvolutionMM": conv,
        "nn.Linear": linear,
        "nn.SpatialBatchNormalization": lambda o, p: bn(o, p, True),
        "nn.BatchNormalization": lambda o, p: bn(o, p, False),
        "nn.SpatialMaxPooling": lambda o, p: pool(o, p, "max"),
        "nn.SpatialAveragePooling": lambda o, p: pool(o, p, "avg"),
    }

    def convert(module_obj, path_parts):
        cls = module_obj.get("__torch_class__", "")
        if cls in ("nn.Sequential", "nn.Concat", "nn.ConcatTable"):
            children = _lua_list(module_obj["fields"].get("modules"))
            if cls == "nn.Concat":
                cont = nn.Concat(int(module_obj["fields"].get("dimension", 2)) - 1)
            elif cls == "nn.ConcatTable":
                cont = nn.ConcatTable()
            else:
                cont = nn.Sequential()
            for i, child in enumerate(children):
                name = str(i)
                cont.add(convert(child, path_parts + [name]), name)
            return cont
        if cls not in SIMPLE:
            raise ValueError(f"no torch-legacy converter for {cls!r}")
        return SIMPLE[cls](module_obj, "/".join(path_parts))

    module = convert(obj, [])
    params, state = module.init(jax.random.key(0))

    def overlay(tree, parts):
        if not isinstance(tree, dict):
            return tree
        repl = loaded_params.get("/".join(parts))
        out = {}
        for k, v in tree.items():
            if repl is not None and k in repl and not isinstance(v, dict):
                arr = np.asarray(repl[k], np.float32)
                if tuple(arr.shape) != tuple(np.shape(v)):
                    raise ValueError(
                        f"t7 weight shape mismatch at {'/'.join(parts)}/{k}: "
                        f"{arr.shape} vs {np.shape(v)}")
                out[k] = arr
            elif isinstance(v, dict):
                out[k] = overlay(v, parts + [k])
            else:
                out[k] = v
        return out

    merged = overlay(params, [])
    return module, merged, state
