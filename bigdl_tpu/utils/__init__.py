from bigdl_tpu.utils.checkpoint import save_checkpoint, load_checkpoint, latest_checkpoint
