from bigdl_tpu.utils.checkpoint import (
    deserialize_payload,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
    serialize_payload,
)
from bigdl_tpu.utils.serializer import (
    SerializationError,
    load_module,
    load_optim_method,
    module_from_spec,
    module_to_spec,
    save_module,
    save_optim_method,
)
