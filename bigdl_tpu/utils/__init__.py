from bigdl_tpu.utils.checkpoint import save_checkpoint, load_checkpoint, latest_checkpoint
from bigdl_tpu.utils.serializer import (
    SerializationError,
    load_module,
    load_optim_method,
    module_from_spec,
    module_to_spec,
    save_module,
    save_optim_method,
)
