"""Overlapped (layer-wise, bucketed) gradient synchronization.

Reference: the reference implements compute/communication overlap in
``DL/optim/ParallelOptimizer.scala:481`` (layer-wise gradient sync
launched as each layer's backward completes) and
``DL/utils/DistriParameterSynchronizer.scala:66-146`` (priority-queued
fetch/reduce threads moving per-layer fp16 blocks while the rest of the
backward still runs).

TPU-native redesign: there are no sync threads to write — the same
schedule property (early buckets' gradients on the wire while later
layers' backward computes) is obtained INSIDE one jitted SPMD program.
Parameters entering the loss are tagged with a ``jax.custom_vjp``
identity per bucket whose backward rule issues the collective — ``psum``
for DDP, ``psum_scatter`` for the ZeRO-1 flavor — at the exact dataflow
point where that bucket's cotangents come into existence. The
collectives therefore sit in the middle of the backward graph carrying
only their true dependencies; the scheduler is free to run the rest of
the backward while the wire is busy, instead of the auto-sharding
baseline where the AllReduceCombiner rolls every gradient into one
all-reduce AFTER the full backward (measured in round 3/4:
``perf/artifacts/overlap_hlo_summary.txt``). ``perf/overlap_sched.py``
AOT-compiles both flavors for a real v5e topology and records the
collective placement as the round-5 artifact.

Gradient-mean semantics: each shard computes the mean loss over its
LOCAL batch rows; the bucket collectives divide the summed cotangents by
the dp axis size, so the resulting gradients equal the global-batch mean
— identical math to the auto-sharded ``DistriOptimizer`` step (equality
tested on the 8-device CPU mesh, ``tests/test_overlap.py``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from bigdl_tpu.parallel._compat import shard_map


# --------------------------------------------------------- bucketing ----

def make_buckets(leaves: Sequence[Any], num_buckets: int) -> List[List[int]]:
    """Group leaf indices into <= num_buckets CONTIGUOUS groups of roughly
    equal byte size. Contiguity in flatten order approximates usage order,
    so each bucket's cotangents become ready at adjacent points of the
    backward — the property layer-wise overlap needs (the reference
    buckets per layer; DistriParameterSynchronizer.scala:96)."""
    sizes = [int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
             if hasattr(l, "shape") else 1 for l in leaves]
    total = sum(sizes)
    if not leaves or num_buckets <= 1 or total == 0:
        return [list(range(len(leaves)))] if leaves else []
    target = total / num_buckets
    buckets: List[List[int]] = [[]]
    acc = 0
    for i, s in enumerate(sizes):
        remaining_buckets = num_buckets - len(buckets)
        if buckets[-1] and acc + s / 2 > target and remaining_buckets > 0:
            buckets.append([])
            acc = 0
        buckets[-1].append(i)
        acc += s
    return buckets


# ----------------------------------------------------- chain gating ----

def _zero_gate(x, dtype):
    """``min(|x|, 0)`` — exactly 0 at runtime but not provably so to the
    algebraic simplifier, so adding it creates a REAL dataflow edge on
    ``x`` that survives XLA's passes. This is the load-bearing
    anti-combiner trick behind the bucket chain: do not replace it with
    ``0.0 * x`` (the simplifier folds that) or ``optimization_barrier``
    (expanded away before the AllReduceCombiner runs, and its diff rule
    only exists on newer jax). Every gating site in this file must use
    this one helper so the idiom cannot drift."""
    return jnp.minimum(jnp.abs(x), 0.0).astype(dtype)


# --------------------------------------------------- DDP bucket psum ----

def _psum_tag(axis_name: str, n: int, wire_dtype=None):
    """custom_vjp identity over ``(token, *leaves)``; backward psums the
    leaf cotangents (one tuple all-reduce per bucket) and divides by the
    axis size — local-mean grads in, global-mean grads out.

    The token threads a data dependency BETWEEN buckets (see the inline
    note in ``bwd``) so the AllReduceCombiner cannot re-merge the
    buckets into one post-backward collective.

    ``wire_dtype`` (e.g. ``jnp.bfloat16``) compresses the collective
    payload — the reference ships per-layer fp16 blocks the same way
    (``DistriParameterSynchronizer.scala:96``); gradients are cast for
    the wire and accumulated back in their original dtype. None = exact.
    """

    @jax.custom_vjp
    def tag(tok, *leaves):
        return (tok, *leaves)

    def fwd(tok, *leaves):
        return (tok, *leaves), None

    def bwd(_, cots):
        tok_cot, *leaf_cots = cots
        dtypes = [g.dtype for g in leaf_cots]
        if wire_dtype is not None:
            leaf_cots = [g.astype(wire_dtype) for g in leaf_cots]
        # chain through the LEAF DATA: every leaf input of this bucket's
        # psum absorbs the zero gate of the token, so bucket i's
        # all-reduce depends directly on bucket i+1's output. Every leaf
        # must be gated: an AR-splitting pass was measured peeling
        # ungated elements out of the bucket and re-combining them.
        # (Three weaker schemes also measured and rejected: a token chain
        # beside the psums, optimization_barrier gating, and a token
        # element inside the psum tuple, which the splitter separated
        # back out; each time the leaf all-reduces were re-merged into
        # one 102 MB post-backward collective.)
        leaf_cots = [g + _zero_gate(tok_cot, g.dtype) for g in leaf_cots]
        summed = lax.psum(tuple(leaf_cots), axis_name)
        # ...and EVERY element's output feeds the outgoing token: with a
        # single-element token source, the combiner was measured peeling
        # the non-source elements out of the bucket (their outputs carry
        # no chain dependency) and merging them into a later bucket's AR
        tok_out = tok_cot + sum(
            _zero_gate(jnp.ravel(g)[0], tok_cot.dtype) for g in summed)
        return (tok_out, *(g.astype(dt) / n
                           for g, dt in zip(summed, dtypes)))

    tag.defvjp(fwd, bwd)
    return tag


def tag_grad_sync(params, axis_name: str, n: int, num_buckets: int = 4,
                  wire_dtype=None):
    """Tag a param pytree so its gradient is synchronized bucket-by-bucket
    during the backward pass. Must run inside ``shard_map`` over
    ``axis_name``. Returns ``(params, token)`` — params unchanged in
    value, plus a scalar token that MUST be folded into the loss (e.g.
    via :func:`fold_token`) so the bucket-chaining dependencies survive.

    Token direction: the forward chain visits buckets FIRST -> LAST, so
    in the backward (cotangent flow reverses it) the LAST bucket — later
    layers, whose cotangents exist earliest — fires first and hands the
    token to the next-earlier bucket as its cotangents become ready: a
    sequential wire schedule in cotangent-availability order, leaving the
    remaining backward free to overlap — exactly the reference's
    priority-queued layer order
    (``DistriParameterSynchronizer.scala:96``)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    out = list(leaves)
    tag = _psum_tag(axis_name, n, wire_dtype)
    tok = jnp.zeros((), leaves[0].dtype if leaves else jnp.float32)
    for idx_group in make_buckets(leaves, num_buckets):
        tok, *synced = tag(tok, *(out[i] for i in idx_group))
        for i, v in zip(idx_group, synced):
            out[i] = v
    return jax.tree_util.tree_unflatten(treedef, out), tok


def fold_token(loss, tok):
    """Attach the chain token to the loss without changing its value
    (:func:`_zero_gate` keeps the dependency alive through the
    simplifier and stays differentiable on every jax version)."""
    return loss + _zero_gate(tok, loss.dtype)


# ------------------------------------------------- ZeRO-1 RS bucket ----

class _BucketLayout:
    """Static flatten/concat layout of one bucket: leaf shapes, dtypes,
    offsets, and the padded per-shard chunk size."""

    def __init__(self, leaves, n):
        self.shapes = [l.shape for l in leaves]
        self.dtypes = [l.dtype for l in leaves]
        self.sizes = [int(np.prod(s)) for s in self.shapes]
        self.total = sum(self.sizes)
        self.chunk = math.ceil(self.total / n) if self.total else 0
        self.padded = self.chunk * n

    def flatten(self, leaves):
        flat = jnp.concatenate(
            [jnp.ravel(l).astype(jnp.float32) for l in leaves])
        if self.padded > self.total:
            flat = jnp.pad(flat, (0, self.padded - self.total))
        return flat

    def unflatten(self, flat):
        outs, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            outs.append(lax.slice_in_dim(flat, off, off + size)
                        .reshape(shape).astype(dtype))
            off += size
        return tuple(outs)


def _rs_tag(axis_name: str, n: int, layout: _BucketLayout):
    """custom_vjp identity whose backward reduce-scatters the bucket's
    flattened cotangents (ZeRO-1 wire pattern: RS in backward, AG of
    updated weights after the optimizer). Each shard's returned cotangent
    holds ONLY its own chunk (zeros elsewhere) — the step slices the
    owned chunk back out; nothing ever reads the zeros. Token chaining as
    in :func:`_psum_tag` (anti-combiner + sequential wire order)."""

    @jax.custom_vjp
    def tag(tok, *leaves):
        return (tok, *leaves)

    def fwd(tok, *leaves):
        return (tok, *leaves), None

    def bwd(_, cots):
        tok_cot, *leaf_cots = cots
        flat = layout.flatten(leaf_cots)
        # chain the collective on the previous bucket's token
        # (:func:`_zero_gate`; see _psum_tag for the measured rationale):
        # the in-place add makes this reduce-scatter's input depend on
        # the previous one's output
        flat = flat.at[0].add(_zero_gate(tok_cot, flat.dtype))
        chunk = lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                 tiled=True) / n
        idx = lax.axis_index(axis_name)
        full = jnp.zeros((layout.padded,), flat.dtype)
        full = lax.dynamic_update_slice(full, chunk, (idx * layout.chunk,))
        tok_cot = tok_cot + _zero_gate(chunk[0], tok_cot.dtype)
        return (tok_cot, *layout.unflatten(full))

    tag.defvjp(fwd, bwd)
    return tag


# -------------------------------------------- module-state reduction ----

#: Per-leaf cross-shard reduction policy for module state after the step.
#: Keyed by the leaf's own dict key: leaves named here reduce with the
#: given collective; every other inexact leaf reduces with ``pmean``
#: (SyncBN-mean running stats). Running EXTREMA must not be averaged:
#: the int8 calibration absmax (``nn/quantized.py`` ``act_absmax``) is a
#: running max over observed activations, and a mean across shards would
#: shrink the calibrated scale as the shard count grows (ADVICE round 5).
STATE_REDUCE_POLICY: Dict[str, str] = {"act_absmax": "max"}


def _reduce_module_state(new_ms, axis_name: str):
    """Cross-shard module-state sync with the per-leaf policy above."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(new_ms)
    out = []
    for path, leaf in flat:
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            out.append(leaf)
            continue
        key = next((p.key for p in reversed(path)
                    if isinstance(p, jax.tree_util.DictKey)), None)
        how = STATE_REDUCE_POLICY.get(key, "mean")
        out.append(lax.pmax(leaf, axis_name) if how == "max"
                   else lax.pmean(leaf, axis_name))
    return jax.tree_util.tree_unflatten(treedef, out)


# ------------------------------------------------------ step builders ----

def make_ddp_overlap_step(model, criterion, method, mesh: Mesh,
                          axis: str = "dp", num_buckets: int = 4,
                          compute_dtype=None, cast_input=None,
                          grad_clip=None, with_rng: bool = False,
                          wire_dtype=None):
    """Data-parallel train step with bucketed overlap-eligible gradient
    all-reduce. Signature: ``step(params, mstate, ostate, x, y, it[, rng])
    -> (params, mstate, ostate, loss)`` with params/state replicated and
    x/y batch-sharded over ``axis``. This is also the engine behind
    ``DistriOptimizer(overlap_buckets=K)`` (which supplies ``cast_input``,
    ``grad_clip`` and ``with_rng`` — keep one implementation of the
    semantics).

    Criterion contract: the loss must be an UNWEIGHTED MEAN over local
    batch rows (``size_average=True``, no per-class ``weights``). The
    bucket collectives divide summed cotangents by the dp axis size,
    which equals the global-batch gradient only under that contract — a
    sum loss is mis-scaled by 1/n and a weighted mean normalizes by the
    local (not global) weight sum. ``DistriOptimizer._build_step``
    enforces this; callers using the builder directly must too.

    Module state (BN running stats) is synced across shards after the
    step with a per-leaf policy (:data:`STATE_REDUCE_POLICY`): means for
    running averages (SyncBN-mean running stats; batch statistics
    themselves stay per-shard — same semantics as torch DDP, a documented
    deviation from the auto-sharded path's exact global statistics), max
    for running extrema like the int8 calibration ``act_absmax``.
    """
    n = mesh.shape[axis]

    def _core(params, mstate, ostate, x, y, it, rng):
        if cast_input is not None:
            x = cast_input(x)
        elif compute_dtype is not None:
            x = x.astype(compute_dtype)

        def loss_fn(p):
            p, tok = tag_grad_sync(p, axis, n, num_buckets, wire_dtype)
            kw = {"rng": rng} if rng is not None else {}
            out, new_ms = model.apply(p, x, state=mstate, training=True, **kw)
            out = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, out)
            return fold_token(criterion.forward(out, y), tok), new_ms

        (loss, new_ms), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        # grads are global means already (bucket psums fired in backward),
        # so grad_clip sees the same values as the auto-sharded path
        if grad_clip is not None:
            grads = grad_clip(grads)
        new_p, new_os = method.update(grads, params, ostate, it)
        new_ms = _reduce_module_state(new_ms, axis)
        return new_p, new_ms, new_os, lax.pmean(loss, axis)

    repl, shard = P(), P(axis)
    if with_rng:
        def _step(params, mstate, ostate, x, y, it, rng):
            # decorrelate per-shard dropout noise (the auto path draws
            # per-row noise from one global key; folding the shard index
            # keeps shards independent — not bit-identical, same law)
            rng = jax.random.fold_in(rng, lax.axis_index(axis))
            return _core(params, mstate, ostate, x, y, it, rng)
        in_specs = (repl, repl, repl, shard, shard, repl, repl)
    else:
        def _step(params, mstate, ostate, x, y, it):
            return _core(params, mstate, ostate, x, y, it, None)
        in_specs = (repl, repl, repl, shard, shard, repl)
    return shard_map(
        _step, mesh=mesh,
        in_specs=in_specs,
        out_specs=(repl, repl, repl, repl),
        check_vma=False,
    )


def zero1_init_state(method, params, mesh: Mesh, axis: str = "dp",
                     num_buckets: int = 4):
    """Per-bucket CHUNKED optimizer state for the ZeRO-1 overlap step:
    each state leaf is a flat (n*chunk,) vector of which every shard owns
    one (chunk,) slice — the reference's PS-partitioned optimizer state
    (``DistriOptimizer.scala:383-390``) as sharded flat vectors. Place
    with :func:`zero1_state_sharding` before use."""
    n = mesh.shape[axis]
    leaves, _ = jax.tree_util.tree_flatten(params)
    states = {}
    for b, idx_group in enumerate(make_buckets(leaves, num_buckets)):
        layout = _BucketLayout([leaves[i] for i in idx_group], n)
        chunk_zeros = jnp.zeros((layout.padded,), jnp.float32)
        states[f"bucket{b}"] = method.init_state({"flat": chunk_zeros})
    return states


def zero1_state_sharding(state, mesh: Mesh, axis: str = "dp"):
    """Shard every (n*chunk,) state vector over the dp axis."""
    sh = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(l, sh) if hasattr(l, "ndim") and l.ndim == 1
        else l, state)


def make_zero1_overlap_step(model, criterion, method, mesh: Mesh,
                            ostate_template, axis: str = "dp",
                            num_buckets: int = 4, compute_dtype=None):
    """ZeRO-1 train step with reduce-scatter-in-backward overlap.

    Wire pattern per bucket: ``psum_scatter`` of the gradient the moment
    the bucket's backward completes (overlap-eligible), an ELEMENTWISE
    optimizer update on the owned 1/n chunk against chunked optimizer
    state, then an ``all_gather`` of the updated weights — exactly the
    reference protocol (gradient reduce-scatter -> per-partition update
    -> weight all-gather, ``DistriOptimizer.scala:323-418``) with XLA
    collectives instead of BlockManager fetches.

    Restriction: the optim method must be elementwise in params/grads
    (SGD/Adam/RMSprop/...); norm-based methods (LARS) would see chunk
    norms. That is the standard ZeRO-1 contract. The criterion contract
    of :func:`make_ddp_overlap_step` applies identically here: an
    unweighted mean loss, because the reduce-scatter divides summed
    cotangents by the dp axis size. Module state syncs with the same
    per-leaf :data:`STATE_REDUCE_POLICY` (mean for running averages, max
    for calibration extrema).

    Signature: ``step(params, mstate, ostate, x, y, it)`` with ``ostate``
    from :func:`zero1_init_state` sharded by :func:`zero1_state_sharding`
    (pass the same object as ``ostate_template`` — its tree structure
    determines the per-leaf shard_map specs: flat vectors dp-sharded,
    scalars like the step count replicated); params/mstate replicated,
    x/y sharded over ``axis``.
    """
    n = mesh.shape[axis]
    state_spec = jax.tree_util.tree_map(
        lambda l: P(axis) if getattr(l, "ndim", 0) >= 1 else P(),
        ostate_template)

    def _step(params, mstate, ostate, x, y, it):
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        buckets = make_buckets(leaves, num_buckets)
        layouts = [_BucketLayout([leaves[i] for i in g], n) for g in buckets]

        def loss_fn(p):
            p_leaves = list(jax.tree_util.tree_flatten(p)[0])
            tok = jnp.zeros((), jnp.float32)
            for g, layout in zip(buckets, layouts):
                tok, *synced = _rs_tag(axis, n, layout)(
                    tok, *(p_leaves[i] for i in g))
                for i, v in zip(g, synced):
                    p_leaves[i] = v
            p = jax.tree_util.tree_unflatten(treedef, p_leaves)
            out, new_ms = model.apply(p, x, state=mstate, training=True)
            out = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, out)
            return fold_token(criterion.forward(out, y), tok), new_ms

        (loss, new_ms), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)

        g_leaves = jax.tree_util.tree_flatten(grads)[0]
        idx = lax.axis_index(axis)
        new_leaves = list(leaves)
        new_ostate = {}
        for b, (group, layout) in enumerate(zip(buckets, layouts)):
            if layout.chunk == 0:
                new_ostate[f"bucket{b}"] = ostate[f"bucket{b}"]
                continue
            gflat = layout.flatten([g_leaves[i] for i in group])
            pflat = layout.flatten([leaves[i] for i in group])
            start = (idx * layout.chunk,)
            gchunk = lax.dynamic_slice(gflat, start, (layout.chunk,))
            pchunk = lax.dynamic_slice(pflat, start, (layout.chunk,))
            new_chunk, new_os = method.update(
                {"flat": gchunk}, {"flat": pchunk},
                ostate[f"bucket{b}"], it)
            new_ostate[f"bucket{b}"] = new_os
            full = lax.all_gather(new_chunk["flat"], axis, tiled=True)
            for i, v in zip(group, layout.unflatten(full)):
                new_leaves[i] = v

        new_p = jax.tree_util.tree_unflatten(treedef, new_leaves)
        new_ms = _reduce_module_state(new_ms, axis)
        return new_p, new_ms, new_ostate, lax.pmean(loss, axis)

    repl, shard = P(), P(axis)
    return shard_map(
        _step, mesh=mesh,
        in_specs=(repl, repl, state_spec, shard, shard, repl),
        out_specs=(repl, repl, state_spec, repl),
        check_vma=False,
    )
