"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

Complement to ring attention (``ring_attention.py``) for long-context
training — absent from the reference (SURVEY.md §5). Where ring attention
keeps the sequence sharded and rotates K/V, Ulysses re-shards: activations
arrive sequence-sharded (each chip holds S/n of every head), an all-to-all
over the ``sp`` axis converts them to head-sharded (each chip holds H/n
heads with the FULL sequence), ordinary (flash) attention runs locally, and
a second all-to-all restores sequence sharding. Two all-to-alls per
attention call, but the inner attention is completely local — best when
heads >= sp and the per-chip full-sequence K/V fits HBM.
"""

from __future__ import annotations

import functools

import jax
from jax import lax

from bigdl_tpu.ops.attention import dot_product_attention


def _a2a(x, axis_name, split_axis, concat_axis):
    """all_to_all keeping (b, h, s, d) rank: split ``split_axis`` across the
    axis group, concatenate the received shards along ``concat_axis``."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      bias=None):
    """Attention on sequence-sharded q/k/v via head<->sequence all-to-all.

    Call inside shard_map. Local shapes (b, h, s_local, d); h must be
    divisible by the size of ``axis_name``.
    """
    n = lax.psum(1, axis_name)
    if q.shape[1] % n:
        raise ValueError(
            f"num_heads ({q.shape[1]}) must be divisible by the "
            f"'{axis_name}' axis size ({n})"
        )
    # seq-sharded -> head-sharded: split heads (axis 1), gather seq (axis 2)
    qh = _a2a(q, axis_name, 1, 2)
    kh = _a2a(k, axis_name, 1, 2)
    vh = _a2a(v, axis_name, 1, 2)
    o = dot_product_attention(qh, kh, vh, bias=bias, causal=causal)
    # head-sharded -> seq-sharded
    return _a2a(o, axis_name, 2, 1)


def make_ulysses_attention(mesh, axis_name: str, causal: bool = False):
    """shard_map wrapper over GLOBAL (b, h, s, d) arrays, seq sharded."""
    from bigdl_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    fn = functools.partial(ulysses_attention, axis_name=axis_name, causal=causal)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)
