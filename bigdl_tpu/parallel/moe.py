"""Mixture-of-Experts with expert parallelism.

The reference has a ``MixtureTable`` gate-combiner (``DL/nn/MixtureTable.scala``)
but no expert parallelism (SURVEY.md §2.3 — EP absent). TPU-native design:
expert weights carry a leading ``[n_experts, ...]`` dim sharded over the
``ep`` mesh axis (declared via ``param_pspecs``); token dispatch/combine are
einsums against one-hot capacity-limited dispatch tensors. Under jit, GSPMD
sees tokens sharded on ``dp``/batch and experts on ``ep`` and inserts the
all-to-all pair automatically — the classic Switch/GShard lowering, no
hand-written collectives.

Router: top-1 (Switch) with capacity factor + auxiliary load-balancing loss
(stashed in module state so trainers can add it to the objective).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bigdl_tpu.core.rng import fold_in_str
from bigdl_tpu.nn.init import Xavier
from bigdl_tpu.nn.module import Context, Module
from bigdl_tpu.parallel.mesh import constrain


class SwitchFFN(Module):
    """Switch-style top-1 MoE FFN: route each token to one expert.

    Input (batch, seq, hidden) -> output same shape. Aux load-balance loss
    is returned via module state key ``aux_loss``.
    """

    def __init__(self, hidden_size: int, filter_size: int, n_experts: int,
                 capacity_factor: float = 1.25, axis: str = "ep",
                 router_noise: float = 0.0):
        super().__init__()
        self.hidden_size = hidden_size
        self.filter_size = filter_size
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.axis = axis
        self.router_noise = router_noise

    def build_params(self, rng):
        xavier = Xavier()
        e, h, f = self.n_experts, self.hidden_size, self.filter_size
        return {
            "router": xavier(fold_in_str(rng, "router"), (h, e), h, e),
            "wi": xavier(fold_in_str(rng, "wi"), (e, h, f), h, f),
            "wo": xavier(fold_in_str(rng, "wo"), (e, f, h), f, h),
        }

    def build_param_pspecs(self):
        return {
            "router": P(),
            "wi": P(self.axis, None, None),
            "wo": P(self.axis, None, None),
        }

    def build_state(self):
        return {"aux_loss": jnp.zeros((), jnp.float32)}

    def forward(self, ctx: Context, x):
        b, s, h = x.shape
        n_tok = b * s
        e = self.n_experts
        cap = max(1, int(self.capacity_factor * n_tok / e))

        tokens = x.reshape(n_tok, h)
        logits = jnp.matmul(tokens.astype(jnp.float32), ctx.param("router"))
        if ctx.training and self.router_noise > 0.0:
            logits = logits + self.router_noise * jax.random.normal(
                ctx.rng(), logits.shape)
        probs = jax.nn.softmax(logits, axis=-1)          # [N, E]
        gate, choice = jnp.max(probs, -1), jnp.argmax(probs, -1)

        # capacity assignment: position of each token within its expert queue
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)      # [N, E]
        pos = jnp.cumsum(onehot, axis=0) - 1                     # [N, E]
        pos_in_expert = jnp.sum(pos * onehot, axis=-1)           # [N]
        keep = pos_in_expert < cap

        # dispatch tensor [N, E, C]: 1 where token n goes to (expert, slot)
        dispatch = (jax.nn.one_hot(choice, e, dtype=x.dtype)[..., None]
                    * jax.nn.one_hot(jnp.where(keep, pos_in_expert, cap), cap,
                                     dtype=x.dtype)[:, None, :])
        combine = dispatch * gate[:, None, None].astype(x.dtype)

        # expert inputs [E, C, H] — GSPMD inserts the all-to-all over ep here
        xin = jnp.einsum("nec,nh->ech", dispatch, tokens)
        xin = constrain(xin, self.axis, None, None)
        wi, wo = ctx.param("wi"), ctx.param("wo")
        hmid = jnp.maximum(jnp.einsum("ech,ehf->ecf", xin, wi.astype(x.dtype)), 0.0)
        xout = jnp.einsum("ecf,efh->ech", hmid, wo.astype(x.dtype))
        xout = constrain(xout, self.axis, None, None)

        out = jnp.einsum("nec,ech->nh", combine, xout)

        # Switch aux loss: E * sum_e (fraction tokens_e * mean prob_e)
        frac = jnp.mean(onehot.astype(jnp.float32), axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        ctx.put_state("aux_loss", e * jnp.sum(frac * mean_prob))

        return out.reshape(b, s, h)


class MoE(SwitchFFN):
    """Alias with the historical name; top-1 Switch routing."""
