"""Parallelism strategies beyond data parallelism.

The reference implements exactly two axes of parallelism — synchronous data
parallelism over a BlockManager parameter server and intra-node thread
replicas (SURVEY.md §2.3 checklist; ``DL/optim/DistriOptimizer.scala``,
``DL/parameters/AllReduceParameter.scala``). Tensor, pipeline,
sequence/context and expert parallelism are absent there. On TPU these are
first-class: a ``jax.sharding.Mesh`` with named axes plus ``shard_map`` and
XLA collectives (psum / all_gather / ppermute / all_to_all) over ICI.

Axis-name conventions used across the framework:

- ``dp``   data parallel (batch dim; gradients psum over it)
- ``fsdp`` parameter/optimizer-state sharding (ZeRO-style)
- ``tp``   tensor (a.k.a. model) parallel — weight-matrix sharding
- ``pp``   pipeline parallel — layer stages
- ``sp``   sequence/context parallel — ring attention over the seq dim
- ``ep``   expert parallel — MoE experts
"""

from bigdl_tpu.parallel.mesh import (
    MeshSpec,
    axis_size,
    constrain,
    current_mesh,
    make_mesh,
    serving_meshes,
    shard_tree,
    tree_shardings,
    use_mesh,
)
from bigdl_tpu.parallel.tp import (
    ColumnParallelLinear,
    RowParallelLinear,
    TensorParallelAttention,
    TensorParallelFFN,
    kv_cache_pspec,
    kv_scale_pspec,
    transformer_tp_pspecs,
)
from bigdl_tpu.parallel.ring_attention import ring_attention
from bigdl_tpu.parallel.ulysses import ulysses_attention
from bigdl_tpu.parallel.pipeline import (
    HeteroPipeline,
    Pipeline,
    make_pp_train_step,
    pipeline_apply,
)
from bigdl_tpu.parallel.moe import MoE, SwitchFFN
from bigdl_tpu.parallel.overlap import (
    fold_token,
    make_buckets,
    make_ddp_overlap_step,
    make_zero1_overlap_step,
    tag_grad_sync,
    zero1_init_state,
    zero1_state_sharding,
)

__all__ = [
    "MeshSpec", "make_mesh", "use_mesh", "current_mesh", "constrain",
    "axis_size", "serving_meshes", "shard_tree", "tree_shardings",
    "ColumnParallelLinear", "RowParallelLinear",
    "TensorParallelAttention", "TensorParallelFFN",
    "kv_cache_pspec", "kv_scale_pspec", "transformer_tp_pspecs",
    "ring_attention", "ulysses_attention",
    "Pipeline", "pipeline_apply", "HeteroPipeline", "make_pp_train_step",
    "MoE", "SwitchFFN",
    "make_buckets", "tag_grad_sync", "fold_token",
    "make_ddp_overlap_step", "make_zero1_overlap_step",
    "zero1_init_state", "zero1_state_sharding",
]
