"""shard_map across jax versions.

Newer jax exports ``jax.shard_map`` (with ``check_vma``); 0.4.x ships it
as ``jax.experimental.shard_map.shard_map`` (with ``check_rep``, the
older name for the same replication/varying-manual-axes check). The
parallel tier targets the new spelling; this shim keeps it importable —
and the mesh/overlap tests runnable — on the 0.4.x images too.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)

except ImportError:  # jax < 0.5: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
