"""Tensor (model) parallelism: Megatron-style sharded linears and blocks.

Absent from the reference (SURVEY.md §2.3 — no TP). TPU-native design:
weights carry ``PartitionSpec`` annotations (via ``Module.param_pspecs``)
and activations get ``with_sharding_constraint`` hints; XLA's GSPMD
partitioner inserts the all-gather / reduce-scatter collectives over the
``tp`` ICI axis. No explicit collective calls are needed in the forward —
the column-parallel -> row-parallel pairing means the only communication is
one psum at the row-parallel output, which GSPMD derives automatically.

Pattern (Megatron-LM, adapted to the jax/GSPMD idiom):

- ``ColumnParallelLinear``: weight (out, in) sharded on ``out`` -> output
  activation sharded on the feature dim; no comm.
- ``RowParallelLinear``: weight (out, in) sharded on ``in`` -> consumes a
  feature-sharded activation, produces a replicated (psum-ed) output.
- FFN = column(hidden->4h) . gelu . row(4h->hidden): one collective total.
- Attention: QKV projections column-parallel (heads shard over tp), output
  projection row-parallel.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from jax.sharding import Mesh

from bigdl_tpu.nn.init import Xavier, Zeros
from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.module import Context, Module
from bigdl_tpu.ops.attention import dot_product_attention
from bigdl_tpu.parallel.mesh import (
    UNCONSTRAINED,
    axis_size,
    constrain,
    current_mesh,
)


class ColumnParallelLinear(Linear):
    """Linear whose (out, in) weight is sharded along ``out`` over ``axis``."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 axis: str = "tp", **kw):
        super().__init__(input_size, output_size, with_bias, **kw)
        self.axis = axis

    def build_param_pspecs(self):
        specs = {"weight": P(self.axis, None)}
        if self.with_bias:
            specs["bias"] = P(self.axis)
        return specs

    def forward(self, ctx: Context, x):
        w = ctx.param("weight")
        y = jnp.matmul(x, w.T.astype(x.dtype))
        if self.with_bias:
            y = y + ctx.param("bias").astype(y.dtype)
        # output features live on the tp axis; batch/seq dims left to GSPMD
        return constrain(y, *([UNCONSTRAINED] * (y.ndim - 1) + [self.axis]))


class RowParallelLinear(Linear):
    """Linear whose (out, in) weight is sharded along ``in`` over ``axis``."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 axis: str = "tp", **kw):
        super().__init__(input_size, output_size, with_bias, **kw)
        self.axis = axis

    def build_param_pspecs(self):
        specs = {"weight": P(None, self.axis)}
        if self.with_bias:
            specs["bias"] = P()
        return specs

    def forward(self, ctx: Context, x):
        # input features arrive sharded on tp (from a column-parallel layer)
        x = constrain(x, *([UNCONSTRAINED] * (x.ndim - 1) + [self.axis]))
        w = ctx.param("weight")
        y = jnp.matmul(x, w.T.astype(x.dtype))  # GSPMD: partial sums -> psum
        # feature dim replicated (forces the psum here); batch/seq dims free
        y = constrain(y, *([UNCONSTRAINED] * (y.ndim - 1) + [None]))
        if self.with_bias:
            y = y + ctx.param("bias").astype(y.dtype)
        return y


class TensorParallelFFN(Module):
    """Transformer FFN with Megatron sharding: one collective per block.

    Mirrors the math of ``FeedForwardNetwork`` (reference:
    ``DL/nn/FeedForwardNetwork.scala``) with tp-sharded weights.
    """

    def __init__(self, hidden_size: int, filter_size: int, axis: str = "tp",
                 activation=None):
        super().__init__()
        self.up = ColumnParallelLinear(hidden_size, filter_size, axis=axis,
                                       weight_init=Xavier(), bias_init=Zeros())
        self.down = RowParallelLinear(filter_size, hidden_size, axis=axis,
                                      weight_init=Xavier(), bias_init=Zeros())
        self.activation = activation

    def forward(self, ctx: Context, x):
        h = self.run_child(ctx, "up", x)
        h = jnp.maximum(h, 0.0) if self.activation is None else self.activation(h)
        return self.run_child(ctx, "down", h)


class TensorParallelAttention(Module):
    """Multi-head attention with heads sharded over the tp axis.

    QKV projections are column-parallel (each tp shard owns
    ``num_heads / tp`` heads end-to-end), output projection row-parallel.
    The head-sharded layout also composes with sequence parallelism: pass
    ``sp_axis`` to additionally shard the sequence dim of activations.
    """

    def __init__(self, hidden_size: int, num_heads: int, axis: str = "tp",
                 sp_axis: Optional[str] = None, attention_dropout: float = 0.0):
        super().__init__()
        if hidden_size % num_heads:
            raise ValueError("num_heads must divide hidden_size")
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        self.axis = axis
        self.sp_axis = sp_axis
        self.attention_dropout = attention_dropout
        for name in ("q", "k", "v"):
            self.add(ColumnParallelLinear(hidden_size, hidden_size, with_bias=False,
                                          axis=axis, weight_init=Xavier()), name)
        self.add(RowParallelLinear(hidden_size, hidden_size, with_bias=False,
                                   axis=axis, weight_init=Xavier()), "out")

    def _heads(self, t):
        b, s, _ = t.shape
        t = t.reshape(b, s, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        return constrain(t, UNCONSTRAINED, self.axis, self.sp_axis or UNCONSTRAINED,
                         UNCONSTRAINED)

    def forward(self, ctx: Context, x, bias=None, causal: bool = False):
        q = self._heads(self.run_child(ctx, "q", x))
        k = self._heads(self.run_child(ctx, "k", x))
        v = self._heads(self.run_child(ctx, "v", x))
        # Under an active mesh the heads/sequence dims are sharded; the
        # Pallas flash kernel is a Mosaic custom call with no GSPMD
        # partitioning rule, so force the XLA einsum path there (XLA
        # partitions it and inserts the collectives). Single-chip keeps the
        # auto-selected flash kernel.
        use_flash = False if current_mesh() is not None else None
        o = dot_product_attention(
            q, k, v, bias=bias, causal=causal,
            dropout_rate=self.attention_dropout if ctx.training else 0.0,
            dropout_rng=ctx.rng() if (ctx.training and self.attention_dropout) else None,
            use_flash=use_flash,
        )
        o = constrain(o, UNCONSTRAINED, self.axis, self.sp_axis or UNCONSTRAINED,
                      UNCONSTRAINED)
        b, h, s, d = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        return self.run_child(ctx, "out", o)


# --------------------------------------------------------------------------
# Serving-side tensor parallelism: Megatron pspecs for an ``nn.Transformer``.
#
# The serving tier decodes through ``nn.Transformer``'s incremental API
# (prefill / decode_step and their paged twins), whose layers are plain
# ``Linear``s with no sharding annotations. These helpers map that exact
# parameter tree onto the column->row pattern the classes above implement
# for training, so an ``InferenceService``/``GenerationEngine`` can pjit
# the SAME kernels over tensor-parallel weights: q/k/v projections shard
# like :class:`ColumnParallelLinear` (each tp shard owns
# ``num_heads / tp`` heads end to end, which is also how the KV cache
# shards), attention output + FFN down projection like
# :class:`RowParallelLinear` (the two psums per block), embeddings and
# norms replicated. GSPMD derives every collective from the weight
# shardings alone — the serving model source is untouched.


def kv_cache_pspec(axis: str = "tp") -> P:
    """PartitionSpec for serving KV caches, dense or paged: both are
    ``(slots|pages, heads, rows, head_dim)`` per layer, sharded on the
    HEADS axis — the same per-head ownership the column-parallel q/k/v
    projections produce, so cache reads/writes need no collective."""
    return P(None, axis)


def kv_scale_pspec() -> P:
    """PartitionSpec for the int8 KV pools' per-token scale pools
    (``(num_pages, page_size)`` fp32, ``init_paged_cache`` with
    ``dtype="int8"``): REPLICATED. Scales are shared across heads, so
    they have no heads axis to shard on; the write-side cross-head absmax
    becomes one tiny all-reduce max GSPMD derives — an exact reduction,
    so sharded and single-device int8 quantization agree bitwise."""
    return P()


def transformer_tp_pspecs(model, mesh: Optional[Mesh] = None,
                          axis: str = "tp", params=None):
    """Sparse Megatron PartitionSpec tree for an ``nn.Transformer``'s
    params (LANGUAGE_MODEL mode — the serving decode surface).

    Returns only the sharded leaves (``parallel.mesh.tree_shardings``
    replicates everything else: embedding, norms, output biases). With a
    ``mesh``, validates that the ``axis`` size divides ``num_heads`` —
    attention is parallel over whole heads, never head fractions.

    Pass the actual ``params`` tree to cover an int8 serving tree
    (``nn.quantized.quantize_for_serving``): ``weight_q`` shards exactly
    like ``weight``, and the per-output-channel ``scale`` vector follows
    its channels — sharded over ``axis`` for column-parallel layers
    (each shard rescales the heads it owns), replicated for
    row-parallel ones (their output channels are not sharded; the s32
    partial sums psum exactly, so sharded int8 GEMMs stay bitwise equal
    to single-device).
    """
    from bigdl_tpu.nn.layers.attention import LANGUAGE_MODEL, Transformer

    if not isinstance(model, Transformer):
        raise TypeError(
            f"transformer_tp_pspecs needs an nn.Transformer, got "
            f"{type(model).__name__}; pass explicit param_pspecs for "
            f"other model families")
    if model.transformer_type != LANGUAGE_MODEL:
        raise ValueError("serving tensor parallelism covers language_model "
                         "(decoder-only) transformers")
    if mesh is not None:
        tp = axis_size(mesh, axis)
        if model.num_heads % tp:
            raise ValueError(
                f"mesh axis '{axis}' size {tp} must divide num_heads "
                f"{model.num_heads} (heads shard whole, like "
                f"TensorParallelAttention)")
    quantized = False
    if params is not None:
        first = next((n for n in model.modules
                      if n.startswith("decoder_")), None)
        try:
            leaf = params[first]["self_attention"]["inner"]["q_layer"]
            quantized = "weight_q" in leaf
        except (KeyError, TypeError):
            quantized = False
    if quantized:
        col = {"weight_q": P(axis, None), "scale": P(axis)}
        row = {"weight_q": P(None, axis), "scale": P()}
        ffn_up = {"weight_q": P(axis, None), "scale": P(axis),
                  "bias": P(axis)}
        ffn_down = {"weight_q": P(None, axis), "scale": P(), "bias": P()}
    else:
        col = {"weight": P(axis, None)}   # ColumnParallelLinear pattern
        row = {"weight": P(None, axis)}   # RowParallelLinear pattern
        ffn_up = {"weight": P(axis, None), "bias": P(axis)}
        ffn_down = {"weight": P(None, axis), "bias": P()}
    attn = {"inner": {"q_layer": col, "k_layer": col, "v_layer": col,
                      "output_layer": row}}
    ffn = {"inner": {"filter_layer": ffn_up, "output_layer": ffn_down}}
    layer = {"self_attention": attn, "ffn": ffn}
    return {name: layer for name in model.modules
            if name.startswith("decoder_")}
