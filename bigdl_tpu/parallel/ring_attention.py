"""Ring attention: exact attention over sequences sharded across chips.

Absent from the reference — its longest-sequence story is padding +
single-node BPTT (SURVEY.md §5 "Long-context / sequence parallelism:
Absent", ``DL/dataset/MiniBatch.scala:523-587``). On TPU, long context is a
first-class axis: the sequence dim is sharded over the ``sp`` mesh axis,
each chip holds its local Q block permanently, and K/V blocks rotate around
the ring via ``ppermute`` while an online-softmax accumulator (running max
``m`` and normalizer ``l``, exactly the flash-attention statistics) merges
each visiting block. Peak memory per chip is O(S/n * S_block) instead of
O(S^2); communication is n-1 ppermute hops that overlap with compute on
real ICI rings.

Causal handling is by block index: a visiting K/V block strictly *after*
my Q block contributes nothing (skipped via masking), the diagonal block
applies the triangular mask, earlier blocks attend fully.

API: ``ring_attention(q, k, v, axis_name, causal=...)`` must be called
*inside* a ``shard_map`` whose mesh has ``axis_name``; q/k/v are the local
shards, shape (batch, heads, seq_local, head_dim).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


from bigdl_tpu.parallel.mesh import mark_varying as _mark_varying
from bigdl_tpu.parallel.mesh import ring_perm


def _block_attend(q, k, v, scale, mask):
    """Scores + masked partial softmax stats for one (q_block, kv_block) pair.

    Returns (numerator [b,h,sq,d], row max m [b,h,sq], row sum l [b,h,sq]).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    num = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return num, m, l


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: float | None = None):
    """Exact attention with K/V rotated around the ``axis_name`` ring.

    Call inside shard_map; q/k/v: (b, h, s_local, d) local shards with the
    global sequence laid out contiguously along the mesh axis.
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    b, h, sq, d = q.shape
    perm = ring_perm(n)

    qf = q.astype(jnp.float32)

    def step(carry, i):
        k_cur, v_cur, num, m, l = carry
        src = (my_idx - i) % n  # global block index of the visiting K/V

        s_mask = None
        if causal:
            # rows: global positions my_idx*sq + [0,sq); cols: src*sq + [0,sq)
            rows = my_idx * sq + jnp.arange(sq)
            cols = src * sq + jnp.arange(k_cur.shape[2])
            s_mask = rows[:, None] >= cols[None, :]

        bnum, bm, bl = _block_attend(qf, k_cur, v_cur, scale, s_mask)
        if causal:
            # a fully-masked block yields m = -inf rows; guard the merge
            dead = src * sq > my_idx * sq + sq - 1  # block strictly after mine
        else:
            dead = False

        new_m = jnp.maximum(m, bm)
        alpha = jnp.exp(m - new_m)
        beta = jnp.exp(bm - new_m)
        num2 = num * alpha[..., None] + bnum * beta[..., None]
        l2 = l * alpha + bl * beta
        num2, m2, l2 = jax.tree_util.tree_map(
            lambda new, old: jnp.where(dead, old, new) if causal else new,
            (num2, new_m, l2), (num, m, l),
        )

        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, num2, m2, l2), None

    num0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    # mark the accumulators device-varying over the ring axis so the scan
    # carry types line up with the (varying) k/v shards
    num0, m0, l0 = (_mark_varying(t, axis_name) for t in (num0, m0, l0))
    (k_f, v_f, num, m, l), _ = lax.scan(
        step, (k, v, num0, m0, l0), jnp.arange(n)
    )
    out = num / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def make_ring_attention(mesh, axis_name: str, causal: bool = False):
    """Wrap ``ring_attention`` in a shard_map over ``mesh``.

    Returns a function (q, k, v) -> out operating on GLOBAL arrays whose
    sequence dim (axis 2) is sharded over ``axis_name``.
    """
    from bigdl_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)
