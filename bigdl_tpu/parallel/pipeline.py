"""Pipeline parallelism: GPipe-style microbatched stage execution.

Absent from the reference (SURVEY.md §2.3 — no PP). TPU-native design:
stage parameters are stacked along a leading ``[n_stages, ...]`` dim that is
sharded over the ``pp`` mesh axis, so each chip physically holds exactly one
stage's weights. A ``shard_map`` runs the classic GPipe schedule: for
``n_micro + n_stages - 1`` ticks, every chip applies its stage to the
activation it holds and ``ppermute``s the result to the next chip. The
schedule is a ``lax.scan`` (static trip count — XLA-friendly), and the whole
thing is reverse-differentiable: the transpose of ``ppermute`` is the
reverse ppermute, so ``jax.grad`` of a pipelined loss yields the standard
backward pipeline schedule automatically.

This mirrors the collective-pipelining recipe of the public scaling
literature rather than anything in the reference, whose only scale-out axis
is data parallelism over the BlockManager PS (SURVEY.md §3.1).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from bigdl_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.mesh import mark_varying, ring_perm


def _stage_body(stage_fn, n_stages, n_micro, axis_name, params, xs):
    """Per-chip GPipe schedule. ``params``: this chip's stage params (leading
    stage dim of size 1, squeezed). ``xs``: [n_micro, ...] microbatches
    (meaningful on stage 0; other chips carry zeros)."""
    stage = lax.axis_index(axis_name)
    n = n_stages
    total = n_micro + n - 1
    perm = ring_perm(n)

    micro_shape = xs.shape[1:]
    out0 = jnp.zeros((n_micro,) + micro_shape, xs.dtype)
    recv0 = jnp.zeros(micro_shape, xs.dtype)
    out0 = mark_varying(out0, axis_name)
    recv0 = mark_varying(recv0, axis_name)
    xs = mark_varying(xs, axis_name)

    def tick(carry, t):
        recv, outs = carry
        # stage 0 feeds microbatch t (clipped; masked out when t >= n_micro)
        feed = xs[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(stage == 0, feed, recv)
        y = stage_fn(params, x_in)
        # last stage banks output for microbatch t-(n-1)
        widx = t - (n - 1)
        wclip = jnp.clip(widx, 0, n_micro - 1)
        bank = jnp.where((stage == n - 1) & (widx >= 0), y, outs[wclip])
        outs = lax.dynamic_update_index_in_dim(outs, bank, wclip, 0)
        recv_next = lax.ppermute(y, axis_name, perm)
        return (recv_next, outs), None

    (recv, outs), _ = lax.scan(tick, (recv0, out0), jnp.arange(total))
    # deliver outputs from the last stage to every chip (so the caller can
    # compute a replicated loss); psum of a one-hot-masked bank
    outs = lax.psum(
        jnp.where(stage == n - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   mesh: Mesh,
                   stacked_params: Any,
                   x: jax.Array,
                   n_micro: int,
                   axis_name: str = "pp"):
    """Run ``x`` through a pipeline of stages over ``mesh[axis_name]``.

    - ``stage_fn(params_i, x_micro) -> y_micro`` — one stage's computation;
      every stage must map the same activation shape to itself.
    - ``stacked_params``: pytree whose leaves have leading dim n_stages,
      sharded over ``axis_name``.
    - ``x``: [batch, ...] global batch; must divide into ``n_micro``
      microbatches.

    Returns [batch, ...] outputs (replicated over the pp axis).
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible into {n_micro} microbatches")
    xs = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    body = functools.partial(_stage_body, stage_fn, n_stages, n_micro,
                             axis_name)

    def per_chip(params, xs_local):
        squeezed = jax.tree_util.tree_map(lambda a: a[0], params)
        return body(squeezed, xs_local)

    fn = shard_map(per_chip, mesh=mesh,
                   in_specs=(param_specs, P()),
                   out_specs=P())
    ys = fn(stacked_params, xs)
    return ys.reshape((b,) + ys.shape[2:])


def _hetero_body(stage_fns, n_stages, n_micro, axis_name,
                 params, states, xs, rng, training):
    """Per-chip GPipe schedule for HETEROGENEOUS, STATEFUL stages.

    Differences from :func:`_stage_body`:

    - the stage computation is a ``lax.switch`` on the chip's pp index
      over per-stage branches, so stages may be arbitrary distinct
      modules (params/state held in a ``{"stage{i}": ...}`` dict,
      replicated — the memory trade documented in ``HeteroPipeline``);
    - module state (BN running stats, ...) is threaded through the scan
      carry, with updates COMMITTED only on valid ticks (a chip at pp
      index s is warming up while ``t < s`` and draining while
      ``t - s >= n_micro``; its garbage computations must not pollute
      running statistics);
    - a per-(stage, microbatch) rng is folded for dropout streams,
      matching the sequential-microbatch reference semantics.
    """
    stage = lax.axis_index(axis_name)
    n = n_stages
    total = n_micro + n - 1
    perm = ring_perm(n)

    micro_shape = xs.shape[1:]
    out0 = mark_varying(jnp.zeros((n_micro,) + micro_shape, xs.dtype), axis_name)
    recv0 = mark_varying(jnp.zeros(micro_shape, xs.dtype), axis_name)
    xs = mark_varying(xs, axis_name)
    states = jax.tree_util.tree_map(
        lambda a: mark_varying(a, axis_name), states)

    def branches(i):
        def br(x, st, key):
            y, ns_i = stage_fns[i](params[f"stage{i}"], x,
                                   st[f"stage{i}"], key, training)
            return y, {**st, f"stage{i}": ns_i}
        return br

    brs = [branches(i) for i in range(n)]

    def tick(carry, t):
        recv, outs, st = carry
        feed = xs[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(stage == 0, feed, recv)
        # the microbatch this chip touches at tick t, and its validity
        midx = jnp.clip(t - stage, 0, n_micro - 1)
        valid = (t >= stage) & (t - stage < n_micro)
        key = None
        if rng is not None:
            key = jax.random.fold_in(jax.random.fold_in(rng, stage), midx)
        y, new_st = lax.switch(stage, brs, x_in, st, key)
        st = jax.tree_util.tree_map(
            lambda a, b: jnp.where(valid, a, b), new_st, st)
        widx = t - (n - 1)
        wclip = jnp.clip(widx, 0, n_micro - 1)
        bank = jnp.where((stage == n - 1) & (widx >= 0), y, outs[wclip])
        outs = lax.dynamic_update_index_in_dim(outs, bank, wclip, 0)
        recv_next = lax.ppermute(y, axis_name, perm)
        return (recv_next, outs, st), None

    (recv, outs, states), _ = lax.scan(
        tick, (recv0, out0, states), jnp.arange(total))
    outs = lax.psum(
        jnp.where(stage == n - 1, outs, jnp.zeros_like(outs)), axis_name)
    # merge state: stage i's entries are authoritative on chip i only
    merged = {}
    for i in range(n):
        merged[f"stage{i}"] = jax.tree_util.tree_map(
            lambda a: lax.psum(jnp.where(stage == i, a, jnp.zeros_like(a)),
                               axis_name),
            states[f"stage{i}"])
    return outs, merged


class HeteroPipeline:
    """Trainable pipeline over a HETEROGENEOUS list of stage modules with
    mutable state (BatchNorm running stats), dropout rng, and an optional
    remat mode.

    Semantics: identical to running the microbatches SEQUENTIALLY through
    ``stages[0] .. stages[n-1]`` on one device with the module state
    threaded micro-by-micro (each microbatch is normalized by its own
    batch statistics — grad-accumulation/ghost-BN semantics; equality
    tested in ``tests/test_parallel.py``).

    Placement trade (documented): per-stage params are REPLICATED over
    the pp axis and selected by ``lax.switch`` — heterogeneous pytrees
    cannot be stacked-and-sharded like :class:`Pipeline`'s homogeneous
    stages, so this class buys arbitrary stage structure at the price of
    per-chip weight memory. Use :class:`Pipeline` when the stages are
    one repeated block; use this when they are not.

    ``remat=True`` wraps each stage application in ``jax.checkpoint`` so
    the backward pipeline (the scan's transpose — ppermutes reverse
    automatically) recomputes stage internals instead of saving them:
    per-tick residuals shrink to the stage INPUT, the memory property
    1F1B schedules exist for. A hand-interleaved 1F1B would fight XLA's
    scheduler for decisions it owns (SURVEY §7: static schedules belong
    to the compiler); the scan transpose already yields the reverse
    pipeline order.
    """

    def __init__(self, stages, mesh: Mesh, n_micro: int,
                 axis_name: str = "pp", remat: bool = False):
        self.stages = list(stages)
        self.mesh = mesh
        self.n_micro = n_micro
        self.axis_name = axis_name
        self.remat = remat
        self.n_stages = mesh.shape[axis_name]
        if len(self.stages) != self.n_stages:
            raise ValueError(
                f"{len(self.stages)} stage modules for a "
                f"{self.n_stages}-way '{axis_name}' mesh axis")

    def init(self, rng):
        params, states = {}, {}
        for i, (m, k) in enumerate(
                zip(self.stages, jax.random.split(rng, self.n_stages))):
            p, s = m.init(k)
            params[f"stage{i}"] = p
            states[f"stage{i}"] = s
        return params, states

    def _stage_fns(self):
        fns = []
        for m in self.stages:
            def fn(p, x, s, key, training, m=m):
                out, ns = m.apply(p, x, state=s, training=training, rng=key)
                return out, ns
            fns.append(jax.checkpoint(fn, static_argnums=(4,))
                       if self.remat else fn)
        return fns

    def apply(self, params, states, x, training: bool = False, rng=None):
        """Returns ``(outputs [batch, ...], new_states)`` — both
        replicated over the pp axis.

        Constraint (inherent to the ring schedule): every stage must map
        a microbatch to the SAME shape and dtype — the ppermute buffers
        are sized once from the input. Width-changing stages need an
        embedding into a common activation shape.
        """
        n = self.n_stages
        b = x.shape[0]
        if b % self.n_micro:
            raise ValueError(
                f"batch {b} not divisible into {self.n_micro} microbatches")
        mb = (b // self.n_micro,) + x.shape[1:]
        xm = jax.ShapeDtypeStruct(mb, x.dtype)
        for i, m in enumerate(self.stages):
            # probe in eval mode: shapes are identical and no rng is
            # needed (Dropout in training mode would demand one)
            out_sd = jax.eval_shape(
                lambda p, s, a, m=m: m.apply(p, a, state=s,
                                             training=False)[0],
                params[f"stage{i}"], states[f"stage{i}"], xm)
            if out_sd.shape != mb or out_sd.dtype != x.dtype:
                raise ValueError(
                    f"pipeline stage {i} maps {mb}/{x.dtype} -> "
                    f"{out_sd.shape}/{out_sd.dtype}; every stage must "
                    "preserve the microbatch shape and dtype (the ring "
                    "schedule's buffers are sized once from the input)")
        xs = x.reshape((self.n_micro, b // self.n_micro) + x.shape[1:])
        body = functools.partial(
            _hetero_body, self._stage_fns(), n, self.n_micro, self.axis_name)

        def per_chip(params, states, xs_local, rng_in):
            return body(params, states, xs_local, rng_in, training)

        repl = P()
        fn = shard_map(per_chip, mesh=self.mesh,
                       in_specs=(repl, repl, repl, repl),
                       out_specs=(repl, repl),
                       check_vma=False)
        ys, new_states = fn(params, states, xs, rng)
        return ys.reshape((b,) + ys.shape[2:]), new_states


def make_pp_train_step(pipeline: "HeteroPipeline", criterion, method):
    """One jittable train step over a :class:`HeteroPipeline`:
    ``step(params, states, ostate, x, y, it[, rng]) ->
    (params, states, ostate, loss)``. Gradients flow through the
    ppermute schedule (its transpose is the reverse pipeline); cotangent
    psums for the replicated stage params are inserted by shard_map's
    transpose automatically."""

    def step(params, states, ostate, x, y, it, rng=None):
        def loss_fn(p):
            ys, ns = pipeline.apply(p, states, x, training=True, rng=rng)
            ys = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, ys)
            return criterion.forward(ys, y), ns

        (loss, new_states), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_p, new_os = method.update(grads, params, ostate, it)
        return new_p, new_states, new_os, loss

    return jax.jit(step)


class Pipeline:
    """Convenience wrapper: stack per-stage params and apply the schedule.

    ``Pipeline(module, mesh, n_micro)`` treats ``module`` as ONE repeated
    stage (the homogeneous-stage case — e.g. a transformer block repeated
    ``pp`` times). ``init`` builds per-stage params stacked on dim 0 with
    per-stage RNG streams; ``apply`` runs the GPipe schedule.
    """

    def __init__(self, stage_module, mesh: Mesh, n_micro: int,
                 axis_name: str = "pp"):
        self.stage = stage_module
        self.mesh = mesh
        self.n_micro = n_micro
        self.axis_name = axis_name
        self.n_stages = mesh.shape[axis_name]

    def init(self, rng):
        keys = jax.random.split(rng, self.n_stages)
        inits = [self.stage.init(k) for k in keys]
        if any(s for _, s in inits):
            raise ValueError(
                "Pipeline (stacked homogeneous stages) does not thread "
                "mutable state through the schedule. Use HeteroPipeline, "
                "which supports stateful stages (BatchNorm running stats), "
                "dropout rng, and heterogeneous stage lists."
            )
        ps = [p for p, _ in inits]
        stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *ps)
        sharding = jax.tree_util.tree_map(
            lambda _: jax.sharding.NamedSharding(self.mesh, P(self.axis_name)),
            stacked)
        return jax.tree_util.tree_map(jax.device_put, stacked, sharding)

    def apply(self, stacked_params, x):
        def stage_fn(p, xm):
            out, _ = self.stage.apply(p, xm)
            return out

        return pipeline_apply(stage_fn, self.mesh, stacked_params, x,
                              self.n_micro, self.axis_name)
