"""Pipeline parallelism: GPipe-style microbatched stage execution.

Absent from the reference (SURVEY.md §2.3 — no PP). TPU-native design:
stage parameters are stacked along a leading ``[n_stages, ...]`` dim that is
sharded over the ``pp`` mesh axis, so each chip physically holds exactly one
stage's weights. A ``shard_map`` runs the classic GPipe schedule: for
``n_micro + n_stages - 1`` ticks, every chip applies its stage to the
activation it holds and ``ppermute``s the result to the next chip. The
schedule is a ``lax.scan`` (static trip count — XLA-friendly), and the whole
thing is reverse-differentiable: the transpose of ``ppermute`` is the
reverse ppermute, so ``jax.grad`` of a pipelined loss yields the standard
backward pipeline schedule automatically.

This mirrors the collective-pipelining recipe of the public scaling
literature rather than anything in the reference, whose only scale-out axis
is data parallelism over the BlockManager PS (SURVEY.md §3.1).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.mesh import mark_varying, ring_perm


def _stage_body(stage_fn, n_stages, n_micro, params, xs):
    """Per-chip GPipe schedule. ``params``: this chip's stage params (leading
    stage dim of size 1, squeezed). ``xs``: [n_micro, ...] microbatches
    (meaningful on stage 0; other chips carry zeros)."""
    stage = lax.axis_index("pp")
    n = n_stages
    total = n_micro + n - 1
    perm = ring_perm(n)

    micro_shape = xs.shape[1:]
    out0 = jnp.zeros((n_micro,) + micro_shape, xs.dtype)
    recv0 = jnp.zeros(micro_shape, xs.dtype)
    out0 = mark_varying(out0, "pp")
    recv0 = mark_varying(recv0, "pp")
    xs = mark_varying(xs, "pp")

    def tick(carry, t):
        recv, outs = carry
        # stage 0 feeds microbatch t (clipped; masked out when t >= n_micro)
        feed = xs[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(stage == 0, feed, recv)
        y = stage_fn(params, x_in)
        # last stage banks output for microbatch t-(n-1)
        widx = t - (n - 1)
        wclip = jnp.clip(widx, 0, n_micro - 1)
        bank = jnp.where((stage == n - 1) & (widx >= 0), y, outs[wclip])
        outs = lax.dynamic_update_index_in_dim(outs, bank, wclip, 0)
        recv_next = lax.ppermute(y, "pp", perm)
        return (recv_next, outs), None

    (recv, outs), _ = lax.scan(tick, (recv0, out0), jnp.arange(total))
    # deliver outputs from the last stage to every chip (so the caller can
    # compute a replicated loss); psum of a one-hot-masked bank
    outs = lax.psum(jnp.where(stage == n - 1, outs, jnp.zeros_like(outs)), "pp")
    return outs


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   mesh: Mesh,
                   stacked_params: Any,
                   x: jax.Array,
                   n_micro: int,
                   axis_name: str = "pp"):
    """Run ``x`` through a pipeline of stages over ``mesh[axis_name]``.

    - ``stage_fn(params_i, x_micro) -> y_micro`` — one stage's computation;
      every stage must map the same activation shape to itself.
    - ``stacked_params``: pytree whose leaves have leading dim n_stages,
      sharded over ``axis_name``.
    - ``x``: [batch, ...] global batch; must divide into ``n_micro``
      microbatches.

    Returns [batch, ...] outputs (replicated over the pp axis).
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible into {n_micro} microbatches")
    xs = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    body = functools.partial(_stage_body, stage_fn, n_stages, n_micro)

    def per_chip(params, xs_local):
        squeezed = jax.tree_util.tree_map(lambda a: a[0], params)
        return body(squeezed, xs_local)

    fn = shard_map(per_chip, mesh=mesh,
                   in_specs=(param_specs, P()),
                   out_specs=P())
    ys = fn(stacked_params, xs)
    return ys.reshape((b,) + ys.shape[2:])


class Pipeline:
    """Convenience wrapper: stack per-stage params and apply the schedule.

    ``Pipeline(module, mesh, n_micro)`` treats ``module`` as ONE repeated
    stage (the homogeneous-stage case — e.g. a transformer block repeated
    ``pp`` times). ``init`` builds per-stage params stacked on dim 0 with
    per-stage RNG streams; ``apply`` runs the GPipe schedule.
    """

    def __init__(self, stage_module, mesh: Mesh, n_micro: int,
                 axis_name: str = "pp"):
        self.stage = stage_module
        self.mesh = mesh
        self.n_micro = n_micro
        self.axis_name = axis_name
        self.n_stages = mesh.shape[axis_name]

    def init(self, rng):
        keys = jax.random.split(rng, self.n_stages)
        inits = [self.stage.init(k) for k in keys]
        if any(s for _, s in inits):
            raise ValueError(
                "Pipeline stages with mutable state (BatchNorm running stats, "
                "...) are not supported yet: state/training/rng are not "
                "threaded through the GPipe schedule. Use stateless stages "
                "(e.g. LayerNormalization instead of BatchNormalization)."
            )
        ps = [p for p, _ in inits]
        stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *ps)
        sharding = jax.tree_util.tree_map(
            lambda _: jax.sharding.NamedSharding(self.mesh, P(self.axis_name)),
            stacked)
        return jax.tree_util.tree_map(jax.device_put, stacked, sharding)

    def apply(self, stacked_params, x):
        def stage_fn(p, xm):
            out, _ = self.stage.apply(p, xm)
            return out

        return pipeline_apply(stage_fn, self.mesh, stacked_params, x,
                              self.n_micro, self.axis_name)
