"""Device-mesh construction and sharding-constraint helpers.

The reference's notion of topology is ``Engine.nodeNumber x coreNumber``
(``DL/utils/Engine.scala:279,302``) wired into Spark partition placement.
The TPU-native topology is a named ``jax.sharding.Mesh``; every parallelism
strategy is an axis name, and placement is expressed as ``PartitionSpec``s
that XLA's GSPMD partitioner turns into collectives over ICI/DCN.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_local = threading.local()


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named mesh axes, e.g. ``MeshSpec(dp=2, tp=2, sp=2)``.

    Axis order follows the declaration order; put the fastest-varying
    (innermost-ICI) axis last — on real slices, XLA maps trailing mesh dims
    to the most tightly coupled devices, so ``tp``/``sp`` (which carry
    per-layer collectives) should come after ``dp``/``pp``.
    """

    axes: Tuple[Tuple[str, int], ...]

    def __init__(self, axes: Optional[Sequence[Tuple[str, int]]] = None, **kw: int):
        entries = tuple(axes or ()) + tuple(kw.items())
        object.__setattr__(self, "axes", entries)

    @property
    def size(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= s
        return n

    def names(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self.axes)


def make_mesh(spec: MeshSpec, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if spec.size > len(devices):
        raise ValueError(f"mesh needs {spec.size} devices, have {len(devices)}")
    arr = np.asarray(devices[: spec.size]).reshape([s for _, s in spec.axes])
    return Mesh(arr, spec.names())


def factor_devices(n: int, want: Sequence[str]) -> Dict[str, int]:
    """Greedily factor ``n`` devices over the requested axis names.

    Each axis gets the smallest prime factor still available (so e.g.
    n=8, want=(dp, tp, sp) -> {dp: 2, tp: 2, sp: 2}); leftover factors fold
    into the first axis. Axes that can't get a factor >1 get size 1.
    """
    sizes = {name: 1 for name in want}
    rem = n
    for name in want:
        for f in (2, 3, 5, 7):
            if rem % f == 0:
                sizes[name] = f
                rem //= f
                break
    if rem > 1 and want:
        sizes[want[0]] *= rem
    return sizes


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Activate ``mesh`` for `constrain` calls in this thread."""
    prev = getattr(_local, "mesh", None)
    _local.mesh = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _local.mesh = prev


def current_mesh() -> Optional[Mesh]:
    return getattr(_local, "mesh", None)


UNCONSTRAINED = P.UNCONSTRAINED


def constrain(x, *spec_parts):
    """``with_sharding_constraint`` that degrades to a no-op.

    Spec-part semantics per dim:

    - an axis name (or tuple of names): shard over those mesh axes;
    - ``None``: explicitly REPLICATED over all mesh axes;
    - ``UNCONSTRAINED``: leave the dim's layout to GSPMD (use this for
      batch/sequence dims so a tp constraint never un-shards dp/sp).

    Degrades: with no active mesh the call is a no-op; axis names missing
    from the active mesh become UNCONSTRAINED (not replicated), so
    tensor-parallel layers run unchanged on a single chip or a pure-dp
    mesh; if after degradation every dim is UNCONSTRAINED, no constraint
    is emitted at all.
    """
    mesh = getattr(_local, "mesh", None)
    if mesh is None:
        return x
    names = set(mesh.axis_names)

    def keep(part):
        if part is None or part is UNCONSTRAINED:
            return part
        if isinstance(part, (tuple, list)):
            kept = tuple(p for p in part if p in names)
            return kept if kept else UNCONSTRAINED
        return part if part in names else UNCONSTRAINED

    cleaned = [keep(p) for p in spec_parts]
    if all(c is UNCONSTRAINED for c in cleaned):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*cleaned)))


def tree_shardings(mesh: Mesh, tree, pspecs=None):
    """Expand a SPARSE PartitionSpec tree into a full ``NamedSharding``
    tree mirroring ``tree``.

    ``pspecs`` follows ``Module.param_pspecs()`` conventions: nested dicts
    holding ``PartitionSpec`` leaves for the annotated parameters only.
    Every leaf of ``tree`` with no spec (missing key, or ``pspecs=None``)
    gets ``P()`` — explicitly REPLICATED over the whole mesh, the safe
    default for embeddings / norms / biases. The result is what
    ``jax.device_put(tree, tree_shardings(...))`` and a reload both need:
    one sharding per leaf, structurally identical to the value tree.
    """
    def walk(node, spec, path):
        if isinstance(node, dict):
            if spec is not None and not isinstance(spec, dict):
                # a P() attached to a SUBTREE would otherwise silently
                # replicate every leaf under it — a memory/perf regression
                # with no symptom; specs apply to leaves (or tuple nodes)
                raise ValueError(
                    f"pspec at {'/'.join(path) or '<root>'} is "
                    f"{spec!r} but the params tree has a dict there; "
                    f"attach PartitionSpecs to leaves")
            sub = spec or {}
            extra = set(sub) - set(node)
            if extra:
                raise ValueError(
                    f"pspec keys {sorted(extra)} at "
                    f"{'/'.join(path) or '<root>'} match no parameter")
            return {k: walk(v, sub.get(k), path + (k,))
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)) and not isinstance(node, P):
            if isinstance(spec, P) or spec is None:
                sub = [spec] * len(node)  # one spec covers homogeneous kids
            else:
                sub = list(spec)
                if len(sub) != len(node):
                    raise ValueError(
                        f"pspec list at {'/'.join(path) or '<root>'} has "
                        f"{len(sub)} entries for {len(node)} children")
            out = [walk(v, s, path + (str(i),))
                   for i, (v, s) in enumerate(zip(node, sub))]
            return type(node)(out)
        return NamedSharding(mesh, spec if spec is not None else P())

    return walk(tree, pspecs, ())


def shard_tree(mesh: Mesh, tree, pspecs=None):
    """``(sharded tree, sharding tree)``: place every leaf of ``tree``
    per the sparse ``pspecs`` (unannotated leaves replicated). The
    returned sharding tree is the reload contract — hot-swapped weights
    must be ``device_put`` with exactly these shardings or the jitted
    step would miss its executable cache."""
    shardings = tree_shardings(mesh, tree, pspecs)
    return jax.device_put(tree, shardings), shardings


def axis_size(mesh: Mesh, axis: str) -> int:
    """Size of named ``axis`` in ``mesh`` (1 when absent — the degraded
    single-chip case every tp layer must tolerate)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return int(sizes.get(axis, 1))


def serving_meshes(n_replicas: int, tp: int = 1, *, axis: str = "tp",
                   devices=None):
    """``n_replicas`` disjoint single-axis meshes of ``tp`` devices each —
    the replica-group topology for sharded + replicated serving: every
    replica runs its tensor-parallel engine on its own device set, so one
    replica's death or reload never touches a sibling's chips.

    Raises when ``n_replicas * tp`` exceeds the available devices
    (serving replicas must not share chips; for CPU tests use
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``).
    """
    if n_replicas < 1 or tp < 1:
        raise ValueError("n_replicas and tp must be >= 1")
    devices = list(devices if devices is not None else jax.devices())
    need = n_replicas * tp
    if need > len(devices):
        raise ValueError(
            f"{n_replicas} replicas x tp={tp} needs {need} devices, have "
            f"{len(devices)} (CPU: set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={need})")
    return [make_mesh(MeshSpec(**{axis: tp}), devices[i * tp:(i + 1) * tp])
            for i in range(n_replicas)]


def mark_varying(t, axis_name):
    """Cast ``t`` to device-varying over ``axis_name`` (shard_map type
    system). ``pcast`` is the current API; ``pvary`` its deprecated
    ancestor; very old jax has neither and tracks no varying types, so
    identity is correct. Shared by the ring-attention and pipeline
    collectives."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(t, axis_name, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(t, (axis_name,))
    return t


def ring_perm(n: int):
    """Neighbor permutation for ``lax.ppermute`` ring shifts."""
    return [(i, (i + 1) % n) for i in range(n)]
