"""Device-mesh construction and sharding-constraint helpers.

The reference's notion of topology is ``Engine.nodeNumber x coreNumber``
(``DL/utils/Engine.scala:279,302``) wired into Spark partition placement.
The TPU-native topology is a named ``jax.sharding.Mesh``; every parallelism
strategy is an axis name, and placement is expressed as ``PartitionSpec``s
that XLA's GSPMD partitioner turns into collectives over ICI/DCN.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_local = threading.local()


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named mesh axes, e.g. ``MeshSpec(dp=2, tp=2, sp=2)``.

    Axis order follows the declaration order; put the fastest-varying
    (innermost-ICI) axis last — on real slices, XLA maps trailing mesh dims
    to the most tightly coupled devices, so ``tp``/``sp`` (which carry
    per-layer collectives) should come after ``dp``/``pp``.
    """

    axes: Tuple[Tuple[str, int], ...]

    def __init__(self, axes: Optional[Sequence[Tuple[str, int]]] = None, **kw: int):
        entries = tuple(axes or ()) + tuple(kw.items())
        object.__setattr__(self, "axes", entries)

    @property
    def size(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= s
        return n

    def names(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self.axes)


def make_mesh(spec: MeshSpec, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if spec.size > len(devices):
        raise ValueError(f"mesh needs {spec.size} devices, have {len(devices)}")
    arr = np.asarray(devices[: spec.size]).reshape([s for _, s in spec.axes])
    return Mesh(arr, spec.names())


def factor_devices(n: int, want: Sequence[str]) -> Dict[str, int]:
    """Greedily factor ``n`` devices over the requested axis names.

    Each axis gets the smallest prime factor still available (so e.g.
    n=8, want=(dp, tp, sp) -> {dp: 2, tp: 2, sp: 2}); leftover factors fold
    into the first axis. Axes that can't get a factor >1 get size 1.
    """
    sizes = {name: 1 for name in want}
    rem = n
    for name in want:
        for f in (2, 3, 5, 7):
            if rem % f == 0:
                sizes[name] = f
                rem //= f
                break
    if rem > 1 and want:
        sizes[want[0]] *= rem
    return sizes


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Activate ``mesh`` for `constrain` calls in this thread."""
    prev = getattr(_local, "mesh", None)
    _local.mesh = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _local.mesh = prev


def current_mesh() -> Optional[Mesh]:
    return getattr(_local, "mesh", None)


UNCONSTRAINED = P.UNCONSTRAINED


def constrain(x, *spec_parts):
    """``with_sharding_constraint`` that degrades to a no-op.

    Spec-part semantics per dim:

    - an axis name (or tuple of names): shard over those mesh axes;
    - ``None``: explicitly REPLICATED over all mesh axes;
    - ``UNCONSTRAINED``: leave the dim's layout to GSPMD (use this for
      batch/sequence dims so a tp constraint never un-shards dp/sp).

    Degrades: with no active mesh the call is a no-op; axis names missing
    from the active mesh become UNCONSTRAINED (not replicated), so
    tensor-parallel layers run unchanged on a single chip or a pure-dp
    mesh; if after degradation every dim is UNCONSTRAINED, no constraint
    is emitted at all.
    """
    mesh = getattr(_local, "mesh", None)
    if mesh is None:
        return x
    names = set(mesh.axis_names)

    def keep(part):
        if part is None or part is UNCONSTRAINED:
            return part
        if isinstance(part, (tuple, list)):
            kept = tuple(p for p in part if p in names)
            return kept if kept else UNCONSTRAINED
        return part if part in names else UNCONSTRAINED

    cleaned = [keep(p) for p in spec_parts]
    if all(c is UNCONSTRAINED for c in cleaned):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*cleaned)))


def mark_varying(t, axis_name):
    """Cast ``t`` to device-varying over ``axis_name`` (shard_map type
    system). ``pcast`` is the current API; ``pvary`` its deprecated
    ancestor; very old jax has neither and tracks no varying types, so
    identity is correct. Shared by the ring-attention and pipeline
    collectives."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(t, axis_name, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(t, (axis_name,))
    return t


def ring_perm(n: int):
    """Neighbor permutation for ``lax.ppermute`` ring shifts."""
    return [(i, (i + 1) % n) for i in range(n)]
