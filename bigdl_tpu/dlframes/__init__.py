"""DataFrame ML pipeline tier.

Reference: ``DL/dlframes/`` (821 LoC) — ``DLEstimator``/``DLModel``
(``DLEstimator.scala:163,362``: Spark DataFrame in, ``fit`` runs an
Optimizer, ``transform`` appends a prediction column),
``DLClassifier``/``DLClassifierModel`` (:37,68), ``DLImageReader``,
``DLImageTransformer``.

TPU-native redesign: the DataFrame engine is **pandas** — on a TPU-VM the
host process owns the data, so the estimator consumes a local DataFrame
directly instead of an RDD-backed one (the reference's Spark coupling is
an artifact of its executor-resident training; here training is
chip-resident and the frame is just a feature store). The estimator/model
API (featuresCol/labelCol/predictionCol, fit/transform) is kept intact so
pipeline code ports 1:1.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Criterion, Module


def _column_matrix(df, col: str) -> np.ndarray:
    vals = df[col].tolist()
    return np.asarray([np.asarray(v, np.float32).reshape(-1) for v in vals])


class DLModel:
    """Fitted transformer (reference ``DLModel``, ``DLEstimator.scala:362``):
    ``transform`` appends ``predictionCol`` holding the raw model output."""

    def __init__(self, model: Module, params, state=None,
                 features_col: str = "features",
                 prediction_col: str = "prediction",
                 batch_size: int = 32,
                 feature_size: Optional[Sequence[int]] = None):
        self.model = model
        self.params = params
        self.state = state or {}
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.batch_size = batch_size
        self.feature_size = tuple(feature_size) if feature_size else None

    def set_features_col(self, name: str) -> "DLModel":
        self.features_col = name
        return self

    def set_prediction_col(self, name: str) -> "DLModel":
        self.prediction_col = name
        return self

    def _features(self, df) -> np.ndarray:
        x = _column_matrix(df, self.features_col)
        if self.feature_size:
            x = x.reshape((-1,) + self.feature_size)
        return x

    def _predictor(self):
        from bigdl_tpu.optim.predictor import Predictor

        return Predictor(self.model, self.params, self.state,
                         batch_size=self.batch_size)

    def _predict_raw(self, df) -> np.ndarray:
        outs = self._predictor().predict(self._features(df), flatten=False)
        return np.concatenate([np.asarray(o) for o in outs])

    def transform(self, df):
        out = df.copy()
        raw = self._predict_raw(df)
        out[self.prediction_col] = list(raw)
        return out


class DLClassifierModel(DLModel):
    """Classifier variant (reference ``DLClassifierModel``): prediction is
    the argmax class index."""

    def transform(self, df):
        out = df.copy()
        cls = self._predictor().predict_class(self._features(df))
        out[self.prediction_col] = cls.astype(np.int64)
        return out


class DLEstimator:
    """Reference ``DLEstimator.scala:163``: wraps (model, criterion) as an
    ML-pipeline estimator; ``fit(df)`` trains with the framework Optimizer
    and returns a :class:`DLModel`."""

    model_cls = DLModel

    def __init__(self, model: Module, criterion: Criterion,
                 feature_size: Optional[Sequence[int]] = None,
                 label_size: Optional[Sequence[int]] = None,
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction"):
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size) if feature_size else None
        self.label_size = tuple(label_size) if label_size else None
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.batch_size = 32
        self.max_epoch = 10
        self.learning_rate = 1e-3
        self.optim_method = None

    # -- builder setters (reference param setters) ------------------------
    def set_batch_size(self, n: int) -> "DLEstimator":
        self.batch_size = n
        return self

    def set_max_epoch(self, n: int) -> "DLEstimator":
        self.max_epoch = n
        return self

    def set_learning_rate(self, lr: float) -> "DLEstimator":
        self.learning_rate = lr
        return self

    def set_optim_method(self, method) -> "DLEstimator":
        self.optim_method = method
        return self

    def _labels(self, df) -> np.ndarray:
        y = np.asarray(df[self.label_col].tolist())
        if self.label_size:
            y = y.reshape((-1,) + self.label_size)
        return y

    def fit(self, df) -> DLModel:
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.optim import SGD, Trigger, optimizer

        x = _column_matrix(df, self.features_col)
        if self.feature_size:
            x = x.reshape((-1,) + self.feature_size)
        y = self._labels(df)

        opt = optimizer(self.model, DataSet.tensors(x, y), self.criterion,
                        batch_size=min(self.batch_size, len(x)))
        opt.set_optim_method(self.optim_method
                             or SGD(learning_rate=self.learning_rate))
        opt.set_end_when(Trigger.max_epoch(self.max_epoch))
        params, state = opt.optimize()
        return self.model_cls(
            self.model, params, state, self.features_col,
            self.prediction_col, self.batch_size, self.feature_size)


class DLClassifier(DLEstimator):
    """Reference ``DLClassifier.scala:37``: integer labels, argmax
    predictions."""

    model_cls = DLClassifierModel

    def _labels(self, df) -> np.ndarray:
        return np.asarray(df[self.label_col].tolist()).astype(np.int32)


class DLImageReader:
    """Reference ``DLImageReader``: read a directory of images into a
    DataFrame with an 'image' column (HWC float arrays) and 'uri'."""

    @staticmethod
    def read_images(path: str):
        import pandas as pd

        from bigdl_tpu.vision import ImageFrame

        frame = ImageFrame.read(path)
        return pd.DataFrame({
            "uri": [f.get("uri") for f in frame],
            "image": [f.image for f in frame],
        })


class DLImageTransformer:
    """Reference ``DLImageTransformer``: apply a vision FeatureTransformer
    chain to the 'image' column, writing ``output_col``."""

    def __init__(self, transformer, input_col: str = "image",
                 output_col: str = "transformed"):
        self.transformer = transformer
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, df):
        from bigdl_tpu.vision import ImageFeature

        out = df.copy()
        results = []
        for img in df[self.input_col]:
            feat = self.transformer(ImageFeature(np.asarray(img, np.float32)))
            results.append(feat.get("tensor", feat.image))
        out[self.output_col] = results
        return out
