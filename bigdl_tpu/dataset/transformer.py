"""Composable transformer chains.

Reference: ``DL/dataset/Transformer.scala:44`` — a ``Transformer[A, B]``
maps ``Iterator[A] -> Iterator[B]`` and chains with ``->``
(``SampleToMiniBatch`` at :309). Here chaining is ``>>``::

    pipeline = BytesToGreyImg(28, 28) >> GreyImgNormalizer(mean, std) >> SampleToMiniBatch(128)

Each transformer is host-side (numpy) — this is the CPU input pipeline that
feeds device prefetch, the TPU analogue of the reference's Spark-executor
transformer chains.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional

import numpy as np

from bigdl_tpu.core.rng import RandomGenerator
from bigdl_tpu.dataset.sample import MiniBatch, PaddingParam, Sample


class Transformer:
    #: True when the transformer maps each element independently (1 in ->
    #: 0..k out, no cross-element state) — the worker pool
    #: (``parallel_pipeline``) may fan such stages across workers.
    #: Stream-stateful stages (batching, shuffling) set this False.
    elementwise = True

    def apply(self, it: Iterator[Any]) -> Iterator[Any]:
        raise NotImplementedError

    def __call__(self, it):
        return self.apply(iter(it))

    def __rshift__(self, other: "Transformer") -> "Transformer":
        return ChainedTransformer(self, other)

    def parallel(self, n_workers: int, **kwargs) -> "Transformer":
        """Run this (elementwise) transformer on a pool of ``n_workers``
        workers — see :class:`bigdl_tpu.dataset.parallel_pipeline
        .ParallelTransformer` (``ordered=``, ``processes=``, ``depth=``,
        ``chunk=``, ``base_seed=``, ``stats=``). Any ``>>`` chain opts in
        with one call::

            pipeline = (aug >> flip).parallel(8) >> SampleToMiniBatch(128)
        """
        from bigdl_tpu.dataset.parallel_pipeline import ParallelTransformer

        return ParallelTransformer(self, n_workers, **kwargs)


class ChainedTransformer(Transformer):
    def __init__(self, first: Transformer, second: Transformer):
        self.first, self.second = first, second

    @property
    def elementwise(self):  # a chain is elementwise iff all its links are
        return (getattr(self.first, "elementwise", True)
                and getattr(self.second, "elementwise", True))

    def apply(self, it):
        return self.second.apply(self.first.apply(it))


class FunctionTransformer(Transformer):
    """Wrap a per-element function."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def apply(self, it):
        return (self.fn(x) for x in it)


class SampleToMiniBatch(Transformer):
    """Group samples into MiniBatches (reference: ``SampleToMiniBatch``,
    ``Transformer.scala:309``). ``partial_batch``: emit the trailing
    incomplete batch (the reference drops it in training)."""

    elementwise = False  # N:1 grouping — must stay outside a worker pool

    def __init__(
        self,
        batch_size: int,
        feature_padding: Optional[PaddingParam] = None,
        label_padding: Optional[PaddingParam] = None,
        partial_batch: bool = False,
    ):
        self.batch_size = batch_size
        self.feature_padding = feature_padding
        self.label_padding = label_padding
        self.partial_batch = partial_batch

    def apply(self, it):
        buf = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield MiniBatch.stack(buf, self.feature_padding, self.label_padding)
                buf = []
        if buf and self.partial_batch:
            yield MiniBatch.stack(buf, self.feature_padding, self.label_padding)


class Shuffle(Transformer):
    """Full-buffer shuffle (reference: ``CachedDistriDataSet.shuffle``)."""

    elementwise = False  # whole-stream state

    def __init__(self, rng: Optional[RandomGenerator] = None):
        self.rng = rng or RandomGenerator.default()

    def apply(self, it):
        items = list(it)
        perm = self.rng.permutation(len(items))
        return (items[i] for i in perm)
