"""Host->device prefetch.

The reference feeds executors from cached RDD partitions
(``CachedDistriDataSet``); on TPU the equivalent hot path is overlapping
host batch preparation with device compute. ``device_prefetch`` keeps
``buffer_size`` batches in flight via ``jax.device_put`` (async dispatch),
optionally sharding the batch over a mesh's dp axis (replacing the
reference's per-partition locality pinning,
``ZippedPartitionsWithLocalityRDD.scala:28``).

Both stages feed the per-stage observability layer
(:class:`~bigdl_tpu.dataset.parallel_pipeline.PipelineStats`) when given
``stats=``: items/bytes per stage, producer stall and consumer starve
time, and queue occupancy — the counters ``bench.py --mode pipeline``
turns into per-stage img/s.
"""

from __future__ import annotations

import threading
import time
from typing import Iterator, Optional

import jax

from bigdl_tpu.dataset.parallel_pipeline import (
    Closed, CloseableQueue, PipelineStats, nbytes_of,
)
from bigdl_tpu.dataset.sample import MiniBatch


def device_put_batch(batch: MiniBatch, sharding=None):
    """Move a host MiniBatch to device(s), batch-sharded if given."""
    put = (lambda a: jax.device_put(a, sharding)) if sharding is not None else jax.device_put
    inp = jax.tree_util.tree_map(put, batch.input)
    tgt = None if batch.target is None else jax.tree_util.tree_map(put, batch.target)
    return inp, tgt


def device_prefetch(
    batches: Iterator[MiniBatch],
    sharding=None,
    buffer_size: int = 2,
    host_depth: int = 0,
    stats: Optional[PipelineStats] = None,
):
    """Yield (input, target) device trees, keeping a small pipeline of
    transfers in flight ahead of compute. ``host_depth > 0`` additionally
    runs the host pipeline in a background thread (see
    :func:`host_prefetch`) so decode/augment overlaps device compute.
    ``buffer_size <= 0`` falls back to unbuffered transfer-per-batch
    iteration (no in-flight pipeline; every batch still flows — a
    non-positive buffer must never silently drop the stream)."""
    if host_depth > 0:
        batches = host_prefetch(batches, host_depth, stats=stats)
    st = stats.stage("transfer") if stats is not None else None
    batches = iter(batches)

    def put_tracked(batch):
        if st is not None:
            st.record(batch.size() if hasattr(batch, "size") else 1,
                      nbytes_of(batch))
        return device_put_batch(batch, sharding)

    def pull():
        """next() with the wait attributed as this stage starving."""
        if st is None:
            return next(batches, None)
        t0 = time.perf_counter()
        nxt = next(batches, None)
        st.record_starve(time.perf_counter() - t0)
        return nxt

    if buffer_size <= 0:
        while True:
            nxt = pull()
            if nxt is None:
                return
            yield put_tracked(nxt)
        return

    queue = []
    while len(queue) < buffer_size:
        nxt = pull()
        if nxt is None:
            break
        queue.append(put_tracked(nxt))
    while queue:
        out = queue.pop(0)
        nxt = pull()
        if nxt is not None:
            queue.append(put_tracked(nxt))
        yield out


def host_prefetch(
    items: Iterator,
    depth: int = 4,
    stats: Optional[PipelineStats] = None,
    stage: str = "stage",
) -> Iterator:
    """Run the producing iterator in a background thread, buffering up to
    ``depth`` ready items (the host-side staging stage between the input
    pipeline and device infeed — reference analogue: the ThreadPool-driven
    ``MTLabeledBGRImgToBatch`` batcher).

    Items (MiniBatches / arrays) cross threads by reference through a
    bounded :class:`CloseableQueue` — no serialization, and no poll loops:
    a producer blocked on a full queue sleeps on a condition that consumer
    gets and shutdown both notify, so an idle prefetch thread costs zero
    wakeups (the old implementation burned one every 50 ms). The producer
    thread shuts down promptly when the consumer abandons the generator
    (the normal way training loops exit an infinite batch stream), and a
    producer exception fails the consumer after the buffered items drain.
    """
    q = CloseableQueue(depth)
    st = stats.stage(stage) if stats is not None else None
    err: list = []

    def produce():
        try:
            for item in items:
                stalled = q.put(item)
                if st is not None:
                    st.record_stall(stalled)
        except Closed:
            pass  # consumer walked away; queue already aborted
        except BaseException as e:  # surface pipeline errors to the consumer
            err.append(e)
        finally:
            q.close()  # graceful: consumer drains buffered items, then ends
            # retire the upstream pipeline deterministically (a parallel
            # worker pool upstream shuts its workers/processes down in
            # its generator finally — don't leave that to GC racing
            # interpreter exit)
            close = getattr(items, "close", None)
            if close is not None:
                try:
                    close()
                except BaseException:
                    pass

    t = threading.Thread(target=produce, name="host-prefetch", daemon=True)
    t.start()
    try:
        while True:
            try:
                item, starved = q.get()
            except Closed:
                if err:
                    raise err[0]
                return
            if st is not None:
                st.record_starve(starved)
                st.record_queue(q.qsize(), q.maxsize)
                st.record(1, nbytes_of(item))
            yield item
    finally:
        q.abort()  # unblock and retire the producer on early exit
        t.join(timeout=10)  # bounded: upstream teardown completes before
        # the training loop returns (worker pools terminate/drain here)
