"""Host->device prefetch.

The reference feeds executors from cached RDD partitions
(``CachedDistriDataSet``); on TPU the equivalent hot path is overlapping
host batch preparation with device compute. ``device_prefetch`` keeps
``buffer_size`` batches in flight via ``jax.device_put`` (async dispatch),
optionally sharding the batch over a mesh's dp axis (replacing the
reference's per-partition locality pinning,
``ZippedPartitionsWithLocalityRDD.scala:28``).
"""

from __future__ import annotations

import collections
import itertools
from typing import Iterator, Optional

import jax

from bigdl_tpu.dataset.sample import MiniBatch


def device_put_batch(batch: MiniBatch, sharding=None):
    """Move a host MiniBatch to device(s), batch-sharded if given."""
    put = (lambda a: jax.device_put(a, sharding)) if sharding is not None else jax.device_put
    inp = jax.tree_util.tree_map(put, batch.input)
    tgt = None if batch.target is None else jax.tree_util.tree_map(put, batch.target)
    return inp, tgt


def device_prefetch(
    batches: Iterator[MiniBatch],
    sharding=None,
    buffer_size: int = 2,
    host_depth: int = 0,
):
    """Yield (input, target) device trees, keeping a small pipeline of
    transfers in flight ahead of compute. ``host_depth > 0`` additionally
    runs the host pipeline in a background thread (see
    :func:`host_prefetch`) so decode/augment overlaps device compute."""
    if host_depth > 0:
        batches = host_prefetch(batches, host_depth)
    queue = collections.deque()
    batches = iter(batches)
    for batch in itertools.islice(batches, buffer_size):
        queue.append(device_put_batch(batch, sharding))
    while queue:
        out = queue.popleft()
        nxt = next(batches, None)
        if nxt is not None:
            queue.append(device_put_batch(nxt, sharding))
        yield out


def host_prefetch(items: Iterator, depth: int = 4) -> Iterator:
    """Run the producing iterator in a background thread, buffering up to
    ``depth`` ready items (the host-side staging stage between the input
    pipeline and device infeed — reference analogue: the ThreadPool-driven
    ``MTLabeledBGRImgToBatch`` batcher).

    Items (MiniBatches / arrays) cross threads by reference through a
    bounded ``queue.Queue`` — no serialization. (Byte-record streams have
    their own native-ring staging in ``TFRecordPrefetcher``.) The producer
    thread shuts down promptly when the consumer abandons the generator
    (the normal way training loops exit an infinite batch stream).
    """
    import queue as _queue
    import threading

    q: _queue.Queue = _queue.Queue(maxsize=depth)
    _SENTINEL = object()
    stop = threading.Event()
    err: list = []

    def produce():
        try:
            for item in items:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.05)
                        break
                    except _queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # surface pipeline errors to the consumer
            err.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(_SENTINEL, timeout=0.05)
                    break
                except _queue.Full:
                    continue

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()  # unblock and retire the producer on early exit
