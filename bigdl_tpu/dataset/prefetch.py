"""Host->device prefetch.

The reference feeds executors from cached RDD partitions
(``CachedDistriDataSet``); on TPU the equivalent hot path is overlapping
host batch preparation with device compute. ``device_prefetch`` keeps
``buffer_size`` batches in flight via ``jax.device_put`` (async dispatch),
optionally sharding the batch over a mesh's dp axis (replacing the
reference's per-partition locality pinning,
``ZippedPartitionsWithLocalityRDD.scala:28``).
"""

from __future__ import annotations

import collections
import itertools
from typing import Iterator, Optional

import jax

from bigdl_tpu.dataset.sample import MiniBatch


def device_put_batch(batch: MiniBatch, sharding=None):
    """Move a host MiniBatch to device(s), batch-sharded if given."""
    put = (lambda a: jax.device_put(a, sharding)) if sharding is not None else jax.device_put
    inp = jax.tree_util.tree_map(put, batch.input)
    tgt = None if batch.target is None else jax.tree_util.tree_map(put, batch.target)
    return inp, tgt


def device_prefetch(
    batches: Iterator[MiniBatch],
    sharding=None,
    buffer_size: int = 2,
):
    """Yield (input, target) device trees, keeping a small pipeline of
    transfers in flight ahead of compute."""
    queue = collections.deque()
    batches = iter(batches)
    for batch in itertools.islice(batches, buffer_size):
        queue.append(device_put_batch(batch, sharding))
    while queue:
        out = queue.popleft()
        nxt = next(batches, None)
        if nxt is not None:
            queue.append(device_put_batch(nxt, sharding))
        yield out
