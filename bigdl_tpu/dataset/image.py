"""Image transformers (host-side numpy).

Reference: ``DL/dataset/image/`` (24 files) — ``BytesToGreyImg``,
``GreyImgNormalizer``, ``GreyImgToSample``, ``BGRImgNormalizer``,
``BGRImgCropper``, ``HFlip``, ``ColorJitter``, ``Lighting``,
``RGBImgToSample``. The reference's multi-threaded batcher
(``MTLabeledBGRImgToBatch``) is unnecessary — batches here are cheap numpy
stacks and the heavy lifting (normalize/crop) is vectorized.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from bigdl_tpu.core.rng import RandomGenerator
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class BytesToGreyImg(Transformer):
    """(bytes, label) -> (H, W) float image in [0, 255]
    (reference: ``BytesToGreyImg.scala``)."""

    def __init__(self, row: int, col: int):
        self.row, self.col = row, col

    def apply(self, it):
        for raw, label in it:
            img = np.frombuffer(raw, dtype=np.uint8).astype(np.float32)
            yield img.reshape(self.row, self.col), label


class GreyImgNormalizer(Transformer):
    """(img, label) -> ((img - mean) / std, label)
    (reference: ``GreyImgNormalizer.scala``)."""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = mean, std

    def apply(self, it):
        for img, label in it:
            yield (img - self.mean) / self.std, label


class GreyImgToSample(Transformer):
    """(img, label) -> Sample with (1, H, W) feature
    (reference: ``GreyImgToSample.scala``)."""

    def apply(self, it):
        for img, label in it:
            yield Sample(img[None].astype(np.float32), np.asarray(label, np.int32))


class BGRImgNormalizer(Transformer):
    """Per-channel normalize a (C, H, W) image
    (reference: ``BGRImgNormalizer.scala``)."""

    def __init__(self, means: Tuple[float, ...], stds: Tuple[float, ...]):
        self.means = np.asarray(means, np.float32).reshape(-1, 1, 1)
        self.stds = np.asarray(stds, np.float32).reshape(-1, 1, 1)

    def apply(self, it):
        for img, label in it:
            yield (img - self.means) / self.stds, label


class RandomCropper(Transformer):
    """Random crop to (crop_h, crop_w), optionally padded first
    (reference: ``BGRImgCropper.scala`` / ``BGRImgRdmCropper``)."""

    def __init__(self, crop_w: int, crop_h: int, pad: int = 0,
                 rng: Optional[RandomGenerator] = None):
        self.crop_w, self.crop_h, self.pad = crop_w, crop_h, pad
        self.rng = rng or RandomGenerator.default()

    def apply(self, it):
        np_rng = self.rng.numpy()
        for img, label in it:
            if self.pad:
                img = np.pad(
                    img, [(0, 0), (self.pad, self.pad), (self.pad, self.pad)], mode="constant"
                )
            _, h, w = img.shape
            y = np_rng.integers(0, h - self.crop_h + 1)
            x = np_rng.integers(0, w - self.crop_w + 1)
            yield img[:, y : y + self.crop_h, x : x + self.crop_w], label


class CenterCropper(Transformer):
    def __init__(self, crop_w: int, crop_h: int):
        self.crop_w, self.crop_h = crop_w, crop_h

    def apply(self, it):
        for img, label in it:
            _, h, w = img.shape
            y = (h - self.crop_h) // 2
            x = (w - self.crop_w) // 2
            yield img[:, y : y + self.crop_h, x : x + self.crop_w], label


class HFlip(Transformer):
    """Random horizontal flip (reference: ``HFlip.scala``)."""

    def __init__(self, threshold: float = 0.5, rng: Optional[RandomGenerator] = None):
        self.threshold = threshold
        self.rng = rng or RandomGenerator.default()

    def apply(self, it):
        np_rng = self.rng.numpy()
        for img, label in it:
            if np_rng.random() < self.threshold:
                img = img[..., ::-1].copy()
            yield img, label


class BGRImgToSample(Transformer):
    def apply(self, it):
        for img, label in it:
            yield Sample(np.ascontiguousarray(img, np.float32), np.asarray(label, np.int32))


class MTImageToBatch(Transformer):
    """(HWC uint8 image, label) stream -> normalized NCHW fp32
    MiniBatches via the native fused batcher (reference
    ``MTLabeledBGRImgToBatch.scala``: the multi-threaded batch assembly
    hot loop; transpose + normalize touch each byte once in C++,
    threaded over the batch). Python fallback built in (see
    ``native.batch_hwc_to_nchw``)."""

    elementwise = False  # N:1 batch assembly — stays outside a worker pool

    def __init__(self, batch_size: int, means, stds, scale: float = 1.0,
                 n_threads: int = 4, partial_batch: bool = False):
        self.batch_size = batch_size
        self.means, self.stds, self.scale = means, stds, scale
        self.n_threads = n_threads
        self.partial_batch = partial_batch

    def apply(self, it):
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.native import batch_hwc_to_nchw

        images, labels = [], []
        for img, label in it:
            images.append(np.asarray(img, np.uint8))
            labels.append(label)
            if len(images) == self.batch_size:
                x = batch_hwc_to_nchw(np.stack(images), self.means, self.stds,
                                      self.scale, self.n_threads)
                yield MiniBatch(x, np.asarray(labels, np.int32))
                images, labels = [], []
        if images and self.partial_batch:
            x = batch_hwc_to_nchw(np.stack(images), self.means, self.stds,
                                  self.scale, self.n_threads)
            yield MiniBatch(x, np.asarray(labels, np.int32))
