"""Text pipeline: tokenization, vocabulary, sentence -> sample.

Reference: ``DL/dataset/text/`` (846 LoC) — ``SentenceTokenizer`` (+ the
``utils/`` treebank tokenizer), ``Dictionary`` (vocab with discard
threshold and UNK), ``SentenceBiPadding``, ``TextToLabeledSentence``,
``LabeledSentenceToSample``, ``LabeledSentence``.
"""

from __future__ import annotations

import collections
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer

SENTENCE_START = "SENTENCE_START"
SENTENCE_END = "SENTENCE_END"
UNKNOWN = "<unk>"

_TOKEN_RE = re.compile(r"[A-Za-z]+|[0-9]+|[^\sA-Za-z0-9]")


def tokenize(sentence: str, lower: bool = True) -> List[str]:
    """Simple treebank-style word/punct splitter (reference
    ``SentenceTokenizer.scala`` wraps a java tokenizer; same contract:
    words, numbers and punctuation as separate tokens)."""
    if lower:
        sentence = sentence.lower()
    return _TOKEN_RE.findall(sentence)


class SentenceTokenizer(Transformer):
    """sentence string -> token list (reference ``SentenceTokenizer``)."""

    def __init__(self, lower: bool = True):
        self.lower = lower

    def apply(self, it: Iterator[str]) -> Iterator[List[str]]:
        for sentence in it:
            yield tokenize(sentence, self.lower)


class SentenceBiPadding(Transformer):
    """Wrap token lists with start/end markers (reference
    ``SentenceBiPadding.scala``)."""

    def __init__(self, start: bool = True, end: bool = True):
        self.start = start
        self.end = end

    def apply(self, it):
        for tokens in it:
            out = list(tokens)
            if self.start:
                out = [SENTENCE_START] + out
            if self.end:
                out = out + [SENTENCE_END]
            yield out


class Dictionary:
    """Vocabulary with frequency-ranked indices and UNK handling
    (reference ``Dictionary.scala``: built from a corpus with
    ``vocabSize`` cap; ``getIndex``/``getWord``; unknown -> vocab size)."""

    def __init__(self, sentences: Optional[Iterable[Sequence[str]]] = None,
                 vocab_size: Optional[int] = None):
        self.word2index: Dict[str, int] = {}
        self.index2word: List[str] = []
        if sentences is not None:
            counts = collections.Counter()
            for tokens in sentences:
                counts.update(tokens)
            ordered = [w for w, _ in counts.most_common(vocab_size)]
            for w in ordered:
                self.word2index[w] = len(self.index2word)
                self.index2word.append(w)

    @property
    def vocab_size(self) -> int:
        return len(self.index2word)

    def unk_index(self) -> int:
        return self.vocab_size  # reference: unknown maps past the vocab

    def get_index(self, word: str) -> int:
        return self.word2index.get(word, self.unk_index())

    def get_word(self, index: int) -> str:
        if 0 <= index < self.vocab_size:
            return self.index2word[index]
        return UNKNOWN

    def indices(self, tokens: Sequence[str]) -> np.ndarray:
        return np.asarray([self.get_index(t) for t in tokens], np.int32)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for w in self.index2word:
                f.write(w + "\n")

    @staticmethod
    def load(path: str) -> "Dictionary":
        d = Dictionary()
        with open(path) as f:
            for line in f:
                w = line.rstrip("\n")
                d.word2index[w] = len(d.index2word)
                d.index2word.append(w)
        return d


class LabeledSentence:
    """Token-index sequence with per-step labels (reference
    ``LabeledSentence.scala``)."""

    def __init__(self, data: np.ndarray, labels: np.ndarray):
        self.data = np.asarray(data)
        self.labels = np.asarray(labels)

    def __len__(self):
        return len(self.data)


class TextToLabeledSentence(Transformer):
    """Token list -> LabeledSentence for next-word LM training: data =
    tokens[:-1], label = tokens[1:] (reference
    ``TextToLabeledSentence.scala``)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def apply(self, it):
        for tokens in it:
            idx = self.dictionary.indices(tokens)
            if len(idx) < 2:
                continue
            yield LabeledSentence(idx[:-1], idx[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence -> Sample, padded/truncated to ``fixed_length``
    when given (reference ``LabeledSentenceToSample.scala``). Padded label
    positions get -1 so mask criterions skip them; pass the dictionary's
    ``unk_index()`` as ``pad_data`` to pad inputs with UNK (default 0)."""

    def __init__(self, fixed_length: Optional[int] = None,
                 pad_data: int = 0, pad_label: int = -1):
        self.fixed_length = fixed_length
        self.pad_data = pad_data
        self.pad_label = pad_label

    def apply(self, it):
        for ls in it:
            data, labels = ls.data, ls.labels
            if self.fixed_length is not None:
                n = self.fixed_length
                if len(data) >= n:
                    data, labels = data[:n], labels[:n]
                else:
                    data = np.concatenate(
                        [data, np.full(n - len(data), self.pad_data, data.dtype)])
                    labels = np.concatenate(
                        [labels, np.full(n - len(labels), self.pad_label,
                                         labels.dtype)])
            yield Sample(data.astype(np.int32), labels.astype(np.int32))
