"""Row-to-Table transformers for tabular (data-mining) pipelines.

Reference: ``DL/dataset/datamining/RowTransformer.scala:44`` (326 LoC) —
transforms Spark SQL ``Row``s into ``Table``s of tensors through
pluggable per-schema converters (``ColToTensor`` one column -> one
tensor; ``ColsToNumeric`` several numeric columns -> one concatenated
tensor), with factories ``atomic``/``numeric``/``atomicWithNumeric``.

TPU-native: a row is a ``dict``/``pandas.Series``/sequence; the output
``Table`` is a dict of numpy arrays keyed by schema key — the same
transformer-chain contract as the rest of ``bigdl_tpu.dataset`` (the
Spark ``Row``+``StructField`` machinery is an artifact of RDD typing).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Mapping, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer


def _row_get(row, key_or_index):
    """Fetch a cell by field name (mapping/Series) or position."""
    if isinstance(key_or_index, str):
        return row[key_or_index]
    if isinstance(row, Mapping):
        return list(row.values())[key_or_index]
    return row[key_or_index]


class RowTransformSchema:
    """One output slot (reference ``RowTransformSchema``): selects columns
    by ``field_names`` (wins) or ``indices`` (else all), and converts the
    selected values to one array."""

    def __init__(self, schema_key: str,
                 indices: Sequence[int] = (),
                 field_names: Sequence[str] = ()):
        self.schema_key = schema_key
        self.indices = list(indices)
        self.field_names = list(field_names)

    def select(self, row) -> list:
        if self.field_names:
            return [_row_get(row, f) for f in self.field_names]
        if self.indices:
            return [_row_get(row, i) for i in self.indices]
        vals = list(row.values()) if isinstance(row, Mapping) else list(row)
        return vals

    def transform(self, values: Sequence[Any]) -> np.ndarray:
        raise NotImplementedError


class ColToTensor(RowTransformSchema):
    """One column -> one array, dtype preserved (reference
    ``ColToTensor``: supports any atomic type incl. strings)."""

    def __init__(self, schema_key: str, field):
        if isinstance(field, str):
            super().__init__(schema_key, field_names=[field])
        else:
            super().__init__(schema_key, indices=[int(field)])

    def transform(self, values):
        return np.asarray(values[0]).reshape(())


class ColsToNumeric(RowTransformSchema):
    """Numeric columns -> one concatenated 1-D float array (reference
    ``ColsToNumeric``: flattens scalars and array-valued cells)."""

    def __init__(self, schema_key: str, field_names: Sequence[str] = (),
                 dtype=np.float32):
        super().__init__(schema_key, field_names=field_names)
        self.dtype = dtype

    def transform(self, values):
        parts = [np.asarray(v, self.dtype).reshape(-1) for v in values]
        return np.concatenate(parts) if parts else np.zeros(0, self.dtype)


class RowTransformer(Transformer):
    """Rows -> Tables (reference ``RowTransformer.scala:44``). Each
    schema writes one key in the output dict; schema keys must be
    unique."""

    def __init__(self, schemas: Sequence[RowTransformSchema],
                 row_size: Optional[int] = None):
        keys = [s.schema_key for s in schemas]
        if len(set(keys)) != len(keys):
            dup = sorted(k for k in set(keys) if keys.count(k) > 1)
            raise ValueError(f"replicated schemaKey: {dup}")
        if row_size is not None:
            for s in schemas:
                if not s.field_names and any(
                        not (0 <= i < row_size) for i in s.indices):
                    raise ValueError(
                        f"indices out of bound for rowSize={row_size}: {s.indices}")
        self.schemas = list(schemas)

    def apply(self, it: Iterable) -> Iterable[Dict[str, np.ndarray]]:
        for row in it:
            yield {s.schema_key: s.transform(s.select(row))
                   for s in self.schemas}

    # -- factories (reference companion object) ---------------------------
    @staticmethod
    def atomic(indices_or_names: Sequence, row_size: Optional[int] = None
               ) -> "RowTransformer":
        """One tensor per selected column, keyed by column id."""
        return RowTransformer(
            [ColToTensor(str(f), f) for f in indices_or_names], row_size)

    @staticmethod
    def numeric(fields: Optional[Mapping[str, Sequence[str]]] = None,
                schema_key: str = "all") -> "RowTransformer":
        """Concat numeric columns into one tensor per schema key; with no
        ``fields``, all columns concat under ``schema_key``."""
        if fields is None:
            return RowTransformer([ColsToNumeric(schema_key)])
        return RowTransformer(
            [ColsToNumeric(k, names) for k, names in fields.items()])

    @staticmethod
    def atomic_with_numeric(atomic_fields: Sequence[str],
                            numeric_fields: Mapping[str, Sequence[str]]
                            ) -> "RowTransformer":
        schemas: list = [ColToTensor(f, f) for f in atomic_fields]
        schemas += [ColsToNumeric(k, names)
                    for k, names in numeric_fields.items()]
        return RowTransformer(schemas)
