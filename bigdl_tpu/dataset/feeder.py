"""Executor -> TPU-host batch feeding over sockets.

Reference / north star: the reference's training data lives in Spark
executors (``CachedDistriDataSet``, ``DL/dataset/DataSet.scala:247``)
and reaches the compute through the BlockManager; SURVEY §7 names
"Spark-executor x TPU" feeding as the key plumbing — executors must hand
batches to the TPU-VM host process across a process boundary with
backpressure.

TPU-native design: a length-prefixed binary protocol over TCP/Unix
sockets. Any producer (a Spark ``mapPartitions`` task via this module's
pure-python client, a JVM task re-implementing the ~30-line framing, or
another local process) pushes ``.npy``-serialized batch tuples; the host
side exposes them as an ordinary ``AbstractDataSet`` whose bounded queue
gives backpressure (producers block in ``send`` when the trainer falls
behind — the same role the reference's block-fetch pacing plays). The
trainer end then uses the standard host-prefetch + ``device_put`` path.

Frame format (all big-endian):
  handshake:  8 bytes  b"BDLFEED1"
  each batch: uint32 n_arrays, then per array uint64 length + npy bytes
  end:        uint32 0
"""

from __future__ import annotations

import io
import queue
import socket
import struct
import threading
from typing import Any, Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import faults
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch

_MAGIC = b"BDLFEED1"


class _StreamError:
    """Queue marker: a producer failed; consumers must not mistake the
    truncated stream for a clean end."""

    def __init__(self, error: BaseException):
        self.error = error


def _send_all(sock: socket.socket, data: bytes) -> None:
    view = memoryview(data)
    while view:
        n = sock.send(view)
        view = view[n:]


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _dump_array(arr: np.ndarray) -> bytes:
    bio = io.BytesIO()
    np.save(bio, np.ascontiguousarray(arr), allow_pickle=False)
    return bio.getvalue()


class BatchFeedClient:
    """Producer side (runs inside the executor process)."""

    def __init__(self, address):
        if isinstance(address, str):
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.connect(address)
        _send_all(self._sock, _MAGIC)

    def push(self, *arrays: np.ndarray) -> None:
        payloads = [_dump_array(np.asarray(a)) for a in arrays]
        frame = [struct.pack(">I", len(payloads))]
        for p in payloads:
            frame.append(struct.pack(">Q", len(p)))
            frame.append(p)
        _send_all(self._sock, b"".join(frame))

    def close(self) -> None:
        try:
            _send_all(self._sock, struct.pack(">I", 0))
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def push_batches(address, batches: Iterable[Sequence[np.ndarray]]) -> int:
    """Convenience producer: stream an iterable of array tuples. This is
    the function a Spark ``mapPartitions`` closure calls per partition."""
    n = 0
    with BatchFeedClient(address) as c:
        for arrays in batches:
            c.push(*arrays)
            n += 1
    return n


class SocketFeedDataSet(AbstractDataSet):
    """Host side: listens on ``address``, accepts ``n_producers``
    connections, exposes received batches as MiniBatches. ``depth``
    bounds the in-flight queue (backpressure: TCP flow control stalls
    producers once the queue and socket buffers fill)."""

    def __init__(self, address, n_producers: int = 1, depth: int = 8,
                 epoch_size: Optional[int] = None):
        self.address = address
        self.n_producers = n_producers
        self.depth = depth
        self._epoch_size = epoch_size
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._failed: Optional[BaseException] = None
        self._open_producers = 0
        self._connected = 0  # total accepted so far (end-of-stream fires
        # only after ALL n_producers have connected AND finished — a fast
        # first producer must not end the stream early)
        self._lock = threading.Lock()
        fam = socket.AF_UNIX if isinstance(address, str) else socket.AF_INET
        self._server = socket.socket(fam, socket.SOCK_STREAM)
        if fam == socket.AF_INET:
            self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(address)
        self._server.listen(n_producers)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    @property
    def bound_address(self):
        """Actual address (resolves port 0 to the assigned port)."""
        return self._server.getsockname()

    def _accept_loop(self) -> None:
        for _ in range(self.n_producers):
            conn, _ = self._server.accept()
            with self._lock:
                self._open_producers += 1
                self._connected += 1
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket) -> None:
        error: Optional[BaseException] = None
        try:
            magic = _recv_exact(conn, len(_MAGIC))
            if magic != _MAGIC:
                raise IOError(f"bad feed handshake {magic!r}")
            frame = 0
            while True:
                # fault site, once per frame: an armed exception IS a
                # producer dying mid-frame — it rides the existing error
                # path (sticky failure, consumer raises, never a clean
                # EOF) with the site name in the chained message
                faults.fire("feed.producer", key=frame)
                frame += 1
                hdr = _recv_exact(conn, 4)
                if hdr is None:
                    # EOF between frames = producer closed without the
                    # explicit end frame; tolerated (complete batches only)
                    break
                n_arrays = struct.unpack(">I", hdr)[0]
                if n_arrays == 0:
                    break
                arrays = []
                for _ in range(n_arrays):
                    raw = _recv_exact(conn, 8)
                    if raw is None:
                        raise IOError("producer died mid-frame (truncated "
                                      "array header)")
                    ln = struct.unpack(">Q", raw)[0]
                    payload = _recv_exact(conn, ln)
                    if payload is None:
                        raise IOError("producer died mid-frame (truncated "
                                      "array payload)")
                    arrays.append(np.load(io.BytesIO(payload),
                                          allow_pickle=False))
                self._queue.put(tuple(arrays))
        except BaseException as e:  # surface to the consumer, not stderr
            error = e
        finally:
            conn.close()
            with self._lock:
                self._open_producers -= 1
                done = (self._open_producers == 0
                        and self._connected == self.n_producers)
                if error is not None and self._failed is None:
                    # sticky: once any producer died mid-stream, every
                    # future epoch must fail fast — re-entering batches()
                    # after the error marker drained must not let the
                    # healthy producers' remainder pass for a clean
                    # end-of-stream (truncated data as EOF)
                    self._failed = error
            if error is not None:
                self._queue.put(_StreamError(error))
            elif done:
                self._queue.put(None)  # end-of-stream sentinel

    # -- AbstractDataSet ---------------------------------------------------
    def size(self) -> int:
        if self._epoch_size is None:
            raise ValueError("SocketFeedDataSet needs epoch_size for "
                             "epoch-based triggers; pass epoch_size=")
        return self._epoch_size

    def data(self, train: bool) -> Iterator[Any]:
        return self.batches(0, train)

    def batches(self, batch_size: int, train: bool,
                partial_batch: bool = False) -> Iterator[MiniBatch]:
        """Batches arrive pre-batched by the producers; ``batch_size`` is
        ignored (the executor side owns batching, as in the reference
        where per-partition batch = global/nodes)."""
        while True:
            if self._failed is not None:
                # sticky: a failed feed job must keep failing even if a
                # retry loop re-enters batches() on a drained queue
                raise IOError("feed job failed before/while producing "
                              "batches") from self._failed
            item = self._queue.get()
            if item is None:
                # producers all finished cleanly: the stream ends (one
                # shot — re-feed for another epoch from the producers)
                return
            if isinstance(item, _StreamError):
                raise IOError(
                    "batch producer failed mid-stream; refusing to treat "
                    "truncated data as end-of-stream") from item.error
            arrays = item
            if len(arrays) == 1:
                yield MiniBatch(arrays[0], None)
            elif len(arrays) == 2:
                yield MiniBatch(arrays[0], arrays[1])
            else:
                yield MiniBatch(tuple(arrays[:-1]), arrays[-1])

    def fail(self, error: BaseException) -> None:
        """Poison the stream: unblocks a consumer waiting in ``batches()``
        and makes every future epoch fail fast. For feed *drivers* whose
        producer job dies before any producer ever connects (ADVICE r3:
        otherwise optimize() blocks forever on the empty queue)."""
        self._failed = error
        try:
            # non-blocking: if the queue is full the consumer is not
            # stuck in get(), and the sticky _failed check in batches()
            # fails it on its next iteration anyway
            self._queue.put_nowait(_StreamError(error))
        except queue.Full:
            pass

    def close(self) -> None:
        self._server.close()
