from bigdl_tpu.dataset.sample import Sample, MiniBatch, PaddingParam
from bigdl_tpu.dataset.transformer import (
    Transformer,
    ChainedTransformer,
    FunctionTransformer,
    SampleToMiniBatch,
    Shuffle,
)
from bigdl_tpu.dataset.dataset import (
    AbstractDataSet,
    ArrayDataSet,
    TensorDataSet,
    TransformedDataSet,
    DataSet,
)
from bigdl_tpu.dataset.parallel_pipeline import (
    ParallelTransformer,
    PipelineStats,
    parallelize_chain,
)
from bigdl_tpu.dataset.prefetch import device_prefetch, device_put_batch, host_prefetch
from bigdl_tpu.dataset import image, datasets
