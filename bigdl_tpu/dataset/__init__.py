from bigdl_tpu.dataset.sample import Sample, MiniBatch, PaddingParam
from bigdl_tpu.dataset.transformer import (
    Transformer,
    ChainedTransformer,
    FunctionTransformer,
    SampleToMiniBatch,
    Shuffle,
)
from bigdl_tpu.dataset.dataset import (
    AbstractDataSet,
    ArrayDataSet,
    TensorDataSet,
    TransformedDataSet,
    DataSet,
)
from bigdl_tpu.dataset.prefetch import device_prefetch, device_put_batch
from bigdl_tpu.dataset import image, datasets
