"""Built-in dataset loaders: MNIST, CIFAR-10, PTB-style text.

Reference: the Python-side downloaders ``PY/dataset/{mnist,...}.py`` and
the Scala seq-file/local loaders (``DataSet.scala:425-487``). Network
access may be unavailable, so every loader falls back to a deterministic
synthetic dataset of the right shape when files are absent — the same role
the reference's ``DistriOptimizerPerf`` dummy data plays for benchmarks.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Optional, Tuple

import numpy as np

from bigdl_tpu.core.rng import np_rng

MNIST_TRAIN_MEAN = 0.13066047740239506 * 255
MNIST_TRAIN_STD = 0.3081078 * 255
CIFAR_MEANS = (125.3, 123.0, 113.9)
CIFAR_STDS = (63.0, 62.1, 66.7)


def _synthetic_images(n: int, shape, n_classes: int, seed: int):
    rng = np_rng(seed)
    x = (rng.standard_normal((n,) + shape) * 40 + 128).astype(np.float32)
    y = rng.integers(0, n_classes, n).astype(np.int32)
    # class-specific spatial templates (fixed across train/test seeds) give a
    # clearly learnable signal so short demo/CI runs show real convergence
    template_rng = np_rng(12345)
    templates = template_rng.standard_normal((n_classes,) + shape).astype(np.float32) * 25.0
    x += templates[y]
    return x, y


def load_mnist(
    folder: Optional[str] = None, train: bool = True, synthetic_size: int = 2048
) -> Tuple[np.ndarray, np.ndarray]:
    """Return ((N, 28, 28) float images in [0,255], (N,) int labels).

    Reads idx-format files (train-images-idx3-ubyte[.gz] etc.) if present,
    else deterministic synthetic data.
    """
    if folder:
        prefix = "train" if train else "t10k"
        for ext, op in ((".gz", gzip.open), ("", open)):
            img_p = os.path.join(folder, f"{prefix}-images-idx3-ubyte{ext}")
            lab_p = os.path.join(folder, f"{prefix}-labels-idx1-ubyte{ext}")
            if os.path.exists(img_p) and os.path.exists(lab_p):
                with op(img_p, "rb") as f:
                    _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                    images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
                with op(lab_p, "rb") as f:
                    struct.unpack(">II", f.read(8))
                    labels = np.frombuffer(f.read(), np.uint8)
                return images.astype(np.float32), labels.astype(np.int32)
    x, y = _synthetic_images(synthetic_size, (28, 28), 10, seed=7 if train else 8)
    return np.clip(x, 0, 255), y


def load_cifar10(
    folder: Optional[str] = None, train: bool = True, synthetic_size: int = 2048
) -> Tuple[np.ndarray, np.ndarray]:
    """Return ((N, 3, 32, 32) float images in [0,255], (N,) int labels)."""
    if folder and os.path.isdir(folder):
        batch_dir = folder
        sub = os.path.join(folder, "cifar-10-batches-py")
        if os.path.isdir(sub):
            batch_dir = sub
        names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
        xs, ys = [], []
        for name in names:
            p = os.path.join(batch_dir, name)
            if not os.path.exists(p):
                xs = []
                break
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"].reshape(-1, 3, 32, 32))
            ys.append(np.asarray(d[b"labels"]))
        if xs:
            return (
                np.concatenate(xs).astype(np.float32),
                np.concatenate(ys).astype(np.int32),
            )
    x, y = _synthetic_images(synthetic_size, (3, 32, 32), 10, seed=9 if train else 10)
    return np.clip(x, 0, 255), y


def load_ptb(
    folder: Optional[str] = None, split: str = "train", synthetic_tokens: int = 100_000,
    vocab_size: int = 10_000,
) -> np.ndarray:
    """Return a 1-D int32 token stream (reference: ``models/rnn`` PTB data;
    synthetic fallback is a Markov-ish stream so an LM has signal)."""
    if folder:
        p = os.path.join(folder, f"ptb.{split}.txt")
        if os.path.exists(p):
            with open(p) as f:
                words = f.read().replace("\n", " <eos> ").split()
            vocab_p = os.path.join(folder, "ptb.train.txt")
            with open(vocab_p) as f:
                train_words = f.read().replace("\n", " <eos> ").split()
            vocab = {w: i for i, w in enumerate(sorted(set(train_words)))}
            return np.asarray([vocab[w] for w in words if w in vocab], np.int32)
    rng = np_rng(11 if split == "train" else 12)
    # order-1 Markov chain over a small transition matrix → learnable structure
    k = min(vocab_size, 1000)
    next_tok = rng.integers(0, k, size=(k, 4))
    stream = np.empty(synthetic_tokens, np.int32)
    t = 0
    for i in range(synthetic_tokens):
        stream[i] = t
        t = int(next_tok[t, rng.integers(0, 4)])
    return stream


def load_movielens(
    folder: Optional[str] = None, synthetic_users: int = 200,
    synthetic_items: int = 100, synthetic_ratings: int = 4000,
) -> np.ndarray:
    """Return (N, 3) int32 [user_id, item_id, rating] rows, ids 1-based
    (reference: ``PY/dataset/movielens.py`` reads ml-1m ``ratings.dat``
    ``user::item::rating::ts`` lines). Synthetic fallback generates a
    low-rank preference structure so recommenders have signal."""
    if folder:
        for name in ("ratings.dat", os.path.join("ml-1m", "ratings.dat")):
            path = os.path.join(folder, name)
            if os.path.exists(path):
                rows = []
                with open(path, errors="ignore") as f:
                    for line in f:
                        parts = line.strip().split("::")
                        if len(parts) >= 3:
                            rows.append([int(parts[0]), int(parts[1]),
                                         int(float(parts[2]))])
                return np.asarray(rows, np.int32)
    rng = np_rng(11)
    u_f = rng.standard_normal((synthetic_users, 4))
    i_f = rng.standard_normal((synthetic_items, 4))
    users = rng.integers(0, synthetic_users, synthetic_ratings)
    items = rng.integers(0, synthetic_items, synthetic_ratings)
    score = (u_f[users] * i_f[items]).sum(1)
    rating = np.clip(np.round(3 + score), 1, 5).astype(np.int32)
    return np.stack([users + 1, items + 1, rating], 1).astype(np.int32)


def load_news20(folder: Optional[str] = None, n_classes: int = 4,
                n_per_class: int = 64):
    """Return (list of token lists, list of int labels) — the news20
    corpus layout (category subdirs) or a class-separable synthetic
    corpus (reference: ``PY/dataset/news20.py``). Thin alias over the
    text-classification example's loader so both share one format."""
    from bigdl_tpu.examples.text_classification import load_corpus

    return load_corpus(folder, n_classes=n_classes, n_per_class=n_per_class)
