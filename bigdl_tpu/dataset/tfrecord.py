"""TFRecord file I/O.

Reference: ``DL/utils/tf/TFRecordIterator`` / ``TFRecordWriter`` (+ the
CRC framing in ``DLJ/netty/Crc32c.java``): the standard TFRecord frame
``u64le length | u32le masked_crc(length) | payload | u32le
masked_crc(payload)``.

CRC runs through the native library (``bigdl_tpu.native``) when built,
python table fallback otherwise. A threaded :class:`TFRecordPrefetcher`
pumps records through the native ring buffer — the host-side staging stage
of the input pipeline.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterator, Optional, Sequence

from bigdl_tpu.native import PrefetchRing, masked_crc32c


class TFRecordWriter:
    def __init__(self, path: str):
        self._f = open(path, "wb")

    def write(self, record: bytes) -> None:
        length = struct.pack("<Q", len(record))
        self._f.write(length)
        self._f.write(struct.pack("<I", masked_crc32c(length)))
        self._f.write(record)
        self._f.write(struct.pack("<I", masked_crc32c(record)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_tfrecords(path: str, verify_crc: bool = True) -> Iterator[bytes]:
    """Yield raw record payloads (reference ``TFRecordIterator``).

    Fast path: one native C pass over the whole file validates both CRCs
    and returns payload framing; Python slices records out of the buffer
    (no per-record read()/struct/crc round-trips — the reference parses
    records JVM-side for the same reason). Pure-python fallback when the
    native library is unavailable."""
    import mmap

    from bigdl_tpu.native import native_available, tfrecord_scan

    # (probe also rejects a stale prebuilt .so lacking the scan symbol)
    if (native_available() and tfrecord_scan(b"") is not None
            and os.path.getsize(path) > 0):
        with open(path, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
            try:
                pos = 0
                while True:
                    try:
                        offs, lens, truncated = tfrecord_scan(
                            mm, start=pos, verify=verify_crc)
                    except IOError as e:
                        raise IOError(f"{path}: {e}") from None
                    for off, ln in zip(offs, lens):
                        yield mm[off:off + ln]  # bytes copy of one record
                    if truncated or not len(offs):
                        # partial tail (shard still being written) ends the
                        # stream after the complete records, matching the
                        # streaming fallback's tolerance
                        return
                    pos = int(offs[-1] + lens[-1] + 4)
                    if pos >= len(mm):
                        return
            finally:
                mm.close()
        return

    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if verify_crc and masked_crc32c(header[:8]) != len_crc:
                raise IOError(f"{path}: corrupt length crc")
            payload = f.read(length)
            (data_crc,) = struct.unpack("<I", f.read(4))
            if verify_crc and masked_crc32c(payload) != data_crc:
                raise IOError(f"{path}: corrupt record crc")
            yield payload


class TFRecordPrefetcher:
    """Background reader threads -> native ring -> consumer iterator.

    The analogue of the reference's multi-threaded batch assembly
    (``MTLabeledBGRImgToBatch``): file parsing overlaps with consumption.
    """

    def __init__(self, paths: Sequence[str], capacity: int = 64,
                 n_threads: int = 2, verify_crc: bool = True):
        self.paths = list(paths)
        self.ring = PrefetchRing(capacity)
        self._threads = []
        self._n_live = threading.Semaphore(0)
        chunks = [self.paths[i::n_threads] for i in range(n_threads)]
        self._pending = len([c for c in chunks if c])
        self._lock = threading.Lock()
        for chunk in chunks:
            if not chunk:
                continue
            t = threading.Thread(target=self._pump, args=(chunk, verify_crc),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _pump(self, paths, verify_crc):
        try:
            for p in paths:
                for rec in read_tfrecords(p, verify_crc):
                    if not self.ring.push(rec):
                        return
        finally:
            with self._lock:
                self._pending -= 1
                if self._pending == 0:
                    self.ring.close()

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.ring.pop()
            if rec is None:
                return
            yield rec
