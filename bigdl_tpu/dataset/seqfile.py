"""Hadoop SequenceFile I/O and the ImageNet seq-file pipeline.

Reference: the reference distributes ImageNet as Hadoop SequenceFiles —
written by ``DL/dataset/image/BGRImgToLocalSeqFile.scala`` (key =
``Text("name\\nlabel")`` or ``Text("label")``, value = ``Text(4-byte BE
width + 4-byte BE height + raw BGR bytes)``), read back by
``LocalSeqFileToBytes.scala`` and ``DataSet.SeqFileFolder``
(``DataSet.scala:487``: ``readLabel``/``readName`` split the key on
``\\n``).

TPU-native: a dependency-free SequenceFile codec (uncompressed,
version-6 ``SEQ`` files, Hadoop ``Text``/``BytesWritable`` value
serialization, vint lengths, sync markers) — no Hadoop/Java needed on a
TPU-VM host. The decoded stream feeds the ordinary
``Transformer``-chain/host-prefetch path.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import struct
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.core.rng import np_rng, request_seed
from bigdl_tpu.dataset.transformer import Transformer

_MAGIC = b"SEQ"
_VERSION = 6
TEXT_CLASS = "org.apache.hadoop.io.Text"
BYTES_CLASS = "org.apache.hadoop.io.BytesWritable"


# -- Hadoop WritableUtils vint ------------------------------------------------

def write_vint(n: int) -> bytes:
    """Hadoop WritableUtils.writeVInt/VLong."""
    if -112 <= n <= 127:
        return bytes([n & 0xFF])
    length = -112
    if n < 0:
        n = ~n
        length = -120
    tmp = n
    while tmp:
        tmp >>= 8
        length -= 1
    out = [length & 0xFF]
    n_bytes = -(length + 112) if length >= -120 and length < -112 else -(length + 120)
    for i in range(n_bytes - 1, -1, -1):
        out.append((n >> (8 * i)) & 0xFF)
    return bytes(out)


def read_vint(buf: bytes, pos: int) -> Tuple[int, int]:
    """Returns (value, new_pos)."""
    first = struct.unpack_from("b", buf, pos)[0]
    pos += 1
    if first >= -112:
        return first, pos
    negative = first <= -121
    n_bytes = (-(first + 120)) if negative else (-(first + 112))
    val = 0
    for _ in range(n_bytes):
        val = (val << 8) | buf[pos]
        pos += 1
    return (~val if negative else val), pos


def _text(payload: bytes) -> bytes:
    """Hadoop Text serialization: vint length + bytes."""
    return write_vint(len(payload)) + payload


# -- writer -------------------------------------------------------------------

class SeqFileWriter:
    """Uncompressed SequenceFile writer (key/value class = Text by
    default, matching ``BGRImgToLocalSeqFile``)."""

    SYNC_INTERVAL = 2000  # bytes between sync markers (Hadoop default ~2k)

    def __init__(self, path: str, key_class: str = TEXT_CLASS,
                 value_class: str = TEXT_CLASS):
        self._f = open(path, "wb")
        self.key_class = key_class
        self.value_class = value_class
        # keyed on the path CONTENT (crc32 via request_seed), not on
        # Python's per-process randomized hash(): the same records written
        # to the same path now produce byte-identical files across runs
        self._sync = np_rng(request_seed(0, path.encode("utf-8"))).bytes(16)
        self._since_sync = 0
        self._write_header()

    def _write_header(self) -> None:
        f = self._f
        f.write(_MAGIC + bytes([_VERSION]))
        f.write(_text(self.key_class.encode()))
        f.write(_text(self.value_class.encode()))
        f.write(b"\x00")  # no value compression
        f.write(b"\x00")  # no block compression
        f.write(struct.pack(">i", 0))  # empty metadata
        f.write(self._sync)

    def _serialize(self, payload: bytes, cls: str) -> bytes:
        if cls == TEXT_CLASS:
            return _text(payload)
        if cls == BYTES_CLASS:
            return struct.pack(">i", len(payload)) + payload
        raise ValueError(f"unsupported writable class {cls}")

    def append(self, key: bytes, value: bytes) -> None:
        k = self._serialize(key, self.key_class)
        v = self._serialize(value, self.value_class)
        if self._since_sync >= self.SYNC_INTERVAL:
            self._f.write(struct.pack(">i", -1) + self._sync)
            self._since_sync = 0
        rec = struct.pack(">ii", len(k) + len(v), len(k)) + k + v
        self._f.write(rec)
        self._since_sync += len(rec)

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- reader -------------------------------------------------------------------

class SeqFileReader:
    """Reads (key_bytes, value_bytes) records from an uncompressed
    SequenceFile (versions 4-6; Text and BytesWritable payloads are
    unwrapped to their raw bytes)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            self._buf = f.read()
        buf = self._buf
        if buf[:3] != _MAGIC:
            raise ValueError(f"{path}: not a SequenceFile (bad magic)")
        version = buf[3]
        if version < 4:
            raise ValueError(f"{path}: SequenceFile version {version} < 4 unsupported")
        pos = 4
        klen, pos = read_vint(buf, pos)
        self.key_class = buf[pos:pos + klen].decode()
        pos += klen
        vlen, pos = read_vint(buf, pos)
        self.value_class = buf[pos:pos + vlen].decode()
        pos += vlen
        compressed = buf[pos]; pos += 1
        block_compressed = buf[pos]; pos += 1
        if compressed or block_compressed:
            raise ValueError(f"{path}: compressed SequenceFiles unsupported "
                             "(the reference writes uncompressed)")
        n_meta = struct.unpack_from(">i", buf, pos)[0]; pos += 4
        self.metadata = {}
        for _ in range(n_meta):
            kl, pos = read_vint(buf, pos)
            mk = buf[pos:pos + kl].decode(); pos += kl
            vl, pos = read_vint(buf, pos)
            self.metadata[mk] = buf[pos:pos + vl].decode(); pos += vl
        self._sync = buf[pos:pos + 16]
        self._pos = pos + 16

    def _unwrap(self, payload: bytes, cls: str) -> bytes:
        if cls == TEXT_CLASS:
            n, p = read_vint(payload, 0)
            return payload[p:p + n]
        if cls == BYTES_CLASS:
            n = struct.unpack_from(">i", payload, 0)[0]
            return payload[4:4 + n]
        return payload

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        buf, pos = self._buf, self._pos
        while pos < len(buf):
            rec_len = struct.unpack_from(">i", buf, pos)[0]
            pos += 4
            if rec_len == -1:  # sync escape
                if buf[pos:pos + 16] != self._sync:
                    raise ValueError("corrupt seq file: bad sync marker")
                pos += 16
                continue
            key_len = struct.unpack_from(">i", buf, pos)[0]
            pos += 4
            key = buf[pos:pos + key_len]
            value = buf[pos + key_len:pos + rec_len]
            pos += rec_len
            yield (self._unwrap(key, self.key_class),
                   self._unwrap(value, self.value_class))


# -- the ImageNet seq-file pipeline ------------------------------------------

@dataclasses.dataclass
class ByteRecord:
    """Raw bytes + float label (reference ``ByteRecord``)."""

    data: bytes
    label: float


def read_label(key: bytes) -> str:
    """Key text -> label (reference ``SeqFileFolder.readLabel``)."""
    parts = key.decode().split("\n")
    return parts[0] if len(parts) == 1 else parts[1]


def read_name(key: bytes) -> str:
    parts = key.decode().split("\n")
    if len(parts) < 2:
        raise ValueError("key in seq file only contains label, no name")
    return parts[0]


class BGRImgToLocalSeqFile(Transformer):
    """(label, name, HxWx3 uint8 BGR array) stream -> seq files of
    ``block_size`` records each; yields the written paths (reference
    ``BGRImgToLocalSeqFile.scala``: value = 4-byte BE width + height +
    raw bytes; key = "name\\nlabel" when ``has_name``)."""

    elementwise = False  # N:1 block grouping + on-disk writer state —
    # pooled copies would all write {base}_0.seq concurrently

    def __init__(self, block_size: int, base_file_name: str,
                 has_name: bool = False):
        self.block_size = block_size
        self.base = base_file_name
        self.has_name = has_name
        self._index = 0

    def apply(self, it):
        it = iter(it)
        while True:
            try:
                first = next(it)
            except StopIteration:
                return
            path = f"{self.base}_{self._index}.seq"
            with SeqFileWriter(path) as w:
                wrote = 0
                record = first
                while True:
                    label, name, img = record
                    img = np.ascontiguousarray(img, np.uint8)
                    h, w_ = img.shape[:2]
                    value = struct.pack(">ii", w_, h) + img.tobytes()
                    key = (f"{name}\n{int(label)}" if self.has_name
                           else f"{int(label)}")
                    w.append(key.encode(), value)
                    wrote += 1
                    if wrote >= self.block_size:
                        break
                    try:
                        record = next(it)
                    except StopIteration:
                        break
            self._index += 1
            yield path


class LocalSeqFileToBytes(Transformer):
    """seq-file paths -> ByteRecord stream (reference
    ``LocalSeqFileToBytes.scala``)."""

    def apply(self, it):
        for path in it:
            for key, value in SeqFileReader(path):
                yield ByteRecord(value, float(read_label(key)))


def decode_bgr_record(rec: ByteRecord) -> Tuple[np.ndarray, float]:
    """ByteRecord -> (HxWx3 uint8 BGR image, label) using the 8-byte
    width/height prefix the writer emits."""
    w, h = struct.unpack_from(">ii", rec.data, 0)
    img = np.frombuffer(rec.data, np.uint8, count=h * w * 3, offset=8)
    return img.reshape(h, w, 3), rec.label


def find_seq_files(folder: str) -> List[str]:
    paths = sorted(glob.glob(os.path.join(folder, "*.seq")))
    if not paths:
        raise FileNotFoundError(f"no .seq files under {folder}")
    return paths


def load_imagenet_seqfiles(folder: str):
    """All records decoded: yields (HxWx3 uint8 BGR, float label) —
    the ``DataSet.SeqFileFolder.files`` equivalent for a TPU-VM host."""
    for rec in LocalSeqFileToBytes()(find_seq_files(folder)):
        yield decode_bgr_record(rec)
