"""COCO segmentation dataset + mask utilities.

Reference: ``DL/dataset/segmentation/COCODataset.scala`` (annotation-JSON
parse into per-image ROI labels) and ``MaskUtils.scala`` (1,052 LoC total:
COCO-style uncompressed RLE, the compressed LEB128-ish string encoding,
polygon -> binary mask rasterization, RLE area/merge).

Host-side numpy; the masks feed ``vision.roi.RoiLabel`` and the
masked-mAP metric path.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.vision.roi import RoiLabel


# ------------------------------------------------------------- RLE codec

def rle_encode(mask: np.ndarray) -> Dict:
    """Binary (H, W) mask -> COCO uncompressed RLE dict {counts, size}.
    COCO RLE is column-major with counts alternating 0-runs/1-runs
    starting from a 0-run (reference ``MaskUtils.binaryToRLE``)."""
    mask = np.asarray(mask, np.uint8)
    h, w = mask.shape
    flat = mask.T.reshape(-1)  # column-major
    # run lengths
    change = np.flatnonzero(flat[1:] != flat[:-1]) + 1
    runs = np.diff(np.concatenate([[0], change, [flat.size]]))
    counts = list(map(int, runs))
    if flat.size and flat[0] == 1:  # must start with a zero-run
        counts = [0] + counts
    return {"counts": counts, "size": [int(h), int(w)]}


def rle_decode(rle: Dict) -> np.ndarray:
    """COCO uncompressed RLE -> binary (H, W) mask."""
    h, w = rle["size"]
    counts = rle["counts"]
    if isinstance(counts, str):
        counts = rle_from_string(counts, h, w)["counts"]
    flat = np.zeros(h * w, np.uint8)
    pos = 0
    val = 0
    for c in counts:
        if val:
            flat[pos:pos + c] = 1
        pos += c
        val ^= 1
    return flat.reshape(w, h).T


def rle_area(rle: Dict) -> int:
    """Foreground pixel count (reference ``MaskUtils.rleArea``); accepts
    plain or compressed-string counts like :func:`rle_decode`."""
    counts = rle["counts"]
    if isinstance(counts, str):
        counts = rle_from_string(counts, *rle["size"])["counts"]
    return int(sum(counts[1::2]))


def rle_to_string(rle: Dict) -> str:
    """COCO compressed RLE string (LEB128-style with delta encoding,
    reference ``MaskUtils.rleToString`` / pycocotools rleToString)."""
    counts = rle["counts"]
    out = []
    for i, x in enumerate(counts):
        if i > 2:
            x = x - counts[i - 2]
        more = True
        while more:
            c = x & 0x1F
            x >>= 5
            more = not (x == 0 and (c & 0x10) == 0 or x == -1 and (c & 0x10))
            if more:
                c |= 0x20
            out.append(chr(c + 48))
    return "".join(out)


def rle_from_string(s: str, h: int, w: int) -> Dict:
    """Inverse of :func:`rle_to_string`."""
    counts: List[int] = []
    i = 0
    while i < len(s):
        x = 0
        k = 0
        more = True
        while more:
            c = ord(s[i]) - 48
            x |= (c & 0x1F) << (5 * k)
            more = bool(c & 0x20)
            i += 1
            k += 1
            if not more and (c & 0x10):
                x |= -1 << (5 * k)
        if len(counts) > 2:
            x += counts[-2]
        counts.append(int(x))
    return {"counts": counts, "size": [int(h), int(w)]}


# ------------------------------------------------------- polygon -> mask

def polygons_to_mask(polygons: Sequence[Sequence[float]], h: int, w: int) -> np.ndarray:
    """Rasterize COCO polygon segmentation ([x0, y0, x1, y1, ...] lists)
    into a binary (H, W) mask (reference ``MaskUtils.mergePolysToMask``;
    PIL's polygon fill replaces the reference's hand-written scanline)."""
    from PIL import Image, ImageDraw

    img = Image.new("L", (int(w), int(h)), 0)
    draw = ImageDraw.Draw(img)
    for poly in polygons:
        pts = [(float(poly[i]), float(poly[i + 1]))
               for i in range(0, len(poly) - 1, 2)]
        if len(pts) >= 3:
            draw.polygon(pts, outline=1, fill=1)
    return np.asarray(img, np.uint8)


def segmentation_to_mask(seg, h: int, w: int) -> np.ndarray:
    """Any COCO segmentation form -> binary mask: polygon list,
    uncompressed RLE dict, or compressed-string RLE dict."""
    if isinstance(seg, dict):
        return rle_decode(seg)
    return polygons_to_mask(seg, h, w)


# --------------------------------------------------------- COCO dataset

class COCODataset:
    """COCO instance-annotation reader (reference ``COCODataset.scala``:
    deserialized JSON -> per-image annotations with category remapping).

    ``images``: list of dicts {id, file_name, height, width, annotations:
    [{bbox (xyxy), category_id, label, segmentation, area, iscrowd}]}.
    """

    def __init__(self, annotation_path: str, image_dir: Optional[str] = None):
        with open(annotation_path) as f:
            root = json.load(f)
        self.image_dir = image_dir
        cats = sorted(root.get("categories", []), key=lambda c: c["id"])
        # contiguous 0-based labels in category-id order (reference remaps
        # sparse COCO ids to 1..80; 0-based here per repo convention)
        self.cat_to_label = {c["id"]: i for i, c in enumerate(cats)}
        self.label_names = [c["name"] for c in cats]

        by_image: Dict[int, List[Dict]] = {}
        for ann in root.get("annotations", []):
            by_image.setdefault(ann["image_id"], []).append(ann)

        self.images: List[Dict] = []
        for img in root.get("images", []):
            anns = []
            for a in by_image.get(img["id"], []):
                x, y, bw, bh = a["bbox"]
                anns.append({
                    "bbox": (float(x), float(y), float(x + bw), float(y + bh)),
                    "category_id": a["category_id"],
                    "label": self.cat_to_label.get(a["category_id"], -1),
                    "segmentation": a.get("segmentation"),
                    "area": a.get("area", bw * bh),
                    "iscrowd": int(a.get("iscrowd", 0)),
                })
            self.images.append({
                "id": img["id"],
                "file_name": img.get("file_name"),
                "height": img["height"],
                "width": img["width"],
                "annotations": anns,
            })

    def __len__(self):
        return len(self.images)

    def roi_label(self, index: int, with_masks: bool = True) -> RoiLabel:
        """Ground truth of one image as a RoiLabel (bboxes xyxy + labels +
        binary masks), the detection-training input format."""
        img = self.images[index]
        h, w = img["height"], img["width"]
        boxes, labels, masks = [], [], []
        any_mask = False
        for a in img["annotations"]:
            boxes.append(a["bbox"])
            labels.append(a["label"])
            if with_masks:
                # keep masks 1:1 with boxes (RoiLabel contract): a blank
                # mask stands in for segmentation-less annotations
                if a["segmentation"] is not None:
                    masks.append(segmentation_to_mask(a["segmentation"], h, w))
                    any_mask = True
                else:
                    masks.append(np.zeros((h, w), np.uint8))
        return RoiLabel(
            np.asarray(labels, np.int32),
            np.asarray(boxes, np.float32).reshape(-1, 4),
            masks if (with_masks and any_mask) else None,
        )
