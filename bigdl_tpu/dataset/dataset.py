"""DataSet abstractions.

Reference: ``DL/dataset/DataSet.scala`` — ``AbstractDataSet`` (:53) with
``data(train)``/``size()``/``shuffle()``; ``LocalDataSet`` (:117) over
in-memory arrays; ``DistributedDataSet`` (:171) over RDDs, cached per
partition with an infinite shuffled-index iterator
(``CachedDistriDataSet.data``, :262-296).

TPU-native: one host feeds its local chips, so ``ArrayDataSet`` plays both
roles — ``data(train=True)`` is an infinite shuffled-epoch iterator exactly
like the reference's, and sharding across chips happens at the device-put
boundary (see ``prefetch.py``), not by partitioning the dataset object.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.core.rng import RandomGenerator
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class AbstractDataSet:
    def data(self, train: bool) -> Iterator[Any]:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        pass

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        return TransformedDataSet(self, transformer)

    # reference operator: dataset -> transformer
    def __rshift__(self, transformer: Transformer) -> "TransformedDataSet":
        return self.transform(transformer)

    def batches(self, batch_size: int, train: bool,
                partial_batch: bool = False) -> Iterator[Any]:
        """MiniBatch iterator. Default: group ``data()`` samples via
        SampleToMiniBatch; array-backed datasets override with a sliced
        fast path (no per-sample Python objects)."""
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch

        return SampleToMiniBatch(batch_size, partial_batch=partial_batch).apply(
            self.data(train))


class ArrayDataSet(AbstractDataSet):
    """In-memory dataset of Samples or arbitrary elements
    (reference: ``LocalArrayDataSet`` + ``CachedDistriDataSet`` semantics:
    train iterator is infinite with per-epoch reshuffle)."""

    def __init__(self, elements: Sequence[Any], rng: Optional[RandomGenerator] = None):
        self.elements = list(elements)
        self.rng = rng or RandomGenerator.default()
        self._perm = np.arange(len(self.elements))

    def size(self) -> int:
        return len(self.elements)

    def shuffle(self) -> None:
        self._perm = self.rng.permutation(len(self.elements))

    def data(self, train: bool) -> Iterator[Any]:
        if not train:
            return iter(self.elements)
        def infinite():
            while True:
                self.shuffle()
                for i in self._perm:
                    yield self.elements[i]
        return infinite()


class TensorDataSet(AbstractDataSet):
    """Dataset over pre-stacked arrays (features, labels) — avoids the
    per-sample Python object overhead for dense fixed-shape data; slices
    batches directly (fast path used by the vision loaders)."""

    def __init__(
        self,
        features: np.ndarray,
        labels: Optional[np.ndarray] = None,
        rng: Optional[RandomGenerator] = None,
    ):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self.rng = rng or RandomGenerator.default()

    def size(self) -> int:
        return len(self.features)

    def data(self, train: bool) -> Iterator[Sample]:
        if not train:
            for i in range(len(self.features)):
                yield Sample(self.features[i], None if self.labels is None else self.labels[i])
            return
        while True:
            perm = self.rng.permutation(len(self.features))
            for i in perm:
                yield Sample(self.features[i], None if self.labels is None else self.labels[i])

    def batches(self, batch_size: int, train: bool,
                partial_batch: bool = False) -> Iterator["MiniBatch"]:
        """Sliced fast path: one vectorized fancy-index gather per batch —
        no per-sample Sample objects, no re-stacking (the reference's
        ``MTLabeledBGRImgToBatch`` multi-threaded batcher exists to get the
        same effect on the JVM)."""
        from bigdl_tpu.dataset.sample import MiniBatch

        n = len(self.features)

        def eval_batches():
            for i in range(0, n, batch_size):
                if i + batch_size > n and not partial_batch:
                    return
                idx = slice(i, min(i + batch_size, n))
                yield MiniBatch(
                    self.features[idx],
                    None if self.labels is None else self.labels[idx],
                )

        if train and batch_size > n:
            raise ValueError(
                f"batch_size {batch_size} exceeds dataset size {n}: the "
                "drop-last training stream would never yield a batch")

        def train_batches():
            while True:
                perm = self.rng.permutation(n)
                for i in range(0, n - batch_size + 1, batch_size):
                    idx = perm[i:i + batch_size]
                    yield MiniBatch(
                        self.features[idx],
                        None if self.labels is None else self.labels[idx],
                    )

        return train_batches() if train else eval_batches()


class TransformedDataSet(AbstractDataSet):
    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self) -> int:
        return self.base.size()

    def shuffle(self) -> None:
        self.base.shuffle()

    def data(self, train: bool) -> Iterator[Any]:
        return self.transformer.apply(self.base.data(train))

    def parallel(self, n_workers: int, **kwargs) -> "TransformedDataSet":
        """Fan this dataset's elementwise transformer run across a worker
        pool (see :func:`bigdl_tpu.dataset.parallel_pipeline
        .parallelize_chain`); batching/shuffle stages stay serial.
        ``Optimizer.set_data_pipeline`` does the same wiring with the
        optimizer's seed and stats."""
        from bigdl_tpu.dataset.parallel_pipeline import parallelize_chain

        return TransformedDataSet(
            self.base, parallelize_chain(self.transformer, n_workers,
                                         **kwargs))


class DataSet:
    """Factory namespace (reference: object ``DataSet`` at
    ``DataSet.scala:326`` with ``array()``/``rdd()``)."""

    @staticmethod
    def array(elements: Sequence[Any], rng: Optional[RandomGenerator] = None) -> ArrayDataSet:
        return ArrayDataSet(elements, rng)

    @staticmethod
    def tensors(features, labels=None, rng=None) -> TensorDataSet:
        return TensorDataSet(features, labels, rng)
