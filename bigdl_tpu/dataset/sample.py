"""Sample and MiniBatch.

Reference: ``DL/dataset/Sample.scala:32,138,446`` (feature+label tensor
pack) and ``MiniBatch.scala:34,111`` (batched pack with ``slice()`` for
per-thread splits and padding strategies :523-587). Host-side data is
numpy; a ``MiniBatch`` converts to device arrays at the trainer boundary.

The reference's per-thread ``slice()`` is unnecessary under SPMD (one
program per chip) — sharding happens via ``jax.device_put`` with a
NamedSharding instead.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class Sample:
    """One training example: feature pytree + label pytree (numpy)."""

    feature: Any
    label: Any = None

    @staticmethod
    def of(feature, label=None) -> "Sample":
        return Sample(np.asarray(feature), None if label is None else np.asarray(label))

    def feature_shape(self):
        return np.asarray(self.feature).shape

    def label_shape(self):
        return None if self.label is None else np.asarray(self.label).shape


class PaddingParam:
    """Padding strategy for variable-length samples
    (reference: ``MiniBatch.scala:523-587`` PaddingLongest/FixedLength)."""

    def __init__(self, padding_value: float = 0.0, fixed_length: Optional[int] = None):
        self.padding_value = padding_value
        self.fixed_length = fixed_length

    def target_length(self, lengths: Sequence[int]) -> int:
        return self.fixed_length if self.fixed_length is not None else max(lengths)


@dataclasses.dataclass
class MiniBatch:
    """A batch of stacked features/labels (numpy, host)."""

    input: Any
    target: Any = None

    def size(self) -> int:
        leaf = self.input
        while isinstance(leaf, (tuple, list, dict)):
            leaf = list(leaf.values())[0] if isinstance(leaf, dict) else leaf[0]
        return leaf.shape[0]

    def get_input(self):
        return self.input

    def get_target(self):
        return self.target

    @staticmethod
    def stack(
        samples: Sequence[Sample],
        feature_padding: Optional[PaddingParam] = None,
        label_padding: Optional[PaddingParam] = None,
    ) -> "MiniBatch":
        feats = _stack_component([s.feature for s in samples], feature_padding)
        labels = None
        if samples[0].label is not None:
            labels = _stack_component([s.label for s in samples], label_padding)
        return MiniBatch(feats, labels)


def _stack_component(values, padding: Optional[PaddingParam]):
    """Stack one feature/label slot; multi-tensor samples (reference
    ``TensorSample`` with several feature tensors, ``Sample.scala:446``)
    arrive as TUPLES and stack per component (plain lists are raw array
    data, e.g. ``Sample([1.0, 2.0])``, and stack as one tensor)."""
    if isinstance(values[0], tuple):
        n = len(values[0])
        return tuple(
            _stack_padded([np.asarray(v[i]) for v in values], padding)
            for i in range(n)
        )
    return _stack_padded([np.asarray(v) for v in values], padding)


def _stack_padded(arrays, padding: Optional[PaddingParam]):
    shapes = {a.shape for a in arrays}
    if len(shapes) == 1 and padding is None:
        return np.stack(arrays)
    if padding is None:
        raise ValueError(
            f"samples have differing shapes {shapes}; pass a PaddingParam to pad/bucket them"
        )
    # pad dim 0 (sequence dim of each sample) to target length
    lengths = [a.shape[0] for a in arrays]
    target = padding.target_length(lengths)
    out = []
    for a in arrays:
        if a.shape[0] < target:
            widths = [(0, target - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
            a = np.pad(a, widths, constant_values=padding.padding_value)
        elif a.shape[0] > target:
            a = a[:target]
        out.append(a)
    return np.stack(out)


class SparseMiniBatch(MiniBatch):
    """MiniBatch over sparse features (reference ``SparseMiniBatch``,
    ``MiniBatch.scala:588``): stacks per-sample (ids, weights) bags into
    the padded-COO device layout (ids, weights, mask), reusing
    ``core.sparse.SparseTensor`` for the packing (raises on max_nnz
    overflow rather than silently truncating).

    ``stack(samples, max_nnz)``: each sample's feature is a
    ``(ids, weights)`` pair (weights may be None) or a single-row
    ``core.sparse.SparseTensor``.
    """

    @staticmethod
    def stack(samples: Sequence[Sample], max_nnz: Optional[int] = None) -> "SparseMiniBatch":
        from bigdl_tpu.core.sparse import SparseTensor

        bags, weights, n_cols = [], [], 1
        for s in samples:
            f = s.feature
            if isinstance(f, SparseTensor):
                if f.shape[0] != 1:
                    raise ValueError(
                        f"sample feature SparseTensor must be single-row, got shape {f.shape}")
                bags.append([int(c) for c in f.indices[:, 1]])
                weights.append([float(v) for v in f.values])
                n_cols = max(n_cols, f.shape[1])
            else:
                ids_, w_ = (f if isinstance(f, tuple) else (f, None))
                ids_ = [int(i) for i in np.asarray(ids_, np.int64).reshape(-1)]
                bags.append(ids_)
                weights.append(
                    [1.0] * len(ids_) if w_ is None
                    else [float(v) for v in np.asarray(w_, np.float32).reshape(-1)])
                n_cols = max(n_cols, (max(ids_) + 1) if ids_ else 1)
        st = SparseTensor.from_bags(bags, n_cols, weights)
        ids, vals, mask = st.to_padded(max_nnz)
        target = None
        if samples[0].label is not None:
            target = np.stack([np.asarray(s.label) for s in samples])
        return SparseMiniBatch((ids, vals, mask), target)


class SampleToSparseMiniBatch:
    """Transformer: group sparse-feature samples into SparseMiniBatches
    (reference pairs ``SparseMiniBatch`` with ``SampleToMiniBatch``)."""

    elementwise = False  # N:1 grouping — stays outside a worker pool

    def __init__(self, batch_size: int, max_nnz: Optional[int] = None,
                 partial_batch: bool = False):
        self.batch_size = batch_size
        self.max_nnz = max_nnz
        self.partial_batch = partial_batch

    def apply(self, it):
        buf = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield SparseMiniBatch.stack(buf, self.max_nnz)
                buf = []
        if buf and self.partial_batch:
            yield SparseMiniBatch.stack(buf, self.max_nnz)

    def __call__(self, it):
        return self.apply(iter(it))
