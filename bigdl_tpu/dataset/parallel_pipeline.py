"""Parallel host input pipeline: a worker-pool transformer stage.

The round-5 feeder roofline (``perf/feeder_roofline.py``) measured the
augment chain at ~10k img/s on ONE Python thread and projected that once
GB/s-scale DMA replaces the tunnel, host augment/decode becomes the
binding stage for the ~2,900 img/s/chip compute rate. The reference's
answer is a multi-threaded transformer pool
(``DL/dataset/image/MTLabeledBGRImgToBatch.scala``); this module is the
TPU-native equivalent:

- :class:`ParallelTransformer` fans one upstream iterator across
  ``n_workers`` workers each running the (numpy-heavy, GIL-releasing)
  transformer chain, reassembling through bounded, backpressured queues.
  ``ordered=True`` keeps deterministic batch order (round-robin dispatch
  and collection — bounded memory, no unbounded reorder buffer);
  ``ordered=False`` yields whatever finishes first.
- Determinism: each element's augmentation is seeded from
  ``(base_seed, element_index)`` via :func:`bigdl_tpu.core.rng.element_seed`,
  so in ordered mode the emitted stream is bit-identical regardless of
  worker count (test-enforced).
- Error propagation and shutdown follow the sticky-failure / sentinel
  patterns proven in ``host_prefetch`` and ``SocketFeedDataSet``: a worker
  exception fails the consumer with the original exception (traceback
  preserved; process workers attach the remote traceback text), and
  abandoning the generator retires all workers within a bounded join.
- ``processes=True`` runs the workers as spawned processes with results
  handed back through ``multiprocessing.shared_memory`` blocks using
  pickle protocol-5 out-of-band buffers — array payloads are rebuilt
  zero-copy as views of the shared block on the consumer side. For
  Python-bound (GIL-holding) transforms the thread pool can't scale.
- :class:`PipelineStats` counts per-stage items, bytes, queue occupancy,
  producer stall and consumer starve time; ``format_table()`` renders the
  fixed-width dump (like ``ServingMetrics``), and ``bench.py --mode
  pipeline`` reports per-stage img/s plus the end-to-end ratio vs
  ``min(stage rates)`` (the 0.97x methodology from the feeder roofline).
"""

from __future__ import annotations

import collections
import copy
import logging
import pickle
import threading
import time
import traceback
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu import faults
from bigdl_tpu.core.rng import RandomGenerator, element_seed
from bigdl_tpu.dataset.transformer import ChainedTransformer, Transformer
from bigdl_tpu.utils.errors import fresh_exception

log = logging.getLogger("bigdl_tpu.dataset")


# --------------------------------------------------------------------------
# Bounded queue with close/abort (no poll loops: blocked producers and
# consumers are woken by condition notify, not by timing out every 50 ms)
# --------------------------------------------------------------------------

class Closed(Exception):
    """Raised by :class:`CloseableQueue` ops once the queue is closed
    (graceful: after draining) or aborted (immediately)."""


class CloseableQueue:
    """Bounded FIFO whose blocked ``put``/``get`` are woken by ``close()``
    / ``abort()`` instead of polling.

    ``close()`` is the graceful end-of-stream: further ``put`` raises
    :class:`Closed`, ``get`` drains the remaining items then raises.
    ``abort()`` is the shutdown path: discards buffered items and wakes
    everyone immediately (the consumer-walked-away case).
    """

    def __init__(self, maxsize: int):
        self._dq: collections.deque = collections.deque()
        self.maxsize = max(1, int(maxsize))
        lock = threading.Lock()
        self._not_full = threading.Condition(lock)
        self._not_empty = threading.Condition(lock)
        self._closed = False
        self._aborted = False

    def qsize(self) -> int:
        return len(self._dq)

    def put(self, item) -> float:
        """Blocking put; returns seconds spent blocked (producer stall)."""
        waited = 0.0
        with self._not_full:
            while (len(self._dq) >= self.maxsize
                   and not (self._closed or self._aborted)):
                t0 = time.perf_counter()
                self._not_full.wait()
                waited += time.perf_counter() - t0
            if self._closed or self._aborted:
                raise Closed
            self._dq.append(item)
            self._not_empty.notify()
        return waited

    def get(self):
        """Blocking get; returns ``(item, seconds_blocked)``."""
        waited = 0.0
        with self._not_empty:
            while not self._dq and not (self._closed or self._aborted):
                t0 = time.perf_counter()
                self._not_empty.wait()
                waited += time.perf_counter() - t0
            if self._aborted or not self._dq:  # closed-and-drained or aborted
                raise Closed
            item = self._dq.popleft()
            self._not_full.notify()
        return item, waited

    def close(self) -> None:
        with self._not_full:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()

    def abort(self) -> None:
        with self._not_full:
            self._aborted = True
            self._dq.clear()
            self._not_full.notify_all()
            self._not_empty.notify_all()


# --------------------------------------------------------------------------
# Per-stage observability
# --------------------------------------------------------------------------

def nbytes_of(item: Any) -> int:
    """Total array bytes in a pipeline element (MiniBatch / Sample /
    array pytree); 0 for anything unsized."""
    from bigdl_tpu.dataset.sample import MiniBatch, Sample

    if isinstance(item, MiniBatch):
        return nbytes_of(item.input) + nbytes_of(item.target)
    if isinstance(item, Sample):
        return nbytes_of(item.feature) + nbytes_of(item.label)
    if isinstance(item, (tuple, list)):
        return sum(nbytes_of(x) for x in item)
    if isinstance(item, dict):
        return sum(nbytes_of(x) for x in item.values())
    nbytes = getattr(item, "nbytes", None)
    return int(nbytes) if isinstance(nbytes, (int, np.integer)) else 0


class StageStats:
    """Counters for one pipeline stage. All mutators are O(1) and take a
    per-stage lock — cheap enough to stay on in production."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.items = 0
        self.bytes = 0
        self.restarts = 0    # supervised worker restarts (pool stages)
        self.stall_s = 0.0   # producer blocked on a full downstream queue
        self.starve_s = 0.0  # consumer blocked on an empty upstream queue
        self.queue_cap = 0
        self.queue_max = 0
        self._queue_sum = 0
        self._queue_samples = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None

    def record(self, items: int = 1, nbytes: int = 0) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._t_first is None:
                self._t_first = now
            self._t_last = now
            self.items += items
            self.bytes += nbytes

    def record_restart(self) -> None:
        with self._lock:
            self.restarts += 1

    def record_stall(self, dt: float) -> None:
        if dt > 0:
            with self._lock:
                self.stall_s += dt

    def record_starve(self, dt: float) -> None:
        if dt > 0:
            with self._lock:
                self.starve_s += dt

    def record_queue(self, depth: int, cap: int) -> None:
        with self._lock:
            self.queue_cap = cap
            self.queue_max = max(self.queue_max, depth)
            self._queue_sum += depth
            self._queue_samples += 1

    def snapshot(self) -> dict:
        with self._lock:
            elapsed = ((self._t_last - self._t_first)
                       if self._t_first is not None and self._t_last is not None
                       else 0.0)
            # rate over the first->last record window; with one record the
            # window is 0 and the rate is unknowable, not infinite
            rate = (self.items - 1) / elapsed if elapsed > 0 else 0.0
            return {
                "items": self.items,
                "mb": self.bytes / 1e6,
                "restarts": self.restarts,
                "items_per_sec": rate,
                "stall_s": self.stall_s,
                "starve_s": self.starve_s,
                "queue_mean": (self._queue_sum / self._queue_samples
                               if self._queue_samples else 0.0),
                "queue_max": self.queue_max,
                "queue_cap": self.queue_cap,
            }


class PipelineStats:
    """Registry of :class:`StageStats`, one per named stage of the input
    pipeline (produce / augment xN / stage / transfer). ``format_table()``
    is the fixed-width dump in the style of ``ServingMetrics``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stages: "collections.OrderedDict[str, StageStats]" = \
            collections.OrderedDict()

    def stage(self, name: str) -> StageStats:
        with self._lock:
            s = self._stages.get(name)
            if s is None:
                s = self._stages[name] = StageStats(name)
            return s

    def snapshot(self) -> dict:
        with self._lock:
            stages = list(self._stages.items())
        return {name: s.snapshot() for name, s in stages}

    def format_table(self) -> str:
        snap = self.snapshot()
        header = (f"{'stage':<18} {'items':>9} {'MB':>9} {'items/s':>10} "
                  f"{'queue':>9} {'stall_s':>8} {'starve_s':>9}")
        lines = [header]
        for name, s in snap.items():
            occ = (f"{s['queue_mean']:.1f}/{s['queue_cap']}"
                   if s["queue_cap"] else "-")
            lines.append(
                f"{name:<18} {s['items']:>9} {s['mb']:>9.1f} "
                f"{s['items_per_sec']:>10.0f} {occ:>9} "
                f"{s['stall_s']:>8.2f} {s['starve_s']:>9.2f}")
        return "\n".join(lines)


# --------------------------------------------------------------------------
# The worker-pool transformer
# --------------------------------------------------------------------------

class _Failure:
    """Queue marker: a worker failed; carries the original exception (and,
    for process workers, the remote traceback text)."""

    def __init__(self, exc: BaseException, tb_text: str):
        self.exc = exc
        self.tb_text = tb_text

    def reraise(self):
        # raise a per-call copy: a _Failure can be rendered more than once
        # (sticky-fail re-entry, supervised-restart exhaustion reporting),
        # and re-raising the stored object would mutate the traceback a
        # prior consumer already captured (GL001)
        exc = fresh_exception(self.exc)
        if exc.__traceback__ is None and self.tb_text:
            # crossed a process boundary: pickling drops both the
            # traceback and any __cause__, so re-chain the remote text
            raise exc from RuntimeError(
                "pipeline worker traceback:\n" + self.tb_text)
        raise exc  # thread worker: original traceback intact


_PIPELINE_END = None  # process-mode end sentinel (picklable)


def _collect_rng_nodes(transformer) -> List[Any]:
    """Transformers in chain order that hold a ``RandomGenerator`` — the
    nodes the pool reseeds per element for worker-count-independent
    augmentation."""
    nodes: List[Any] = []

    def walk(t):
        if isinstance(t, ChainedTransformer):
            walk(t.first)
            walk(t.second)
            return
        if isinstance(getattr(t, "rng", None), RandomGenerator):
            nodes.append(t)

    walk(transformer)
    return nodes


def _apply_chunk(inner, rng_nodes, base_seed, start_idx, elems) -> list:
    """Run ``inner`` over one dispatched chunk, reseeding every rng-bearing
    node from ``(base_seed, element_index, node_position)`` before each
    element. The reseed rides the source iterator: generator chains are
    pull-driven, so element j's draws all happen between its reseed and
    element j+1's — and the chain is constructed once per chunk, not once
    per element. Output arity is free (filters drop, expanders multiply);
    outputs stay grouped per chunk so ordered reassembly needs exactly one
    queue item per dispatch."""
    def seeded():
        for j, elem in enumerate(elems):
            # fault site, keyed on the ELEMENT index: an armed rate plan
            # faults the same elements whatever the worker count or
            # chunking, so supervised replays stay bit-identical
            faults.fire("pipeline.worker", key=start_idx + j)
            for k, node in enumerate(rng_nodes):
                node.rng.reseed(
                    element_seed(base_seed, start_idx + j, stream=k))
            yield elem

    return list(inner.apply(seeded()))


class ParallelTransformer(Transformer):
    """Worker-pool wrapper around an elementwise transformer (chain).

    ``(aug_chain).parallel(8) >> SampleToMiniBatch(128)`` — any existing
    ``>>`` chain opts in with one call. The wrapped transformer must be
    elementwise (1 element in -> 0..k elements out, no cross-element
    state); batching stages stay outside the pool (or use
    :func:`parallelize_chain`, which splits a full chain automatically).

    ``depth`` bounds each worker's input and output queue (total in-flight
    elements <= ``n_workers * 2 * depth * chunk`` + worker-held chunks):
    the reassembly queue is backpressured, a slow consumer stalls the
    feeder, a slow source starves the consumer, and both times land in
    ``stats``.

    ``processes=True`` ships the wrapped chain to spawned workers by
    pickle — transformers must be picklable (module-level functions, not
    lambdas, inside ``FunctionTransformer``).

    **Supervision**: a worker whose chunk fails with a transient error is
    restarted — a fresh copy of the chain replays the dispatched chunk;
    the per-element reseed makes the replay bit-exact, so ordered-mode
    output is identical whether or not a restart happened. Each worker
    restarts at most ``max_worker_restarts`` times; a poison element
    that kills the replacement too (or an exhausted budget) fails the
    consumer with the ORIGINAL exception and traceback. ``BaseException``
    escapes (KeyboardInterrupt, SystemExit) are never retried.
    """

    elementwise = True  # the pool itself is 1:k per element, poolable-safe

    def __init__(
        self,
        inner,
        n_workers: int,
        *,
        ordered: bool = True,
        processes: bool = False,
        depth: int = 2,
        chunk: int = 1,
        base_seed: Optional[int] = None,
        stats: Optional[PipelineStats] = None,
        stage: Optional[str] = None,
        join_timeout: float = 5.0,
        max_worker_restarts: int = 2,
    ):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        if max_worker_restarts < 0:
            raise ValueError("max_worker_restarts must be >= 0")
        self.inner = inner
        self.n_workers = int(n_workers)
        self.ordered = ordered
        self.processes = processes
        self.depth = max(1, int(depth))
        self.chunk = max(1, int(chunk))
        self.base_seed = (RandomGenerator.default().seed
                          if base_seed is None else int(base_seed))
        self.stats = stats
        self.stage_name = stage or (
            f"augment x{self.n_workers}" + ("p" if processes else ""))
        self.join_timeout = join_timeout
        self.max_worker_restarts = int(max_worker_restarts)

    def apply(self, it: Iterator[Any]) -> Iterator[Any]:
        if self.processes:
            return self._apply_processes(it)
        return self._apply_threads(it)

    # ------------------------------------------------------ thread pool ----
    def _apply_threads(self, it: Iterator[Any]) -> Iterator[Any]:
        n = self.n_workers
        st = self.stats.stage(self.stage_name) if self.stats else None
        # ordered: per-worker queues, round-robin dispatch/collect gives
        # deterministic order with bounded memory. unordered: one shared
        # queue pair, lowest latency.
        if self.ordered:
            inqs = [CloseableQueue(self.depth) for _ in range(n)]
            outqs = [CloseableQueue(self.depth) for _ in range(n)]
        else:
            inqs = [CloseableQueue(self.depth * n)]
            outqs = [CloseableQueue(self.depth * n)]
        out_cap = sum(q.maxsize for q in outqs)
        feed_err: list = []
        live_workers = [n]  # unordered: last worker out closes the shared outq
        lock = threading.Lock()

        def feed():
            try:
                idx = 0
                buf: list = []
                target = 0
                for elem in it:
                    buf.append(elem)
                    if len(buf) < self.chunk:
                        continue
                    stalled = inqs[target % len(inqs)].put((idx, buf))
                    if st is not None:
                        st.record_stall(stalled)
                    idx += len(buf)
                    buf = []
                    target += 1
                if buf:
                    stalled = inqs[target % len(inqs)].put((idx, buf))
                    if st is not None:
                        st.record_stall(stalled)
            except Closed:
                return  # consumer walked away
            except BaseException as e:  # upstream failed: surface it
                feed_err.append(e)
            finally:
                for q in inqs:
                    q.close()

        def work(wid: int):
            state = [copy.deepcopy(self.inner)]
            state.append(_collect_rng_nodes(state[0]))
            budget = [self.max_worker_restarts]
            inq = inqs[wid % len(inqs)]
            outq = outqs[wid % len(outqs)]
            try:
                while True:
                    try:
                        start_idx, elems = inq.get()[0]
                    except Closed:
                        break
                    outs, failure = _supervised_chunk(
                        self.inner, state, self.base_seed, start_idx,
                        elems, budget, st, f"worker {wid}")
                    if failure is not None:
                        try:
                            outq.put(failure)
                        except Closed:
                            pass
                        break
                    try:
                        outq.put(outs)
                    except Closed:
                        break
            finally:
                if self.ordered:
                    outq.close()
                else:
                    with lock:
                        live_workers[0] -= 1
                        last = live_workers[0] == 0
                    if last:
                        outq.close()

        feeder = threading.Thread(target=feed, name="pipeline-feeder",
                                  daemon=True)
        workers = [threading.Thread(target=work, args=(w,),
                                    name=f"pipeline-worker-{w}", daemon=True)
                   for w in range(n)]

        def consume():
            # started HERE, not in apply(): a generator abandoned before
            # its first next() never runs this body (or its finally), so
            # an eager start would strand the feeder and every worker
            # blocked on the filled queues forever
            feeder.start()
            for t in workers:
                t.start()
            try:
                w = 0
                while True:
                    try:
                        item, starved = outqs[w % len(outqs)].get()
                    except Closed:
                        break
                    w += 1
                    if st is not None:
                        st.record_starve(starved)
                        st.record_queue(sum(q.qsize() for q in outqs), out_cap)
                    if isinstance(item, _Failure):
                        item.reraise()
                    for out in item:
                        if st is not None:
                            st.record(1, nbytes_of(out))
                        yield out
                if feed_err:
                    raise feed_err[0]
            finally:
                for q in inqs + outqs:
                    q.abort()
                feeder.join(self.join_timeout)
                for t in workers:
                    t.join(self.join_timeout)

        return consume()

    # ----------------------------------------------------- process pool ----
    def _apply_processes(self, it: Iterator[Any]) -> Iterator[Any]:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")  # fork is unsafe under jax's threads
        n = self.n_workers
        st = self.stats.stage(self.stage_name) if self.stats else None
        if self.ordered:
            inqs = [ctx.Queue(maxsize=self.depth) for _ in range(n)]
            outqs = [ctx.Queue(maxsize=self.depth) for _ in range(n)]
        else:
            inqs = [ctx.Queue(maxsize=self.depth * n)]
            outqs = [ctx.Queue(maxsize=self.depth * n)]
        stop = threading.Event()
        feed_err: list = []

        procs = [
            ctx.Process(
                target=_process_worker_main,
                args=(self.inner, self.base_seed, inqs[w % len(inqs)],
                      outqs[w % len(outqs)], not self.ordered,
                      self.max_worker_restarts),
                daemon=True,
            )
            for w in range(n)
        ]

        def feed():
            import queue as _q

            def put(q, item):
                # mp.Queue has no close-wakes-put; bounded timeout retries
                # woken by the stop flag keep abandonment prompt
                t0 = time.perf_counter()
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        if st is not None:
                            st.record_stall(time.perf_counter() - t0)
                        return True
                    except _q.Full:
                        continue
                return False

            try:
                idx = 0
                buf: list = []
                target = 0
                for elem in it:
                    buf.append(elem)
                    if len(buf) < self.chunk:
                        continue
                    if not put(inqs[target % len(inqs)], (idx, buf)):
                        return
                    idx += len(buf)
                    buf = []
                    target += 1
                if buf and not put(inqs[target % len(inqs)], (idx, buf)):
                    return
            except BaseException as e:
                feed_err.append(e)
            finally:
                # one end sentinel per worker (unordered: all share inqs[0])
                for w in range(n):
                    put(inqs[w % len(inqs)], _PIPELINE_END)

        feeder = threading.Thread(target=feed, name="pipeline-feeder",
                                  daemon=True)

        def consume():
            import queue as _q

            # started HERE, not in apply(): see the thread-mode note —
            # an abandoned-before-first-next() generator must not strand
            # live spawned processes and their queues
            for p in procs:
                p.start()
            feeder.start()

            out_cap = n * self.depth

            def get_checked(qi):
                # a worker killed without its end sentinel (OOM, signal)
                # must not hang the consumer forever. Ordered mode: each
                # queue has ONE owning worker — its death alone starves
                # this queue even while siblings live; unordered: the
                # shared queue dies only with the whole pool.
                q = outqs[qi]
                t0 = time.perf_counter()
                while True:
                    try:
                        msg = q.get(timeout=1.0)
                        break
                    except _q.Empty:
                        owners = [procs[qi]] if self.ordered else procs
                        if not any(p.is_alive() for p in owners):
                            raise RuntimeError(
                                "pipeline worker process(es) died without "
                                "reporting a result") from None
                if st is not None:
                    st.record_starve(time.perf_counter() - t0)
                return msg

            clean_end = False
            try:
                w = 0
                ended = 0
                while ended < (1 if self.ordered else n):
                    msg = get_checked(w % len(outqs))
                    if msg is _PIPELINE_END:
                        ended += 1
                        continue
                    if isinstance(msg, tuple) and len(msg) == 1 \
                            and msg[0] == "restart-stat":
                        # a child-process supervised restart: the child
                        # cannot reach the parent's StageStats, so it
                        # forwards each restart as a marker (same queue,
                        # so it precedes the healed chunk's result)
                        if st is not None:
                            st.record_restart()
                        continue
                    w += 1
                    item = _unpack_result(msg)
                    if st is not None:
                        st.record_queue(sum(q.qsize() for q in outqs), out_cap)
                    if isinstance(item, _Failure):
                        item.reraise()
                    for out in item:
                        if st is not None:
                            st.record(1, nbytes_of(out))
                        yield out
                clean_end = True  # every worker sent its end sentinel
                if feed_err:
                    raise feed_err[0]
            finally:
                stop.set()
                # cleanly-ended workers exit on their own; terminate only
                # stragglers (abandon/error paths), whose SIGTERM handler
                # unwinds cleanly so in-flight messages get flushed
                deadline = time.monotonic() + (self.join_timeout
                                               if clean_end else 0.25)
                for p in procs:
                    p.join(max(0.0, deadline - time.monotonic()))
                for p in procs:
                    if p.is_alive():
                        p.terminate()
                for p in procs:
                    p.join(self.join_timeout)
                feeder.join(self.join_timeout)
                # with the workers dead, unlink shared-memory blocks of
                # messages nobody will ever open (best-effort: a block can
                # still slip through if terminate caught a worker mid-put)
                for q in outqs:
                    _drain_queue_shm(q)
                for q in inqs + outqs:
                    q.cancel_join_thread()
                    q.close()

        return consume()


def _supervised_chunk(template, state, base_seed, start_idx, elems,
                      budget, st, who):
    """Run one dispatched chunk under worker supervision. On a transient
    (``Exception``-class) failure the worker is "restarted": a fresh
    deep copy of the ``template`` chain replaces its state and the chunk
    replays — bit-exact, because every element reseeds its rng nodes
    from ``(base_seed, element_index)``. ``budget`` is the worker's
    remaining restart allowance (mutated in place); once it is exhausted
    — or the same poison element kills the replacement — the failure
    reported to the consumer carries the ORIGINAL exception and
    traceback, not the last retry's. Returns ``(outs, failure)``,
    exactly one non-None."""
    failure = None
    while True:
        try:
            return _apply_chunk(state[0], state[1], base_seed, start_idx,
                                elems), None
        except BaseException as e:
            if failure is None:
                failure = _Failure(e, traceback.format_exc())
            if budget[0] <= 0 or not isinstance(e, Exception):
                return None, failure
            budget[0] -= 1
            if st is not None:
                st.record_restart()
            log.warning(
                "pipeline %s failed on chunk @%d (%s: %s); restarting the "
                "worker with a fresh chain and re-dispatching (%d "
                "restart(s) left)", who, start_idx, type(e).__name__, e,
                budget[0])
            state[0] = copy.deepcopy(template)
            state[1] = _collect_rng_nodes(state[0])


# ---- process-mode helpers (module level: must be importable by spawn) ----


class _QueueRestartStat:
    """Process-worker stand-in for :class:`StageStats`: restarts happen
    in the child, the stats registry lives in the parent, so each
    restart is forwarded as a one-element queue marker the consumer
    folds into the real ``StageStats``."""

    __slots__ = ("outq",)

    def __init__(self, outq):
        self.outq = outq

    def record_restart(self) -> None:
        self.outq.put(("restart-stat",))


def _pack_result(outs: list, name_out: Optional[list] = None):
    """Serialize a chunk's outputs with pickle protocol 5; array payloads
    go out-of-band into ONE shared-memory block so the consumer rebuilds
    them zero-copy. Returns a picklable message. ``name_out``: the block
    name is appended the moment it exists, so an interrupting SIGTERM
    can reclaim it whatever line it lands on."""
    from multiprocessing import shared_memory

    buffers: list = []
    data = pickle.dumps(outs, protocol=5, buffer_callback=buffers.append)
    if not buffers:
        return ("inline", data, None, None)
    raws = [b.raw() for b in buffers]
    total = sum(r.nbytes for r in raws)
    if total == 0:
        return ("inline", pickle.dumps(outs, protocol=4), None, None)
    shm = shared_memory.SharedMemory(create=True, size=total)
    if name_out is not None:
        name_out.append(shm.name)
    spans = []
    off = 0
    for r in raws:
        shm.buf[off:off + r.nbytes] = r
        spans.append((off, r.nbytes))
        off += r.nbytes
    name = shm.name
    shm.close()
    try:  # ownership moves to the consumer; silence this process's tracker
        from multiprocessing import resource_tracker

        resource_tracker.unregister("/" + name.lstrip("/"), "shared_memory")
    except Exception:
        pass
    return ("shm", data, name, spans)


def _unpack_result(msg):
    """Rebuild a packed chunk zero-copy. The block is mapped, the name
    unlinked immediately (POSIX keeps the memory while mapped), and the
    rebuilt arrays are views over the mapping. Lifetime needs no
    finalizers: each array's buffer chain (array -> PickleBuffer ->
    memoryview slice -> mmap) keeps the mapping alive, and the mapping is
    torn down by the mmap object's dealloc when the last view dies — so
    the ``SharedMemory`` wrapper is stripped eagerly (master buffer
    released, fd closed, mmap detached) instead of fighting ``__del__``
    ordering against live buffer exports."""
    kind, data, name, spans = msg
    if kind == "inline":
        return pickle.loads(data)
    from multiprocessing import shared_memory

    shm = shared_memory.SharedMemory(name=name)
    try:
        shm.unlink()
    except FileNotFoundError:
        pass
    # slices export the underlying mmap directly (not shm's master view)
    views = [pickle.PickleBuffer(shm.buf[off:off + ln]) for off, ln in spans]
    outs = pickle.loads(data, buffers=views)
    if shm._buf is not None:
        shm._buf.release()
        shm._buf = None
    shm._mmap = None  # unmapped when the last array view releases it
    if getattr(shm, "_fd", -1) >= 0:
        import os

        os.close(shm._fd)
        shm._fd = -1
    return outs


def _drain_queue_shm(q) -> None:
    """Best-effort unlink of shared blocks still sitting in an abandoned
    result queue (their consumer will never map them)."""
    import queue as _q

    while True:
        try:
            msg = q.get(timeout=0.05)
        except (_q.Empty, OSError, ValueError):
            return
        _unlink_msg_shm(msg)


def _unlink_msg_shm(msg) -> None:
    if isinstance(msg, tuple) and len(msg) == 4 and msg[0] == "shm":
        from multiprocessing import shared_memory

        try:
            shm = shared_memory.SharedMemory(name=msg[2])
            shm.close()
            shm.unlink()
        except Exception:
            pass


def _process_worker_main(inner, base_seed, inq, outq, shared_input,
                         max_restarts=0):
    """Spawned worker process: pull chunks, transform, push packed results.
    ``shared_input``: unordered mode — re-queue the end sentinel so every
    sibling worker also sees it. ``max_restarts`` is this worker's own
    supervision budget (each process supervises itself; a process KILLED
    outright still surfaces through the consumer's liveness check)."""
    import signal

    def sigterm_to_exit(signum, frame):
        raise SystemExit(0)

    # parent shutdown uses terminate() (SIGTERM); converting it to a
    # Python-level unwind lets the interpreter's exit hooks flush the
    # queue's feeder-thread buffer, so in-flight shared-memory messages
    # reach the parent (which unlinks them) instead of leaking
    signal.signal(signal.SIGTERM, sigterm_to_exit)

    # `inner` stays the pristine template (as shipped); the working copy
    # is what restarts replace — matching the thread pool exactly
    state = [copy.deepcopy(inner)]
    state.append(_collect_rng_nodes(state[0]))
    budget = [int(max_restarts)]
    while True:
        task = inq.get()
        if task is _PIPELINE_END:
            if shared_input:
                inq.put(_PIPELINE_END)
            outq.put(_PIPELINE_END)
            return
        start_idx, elems = task
        outs, failure = _supervised_chunk(inner, state, base_seed,
                                          start_idx, elems, budget,
                                          _QueueRestartStat(outq),
                                          "process worker")
        if failure is not None:
            exc, tb_text = failure.exc, failure.tb_text
            try:
                pickle.dumps(exc)
            except Exception:
                exc = RuntimeError(f"{type(exc).__name__}: {exc}")
            # the traceback object cannot cross the process boundary;
            # _Failure.reraise() re-chains its text on the consumer side
            outq.put(("inline", pickle.dumps(_Failure(exc, tb_text)),
                      None, None))
            outq.put(_PIPELINE_END)
            return
        names: list = []
        try:
            outq.put(_pack_result(outs, names))
        except BaseException:
            for nm in names:  # interrupted mid-handoff: reclaim the block
                _unlink_msg_shm(("shm", None, nm, None))
            raise


# --------------------------------------------------------------------------
# Chain-level wiring
# --------------------------------------------------------------------------

def parallelize_chain(transformer, n_workers: int, **kwargs):
    """Wrap the longest run of elementwise stages of a ``>>`` chain in a
    :class:`ParallelTransformer`, keeping stream-stateful stages
    (``Shuffle``, ``SampleToMiniBatch``, ...; ``elementwise = False``)
    serial around it. Returns the original transformer unchanged when
    nothing is parallelizable or ``n_workers <= 1``."""
    from bigdl_tpu.dataset.transformer import ChainedTransformer

    if n_workers <= 1:
        return transformer

    def flatten(t):
        if isinstance(t, ChainedTransformer):
            return flatten(t.first) + flatten(t.second)
        return [t]

    def rechain(stages):
        out = stages[0]
        for s in stages[1:]:
            out = ChainedTransformer(out, s)
        return out

    stages = flatten(transformer)
    best = (0, 0)  # (length, start)
    start = None
    for i, s in enumerate(stages + [None]):
        ok = s is not None and getattr(s, "elementwise", True) \
            and not isinstance(s, ParallelTransformer)
        if ok and start is None:
            start = i
        elif not ok and start is not None:
            if i - start > best[0]:
                best = (i - start, start)
            start = None
    length, start = best
    if length == 0:
        return transformer
    pool = ParallelTransformer(rechain(stages[start:start + length]),
                               n_workers, **kwargs)
    return rechain(stages[:start] + [pool] + stages[start + length:])
