"""HTTP endpoint: ``/metrics`` + ``/metrics.json`` + ``/healthz``.

One stdlib ``http.server`` thread serving a :class:`~bigdl_tpu.obs
.registry.MetricsRegistry` in Prometheus text exposition format (the
scrape surface the autoscaling/canary ROADMAP items consume) and JSON,
plus an aggregated health probe — the surface the future cross-host
fleet's load balancer will hit.

Health model: named zero-arg checks, each returning a dict whose
``"ok"`` key (default True) is the verdict; the endpoint is healthy iff
EVERY check is. Adapters for the stack's two health-bearing components
ship here: :func:`replica_health` (a set with zero placeable replicas
is down; fewer-than-all is ``degraded`` but serving) and
:func:`engine_health` (a failed/stalled engine refuses submits — that
IS down). A check that raises reports unhealthy with the error instead
of breaking the probe.

Lifecycle follows the chaos drain-gate pattern: ``close()`` shuts the
server down, joins the thread, and releases the socket — no leaked
``bigdl-`` threads after close (test-enforced).
"""

from __future__ import annotations

import http.server
import json
import threading
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import urlsplit

from bigdl_tpu.obs.exporters import to_json, to_prometheus
from bigdl_tpu.obs.registry import MetricsRegistry

HealthCheck = Callable[[], Dict[str, Any]]


def engine_health(engine) -> HealthCheck:
    """Health adapter for a :class:`GenerationEngine`: healthy while no
    step failure/stall has stopped the loop (a stopped engine refuses
    every submit). Exposes the watchdog stall count as detail."""

    def check() -> Dict[str, Any]:
        failed = getattr(engine, "_failed", None)
        wd = getattr(engine, "_watchdog", None)
        return {"ok": failed is None,
                "error": None if failed is None
                else f"{type(failed).__name__}: {failed}",
                "watchdog_stalls": 0 if wd is None else wd.stalls}

    return check


def replica_health(rset) -> HealthCheck:
    """Health adapter for a :class:`ReplicaSet`: healthy while at least
    one replica is placeable; ``degraded`` flags a QUARANTINE (a member
    of the serving rotation evicted for failures — the set still
    serves, but a fleet autoscaler wants to know).

    Membership is read LIVE, never assumed fixed: a fleet the
    autoscaler deliberately scaled down reports ``ok`` (the departed
    replica left the rotation, it did not fail out of it), and a fleet
    mid-scale-up does not flap — a WARMING replica (added to the set
    but still compiling, not yet placeable) counts in ``total`` without
    counting against health until it activates."""

    def check() -> Dict[str, Any]:
        healthy = rset.healthy_replicas
        total = rset.n_replicas
        warming = len(getattr(rset, "warming_replicas", ()))
        # degraded = members of the serving rotation that FAILED out of
        # it; warming members are expected to be unplaceable, so only
        # the (total - warming) in-rotation count sets the bar
        quarantined = max(0, total - warming - len(healthy))
        return {"ok": bool(healthy), "healthy": healthy, "total": total,
                "warming": warming, "degraded": quarantined > 0}

    return check


class _Handler(http.server.BaseHTTPRequestHandler):
    # the endpoint instance is attached to the subclass per-server
    endpoint: "MetricsEndpoint"

    def do_GET(self):  # noqa: N802 (stdlib handler contract)
        path = urlsplit(self.path).path
        try:
            if path == "/metrics":
                body = to_prometheus(self.endpoint.registry.collect(),
                                     self.endpoint.prefix)
                self._reply(200, body,
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path in ("/metrics.json", "/json"):
                body = to_json(self.endpoint.registry.collect())
                self._reply(200, body, "application/json")
            elif path == "/healthz":
                ok, detail = self.endpoint.health()
                self._reply(200 if ok else 503, json.dumps(detail),
                            "application/json")
            else:
                self._reply(404, json.dumps(
                    {"error": "not found",
                     "paths": ["/metrics", "/metrics.json", "/healthz"]}),
                    "application/json")
        except Exception as e:  # a broken collect must not kill the thread
            self._reply(500, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}), "application/json")

    def _reply(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args) -> None:  # scrapes are not log events
        pass


class MetricsEndpoint:
    """One background HTTP thread over a registry + health checks.

    ``port=0`` (the default) binds an ephemeral port — read it back
    from :attr:`port` / :meth:`url`. Binds loopback by default; pass
    ``host="0.0.0.0"`` deliberately to expose a fleet probe surface.
    """

    def __init__(self, registry: MetricsRegistry, *,
                 host: str = "127.0.0.1", port: int = 0,
                 health: Optional[Dict[str, HealthCheck]] = None,
                 prefix: str = "bigdl"):
        self.registry = registry
        self.prefix = prefix
        self._health: Dict[str, HealthCheck] = dict(health or {})
        self._lock = threading.Lock()
        self._closed = False
        handler = type("_BoundHandler", (_Handler,), {"endpoint": self})
        self._server = http.server.ThreadingHTTPServer((host, port),
                                                       handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="bigdl-obs-endpoint",
            daemon=True)
        self._thread.start()

    # -------------------------------------------------------- health ----

    def add_health(self, name: str, check: HealthCheck) -> "MetricsEndpoint":
        """Register a named health check (chainable)."""
        with self._lock:
            self._health[name] = check
        return self

    def health(self) -> Tuple[bool, Dict[str, Any]]:
        """Aggregate verdict + per-check detail (what /healthz serves).
        No checks registered = healthy (a metrics-only endpoint)."""
        with self._lock:
            checks = list(self._health.items())
        detail: Dict[str, Any] = {}
        ok = True
        for name, check in checks:
            try:
                d = dict(check())
            except Exception as e:
                d = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            d.setdefault("ok", True)
            ok = ok and bool(d["ok"])
            detail[name] = d
        return ok, {"ok": ok, "checks": detail}

    # ----------------------------------------------------- lifecycle ----

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address[:2]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def url(self, path: str = "/metrics") -> str:
        host, port = self.address
        return f"http://{host}:{port}{path}"

    def close(self, timeout: Optional[float] = 5.0) -> None:
        """Shut down, join the server thread, release the socket —
        idempotent, and no thread survives it (the drain-gate
        contract)."""
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._thread.join(timeout)
        self._server.server_close()

    def __enter__(self) -> "MetricsEndpoint":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
