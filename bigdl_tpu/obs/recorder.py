"""Flight recorder: a bounded ring of structured operational events.

The serving/train stack heals a lot on its own — retries, evictions,
rejoins, rolling reloads, watchdog stalls, checkpoint fallbacks — and
each healed incident used to leave at most a log line. The recorder
keeps the last N of them as STRUCTURED events (kind + fields + sequence
+ timestamp) in fixed memory, so a failed soak or a stalled engine can
print "what happened recently" instead of a bare traceback, and a chaos
harness can reconcile "faults fired" against "faults recorded".

One process-global default instance (:func:`flight_recorder`) is what
the library's incident points record into via :func:`record_event`;
private recorders exist only for isolated tests. Recording is O(1)
(deque append under a lock) and always on — the event sites are rare
(faults, evictions, stalls, retries, checkpoint commits), never
per-token hot paths.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


class FlightRecorder:
    """Bounded ring buffer of ``(seq, t, kind, fields)`` events."""

    def __init__(self, capacity: int = 1024,
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._seq = 0        # total events EVER recorded (ring may drop)
        self._kind_totals: Dict[str, int] = {}   # ever-recorded, per kind

    def record(self, kind: str, **fields) -> None:
        """Append one event. ``kind`` is a dotted family name
        (``"fault.fired"``, ``"replica.evicted"``, ``"ckpt.commit"``,
        ``"watchdog.stall"``, ``"retry"``); ``fields`` must be
        JSON-able scalars (the dump is machine-readable)."""
        with self._lock:
            self._seq += 1
            self._kind_totals[kind] = self._kind_totals.get(kind, 0) + 1
            self._events.append({"seq": self._seq, "t": self._clock(),
                                 "kind": kind, **fields})

    # ------------------------------------------------------- readers ----

    def dump(self, last: Optional[int] = None,
             kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """The retained events oldest->newest (copies), optionally only
        the newest ``last`` and/or one ``kind`` prefix."""
        with self._lock:
            events = list(self._events)
        if kind is not None:
            events = [e for e in events
                      if e["kind"] == kind
                      or e["kind"].startswith(kind + ".")]
        if last is not None:
            events = events[-int(last):]
        return events

    def count(self, kind: Optional[str] = None) -> int:
        """Events recorded EVER — overall, or for one kind (exact
        prefix match on the dotted family). Ever-counts survive ring
        wrap, so delta-based reconciliation (the chaos gate) stays
        correct however long the process has been recording; only
        :meth:`dump` is bounded by the ring."""
        with self._lock:
            if kind is None:
                return self._seq
            return sum(n for k, n in self._kind_totals.items()
                       if k == kind or k.startswith(kind + "."))

    def format_events(self, last: int = 32) -> str:
        """Fixed-width dump of the newest ``last`` events, in the style
        of the metrics tables — what a stall handler or failed soak
        prints."""
        events = self.dump(last=last)
        lines = [f"{'seq':>6} {'t':>12} {'kind':<20} fields"]
        for e in events:
            fields = " ".join(
                f"{k}={e[k]}" for k in e if k not in ("seq", "t", "kind"))
            lines.append(f"{e['seq']:>6} {e['t']:>12.3f} {e['kind']:<20} "
                         f"{fields}")
        return "\n".join(lines)

    def snapshot(self) -> Dict[str, Any]:
        """Registry-friendly gauge view: ever-recorded totals overall
        and per kind (exact, monotonic under ring wrap — scrape-safe)."""
        with self._lock:
            return {"events_total": self._seq,
                    "events_retained": len(self._events),
                    "capacity": self.capacity,
                    "by_kind": dict(sorted(self._kind_totals.items()))}

    def clear(self) -> None:
        """Drop retained events AND reset the totals (test isolation)."""
        with self._lock:
            self._events.clear()
            self._seq = 0
            self._kind_totals.clear()


#: The process-global recorder the library's incident points feed.
_default = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    """The process-global flight recorder."""
    return _default


def record_event(kind: str, **fields) -> None:
    """Record into the process-global flight recorder."""
    _default.record(kind, **fields)
