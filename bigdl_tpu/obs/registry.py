"""MetricsRegistry — one ``collect()`` over every component's gauges.

Every tier of the stack already keeps its own counters (ServingMetrics,
PagePool owner gauges, ReplicaSet health, CheckpointManager commits,
``FaultInjector.snapshot()``, RetryPolicy retries, PipelineStats, the
optimizer's step gauges) behind per-component ``snapshot()`` dicts with
no common schema and no export surface. The registry WRAPS them — it
never replaces a component's own snapshot/table, whose shapes are
golden-order test-pinned — under one flat, stable-key namespace:

    registry = MetricsRegistry()
    registry.register("serving", engine.metrics)     # has snapshot()
    registry.register("pages", engine._pool)         # has snapshot()
    registry.register("faults", faults.default())    # has snapshot()
    registry.register("train", lambda: {...})        # plain callable
    flat = registry.collect()
    # {"serving.served": 12, "pages.by_owner.target": 4, ...}

Key stability: sources collect in registration order, dicts flatten in
their own (insertion) order with dot-joined keys — so two collects of
the same wiring produce the same key sequence, which the Prometheus
round-trip test leans on. A failing source contributes one
``<name>.collect_error`` gauge instead of killing the scrape.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Union

#: What register() accepts: a zero-arg callable returning a dict, an
#: object exposing ``snapshot() -> dict``, or a live dict read at
#: collect time.
Source = Union[Callable[[], dict], Any, dict]

_NAME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_.\-]*$")


def flatten(prefix: str, value: Any, out: Dict[str, Any]) -> None:
    """Flatten nested dicts/sequences under dot-joined keys, in the
    container's own order (the stable-key contract)."""
    if isinstance(value, dict):
        for k, v in value.items():
            flatten(f"{prefix}.{k}", v, out)
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            flatten(f"{prefix}.{i}", v, out)
    else:
        out[prefix] = value


class MetricsRegistry:
    """Named metric sources behind one flat ``collect()``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources: "OrderedDict[str, Source]" = OrderedDict()

    def register(self, name: str, source: Source, *,
                 replace: bool = False) -> "MetricsRegistry":
        """Add ``source`` under ``name`` (the key prefix). Components
        register ONCE, at wiring time; re-registering a taken name
        raises — two sources silently shadowing each other is exactly
        the ad-hoc-dict mess this registry exists to end. The exception
        is DYNAMIC fleet membership (the autoscaler scales a replica
        down and later scales a new one up under the same slot name):
        ``replace=True`` swaps the source idempotently, keeping its
        position in the key order. Returns self for chaining."""
        if not _NAME_RE.match(name or ""):
            raise ValueError(
                f"source name {name!r} must match {_NAME_RE.pattern}")
        if not (callable(source) or isinstance(source, dict)
                or callable(getattr(source, "snapshot", None))):
            raise TypeError(
                f"source {name!r} must be a callable, a dict, or expose "
                f"snapshot(); got {type(source).__name__}")
        with self._lock:
            if name in self._sources and not replace:
                raise ValueError(f"metric source '{name}' already "
                                 f"registered")
            self._sources[name] = source
        return self

    def unregister(self, name: str) -> bool:
        """Drop a source (a scaled-down or dead replica must not leave
        a dead entry that every ``collect()`` drags around — or worse,
        degrades into a ``collect_error`` gauge — forever). Idempotent:
        returns whether the name was actually registered."""
        with self._lock:
            return self._sources.pop(name, None) is not None

    def names(self) -> List[str]:
        with self._lock:
            return list(self._sources)

    def collect(self) -> Dict[str, Any]:
        """One flat snapshot across every source, keys prefixed with
        the source name, insertion-ordered and stable run to run."""
        with self._lock:
            items = list(self._sources.items())
        flat: Dict[str, Any] = {}
        for name, src in items:
            try:
                if isinstance(src, dict):
                    snap: Any = src
                elif callable(getattr(src, "snapshot", None)):
                    snap = src.snapshot()
                else:
                    snap = src()
            except Exception as e:
                # a broken source must not take down /metrics for every
                # healthy one; surface the breakage as a gauge instead
                flat[f"{name}.collect_error"] = 1
                flat[f"{name}.collect_error_type"] = type(e).__name__
                continue
            if not isinstance(snap, dict):
                flat[name] = snap
                continue
            flatten(name, snap, flat)
        return flat
