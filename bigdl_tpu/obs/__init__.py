"""Unified telemetry plane: tracing, metrics registry, export, flight
recorder, and the engine step timeline.

The reference made TRAINING observable (``DL/visualization/Summary
.scala`` -> this repo's ``visualization/`` TensorBoard tier); the
serving stack grew far past it with only per-component ad-hoc
``snapshot()`` dicts. This package is the common plane on top — it
WRAPS the existing per-component surfaces (whose shapes stay
golden-order test-pinned), never replaces them:

- :class:`Tracer` / :class:`RequestTrace` — per-request span trees for
  the full serving lifecycle (submit -> queue wait -> page reservation
  -> prefill chunks -> counted decode/verify steps -> retirement),
  carried on the stream/future through ``ModelRouter -> ReplicaSet ->
  GenerationEngine``; JSONL export + :func:`format_trace` waterfalls;
  disabled cost is one ``is None`` test (< 2 us, test-pinned);
- :class:`MetricsRegistry` — components register their gauges once,
  one ``collect()`` produces a flat stable-key snapshot across
  serving + paging + replicas + ckpt + faults + pipeline + train;
- :func:`to_prometheus` / :func:`to_json` — exporters over a collect;
- :class:`MetricsEndpoint` — stdlib HTTP thread serving ``/metrics``
  (text exposition), ``/metrics.json``, and ``/healthz`` (aggregated
  :func:`engine_health` / :func:`replica_health` checks — the probe
  surface the cross-host fleet will reuse);
- :class:`FlightRecorder` / :func:`record_event` — bounded ring of
  structured incidents (faults fired, evictions/rejoins, watchdog
  stalls, retries, checkpoint commits/fallbacks) so a failed soak
  prints the last N events instead of a bare traceback;
- :class:`StepTimeline` — per-iteration engine breakdown (host
  scheduling vs device wait, prefill/decode/verify split, queue depth
  and occupancy), always on, bounded.

See README "Observability" for the wiring recipe and runbook.
"""

from bigdl_tpu.obs.endpoint import (
    MetricsEndpoint,
    engine_health,
    replica_health,
)
from bigdl_tpu.obs.exporters import prometheus_name, to_json, to_prometheus
from bigdl_tpu.obs.recorder import FlightRecorder, flight_recorder, record_event
from bigdl_tpu.obs.registry import MetricsRegistry
from bigdl_tpu.obs.timeline import StepTimeline
from bigdl_tpu.obs.trace import (
    RequestTrace,
    Span,
    Tracer,
    format_trace,
    submit_trace,
)

__all__ = [
    "FlightRecorder",
    "MetricsEndpoint",
    "MetricsRegistry",
    "RequestTrace",
    "Span",
    "StepTimeline",
    "Tracer",
    "engine_health",
    "flight_recorder",
    "format_trace",
    "prometheus_name",
    "record_event",
    "replica_health",
    "submit_trace",
    "to_json",
    "to_prometheus",
]
