"""Exporters: render a registry snapshot as Prometheus text or JSON.

Prometheus text exposition format (version 0.0.4) is the scrape lingua
franca — the autoscaling and canary items on the ROADMAP both consume
it. Rules applied here:

- metric names are ``<prefix>_<key>`` with every character outside
  ``[a-zA-Z0-9_]`` mapped to ``_`` (the exposition charset); the prefix
  guarantees a legal leading character;
- only numeric values export (bools as 0/1); strings and Nones are
  registry/JSON-only detail — Prometheus gauges are numbers;
- every metric renders exactly once: a post-sanitization collision
  (``a.b`` vs ``a_b``) keeps the FIRST key, matching the registry's
  insertion order (and the round-trip test asserts uniqueness);
- everything is typed ``gauge`` with the raw dotted key as HELP —
  counters monotonically increase anyway, and rate() works on gauges
  scraped as such for this stack's purposes.

The JSON exporter is the machine-readable artifact path
(``bench.py --metrics-out``): the registry's flat dict verbatim, plus
nothing — timestamps and run metadata belong to the caller's envelope.
"""

from __future__ import annotations

import json
import math
import re
from typing import Any, Dict

_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def prometheus_name(key: str, prefix: str = "bigdl") -> str:
    """Registry key -> legal exposition metric name."""
    return f"{prefix}_{_SANITIZE.sub('_', str(key))}"


def _format_value(v) -> str:
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        return repr(v)
    return str(v)


def to_prometheus(flat: Dict[str, Any], prefix: str = "bigdl") -> str:
    """Render a flat registry snapshot as text exposition format."""
    lines = []
    seen = set()
    for key, v in flat.items():
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, (int, float)):
            continue  # strings/None stay JSON-only
        name = prometheus_name(key, prefix)
        if name in seen:
            continue  # first key wins (registry insertion order)
        seen.add(name)
        lines.append(f"# HELP {name} {key}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_format_value(v)}")
    return "\n".join(lines) + "\n"


def to_json(flat: Dict[str, Any], indent=None) -> str:
    """Render a flat registry snapshot as JSON (non-JSON-able values
    stringify rather than fail the dump)."""
    return json.dumps(flat, indent=indent, default=str)
