"""Per-request tracing for the serving stack.

A :class:`Tracer` produces one :class:`RequestTrace` per request,
carrying the request's full lifecycle as spans and events::

    submit -> queue_wait -> page_reserve -> prefill_chunk(s)
           -> decode / verify_round (COUNTED, not one span each)
           -> retire (outcome + token count)

The trace context rides ON the handle (``GenerationStream.trace`` /
``Future.trace``), so it crosses the ``ModelRouter -> ReplicaSet ->
GenerationEngine`` layering without any signature change: the engine
creates and finishes the trace, the router and replica set annotate it
with routing attributes as the handle passes through their hands.

Design constraints, in order:

- **disabled is free.** A component built without a tracer pays ONE
  ``is None`` test on the submit path (:func:`submit_trace`) and one
  attribute load per decode step — the ``faults.SITES`` disarmed-site
  budget (< 2 us, test-pinned). Tracing is opt-in plumbing, not a tax.
- **structure is deterministic.** The span TREE (names, order, counts,
  outcome) is a pure function of the workload and scheduler semantics,
  never of wall time; with an injectable monotonic clock (the
  faults-tier fake-clock pattern) the durations pin down too, so tests
  compare whole traces. High-frequency per-iteration work (decode
  steps, verify rounds) is COUNTED onto one span via :meth:`RequestTrace
  .tick` rather than materialized per step — a 10k-token stream costs
  one span, not 10k.
- **export is boring.** Finished traces land in a bounded ring;
  :meth:`Tracer.dump_jsonl` writes one JSON object per line,
  :func:`format_trace` renders the fixed-width waterfall humans read.

Threading: a trace is touched by the submitting thread (creation + the
submit event) and then exclusively by the engine loop thread; list
appends are atomic under the GIL and the finish handoff into the
tracer's ring takes the tracer lock, so no per-trace lock is needed.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple


class Span:
    """One timed region of a request. ``count`` > 0 marks a COUNTED
    span (one per family, ticked per iteration — see
    :meth:`RequestTrace.tick`)."""

    __slots__ = ("name", "t0", "t1", "attrs", "count")

    def __init__(self, name: str, t0: float, t1: Optional[float] = None,
                 attrs: Optional[Dict[str, Any]] = None, count: int = 0):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs if attrs is not None else {}
        self.count = count

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "t0": self.t0,
                             "t1": self.t1}
        if self.count:
            d["count"] = self.count
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class RequestTrace:
    """One request's lifecycle. Engines drive it; routers/replica sets
    only :meth:`annotate`; consumers read it off the handle."""

    __slots__ = ("trace_id", "kind", "attrs", "t0", "t_end", "outcome",
                 "spans", "events", "_open", "_tracer")

    def __init__(self, tracer: "Tracer", trace_id: int, kind: str,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.trace_id = trace_id
        self.kind = kind
        self.attrs = dict(attrs)
        self.t0 = tracer.now()
        self.t_end: Optional[float] = None
        self.outcome: Optional[str] = None
        self.spans: List[Span] = []
        self.events: List[Tuple[str, float, Dict[str, Any]]] = []
        self._open: Dict[str, Span] = {}   # counted spans by name

    # ------------------------------------------------------ recording ----

    @property
    def done(self) -> bool:
        return self.t_end is not None

    def annotate(self, **attrs) -> None:
        """Attach routing/context attributes (model name, replica)."""
        self.attrs.update(attrs)

    def event(self, name: str, **attrs) -> None:
        """Point-in-time marker (``submit``, ``first_token``)."""
        self.events.append((name, self._tracer.now(), attrs))

    def span(self, name: str, t0: float, **attrs) -> Span:
        """Record a region that STARTED at ``t0`` and ends now (the
        queue-wait shape: the start is the trace's own birth)."""
        sp = Span(name, t0, self._tracer.now(), attrs)
        self.spans.append(sp)
        return sp

    def begin_span(self, name: str, **attrs) -> Span:
        """Open a region now; close it with :meth:`end_span`. Appended
        immediately so span ORDER is begin order."""
        sp = Span(name, self._tracer.now(), None, attrs)
        self.spans.append(sp)
        return sp

    def end_span(self, sp: Span, **attrs) -> Span:
        sp.t1 = self._tracer.now()
        if attrs:
            sp.attrs.update(attrs)
        return sp

    def tick(self, name: str, n: int = 1) -> None:
        """Count one iteration onto the single span named ``name``
        (created at first tick, extended to now on every tick) — the
        decode-step shape: 10k steps cost one span with count=10k."""
        now = self._tracer.now()
        sp = self._open.get(name)
        if sp is None:
            sp = Span(name, now, now)
            self._open[name] = sp
            self.spans.append(sp)
        sp.t1 = now
        sp.count += n

    def finish(self, outcome: str = "done", **attrs) -> None:
        """Terminal: record the outcome, close open counted spans, and
        retire into the tracer's finished ring. Idempotent — the first
        outcome wins (mirrors ``GenerationStream._finish``)."""
        if self.t_end is not None:
            return
        if attrs:
            self.attrs.update(attrs)
        self.outcome = outcome
        self.t_end = self._tracer.now()
        for sp in self._open.values():
            if sp.t1 is None:
                sp.t1 = self.t_end
        self._open.clear()
        self._tracer._retire(self)

    # -------------------------------------------------------- readers ----

    def structure(self) -> tuple:
        """Clock-independent shape: (kind, outcome, ordered (span name,
        count) pairs, sorted structural attrs). Two runs of the same
        workload produce EQUAL structures — the determinism contract
        the trace tests pin."""
        return (self.kind, self.outcome,
                tuple((sp.name, sp.count) for sp in self.spans),
                tuple(sorted((k, v) for k, v in self.attrs.items()
                             if isinstance(v, (str, int, bool)))))

    def to_dict(self) -> Dict[str, Any]:
        return {"id": self.trace_id, "kind": self.kind,
                "outcome": self.outcome, "t0": self.t0,
                "t_end": self.t_end, "attrs": dict(self.attrs),
                "spans": [sp.to_dict() for sp in self.spans],
                "events": [{"name": n, "t": t, **a}
                           for n, t, a in self.events]}


class Tracer:
    """Factory + bounded ring of finished :class:`RequestTrace`.

    ``clock`` is injectable (fake-clock tests); ``max_finished`` bounds
    retention — a long-lived service keeps the newest N traces, the
    started/finished counters keep counting.
    """

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 max_finished: int = 1024):
        self._clock = clock
        self._lock = threading.Lock()
        self._next_id = 0
        self._finished: "deque[RequestTrace]" = deque(maxlen=max_finished)
        self.started = 0
        self.retired = 0

    def now(self) -> float:
        return self._clock()

    def begin(self, kind: str, **attrs) -> RequestTrace:
        with self._lock:
            self._next_id += 1
            self.started += 1
            tid = self._next_id
        return RequestTrace(self, tid, kind, attrs)

    def _retire(self, trace: RequestTrace) -> None:
        with self._lock:
            self.retired += 1
            self._finished.append(trace)

    # -------------------------------------------------------- readers ----

    def finished(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._finished)

    def snapshot(self) -> Dict[str, Any]:
        """Registry-friendly gauges."""
        with self._lock:
            return {"started": self.started, "finished": self.retired,
                    "active": self.started - self.retired,
                    "retained": len(self._finished)}

    def dump_jsonl(self, path_or_file) -> int:
        """Write every retained finished trace as one JSON object per
        line; returns how many were written."""
        traces = self.finished()
        if hasattr(path_or_file, "write"):
            for t in traces:
                path_or_file.write(json.dumps(t.to_dict()) + "\n")
        else:
            with open(path_or_file, "w") as fh:
                for t in traces:
                    fh.write(json.dumps(t.to_dict()) + "\n")
        return len(traces)


def submit_trace(tracer: Optional[Tracer], kind: str,
                 **attrs) -> Optional[RequestTrace]:
    """The submit-path hook: returns a new trace, or ``None`` for free
    when tracing is off. Disabled cost is one ``is None`` test —
    test-pinned under the same < 2 us/call budget as a disarmed
    ``faults.fire`` site."""
    if tracer is None:
        return None
    return tracer.begin(kind, **attrs)


def format_trace(trace: RequestTrace) -> str:
    """Fixed-width waterfall (offsets in ms from the trace start), in
    the style of the metrics tables."""
    base = trace.t0
    total = ((trace.t_end - base) * 1e3
             if trace.t_end is not None else float("nan"))
    attrs = " ".join(f"{k}={v}" for k, v in sorted(trace.attrs.items()))
    lines = [f"trace #{trace.trace_id} {trace.kind} "
             f"outcome={trace.outcome or 'OPEN'} total={total:.3f}ms"
             + (f" {attrs}" if attrs else "")]
    for name, t, a in trace.events:
        extra = " ".join(f"{k}={v}" for k, v in sorted(a.items()))
        lines.append(f"  @ {(t - base) * 1e3:>9.3f}  {name:<16} {extra}"
                     .rstrip())
    for sp in trace.spans:
        t1 = sp.t1 if sp.t1 is not None else trace.t_end
        dur = "?" if t1 is None else f"{(t1 - sp.t0) * 1e3:.3f}"
        extra = " ".join(f"{k}={v}" for k, v in sorted(sp.attrs.items()))
        count = f" x{sp.count}" if sp.count else ""
        lines.append(
            f"    {(sp.t0 - base) * 1e3:>9.3f} {dur:>10}ms "
            f"{sp.name:<16}{count}" + (f" {extra}" if extra else ""))
    return "\n".join(lines)
