"""Engine step timeline: per-iteration breakdown of where time goes.

The MFU push needs to know whether a slow engine is losing time on the
device (kernel wait) or on the host (scheduling/bookkeeping between
kernel calls), and how the device share splits across prefill chunks,
decode steps, and speculative verify rounds. The engine loop records
one :class:`StepTimeline` row per scheduler iteration — a dict append
into a bounded ring, always on, nothing per-token — and the aggregate
``summary()`` feeds the ``engine_steps`` / ``step_host_ms`` /
``step_device_ms`` rows appended to :class:`ServingMetrics`.

Semantics of the split (recorded by ``GenerationEngine._step``):

- ``prefill_s`` / ``decode_s`` / ``verify_s`` — wall time inside the
  phase's kernel-call region (a speculative round, k+1 draft steps +
  one verify forward, books under ``verify_s``); dominated by device
  wait since the host blocks fetching each step's tokens;
- ``host_s`` — the iteration's remainder: admission, page
  reservation, retirement, metrics — pure host scheduling cost.

PR 19 (``async_scheduling=True``) adds the overlap split:

- ``step_gap_s`` — host-side gap between landing step N's tokens and
  dispatching step N+1 (a lower bound on device idle between
  consecutive steps; the sync path never records it);
- ``host_overlapped_s`` — host work done AFTER step N+1 was
  dispatched, i.e. scheduling/bookkeeping hidden under the in-flight
  device step instead of serialized before the next dispatch.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


class StepTimeline:
    """Bounded ring of per-iteration engine records + running totals."""

    _FIELDS = ("host_s", "prefill_s", "decode_s", "verify_s",
               "step_gap_s", "host_overlapped_s")

    def __init__(self, capacity: int = 512,
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._rows: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self.iterations = 0
        self._totals = {f: 0.0 for f in self._FIELDS}

    def record(self, *, host_s: float, prefill_s: float = 0.0,
               decode_s: float = 0.0, verify_s: float = 0.0,
               step_gap_s: float = 0.0, host_overlapped_s: float = 0.0,
               active: int = 0, queue_depth: int = 0,
               occupancy: float = 0.0, pages_in_use: int = 0) -> None:
        """One scheduler iteration (engine loop thread only)."""
        with self._lock:
            self.iterations += 1
            self._totals["host_s"] += host_s
            self._totals["prefill_s"] += prefill_s
            self._totals["decode_s"] += decode_s
            self._totals["verify_s"] += verify_s
            self._totals["step_gap_s"] += step_gap_s
            self._totals["host_overlapped_s"] += host_overlapped_s
            self._rows.append({
                "iter": self.iterations, "t": self._clock(),
                "host_s": host_s, "prefill_s": prefill_s,
                "decode_s": decode_s, "verify_s": verify_s,
                "step_gap_s": step_gap_s,
                "host_overlapped_s": host_overlapped_s,
                "active": active, "queue_depth": queue_depth,
                "occupancy": occupancy, "pages_in_use": pages_in_use,
            })

    # -------------------------------------------------------- readers ----

    def recent(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """Retained per-iteration rows oldest->newest (copies)."""
        with self._lock:
            rows = [dict(r) for r in self._rows]
        return rows[-int(last):] if last is not None else rows

    def snapshot(self) -> Dict[str, Any]:
        """Aggregate view (registry-friendly): totals, means, and the
        host-vs-device split over everything recorded so far."""
        with self._lock:
            n = self.iterations
            totals = dict(self._totals)
            recent = list(self._rows)
        device_s = (totals["prefill_s"] + totals["decode_s"]
                    + totals["verify_s"])
        busy = totals["host_s"] + device_s
        occ = [r["occupancy"] for r in recent]
        depth = [r["queue_depth"] for r in recent]
        return {
            "iterations": n,
            "host_ms_total": round(totals["host_s"] * 1e3, 3),
            "prefill_ms_total": round(totals["prefill_s"] * 1e3, 3),
            "decode_ms_total": round(totals["decode_s"] * 1e3, 3),
            "verify_ms_total": round(totals["verify_s"] * 1e3, 3),
            "host_frac": totals["host_s"] / busy if busy else 0.0,
            "mean_step_ms": round(busy / n * 1e3, 3) if n else 0.0,
            # windowed gauges over the retained ring (recent behavior,
            # which is what an autoscaler actually wants)
            "window_iterations": len(recent),
            "window_mean_occupancy": (sum(occ) / len(occ)) if occ else 0.0,
            "window_mean_queue_depth": (sum(depth) / len(depth)
                                        if depth else 0.0),
            # async-scheduling overlap split (PR 19) — appended after
            # every earlier key, never reordered
            "step_gap_ms": round(totals["step_gap_s"] * 1e3, 3),
            "host_overlapped_ms": round(
                totals["host_overlapped_s"] * 1e3, 3),
        }

    def format_timeline(self, last: int = 16) -> str:
        """Fixed-width per-iteration dump of the newest ``last`` rows,
        in the style of the metrics tables."""
        rows = self.recent(last=last)
        lines = [f"{'iter':>6} {'host_ms':>8} {'prefill':>8} "
                 f"{'decode':>8} {'verify':>8} {'active':>6} "
                 f"{'queue':>6} {'occ':>6}"]
        for r in rows:
            lines.append(
                f"{r['iter']:>6} {r['host_s'] * 1e3:>8.3f} "
                f"{r['prefill_s'] * 1e3:>8.3f} "
                f"{r['decode_s'] * 1e3:>8.3f} "
                f"{r['verify_s'] * 1e3:>8.3f} {r['active']:>6} "
                f"{r['queue_depth']:>6} {r['occupancy'] * 100:>5.1f}%")
        return "\n".join(lines)
