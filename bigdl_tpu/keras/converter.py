"""Keras model converter: JSON architecture + HDF5 weights -> the Keras
tier. Accepts Keras-1.2 JSON (the reference's format), Keras-2/tf.keras
legacy JSON, and Keras-3 ``to_json()`` functional/Sequential graphs
(``__keras_tensor__`` inbound encoding + ``.weights.h5`` layout),
including shared layers in all formats.

Reference: ``PY/keras/converter.py`` (DefinitionLoader / WeightLoader for
Keras 1.2.2 models) + ``PY/keras/backend.py`` (KerasModelWrapper).

Scope mirrors the reference's supported set for Sequential models: Dense,
Activation, Dropout, Flatten, Convolution2D, MaxPooling2D,
AveragePooling2D, GlobalAveragePooling2D, Embedding, SimpleRNN, LSTM, GRU,
BatchNormalization, ZeroPadding2D. Keras 1.2 config field names
(``output_dim``, ``nb_filter``/``nb_row``/``nb_col``, ``subsample``,
``border_mode``, ``dim_ordering``) are translated to the Keras-tier ctor
args; HDF5 weights follow the Keras 1.x layout
(``f.attrs['layer_names']`` -> per-layer ``weight_names`` datasets, the
same layout tf.keras's ``save_weights`` h5 path still writes).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu import keras


def _tuple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


class DefinitionLoader:
    """JSON -> keras-tier Sequential (reference ``DefinitionLoader``)."""

    @staticmethod
    def from_json_path(path: str) -> "keras.Sequential":
        with open(path) as f:
            return DefinitionLoader.from_json_str(f.read())

    @staticmethod
    def from_json_str(text: str):
        spec = json.loads(text)
        cls = spec.get("class_name")
        if cls in ("Model", "Functional"):
            return DefinitionLoader._from_functional(spec)
        if cls != "Sequential":
            raise ValueError(
                f"only Sequential and functional Model graphs are "
                f"supported, got {cls!r} (reference converter scope)")
        layers_cfg = spec["config"]
        if isinstance(layers_cfg, dict):  # keras 2.x nests under "layers"
            layers_cfg = layers_cfg["layers"]
        model = keras.Sequential()
        pending_shape = None
        for lc in layers_cfg:
            if lc["class_name"] == "InputLayer":
                # keras-3 Sequential: shape rides a leading InputLayer
                # ("batch_shape"), not the first real layer's config
                shape = (lc["config"].get("batch_input_shape")
                         or lc["config"].get("batch_shape"))
                if shape:
                    pending_shape = tuple(int(d) for d in shape[1:])
                continue
            layer = DefinitionLoader._convert_layer(lc)
            if layer is not None:
                if pending_shape is not None and layer._input_shape is None:
                    layer._input_shape = pending_shape
                pending_shape = None
                model.add(layer)
        return model

    # keras-2 merge classes -> keras-1 Merge modes
    _MERGE_MODES = {"Add": "sum", "Multiply": "mul", "Average": "ave",
                    "Maximum": "max", "Minimum": "min",
                    "Concatenate": "concat"}

    @staticmethod
    def _from_functional(spec) -> "keras.Model":
        """Functional ``Model`` graphs (reference ``DefinitionLoader``
        handles graph models via inbound_nodes topology,
        ``PY/keras/converter.py:289,462``): each layer entry wires to its
        parents by ``[name, node_index, tensor_index]``; InputLayers
        become :func:`keras.Input` nodes.

        Shared layers (multiple inbound call sites — Siamese towers,
        two-tower recommenders): ONE repo layer instance is created and
        called once per site, so every site produces its own graph node
        while :class:`bigdl_tpu.nn.graph.Graph` keys the params subtree by
        module instance — the call sites share weights exactly like the
        reference's shared-layer handling."""
        cfg = spec["config"]
        # layer name -> one output Node per call site (keras node_index)
        nodes: Dict[str, list] = {}
        for lc in cfg["layers"]:
            name = lc.get("name") or lc["config"].get("name")
            cls = lc["class_name"]
            inbound = lc.get("inbound_nodes") or []
            if cls == "InputLayer" or not inbound:
                shape = (lc["config"].get("batch_input_shape")
                         or lc["config"].get("batch_shape"))
                nodes[name] = [keras.Input(
                    shape=tuple(int(d) for d in shape[1:]), name=name)]
                continue
            inbound = DefinitionLoader._normalize_inbound(inbound)
            if cls == "Merge":
                layer = keras.Merge(
                    mode=lc["config"].get("mode", "sum"),
                    concat_axis=lc["config"].get("concat_axis", -1))
            elif cls in DefinitionLoader._MERGE_MODES:
                layer = keras.Merge(
                    mode=DefinitionLoader._MERGE_MODES[cls],
                    concat_axis=lc["config"].get("axis", -1))
            else:
                layer = DefinitionLoader._convert_layer(lc)
            if name:
                layer.set_name(name)

            def parent(ref):
                # [name, node_index, tensor_index(, kwargs)]
                return nodes[ref[0]][ref[1] if len(ref) > 1 else 0]

            nodes[name] = [
                layer(parents) if len(parents) > 1 else layer(parents[0])
                for call in inbound
                for parents in [[parent(p) for p in call]]]

        def endpoints(key):
            entries = cfg[key]
            if entries and isinstance(entries[0], str):
                entries = [entries]  # keras-3 single endpoint: flat triple
            return [nodes[e[0]][e[1] if len(e) > 1 else 0]
                    for e in entries]

        inputs = endpoints("input_layers")
        outputs = endpoints("output_layers")
        return keras.Model(inputs[0] if len(inputs) == 1 else inputs,
                           outputs[0] if len(outputs) == 1 else outputs)

    @staticmethod
    def _normalize_inbound(inbound):
        """Keras-2 inbound form passes through; Keras-3's
        ``[{"args": [...], "kwargs": {...}}]`` form (one dict per call site,
        tensors encoded as ``__keras_tensor__`` with a
        ``keras_history = [layer, node_index, tensor_index]``) is flattened
        to the keras-2 ``[[name, node_index, tensor_index], ...]`` lists."""
        if not inbound or not isinstance(inbound[0], dict):
            return inbound
        calls = []
        for node in inbound:
            refs: list = []

            def walk(v):
                if isinstance(v, dict):
                    if v.get("class_name") == "__keras_tensor__":
                        refs.append(list(v["config"]["keras_history"]))
                    else:
                        for vv in v.values():
                            walk(vv)
                elif isinstance(v, (list, tuple)):
                    for vv in v:
                        walk(vv)

            walk(node.get("args", []))
            walk(node.get("kwargs", {}))
            calls.append(refs)
        return calls

    @staticmethod
    def _convert_layer(lc: Dict):
        cls = lc["class_name"]
        cfg = dict(lc.get("config", {}))
        name = cfg.get("name")
        input_shape = None
        bis = cfg.get("batch_input_shape")
        if bis is not None:
            input_shape = tuple(int(d) for d in bis[1:])
        kw = {}
        if input_shape is not None:
            kw["input_shape"] = input_shape

        def named(layer):
            if name:
                layer.set_name(name)
            return layer

        if cls == "Dense":
            units = cfg.get("output_dim", cfg.get("units"))
            return named(keras.Dense(int(units),
                                     activation=cfg.get("activation", "linear")
                                     if cfg.get("activation") != "linear" else None,
                                     **kw))
        if cls == "Activation":
            return named(keras.Activation(cfg["activation"], **kw))
        if cls == "Dropout":
            return named(keras.Dropout(float(cfg.get("p", cfg.get("rate", 0.5))), **kw))
        if cls == "Flatten":
            return named(keras.Flatten(**kw))
        if cls in ("Convolution2D", "Conv2D"):
            nb = cfg.get("nb_filter", cfg.get("filters"))
            if "nb_row" in cfg:
                kh, kw_ = int(cfg["nb_row"]), int(cfg["nb_col"])
            else:
                kh, kw_ = _tuple(cfg["kernel_size"])
            stride = _tuple(cfg.get("subsample", cfg.get("strides", (1, 1))))
            border = cfg.get("border_mode", cfg.get("padding", "valid"))
            return named(keras.Convolution2D(
                int(nb), kh, kw_, subsample=tuple(int(s) for s in stride),
                border_mode=border,
                activation=cfg.get("activation") if cfg.get("activation") != "linear" else None,
                **kw))
        if cls in ("MaxPooling2D", "AveragePooling2D"):
            pool = _tuple(cfg.get("pool_size", (2, 2)))
            stride = cfg.get("strides") or pool
            k = keras.MaxPooling2D if cls == "MaxPooling2D" else keras.AveragePooling2D
            return named(k(pool_size=tuple(int(p) for p in pool),
                           strides=tuple(int(s) for s in stride), **kw))
        if cls == "GlobalAveragePooling2D":
            return named(keras.GlobalAveragePooling2D(**kw))
        if cls == "Embedding":
            vocab = cfg.get("input_dim")
            dim = cfg.get("output_dim")
            kw.setdefault("input_shape", (int(cfg["input_length"]),)
                          if cfg.get("input_length") else None)
            if kw.get("input_shape") is None:
                kw.pop("input_shape", None)
            return named(keras.Embedding(int(vocab), int(dim), **kw))
        if cls in ("SimpleRNN", "LSTM", "GRU"):
            units = cfg.get("output_dim", cfg.get("units"))
            k = getattr(keras, cls)
            return named(k(int(units),
                           return_sequences=bool(cfg.get("return_sequences", False)),
                           **kw))
        if cls == "BatchNormalization":
            return named(keras.BatchNormalization(
                epsilon=float(cfg.get("epsilon", 1e-3)),
                momentum=float(cfg.get("momentum", 0.99)), **kw))
        if cls == "ZeroPadding2D":
            return named(keras.ZeroPadding2D(
                padding=tuple(int(p) for p in _tuple(cfg.get("padding", (1, 1)))), **kw))
        if cls == "InputLayer":
            return None  # shape already captured via batch_input_shape
        raise ValueError(f"unsupported Keras layer {cls!r} "
                         "(reference converter scope)")


class WeightLoader:
    """HDF5 -> params overlay (reference ``WeightLoader``)."""

    @staticmethod
    def read_hdf5(path: str) -> List[Dict]:
        """[{name, weights: [arrays...]}] in model order (Keras 1.x
        layout: attrs['layer_names'] / per-group attrs['weight_names'])."""
        import h5py

        out = []
        with h5py.File(path, "r") as f:
            g = f["model_weights"] if "model_weights" in f else f
            if "layer_names" in g.attrs:  # Keras 1.x / tf.keras legacy h5
                layer_names = [n.decode() if isinstance(n, bytes) else n
                               for n in g.attrs["layer_names"]]
                for lname in layer_names:
                    grp = g[lname]
                    wnames = [n.decode() if isinstance(n, bytes) else n
                              for n in grp.attrs.get("weight_names", [])]
                    out.append({
                        "name": lname,
                        "weights": [np.asarray(grp[w]) for w in wnames],
                        "weight_names": wnames,
                    })
                return out
            if "layers" in g:  # Keras 3 .weights.h5 layout
                def collect(grp):
                    """Datasets of this group's ``vars`` plus nested
                    sub-objects' (``cell/vars`` for RNN layers), in keras'
                    save order."""
                    ws = []
                    if "vars" in grp:
                        vg = grp["vars"]
                        for k in sorted(vg.keys(), key=int):
                            ws.append(np.asarray(vg[k]))
                    for k in grp:
                        if k != "vars" and hasattr(grp[k], "keys"):
                            ws.extend(collect(grp[k]))
                    return ws

                for key in g["layers"]:
                    grp = g["layers"][key]
                    # group keys are class-derived ('simple_rnn'); the
                    # LAYER name lives on the (possibly dataset-less)
                    # direct vars group
                    name = key
                    if "vars" in grp and "name" in grp["vars"].attrs:
                        name = grp["vars"].attrs["name"]
                    name = name.decode() if isinstance(name, bytes) else name
                    weights = collect(grp)
                    if weights:
                        out.append({"name": name, "weights": weights,
                                    "weight_names": []})
                return out
        raise ValueError(f"unrecognized Keras weight file layout in {path}")

    @staticmethod
    def convert(kind: str, weights: List[np.ndarray], dim_ordering: str = "th",
                cfg: Optional[Dict] = None):
        """Keras-1.2 weight layout -> this repo's param dict(s).
        ``dim_ordering``: 'th' stores conv kernels OIHW (our native layout),
        'tf' (and tf.keras h5 files) stores HWIO."""
        if kind == "Dense":
            w = weights[0].T  # keras (in, out) -> Linear (out, in)
            p = {"weight": w}
            if len(weights) > 1:
                p["bias"] = weights[1]
            return p
        if kind in ("Convolution2D", "Conv2D"):
            w = weights[0]
            if dim_ordering in ("tf", "channels_last"):
                w = w.transpose(3, 2, 0, 1)  # HWIO -> OIHW
            p = {"weight": w}
            if len(weights) > 1:
                p["bias"] = weights[1]
            return p
        if kind == "Embedding":
            return {"weight": weights[0]}
        if kind == "SimpleRNN":
            # [kernel (in,H), recurrent (H,H), bias] -> packed (in+H, H);
            # use_bias=False saves no bias — overlay an explicit ZERO bias
            # (the cell always owns a bias param; leaving the random init
            # in place would be silently wrong)
            w = np.concatenate([weights[0], weights[1]], axis=0)
            b = weights[2] if len(weights) > 2 \
                else np.zeros(w.shape[1], w.dtype)
            return {"weight": w, "bias": b}
        if kind == "LSTM":
            if len(weights) == 12:
                # Keras 1.2: per-gate [W,U,b] x (i, c, f, o) -> pack and
                # reorder to this repo's (i, f, g=c, o)
                Wg = {g: weights[3 * k] for k, g in enumerate("icfo")}
                Ug = {g: weights[3 * k + 1] for k, g in enumerate("icfo")}
                bg = {g: weights[3 * k + 2] for k, g in enumerate("icfo")}
                kern = np.concatenate([Wg[g] for g in "ifco"], axis=1)
                rec = np.concatenate([Ug[g] for g in "ifco"], axis=1)
                bias = np.concatenate([bg[g] for g in "ifco"])
            else:
                # Keras 2/3: kernel (in,4H) + recurrent (H,4H) + bias (4H),
                # gate order (i, f, c, o) == this repo's (i, f, g, o)
                kern, rec = weights[0], weights[1]
                bias = weights[2] if len(weights) > 2 \
                    else np.zeros(kern.shape[1], kern.dtype)  # use_bias=False
            return {"weight": np.concatenate([kern, rec], axis=0),
                    "bias": bias}
        if kind == "GRU":
            kern, rec = weights[0], weights[1]
            h = rec.shape[0]
            # the recurrence VARIANT comes from the layer config, never
            # inferred from weight shapes (a no-bias GRU has no bias to
            # inspect): reset_after=False applies the reset BEFORE the
            # recurrent matmul — a different function than this repo's
            # GRUCell (torch/cuDNN convention); no faithful weight mapping
            if not (cfg or {}).get("reset_after", True):
                raise ValueError(
                    "GRU weight conversion requires reset_after=True; "
                    "reset_after=False is a different recurrence and "
                    "cannot be mapped")
            bias = weights[2] if len(weights) > 2 \
                else np.zeros((2, 3 * h), kern.dtype)  # use_bias=False
            if bias.ndim != 2:
                raise ValueError(
                    "GRU bias shape %s does not match reset_after=True "
                    "(expected (2, 3H))" % (bias.shape,))
            kz, kr, kh = kern[:, :h], kern[:, h:2 * h], kern[:, 2 * h:]
            rz_, rr, rh = rec[:, :h], rec[:, h:2 * h], rec[:, 2 * h:]
            b_in, b_rec = bias[0], bias[1]
            return {
                # this repo's packed rz columns are (r | z)
                "weight_rz": np.concatenate(
                    [np.concatenate([kr, kz], axis=1),
                     np.concatenate([rr, rz_], axis=1)], axis=0),
                "bias_rz": np.concatenate(
                    [b_in[h:2 * h] + b_rec[h:2 * h], b_in[:h] + b_rec[:h]]),
                "weight_in": kh, "bias_in": b_in[2 * h:],
                "weight_hn": rh, "bias_hn": b_rec[2 * h:],
            }
        if kind == "BatchNormalization":
            # keras order: gamma, beta, moving_mean, moving_variance
            p = {"weight": weights[0], "bias": weights[1]}
            s = {"running_mean": weights[2], "running_var": weights[3]}
            return p, s
        raise ValueError(f"no weight conversion for {kind!r}")


def load_keras(json_path: Optional[str] = None,
               hdf5_path: Optional[str] = None,
               json_str: Optional[str] = None):
    """Build the keras-tier model and load Keras-1.2 weights (reference
    ``KerasModelWrapper``/``load_keras``). Returns the compiled-less
    Sequential with weights set; call ``compile`` to train or ``predict``
    directly."""
    if json_str is None:
        if json_path is None:
            raise ValueError("need json_path or json_str")
        with open(json_path) as f:
            json_str = f.read()
    spec = json.loads(json_str)
    model = DefinitionLoader.from_json_str(json_str)
    params, state = model._require_params()

    if hdf5_path is None:
        return model

    layers_cfg = spec["config"]
    if isinstance(layers_cfg, dict):
        layers_cfg = layers_cfg["layers"]
    cls_by_name = {lc["config"].get("name"): lc["class_name"]
                   for lc in layers_cfg}
    h5_layers = {l["name"]: l for l in WeightLoader.read_hdf5(hdf5_path)}

    def overlay(tree, name, converted):
        """Find the subtree for keras layer `name` and merge weights into
        the first dict level that holds 'weight'."""
        def merge(node):
            if isinstance(node, dict):
                is_leaf_dict = node and all(
                    not isinstance(v, dict) for v in node.values())
                if is_leaf_dict and any(k in node for k in converted):
                    node.update({k: np.asarray(v) for k, v in converted.items()})
                    return True
                for v in node.values():
                    if merge(v):
                        return True
            return False

        sub = tree
        for root in ("seq", "graph"):  # Sequential / functional Model
            if isinstance(sub, dict) and root in sub:
                sub = sub[root]
                break
        if not (isinstance(sub, dict) and name in sub):
            return False
        return merge(sub[name])

    import jax

    params = jax.tree_util.tree_map(np.asarray, params)
    state = jax.tree_util.tree_map(np.asarray, state)
    for lname, info in h5_layers.items():
        if not info["weights"]:
            continue
        kind = cls_by_name.get(lname)
        if kind is None:
            continue
        cfg = next((lc["config"] for lc in layers_cfg
                    if lc["config"].get("name") == lname), {})
        # legacy Convolution2D defaults to 'th' (OIHW); Keras-2+ Conv2D
        # defaults to channels_last (HWIO)
        default_ordering = "channels_last" if kind == "Conv2D" else "th"
        ordering = cfg.get("dim_ordering",
                           cfg.get("data_format", default_ordering))
        conv = WeightLoader.convert(kind, info["weights"], ordering, cfg)
        if isinstance(conv, tuple):
            pconv, sconv = conv
            overlay(params, lname, pconv)
            overlay(state, lname, sconv)
        else:
            overlay(params, lname, conv)
    model.set_weights(jax.tree_util.tree_map(np.asarray, params),
                      jax.tree_util.tree_map(np.asarray, state))
    return model
